"""DDL worker: asynchronous-model job queue + F1 schema-state machine.

Capability parity with reference ddl/ddl_worker.go:300,427-460 (dequeue +
dispatch by ActionType, one state transition per own-txn iteration, schema
version bump per step), ddl/column.go (add/drop column state ladders; the
course's drop-column task at column.go:216), ddl/index.go + ddl/reorg.go
(add-index backfill in checkpointed batches), ddl/rollingback.go (unique
violation rolls the index add back), ddl/schema.go, ddl/table.go.

Each state step commits its own meta txn and bumps the schema version, so
concurrent sessions never observe a jump of more than one state — the F1
invariant that makes online DDL safe with lease-based schema caches.
"""
from __future__ import annotations

from typing import List, Optional

from ..catalog.meta import Meta
from ..catalog.model import (ActionType, ColumnInfo, DBInfo, IndexInfo, Job,
                             JobState, SchemaState, TableInfo)
from ..catalog.table import DuplicateKeyError, Index, Table
from ..codec import tablecodec
from ..kv.errors import KVError, KeyNotFound
from ..utils import failpoint

REORG_BATCH = 256  # reference: ddl variable defaultReorgBatchSize spirit


class DDLWorker:
    """Synchronous owner worker: steps the first queued job until history.
    (Single-process build: the etcd owner election collapses to local
    ownership; owner/manager.go's mock owner is the model.)"""

    def __init__(self, storage, sync_timeout_s: float = 1.0):
        self.storage = storage
        self.sync_timeout_s = sync_timeout_s

    # ---- main loop ------------------------------------------------------
    def run_until_done(self, job_id: int, max_steps: int = 10_000,
                       owner=None) -> None:
        """Step first-queued jobs until `job_id` reaches history.  With
        an `owner` manager, each step re-campaigns (renewing the lease —
        a long backfill must not silently lose ownership mid-job); lost
        ownership returns control to the caller's wait loop."""
        for _ in range(max_steps):
            if owner is not None and not owner.campaign():
                return  # ownership lost/taken: another worker steps now
            txn = self.storage.begin()
            m = Meta(txn)
            if m.get_history_job(job_id) is not None:
                txn.rollback()
                return
            job = m.first_job()
            if job is None:
                txn.rollback()
                return
            try:
                finished = self._run_one_step(m, job)
                if finished:
                    m.pop_job(job.id)
                    job.state = (JobState.CANCELLED if job.error
                                 else JobState.SYNCED)
                    m.add_history_job(job)
                ver = m.bump_schema_version()
                txn.commit()
                # syncer barrier (reference: ddl/util/syncer.go via
                # ddl_worker.go waitSchemaSynced): every registered
                # server domain must load this version before the NEXT
                # state transition — the F1 "at most one state apart"
                # invariant across servers; timeout falls through to the
                # commit-time schema validator as the backstop
                from ..domain import wait_schema_synced
                wait_schema_synced(self.storage, ver,
                                   timeout_s=self.sync_timeout_s)
            except KVError:
                txn.rollback()
                continue  # retry the step
            except Exception as e:  # job-level failure -> record + finish
                txn.rollback()
                txn = self.storage.begin()
                m = Meta(txn)
                job.error = str(e)
                job.state = JobState.CANCELLED
                m.pop_job(job.id)
                m.add_history_job(job)
                m.bump_schema_version()
                txn.commit()
        raise RuntimeError(f"DDL job {job_id} did not converge")

    def run_pending(self, owner=None, max_steps: int = 10_000) -> None:
        """Owner background duty (reference: ddl_worker.go:112 start loop):
        drain whatever is queued — jobs enqueued by OTHER servers must
        not wait for the owner's lease to lapse."""
        for _ in range(max_steps):
            if owner is not None and not owner.campaign():
                return
            txn = self.storage.begin()
            m = Meta(txn)
            job = m.first_job()
            txn.rollback()
            if job is None:
                return
            self.run_until_done(job.id, owner=owner)

    # ---- dispatch (reference: ddl_worker.go:427 runDDLJob) -------------
    def _run_one_step(self, m: Meta, job: Job) -> bool:
        failpoint.inject("ddlStepError")
        handler = {
            ActionType.CREATE_SCHEMA: self._on_create_schema,
            ActionType.DROP_SCHEMA: self._on_drop_schema,
            ActionType.CREATE_TABLE: self._on_create_table,
            ActionType.DROP_TABLE: self._on_drop_table,
            ActionType.TRUNCATE_TABLE: self._on_truncate_table,
            ActionType.ADD_COLUMN: self._on_add_column,
            ActionType.DROP_COLUMN: self._on_drop_column,
            ActionType.ADD_INDEX: self._on_add_index,
            ActionType.DROP_INDEX: self._on_drop_index,
        }[job.tp]
        finished = handler(m, job)
        if not finished:
            m.update_job(job)
        return finished

    # ---- schema ---------------------------------------------------------
    def _on_create_schema(self, m: Meta, job: Job) -> bool:
        db = DBInfo(m.gen_global_id(), job.args[0])
        m.create_database(db)
        job.schema_id = db.id
        job.state = JobState.DONE
        return True

    def _on_drop_schema(self, m: Meta, job: Job) -> bool:
        db = m.get_database(job.schema_id)
        if db is None:
            job.state = JobState.DONE
            return True
        if db.state == SchemaState.PUBLIC:
            db.state = SchemaState.WRITE_ONLY
            m.update_database(db)
            job.schema_state = db.state
            return False
        if db.state == SchemaState.WRITE_ONLY:
            db.state = SchemaState.DELETE_ONLY
            m.update_database(db)
            job.schema_state = db.state
            return False
        # final: drop tables' data + meta
        for t in m.list_tables(db.id):
            self._delete_table_data(t)
        m.drop_database(db.id)
        job.state = JobState.DONE
        return True

    # ---- tables ---------------------------------------------------------
    def _on_create_table(self, m: Meta, job: Job) -> bool:
        info = TableInfo.from_dict(job.args[0])
        info.id = m.gen_global_id()
        info.state = SchemaState.PUBLIC
        m.create_table(job.schema_id, info)
        job.table_id = info.id
        job.state = JobState.DONE
        return True

    def _on_drop_table(self, m: Meta, job: Job) -> bool:
        t = m.get_table(job.schema_id, job.table_id)
        if t is None:
            job.state = JobState.DONE
            return True
        if t.state == SchemaState.PUBLIC:
            t.state = SchemaState.WRITE_ONLY
        elif t.state == SchemaState.WRITE_ONLY:
            t.state = SchemaState.DELETE_ONLY
        else:
            self._delete_table_data(t)
            m.drop_table(job.schema_id, t.id)
            job.state = JobState.DONE
            return True
        m.update_table(job.schema_id, t)
        job.schema_state = t.state
        return False

    def _on_truncate_table(self, m: Meta, job: Job) -> bool:
        t = m.get_table(job.schema_id, job.table_id)
        self._delete_table_data(t)
        old_id = t.id
        m.drop_table(job.schema_id, old_id)
        t.id = m.gen_global_id()
        m.create_table(job.schema_id, t)
        job.args = [old_id, t.id]
        job.state = JobState.DONE
        return True

    def _delete_table_data(self, t: TableInfo) -> None:
        """Synchronous delete-range (reference defers to GC delete-ranges;
        in-proc we clear eagerly)."""
        txn = self.storage.begin()
        lo = tablecodec.encode_table_prefix(t.id)
        hi = lo + b"\xff" * 20
        for k, _ in list(txn.iter_range(lo, hi)):
            txn.delete(k)
        txn.commit()
        from ..statistics.table_stats import drop_stats
        drop_stats(self.storage, t.id)

    # ---- columns (reference: ddl/column.go; course stub :216) ----------
    def _on_add_column(self, m: Meta, job: Job) -> bool:
        t = m.get_table(job.schema_id, job.table_id)
        col = t.find_column(ColumnInfo.from_dict(job.args[0]).name)
        if col is None:
            col = ColumnInfo.from_dict(job.args[0])
            t.max_column_id += 1
            col.id = t.max_column_id
            col.offset = len(t.columns)
            col.state = SchemaState.DELETE_ONLY
            t.columns.append(col)
        elif col.state == SchemaState.DELETE_ONLY:
            col.state = SchemaState.WRITE_ONLY
        elif col.state == SchemaState.WRITE_ONLY:
            col.state = SchemaState.WRITE_REORG
        else:
            # no backfill needed: absent values read as the default
            # (rowcodec fills defaults on decode)
            col.state = SchemaState.PUBLIC
            m.update_table(job.schema_id, t)
            job.state = JobState.DONE
            return True
        m.update_table(job.schema_id, t)
        job.schema_state = col.state
        return False

    def _on_drop_column(self, m: Meta, job: Job) -> bool:
        t = m.get_table(job.schema_id, job.table_id)
        col = t.find_column(job.args[0])
        if col is None:
            job.state = JobState.DONE
            return True
        if col.state == SchemaState.PUBLIC:
            col.state = SchemaState.WRITE_ONLY
        elif col.state == SchemaState.WRITE_ONLY:
            col.state = SchemaState.DELETE_ONLY
        elif col.state == SchemaState.DELETE_ONLY:
            col.state = SchemaState.WRITE_REORG
        else:
            t.columns.remove(col)
            for i, c in enumerate(sorted(t.columns, key=lambda c: c.offset)):
                c.offset = i
            t.columns.sort(key=lambda c: c.offset)
            m.update_table(job.schema_id, t)
            job.state = JobState.DONE
            return True
        m.update_table(job.schema_id, t)
        job.schema_state = col.state
        return False

    # ---- indices (reference: ddl/index.go + reorg.go backfill) ---------
    def _on_add_index(self, m: Meta, job: Job) -> bool:
        t = m.get_table(job.schema_id, job.table_id)
        want = IndexInfo.from_dict(job.args[0])
        idx = t.find_index(want.name)
        if job.state == JobState.ROLLINGBACK:
            return self._rollback_add_index(m, job, t, idx)
        if idx is None:
            idx = IndexInfo.from_dict(job.args[0])
            t.max_index_id += 1
            idx.id = t.max_index_id
            idx.state = SchemaState.DELETE_ONLY
            t.indices.append(idx)
        elif idx.state == SchemaState.DELETE_ONLY:
            idx.state = SchemaState.WRITE_ONLY
        elif idx.state == SchemaState.WRITE_ONLY:
            idx.state = SchemaState.WRITE_REORG
            job.reorg_handle = 0
        elif idx.state == SchemaState.WRITE_REORG:
            try:
                done = self._backfill_batch(t, idx, job)
            except DuplicateKeyError as e:
                job.state = JobState.ROLLINGBACK
                job.error = str(e)
                m.update_job(job)
                return False
            if not done:
                m.update_job(job)
                return False
            idx.state = SchemaState.PUBLIC
            m.update_table(job.schema_id, t)
            job.state = JobState.DONE
            return True
        m.update_table(job.schema_id, t)
        job.schema_state = idx.state
        return False

    def _rollback_add_index(self, m: Meta, job: Job, t: TableInfo,
                            idx: Optional[IndexInfo]) -> bool:
        """reference: rollingback.go — walk states back, drop entries."""
        if idx is None:
            job.error = job.error or "add index rolled back"
            return True
        if idx.state in (SchemaState.WRITE_REORG, SchemaState.WRITE_ONLY):
            idx.state = SchemaState.DELETE_ONLY
            m.update_table(job.schema_id, t)
            return False
        self._delete_index_data(t, idx)
        t.indices.remove(idx)
        m.update_table(job.schema_id, t)
        job.error = job.error or "add index rolled back"
        return True

    def _backfill_batch(self, t: TableInfo, idx_info: IndexInfo,
                        job: Job) -> bool:
        """One checkpointed backfill batch in its own txn (reference:
        reorg.go backfill loop; job.reorg_handle is the crash-resume
        checkpoint).  Returns True when the scan is exhausted."""
        failpoint.inject("reorgBatchError")
        txn = self.storage.begin()
        tbl = Table(t)
        idx = Index(tbl, idx_info)
        count = 0
        last_handle = None
        start = job.reorg_handle + 1 if job.reorg_handle else None
        for handle, row in tbl.iter_records(txn, start_handle=start):
            k, v = idx.key(row, handle)
            if idx_info.unique:
                existing = idx.exists_conflict(txn, row)
                if existing is not None and existing != handle:
                    txn.rollback()
                    raise DuplicateKeyError(t.name, idx_info.name,
                                            idx._index_values(row))
            txn.set(k, v)
            last_handle = handle
            count += 1
            if count >= REORG_BATCH:
                break
        txn.commit()
        job.row_count += count
        if last_handle is not None:
            job.reorg_handle = last_handle
        return count < REORG_BATCH

    def _on_drop_index(self, m: Meta, job: Job) -> bool:
        t = m.get_table(job.schema_id, job.table_id)
        idx = t.find_index(job.args[0])
        if idx is None:
            job.state = JobState.DONE
            return True
        if idx.state == SchemaState.PUBLIC:
            idx.state = SchemaState.WRITE_ONLY
        elif idx.state == SchemaState.WRITE_ONLY:
            idx.state = SchemaState.DELETE_ONLY
        else:
            self._delete_index_data(t, idx)
            t.indices.remove(idx)
            m.update_table(job.schema_id, t)
            job.state = JobState.DONE
            return True
        m.update_table(job.schema_id, t)
        job.schema_state = idx.state
        return False

    def _delete_index_data(self, t: TableInfo, idx: IndexInfo) -> None:
        txn = self.storage.begin()
        lo, hi = tablecodec.index_range(t.id, idx.id)
        for k, _ in list(txn.iter_range(lo, hi)):
            txn.delete(k)
        txn.commit()
