"""DDL owner election (reference: owner/manager.go:46 — campaign on an
etcd election with a leased key; owner/mock.go for single-node).

In-proc analogue: a leased (owner_id, expires_at) slot on the shared
storage object guarded by one lock — the same campaign/renew/retire
protocol without etcd.  Exactly one live manager is owner at a time;
ownership lapses when the lease expires (crashed owner) and any other
campaigner takes over.
"""
from __future__ import annotations

import threading
import time
from typing import Optional, Tuple


def _slot(storage):
    s = getattr(storage, "_ddl_owner_slot", None)
    if s is None:
        s = storage._ddl_owner_slot = {"lock": threading.Lock(),
                                       "owner": None}  # (id, expires_at)
    return s


class OwnerManager:
    def __init__(self, storage, owner_id: Optional[str] = None,
                 ttl_s: float = 1.0):
        self.storage = storage
        self.owner_id = owner_id or f"ddl-owner-{id(self):x}"
        self.ttl_s = ttl_s

    def campaign(self) -> bool:
        """Try to become (or stay) owner; renews the lease on success."""
        s = _slot(self.storage)
        now = time.monotonic()
        with s["lock"]:
            cur: Optional[Tuple[str, float]] = s["owner"]
            if cur is None or cur[1] <= now or cur[0] == self.owner_id:
                s["owner"] = (self.owner_id, now + self.ttl_s)
                return True
            return False

    def is_owner(self) -> bool:
        s = _slot(self.storage)
        now = time.monotonic()
        with s["lock"]:
            cur = s["owner"]
            return (cur is not None and cur[0] == self.owner_id
                    and cur[1] > now)

    def retire(self) -> None:
        """Resign ownership (reference: manager.ResignOwner)."""
        s = _slot(self.storage)
        with s["lock"]:
            if s["owner"] is not None and s["owner"][0] == self.owner_id:
                s["owner"] = None


class MockOwner(OwnerManager):
    """Always-owner single-node manager (reference: owner/mock.go)."""

    def campaign(self) -> bool:
        return True

    def is_owner(self) -> bool:
        return True
