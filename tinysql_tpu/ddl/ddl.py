"""DDL API: statement validation + TableInfo construction + job execution.

Capability parity with reference ddl/ddl_api.go (validation + job build;
1,949 L) and the per-action impls (table.go, column.go, index.go,
schema.go).  This module runs jobs through the owner worker
(ddl/worker.py) which steps the F1 schema-state machine; every finished
job lands in the history queue for ADMIN SHOW DDL JOBS.
"""
from __future__ import annotations

from typing import List, Optional

from ..catalog.meta import Meta
from ..catalog.model import (ActionType, ColumnInfo, DBInfo, IndexColumn,
                             IndexInfo, Job, JobState, SchemaState, TableInfo)
from ..mytypes import (EvalType, FLAG_AUTO_INCREMENT, FLAG_NOT_NULL,
                       FLAG_PRI_KEY, FLAG_UNIQUE_KEY, cast_datum)
from ..parser import ast


class DDLError(Exception):
    pass


class DBExists(DDLError):
    def __init__(self, name):
        super().__init__(f"Can't create database '{name}'; database exists")


class TableExists(DDLError):
    def __init__(self, name):
        super().__init__(f"Table '{name}' already exists")


def build_table_info(stmt: ast.CreateTableStmt, alloc_id) -> TableInfo:
    """AST -> TableInfo (reference: ddl_api.go buildTableInfo)."""
    cols: List[ColumnInfo] = []
    indices: List[IndexInfo] = []
    pk_col: Optional[str] = None
    seen = set()
    for off, cd in enumerate(stmt.cols):
        lname = cd.name.lower()
        if lname in seen:
            raise DDLError(f"Duplicate column name '{cd.name}'")
        seen.add(lname)
        ft = cd.ft.clone()
        default = None
        is_unique = False
        for opt in cd.options:
            if opt.tp == "not_null":
                ft.flag |= FLAG_NOT_NULL
            elif opt.tp == "primary":
                if pk_col is not None:
                    raise DDLError("Multiple primary key defined")
                pk_col = cd.name
                ft.flag |= FLAG_PRI_KEY | FLAG_NOT_NULL
            elif opt.tp == "unique":
                is_unique = True
            elif opt.tp == "auto_increment":
                ft.flag |= FLAG_AUTO_INCREMENT
            elif opt.tp == "default":
                default = cast_datum(opt.value, ft) if opt.value is not None else None
        ci = ColumnInfo(off + 1, cd.name, off, ft, default)
        cols.append(ci)
        if is_unique:
            indices.append(IndexInfo(0, cd.name, [IndexColumn(cd.name, off)],
                                     unique=True))
    col_by_name = {c.name.lower(): c for c in cols}

    for cons in stmt.constraints:
        icols = []
        for cname, plen in cons.columns:
            c = col_by_name.get(cname.lower())
            if c is None:
                raise DDLError(f"Key column '{cname}' doesn't exist in table")
            icols.append(IndexColumn(c.name, c.offset, plen))
        if cons.tp == "primary":
            if pk_col is not None:
                raise DDLError("Multiple primary key defined")
            if len(icols) == 1:
                c = col_by_name[icols[0].name.lower()]
                c.ft.flag |= FLAG_PRI_KEY | FLAG_NOT_NULL
                pk_col = c.name
            else:
                # composite pk -> unique index named PRIMARY
                for ic in icols:
                    col_by_name[ic.name.lower()].ft.flag |= FLAG_NOT_NULL
                indices.append(IndexInfo(0, "PRIMARY", icols, unique=True,
                                         primary=True))
                pk_col = ""
        elif cons.tp == "unique":
            indices.append(IndexInfo(0, cons.name or _auto_index_name(indices, icols),
                                     icols, unique=True))
        else:
            indices.append(IndexInfo(0, cons.name or _auto_index_name(indices, icols),
                                     icols))

    # pk-as-handle only for a single integer primary key
    pk_is_handle = False
    if pk_col:
        c = col_by_name[pk_col.lower()]
        if c.ft.eval_type is EvalType.INT:
            pk_is_handle = True
        else:
            indices.append(IndexInfo(0, "PRIMARY",
                                     [IndexColumn(c.name, c.offset)],
                                     unique=True, primary=True))

    info = TableInfo(id=0, name=stmt.table.name, columns=cols,
                     indices=indices, pk_is_handle=pk_is_handle,
                     max_column_id=len(cols))
    for i, idx in enumerate(info.indices):
        idx.id = i + 1
    info.max_index_id = len(info.indices)
    return info


def _auto_index_name(indices, icols) -> str:
    base = icols[0].name
    names = {i.name.lower() for i in indices}
    if base.lower() not in names:
        return base
    k = 2
    while f"{base}_{k}".lower() in names:
        k += 1
    return f"{base}_{k}"


class DDL:
    """DDL API facade bound to a storage; runs jobs synchronously through
    the worker's state machine (reference: ddl.go:158 DDL iface + doDDLJob
    :421 enqueue-and-wait)."""

    def __init__(self, storage, owner=None):
        self.storage = storage
        from .owner import MockOwner, OwnerManager
        from .worker import DDLWorker
        # single-node default: always-owner mock (reference: owner/mock.go);
        # a Server passes a real campaigning OwnerManager
        if owner is None or owner is True:
            owner = MockOwner(storage)
        assert isinstance(owner, OwnerManager)
        self.owner = owner
        self.worker = DDLWorker(storage)

    # ---- helpers --------------------------------------------------------
    def _run_job(self, job: Job, wait_timeout_s: float = 30.0) -> Job:
        """Enqueue, then either step the state machine (this server won
        the owner campaign) or wait for the owner server to finish it
        (reference: ddl.go doDDLJob :421 enqueue-and-wait — any server
        enqueues, only the owner's worker runs)."""
        import time
        txn = self.storage.begin()
        m = Meta(txn)
        job.id = m.gen_global_id()
        m.enqueue_job(job)
        txn.commit()
        deadline = time.monotonic() + wait_timeout_s
        done = None
        while done is None:
            if self.owner.campaign():
                self.worker.run_until_done(job.id, owner=self.owner)
            txn = self.storage.begin()
            done = Meta(txn).get_history_job(job.id)
            txn.rollback()
            if done is None:
                if time.monotonic() > deadline:
                    # a job the owner is actively stepping WILL commit:
                    # keep waiting instead of reporting a false failure
                    if self._job_in_flight(job.id):
                        deadline = time.monotonic() + wait_timeout_s
                        time.sleep(0.005)  # qlint: disable=FP501 -- deadline-bounded owner-completion poll, not an RPC retry ladder
                        continue
                    self._cancel_queued(job)
                    # outcome re-check: the owner may have finished (or
                    # be unstoppably mid-flight) in the cancel window —
                    # never report 'failed' for a DDL that committed
                    txn = self.storage.begin()
                    done = Meta(txn).get_history_job(job.id)
                    txn.rollback()
                    if done is None or (
                            done.error and "timed out" in done.error):
                        raise DDLError(f"DDL job {job.id} timed out "
                                       "waiting for the owner")
                    break
                time.sleep(0.005)  # qlint: disable=FP501 -- deadline-bounded owner-completion poll, not an RPC retry ladder
        if done.error:
            raise DDLError(done.error)
        # the OWNER thread may still be inside the final syncer barrier;
        # the DDL statement must not return before every live server has
        # loaded the final schema (reference: doDDLJob returns only after
        # checkSchemaSynced — a client's next connection may land on any
        # server and must see the new object)
        txn = self.storage.begin()
        try:
            final_ver = Meta(txn).schema_version()
        finally:
            txn.rollback()
        from ..domain import wait_schema_synced
        wait_schema_synced(self.storage, final_ver,
                           timeout_s=self.worker.sync_timeout_s)
        return done

    def _job_in_flight(self, job_id: int) -> bool:
        """Has the owner started stepping this job (schema state moved
        past NONE)?  Such a job must run to completion or roll back via
        the worker — cancelling or failing it would strand intermediate
        F1 states."""
        from ..catalog.model import SchemaState
        txn = self.storage.begin()
        try:
            queued = next((j for j in Meta(txn)._load_queue()
                           if j.id == job_id), None)
        finally:
            txn.rollback()
        return (queued is not None
                and (queued.schema_state != SchemaState.NONE
                     or queued.state != JobState.NONE))

    def _cancel_queued(self, job: Job) -> None:
        """A job reported as failed must never execute later: dequeue it
        on the timeout path — but ONLY while it is still untouched
        (schema_state NONE)."""
        from ..kv.errors import KVError
        txn = self.storage.begin()
        committed = False
        try:
            m = Meta(txn)
            if m.get_history_job(job.id) is None:
                from ..catalog.model import SchemaState
                queued = next((j for j in m._load_queue()
                               if j.id == job.id), None)
                if (queued is not None
                        and queued.schema_state == SchemaState.NONE
                        and queued.state == JobState.NONE):
                    m.pop_job(job.id)
                    job.state = JobState.CANCELLED
                    job.error = "timed out waiting for the DDL owner"
                    m.add_history_job(job)
                    m.bump_schema_version()
                    txn.commit()
                    committed = True
        except KVError:
            pass  # lost a write conflict to the owner: it took the job
        finally:
            if not committed:
                try:
                    txn.rollback()
                except Exception:
                    pass

    # ---- databases ------------------------------------------------------
    def create_database(self, name: str, if_not_exists=False) -> bool:
        """Returns True when IF NOT EXISTS made this a no-op (the
        session's Note 1007 rides the authoritative check here)."""
        txn = self.storage.begin()
        m = Meta(txn)
        exists = any(d.name.lower() == name.lower() for d in m.list_databases())
        txn.rollback()
        if exists:
            if if_not_exists:
                return True
            raise DBExists(name)
        self._run_job(Job(0, ActionType.CREATE_SCHEMA, 0, 0, args=[name]))
        return False

    def drop_database(self, name: str, if_exists=False) -> bool:
        """True when IF EXISTS made this a no-op (session Note 1008)."""
        db_id = self._db_id(name)
        if db_id is None:
            if if_exists:
                return True
            raise DDLError(f"Can't drop database '{name}'; database doesn't exist")
        self._run_job(Job(0, ActionType.DROP_SCHEMA, db_id, 0))
        return False

    def _db_id(self, name: str) -> Optional[int]:
        txn = self.storage.begin()
        m = Meta(txn)
        hit = next((d.id for d in m.list_databases()
                    if d.name.lower() == name.lower()), None)
        txn.rollback()
        return hit

    def _table(self, db_id: int, name: str) -> Optional[TableInfo]:
        txn = self.storage.begin()
        m = Meta(txn)
        hit = next((t for t in m.list_tables(db_id)
                    if t.name.lower() == name.lower()), None)
        txn.rollback()
        return hit

    def _require_db(self, name: str) -> int:
        db_id = self._db_id(name)
        if db_id is None:
            raise DDLError(f"Unknown database '{name}'")
        return db_id

    def _require_table(self, db_id: int, name: str) -> TableInfo:
        t = self._table(db_id, name)
        if t is None:
            raise DDLError(f"Table '{name}' doesn't exist")
        return t

    # ---- tables ---------------------------------------------------------
    def create_table(self, db_name: str, stmt: ast.CreateTableStmt) -> bool:
        """True when IF NOT EXISTS made this a no-op (session Note 1050)."""
        db_id = self._require_db(db_name)
        if self._table(db_id, stmt.table.name) is not None:
            if stmt.if_not_exists:
                return True
            raise TableExists(stmt.table.name)
        info = build_table_info(stmt, None)
        self._run_job(Job(0, ActionType.CREATE_TABLE, db_id, 0,
                          args=[info.to_dict()]))
        return False

    def drop_table(self, db_name: str, table: str, if_exists=False) -> bool:
        """True when IF EXISTS made this a no-op (session Note 1051)."""
        db_id = self._require_db(db_name)
        t = self._table(db_id, table)
        if t is None:
            if if_exists:
                return True
            raise DDLError(f"Unknown table '{table}'")
        self._run_job(Job(0, ActionType.DROP_TABLE, db_id, t.id))
        return False

    def truncate_table(self, db_name: str, table: str) -> None:
        db_id = self._require_db(db_name)
        t = self._require_table(db_id, table)
        self._run_job(Job(0, ActionType.TRUNCATE_TABLE, db_id, t.id))

    # ---- columns --------------------------------------------------------
    def add_column(self, db_name: str, table: str, cd: ast.ColumnDef) -> None:
        db_id = self._require_db(db_name)
        t = self._require_table(db_id, table)
        if t.find_column(cd.name) is not None:
            raise DDLError(f"Duplicate column name '{cd.name}'")
        ft = cd.ft.clone()
        default = None
        for opt in cd.options:
            if opt.tp == "not_null":
                ft.flag |= FLAG_NOT_NULL
            elif opt.tp == "default":
                default = opt.value
            elif opt.tp in ("primary", "unique", "auto_increment"):
                raise DDLError(f"unsupported option {opt.tp} in ADD COLUMN")
        col = ColumnInfo(0, cd.name, 0, ft, default)
        self._run_job(Job(0, ActionType.ADD_COLUMN, db_id, t.id,
                          args=[col.to_dict()]))

    def drop_column(self, db_name: str, table: str, col_name: str) -> None:
        db_id = self._require_db(db_name)
        t = self._require_table(db_id, table)
        c = t.find_column(col_name)
        if c is None:
            raise DDLError(f"Can't DROP '{col_name}'; check that column exists")
        if len(t.public_columns()) == 1:
            raise DDLError(f"Can't delete all columns with ALTER TABLE")
        if t.pk_is_handle and (c.ft.flag & FLAG_PRI_KEY):
            raise DDLError("Unsupported drop primary key column")
        for idx in t.indices:
            if any(ic.name.lower() == col_name.lower() for ic in idx.columns):
                raise DDLError(
                    f"column '{col_name}' is covered by index '{idx.name}'; "
                    f"drop the index first")
        self._run_job(Job(0, ActionType.DROP_COLUMN, db_id, t.id,
                          args=[c.name]))

    # ---- indices --------------------------------------------------------
    def add_index(self, db_name: str, table: str, index_name: str,
                  columns: List, unique: bool) -> None:
        db_id = self._require_db(db_name)
        t = self._require_table(db_id, table)
        if index_name and t.find_index(index_name) is not None:
            raise DDLError(f"Duplicate key name '{index_name}'")
        icols = []
        for cname, plen in columns:
            c = t.find_column(cname)
            if c is None:
                raise DDLError(f"Key column '{cname}' doesn't exist in table")
            icols.append(IndexColumn(c.name, c.offset, plen))
        info = IndexInfo(0, index_name or _auto_index_name(t.indices, icols),
                         icols, unique=unique)
        self._run_job(Job(0, ActionType.ADD_INDEX, db_id, t.id,
                          args=[info.to_dict()]))

    def drop_index(self, db_name: str, table: str, index_name: str) -> None:
        db_id = self._require_db(db_name)
        t = self._require_table(db_id, table)
        if t.find_index(index_name) is None:
            raise DDLError(f"Can't DROP '{index_name}'; check that index exists")
        self._run_job(Job(0, ActionType.DROP_INDEX, db_id, t.id,
                          args=[index_name]))
