"""Benchmarks (BASELINE.json configs): TPC-H Q1/Q3/Q6 + operator micros."""
from . import tpch

__all__ = ["tpch"]
