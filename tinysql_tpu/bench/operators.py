"""Operator-level micro-benchmarks (BASELINE.json configs 1-4).

The reference ships per-operator Go harnesses (mvmap put/get
util/mvmap/mvmap_test.go:64-73, expression vec-vs-row
expression/bench_test.go, chunk codec) but publishes no numbers; these
four SQL shapes exercise the same operators end to end — HashAgg
group-by, int64 equi hash join, vectorized projection+filter, top-k
sort — and report rows/sec per tier so operator regressions show up
independent of the TPC-H query mix (VERDICT r4 next-8).
"""
from __future__ import annotations

import time

import numpy as np

N_FACT = 1 << 21          # 2M rows: big enough to amortize dispatch
N_DIM = 1 << 16


def _gen(seed: int = 13):
    rng = np.random.default_rng(seed)
    fact = {
        "id": np.arange(1, N_FACT + 1, dtype=np.int64),
        "a": rng.integers(0, 1 << 20, N_FACT).astype(np.int64),
        "b": rng.integers(0, 1 << 16, N_FACT).astype(np.int64),
        "k": rng.integers(1, N_DIM + 1, N_FACT).astype(np.int64),
        "c": rng.random(N_FACT),
    }
    dim = {
        "k": np.arange(1, N_DIM + 1, dtype=np.int64),
        "v": rng.integers(0, 1000, N_DIM).astype(np.int64),
    }
    return fact, dim


# operator -> (sql, input-rows for the rows/sec denominator)
OPERATORS = {
    # 1. HashAggExec: SUM/COUNT group-by over int64 chunks
    "hash_agg": ("select b, sum(a), count(*) from opbench_fact group by b",
                 N_FACT),
    # 2. HashJoinExec: inner equi-join on int64 key (scalar agg above
    #    keeps the bench operator-bound, not resultset-bound)
    "hash_join": ("select sum(opbench_dim.v + opbench_fact.b) from "
                  "opbench_fact join opbench_dim "
                  "on opbench_fact.k = opbench_dim.k", N_FACT),
    # 3. Projection + vectorized compare/arithmetic filter
    "proj_filter": ("select count(*), sum(a * 2 + b) from opbench_fact "
                    "where a * 3 - b * 2 > 500000", N_FACT),
    # 4. SortExec top-k: ORDER BY int64, float64 with LIMIT
    "topk_sort": ("select a, c from opbench_fact "
                  "order by a, c limit 100", N_FACT),
}


_DATA = None


def _data():
    """Generate once per process: run() reuses load()'s arrays for the
    sqlite twin instead of paying the 2M-row RNG twice."""
    global _DATA
    if _DATA is None:
        _DATA = _gen()
    return _DATA


def load(session) -> None:
    from ..columnar.store import bulk_load
    fact, dim = _data()
    session.execute("create database if not exists opbench")
    session.execute("use opbench")
    for name, data in (("opbench_fact", fact), ("opbench_dim", dim)):
        session.execute(f"drop table if exists {name}")
    session.execute("create table opbench_fact (id bigint primary key, "
                    "a bigint, b bigint, k bigint, c double)")
    session.execute("create table opbench_dim (k bigint primary key, "
                    "v bigint)")
    info = session.infoschema().table_by_name("opbench", "opbench_fact")
    bulk_load(session.storage, info, fact)
    info = session.infoschema().table_by_name("opbench", "opbench_dim")
    bulk_load(session.storage, info, dim)


def run(session, dev_tier: str, reps: int = 3) -> dict:
    """Returns {op: {"<tier>_rows_per_s": N, "cpu_rows_per_s": N,
    "sqlite_rows_per_s": N, "match": bool}}."""
    import sys
    session.execute("use opbench")
    lite = _sqlite_times()
    out = {}
    for op, (sql, n_rows) in OPERATORS.items():
        # liveness marker: a cold compile cache can make the first run of
        # an operator take minutes on XLA:CPU (cached thereafter in
        # .jax_cache) — never look hung
        print(f"[bench] op {op} running ...", file=sys.stderr)
        entry = {}
        rows_by_tier = {}
        for tier, flag in ((dev_tier, 1), ("cpu", 0)):
            session.execute(f"set @@tidb_use_tpu = {flag}")
            best = float("inf")
            for _ in range(reps):
                t0 = time.time()
                rows = session.query(sql).rows
                best = min(best, time.time() - t0)
            rows_by_tier[tier] = rows
            entry[f"{tier}_rows_per_s"] = round(n_rows / best)
            entry[f"{tier}_wall_s"] = round(best, 4)
        session.execute("set @@tidb_use_tpu = 1")
        lite_best, lite_rows = lite[op]
        entry["sqlite_rows_per_s"] = round(n_rows / lite_best)
        entry["match"] = (_canon(rows_by_tier[dev_tier])
                          == _canon(rows_by_tier["cpu"])
                          == _canon(lite_rows))
        out[op] = entry
    return out


def _canon(rows):
    return sorted(tuple(f"{v:.9g}" if isinstance(v, float) else str(v)
                        for v in r) for r in rows)


def _sqlite_times(reps: int = 3):
    import sqlite3
    fact, dim = _data()
    db = sqlite3.connect(":memory:")
    db.execute("PRAGMA journal_mode=OFF")
    db.execute("create table opbench_fact (id integer primary key, "
               "a integer, b integer, k integer, c real)")
    db.execute("create table opbench_dim (k integer primary key, "
               "v integer)")
    db.executemany("insert into opbench_fact values (?,?,?,?,?)",
                   zip(*(fact[c].tolist()
                         for c in ("id", "a", "b", "k", "c"))))
    db.executemany("insert into opbench_dim values (?,?)",
                   zip(*(dim[c].tolist() for c in ("k", "v"))))
    out = {}
    for op, (sql, _) in OPERATORS.items():
        best, rows = float("inf"), None
        for _ in range(reps):
            t0 = time.time()
            rows = db.execute(sql).fetchall()
            best = min(best, time.time() - t0)
        out[op] = (best, [list(r) for r in rows])
    db.close()
    return out
