"""Operator-level micro-benchmarks (BASELINE.json configs 1-4).

The reference ships per-operator Go harnesses (mvmap put/get
util/mvmap/mvmap_test.go:64-73, expression vec-vs-row
expression/bench_test.go, chunk codec) but publishes no numbers; these
four SQL shapes exercise the same operators end to end — HashAgg
group-by, int64 equi hash join, vectorized projection+filter, top-k
sort — and report rows/sec per tier so operator regressions show up
independent of the TPC-H query mix (VERDICT r4 next-8).
"""
from __future__ import annotations

import time

import numpy as np

N_FACT = 1 << 21          # 2M rows: big enough to amortize dispatch
N_DIM = 1 << 16


def _gen(seed: int = 13):
    rng = np.random.default_rng(seed)
    fact = {
        "id": np.arange(1, N_FACT + 1, dtype=np.int64),
        "a": rng.integers(0, 1 << 20, N_FACT).astype(np.int64),
        "b": rng.integers(0, 1 << 16, N_FACT).astype(np.int64),
        "k": rng.integers(1, N_DIM + 1, N_FACT).astype(np.int64),
        "c": rng.random(N_FACT),
    }
    dim = {
        "k": np.arange(1, N_DIM + 1, dtype=np.int64),
        "v": rng.integers(0, 1000, N_DIM).astype(np.int64),
    }
    return fact, dim


# operator -> (sql, input-rows for the rows/sec denominator)
OPERATORS = {
    # 1. HashAggExec: SUM/COUNT group-by over int64 chunks
    "hash_agg": ("select b, sum(a), count(*) from opbench_fact group by b",
                 N_FACT),
    # 2. HashJoinExec: inner equi-join on int64 key (scalar agg above
    #    keeps the bench operator-bound, not resultset-bound)
    "hash_join": ("select sum(opbench_dim.v + opbench_fact.b) from "
                  "opbench_fact join opbench_dim "
                  "on opbench_fact.k = opbench_dim.k", N_FACT),
    # 3. Projection + vectorized compare/arithmetic filter
    "proj_filter": ("select count(*), sum(a * 2 + b) from opbench_fact "
                    "where a * 3 - b * 2 > 500000", N_FACT),
    # 4. SortExec top-k: ORDER BY int64, float64 with LIMIT
    "topk_sort": ("select a, c from opbench_fact "
                  "order by a, c limit 100", N_FACT),
}


_DATA = None


def _data():
    """Generate once per process: run() reuses load()'s arrays for the
    sqlite twin instead of paying the 2M-row RNG twice."""
    global _DATA
    if _DATA is None:
        _DATA = _gen()
    return _DATA


def load(session) -> None:
    from ..columnar.store import bulk_load
    fact, dim = _data()
    session.execute("create database if not exists opbench")
    session.execute("use opbench")
    for name, data in (("opbench_fact", fact), ("opbench_dim", dim)):
        session.execute(f"drop table if exists {name}")
    session.execute("create table opbench_fact (id bigint primary key, "
                    "a bigint, b bigint, k bigint, c double)")
    session.execute("create table opbench_dim (k bigint primary key, "
                    "v bigint)")
    info = session.infoschema().table_by_name("opbench", "opbench_fact")
    bulk_load(session.storage, info, fact)
    info = session.infoschema().table_by_name("opbench", "opbench_dim")
    bulk_load(session.storage, info, dim)


def run(session, dev_tier: str, reps: int = 3) -> dict:
    """Returns {op: {"<tier>_rows_per_s": N, "cpu_rows_per_s": N,
    "sqlite_rows_per_s": N, "match": bool}}."""
    import sys
    session.execute("use opbench")
    lite = _sqlite_times()
    out = {}
    for op, (sql, n_rows) in OPERATORS.items():
        # liveness marker: a cold compile cache can make the first run of
        # an operator take minutes on XLA:CPU (cached thereafter in
        # .jax_cache) — never look hung
        print(f"[bench] op {op} running ...", file=sys.stderr)
        entry = {}
        rows_by_tier = {}
        for tier, flag in ((dev_tier, 1), ("cpu", 0)):
            session.execute(f"set @@tidb_use_tpu = {flag}")
            best = float("inf")
            for _ in range(reps):
                t0 = time.time()
                rows = session.query(sql).rows
                best = min(best, time.time() - t0)
            rows_by_tier[tier] = rows
            entry[f"{tier}_rows_per_s"] = round(n_rows / best)
            entry[f"{tier}_wall_s"] = round(best, 4)
        session.execute("set @@tidb_use_tpu = 1")
        lite_best, lite_rows = lite[op]
        entry["sqlite_rows_per_s"] = round(n_rows / lite_best)
        entry["match"] = (_canon(rows_by_tier[dev_tier])
                          == _canon(rows_by_tier["cpu"])
                          == _canon(lite_rows))
        out[op] = entry
    return out


def _canon(rows):
    return sorted(tuple(f"{v:.9g}" if isinstance(v, float) else str(v)
                        for v in r) for r in rows)


# ---- mesh-sharded operator tier (ISSUE 17) --------------------------------

N_SHARD_ROWS = 1 << 19    # 512k rows: enough for the collectives to pay
N_SHARD_BUILD = 1 << 16   # unique build side for the partitioned join


def run_sharded(reps: int = 3) -> dict:
    """Per-device-count rows/s for the partition-parallel operator tier
    (ops/shardops.py): ``hash_agg`` (partial->final scalar aggregate),
    ``join_probe`` (partitioned build/probe unique join) and ``sort``
    (per-shard sort + rank merge), measured at every power-of-two submesh
    the process exposes.  N=1 is the single-device kernel — the row the
    sharded tier has to beat — and ``match`` asserts byte-identity of
    every sharded result against it, so a scaling number from a wrong
    answer can never publish.

    TWO throughputs per (family, N), both from the same measured run:

    - ``rows_per_s_wall`` — raw host wall.  A forced host mesh timeshares
      its N virtual devices onto the physical cores (1 in CI), so this
      number can NEVER scale with N on a host mesh; it is the honest
      serialized cost and the regression-tracking number.
    - ``rows_per_s`` (headline) — balanced-shard critical path: the
      serial host sections (partition scatter, probe-order re-assembly)
      at measured cost plus the measured shard-parallel device region
      (shardops.LAST_DEVICE_REGION_S) divided by N.  Row-sliced shards
      (hash_agg, sort) carry exactly nb/N rows each and hash-partition
      blocks are capacity-equalized, so max-over-shards == mean and the
      division is the wall a real N-device mesh would see — the
      host-mesh proxy for ICI scaling (PROFILE.md §14).
    """
    import sys

    from ..ops import kernels, shardops
    from ..parallel import dist

    ndev = len(kernels.jax().devices())
    sizes = [n for n in (1, 2, 4, 8) if n <= ndev]
    rng = np.random.default_rng(1117)
    n = N_SHARD_ROWS
    nb = kernels.bucket(n)

    # shared inputs: f64 measure column (integer-valued so the partial
    # sums are order-exact), int64 probe/sort keys, ~1% nulls
    vals = rng.integers(0, 1000, n).astype(np.float64)
    nulls = rng.random(n) < 0.01
    probe = rng.integers(0, N_SHARD_BUILD * 2, n).astype(np.int64)
    build = rng.permutation(N_SHARD_BUILD).astype(np.int64)
    bnull = np.zeros(N_SHARD_BUILD, dtype=bool)
    sortk = rng.integers(-(1 << 40), 1 << 40, n).astype(np.int64)
    valid = np.ones(nb, dtype=bool)
    valid[n:] = False

    dev_cols = ((kernels.h2d_pad(vals, nb),
                 kernels.h2d_pad(nulls, nb)),)
    mask = ("host", kernels.h2d(valid))
    specs = (("sum", True), ("min", True), ("max", True),
             ("count_star", False))
    args = [lambda cols, pr: (cols[0][0], cols[0][1])] * 3 + [None]

    def agg(mesh):
        if mesh is None:
            outs, _ = kernels.fused_scalar_aggregate(
                dev_cols, specs, args, n, nb, mask,
                program_key=("opbench_sharded",))
        else:
            outs, _ = shardops.fused_scalar_aggregate_sharded(
                mesh, dev_cols, specs, args, n, nb, mask,
                program_key=("opbench_sharded",))
        return [(np.asarray(v), np.asarray(m)) for v, m in outs]

    def join(mesh):
        if mesh is None:
            return kernels.unique_join_match(
                (probe, nulls), n, (build, bnull), N_SHARD_BUILD)
        return shardops.unique_join_match_sharded(
            mesh, (probe, nulls), n, (build, bnull), N_SHARD_BUILD)

    def sort(mesh):
        if mesh is None:
            return kernels.sort_permutation([(sortk, nulls)], [False], n)
        return shardops.sort_permutation_sharded(
            mesh, [(sortk, nulls)], [False], n)

    families = {"hash_agg": agg, "join_probe": join, "sort": sort}
    import os
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover
        cores = os.cpu_count() or 1
    fams = {}
    for fam, fn in families.items():
        entry = {"input_rows": n, "rows_per_s": {}, "rows_per_s_wall": {},
                 "wall_s": {}, "device_region_s": {}, "serial_host_s": {}}
        baseline = None
        for ns in sizes:
            mesh = dist.sized_mesh(ns)
            res = fn(mesh)  # warm compile before timing
            assert res is not None, (fam, ns)
            best, region = float("inf"), 0.0
            for _ in range(reps):
                t0 = time.time()
                res = fn(mesh)
                wall = time.time() - t0
                if wall < best:
                    best, region = wall, shardops.LAST_DEVICE_REGION_S
            if mesh is None:
                region, serial = best, 0.0  # whole run IS the one device
            else:
                serial = max(best - region, 0.0)
            critical = serial + region / ns
            entry["wall_s"][str(ns)] = round(best, 4)
            entry["device_region_s"][str(ns)] = round(region, 4)
            entry["serial_host_s"][str(ns)] = round(serial, 4)
            entry["rows_per_s_wall"][str(ns)] = round(n / best)
            entry["rows_per_s"][str(ns)] = round(n / critical)
            if baseline is None:
                baseline, match = res, True
            else:
                match = entry.get("match", True) and _same(baseline, res)
            entry["match"] = match
            print(f"[bench] sharded {fam} n={ns}: "
                  f"{entry['rows_per_s'][str(ns)]:,} rows/s "
                  f"(wall {entry['rows_per_s_wall'][str(ns)]:,}) "
                  f"match={entry['match']}", file=sys.stderr)
        one = entry["rows_per_s"].get("1", 0)
        peak_n = max(entry["rows_per_s"], key=entry["rows_per_s"].get)
        entry["best_devices"] = int(peak_n)
        entry["speedup_max_vs_1"] = (
            round(entry["rows_per_s"][peak_n] / one, 3) if one else 0.0)
        fams[fam] = entry
    return {
        "host_cores": cores,
        "definition": ("rows_per_s = input_rows / (serial_host_s + "
                       "device_region_s / N): balanced-shard critical "
                       "path, the host-mesh proxy for an N-device ICI "
                       "mesh (the host timeshares its N virtual devices "
                       "onto the physical cores, so rows_per_s_wall "
                       "cannot scale with N here — PROFILE.md §14)"),
        "families": fams,
    }


def _same(a, b):
    """Byte-identity between a single-device result and a sharded one:
    matching tuple arity and exact array equality, leaf by leaf."""
    if isinstance(a, (tuple, list)):
        return len(a) == len(b) and all(_same(x, y) for x, y in zip(a, b))
    return bool(np.array_equal(np.asarray(a), np.asarray(b)))


def _sqlite_times(reps: int = 3):
    import sqlite3
    fact, dim = _data()
    db = sqlite3.connect(":memory:")
    db.execute("PRAGMA journal_mode=OFF")
    db.execute("create table opbench_fact (id integer primary key, "
               "a integer, b integer, k integer, c real)")
    db.execute("create table opbench_dim (k integer primary key, "
               "v integer)")
    db.executemany("insert into opbench_fact values (?,?,?,?,?)",
                   zip(*(fact[c].tolist()
                         for c in ("id", "a", "b", "k", "c"))))
    db.executemany("insert into opbench_dim values (?,?)",
                   zip(*(dim[c].tolist() for c in ("k", "v"))))
    out = {}
    for op, (sql, _) in OPERATORS.items():
        best, rows = float("inf"), None
        for _ in range(reps):
            t0 = time.time()
            rows = db.execute(sql).fetchall()
            best = min(best, time.time() - t0)
        out[op] = (best, [list(r) for r in rows])
    db.close()
    return out
