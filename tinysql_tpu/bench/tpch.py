"""TPC-H data generation + schema + queries (BASELINE.json configs).

Numpy-vectorized generator with TPC-H-shaped cardinalities (SF=1:
6M lineitem / 1.5M orders / 150k customer / 10k supplier / 25 nation /
5 region), loaded through the columnar bulk-ingest path
(columnar/store.py).  Dates are 'YYYY-MM-DD' strings (lexicographic
compare == date compare), matching the engine's 3-family type system
(SURVEY §0.2 — no DATE type in the reference either).

Two query sets:
- ``QUERIES``  — Q1/Q3/Q6, the long-standing perf benchmark trio; every
  historical bench section (param_reuse, spill squeeze, prewarm) keys on
  these, so their membership is stable.
- ``WORKLOAD`` — Q5/Q10/Q18, the workload-diversity trio (ROADMAP item
  5): multi-join chains, IN-subquery semijoins (decorrelation), and
  GROUP BY + ORDER BY + LIMIT compositions.  Q5 phrases the region
  restriction as an IN subquery so the planner's decorrelation ->
  device-semijoin path is exercised end-to-end; Q18 is the classic
  aggregate-subquery membership shape.
"""
from __future__ import annotations

import numpy as np

SCHEMAS = {
    "region": """create table region (
        r_regionkey bigint primary key,
        r_name varchar(12))""",
    "nation": """create table nation (
        n_nationkey bigint primary key,
        n_name varchar(25),
        n_regionkey bigint)""",
    "supplier": """create table supplier (
        s_suppkey bigint primary key,
        s_name varchar(25),
        s_nationkey bigint,
        s_acctbal double)""",
    "customer": """create table customer (
        c_custkey bigint primary key,
        c_name varchar(25),
        c_address varchar(40),
        c_phone varchar(15),
        c_mktsegment varchar(10),
        c_nationkey bigint,
        c_acctbal double,
        c_comment varchar(60))""",
    "orders": """create table orders (
        o_orderkey bigint primary key,
        o_custkey bigint,
        o_orderstatus varchar(1),
        o_totalprice double,
        o_orderdate varchar(10),
        o_shippriority bigint)""",
    "lineitem": """create table lineitem (
        l_id bigint primary key,
        l_orderkey bigint,
        l_suppkey bigint,
        l_quantity double,
        l_extendedprice double,
        l_discount double,
        l_tax double,
        l_returnflag varchar(1),
        l_linestatus varchar(1),
        l_shipdate varchar(10))""",
}

Q1 = """select l_returnflag, l_linestatus,
    sum(l_quantity) as sum_qty,
    sum(l_extendedprice) as sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
    avg(l_quantity) as avg_qty,
    avg(l_extendedprice) as avg_price,
    avg(l_discount) as avg_disc,
    count(*) as count_order
from lineitem
where l_shipdate <= '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus"""

Q3 = """select l_orderkey,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING'
  and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < '1995-03-15'
  and l_shipdate > '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10"""

Q5 = """select n_name,
    sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation
where c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey
  and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey
  and n_regionkey in (select r_regionkey from region
                      where r_name = 'ASIA')
  and o_orderdate >= '1994-01-01'
  and o_orderdate < '1995-01-01'
group by n_name
order by revenue desc"""

Q6 = """select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= '1994-01-01'
  and l_shipdate < '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24"""

Q10 = """select c_custkey, c_name,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    c_acctbal, n_name, c_address, c_phone, c_comment
from customer, orders, lineitem, nation
where c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate >= '1993-10-01'
  and o_orderdate < '1994-01-01'
  and l_returnflag = 'R'
  and c_nationkey = n_nationkey
group by c_custkey, c_name, c_acctbal, c_phone, n_name, c_address,
    c_comment
order by revenue desc
limit 20"""

Q18 = """select c_name, c_custkey, o_orderkey, o_orderdate,
    o_totalprice, sum(l_quantity) as sum_qty
from customer, orders, lineitem
where o_orderkey in (select l_orderkey from lineitem
                     group by l_orderkey
                     having sum(l_quantity) > 300)
  and c_custkey = o_custkey
  and o_orderkey = l_orderkey
group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
order by o_totalprice desc, o_orderdate
limit 100"""

QUERIES = {"Q1": Q1, "Q3": Q3, "Q6": Q6}
WORKLOAD = {"Q5": Q5, "Q10": Q10, "Q18": Q18}
ALL_QUERIES = {**QUERIES, **WORKLOAD}

_SEGMENTS = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE",
                      "MACHINERY", "HOUSEHOLD"])
_EPOCH = np.datetime64("1992-01-01")

# TPC-H specification nation/region fixed tables
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

_COMMENT_WORDS = np.array(["furiously", "carefully", "quickly", "slyly",
                           "blithely", "even", "final", "ironic",
                           "pending", "regular", "express", "bold"])


def _dates(rng, n, lo_days=0, hi_days=2405):
    days = rng.integers(lo_days, hi_days, n)
    return (_EPOCH + days.astype("timedelta64[D]")).astype("datetime64[D]").astype(str)


def _tagged_names(tag: str, ids: np.ndarray) -> np.ndarray:
    """'Customer#000000007'-style names, vectorized."""
    return np.char.add(tag + "#", np.char.zfill(ids.astype(str), 9))


def generate(sf: float = 1.0, seed: int = 7):
    """Returns {table: {col: ndarray}} at scale factor sf (column order
    per table matches the CREATE TABLE column order — the sqlite
    baseline inserts positionally)."""
    rng = np.random.default_rng(seed)
    n_cust = int(150_000 * sf)
    n_ord = int(1_500_000 * sf)
    n_supp = max(int(10_000 * sf), 10)
    n_li_avg = 4  # ~6M lineitems at SF=1

    region = {
        "r_regionkey": np.arange(len(_REGIONS), dtype=np.int64),
        "r_name": np.array(_REGIONS),
    }
    nation = {
        "n_nationkey": np.arange(len(_NATIONS), dtype=np.int64),
        "n_name": np.array([n for n, _ in _NATIONS]),
        "n_regionkey": np.array([r for _, r in _NATIONS], dtype=np.int64),
    }
    supp_ids = np.arange(1, n_supp + 1, dtype=np.int64)
    supplier = {
        "s_suppkey": supp_ids,
        "s_name": _tagged_names("Supplier", supp_ids),
        "s_nationkey": rng.integers(0, len(_NATIONS),
                                    n_supp).astype(np.int64),
        "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_supp), 2),
    }

    cust_ids = np.arange(1, n_cust + 1, dtype=np.int64)
    c_nationkey = rng.integers(0, len(_NATIONS), n_cust).astype(np.int64)
    customer = {
        "c_custkey": cust_ids,
        "c_name": _tagged_names("Customer", cust_ids),
        "c_address": np.char.add(
            "addr-", rng.integers(0, 10 ** 9, n_cust).astype(str)),
        "c_phone": np.char.add(
            np.char.add((c_nationkey + 10).astype(str), "-"),
            rng.integers(100_0000, 999_9999, n_cust).astype(str)),
        "c_mktsegment": _SEGMENTS[rng.integers(0, len(_SEGMENTS), n_cust)],
        "c_nationkey": c_nationkey,
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2),
        "c_comment": np.char.add(
            np.char.add(
                _COMMENT_WORDS[rng.integers(0, len(_COMMENT_WORDS),
                                            n_cust)], " "),
            _COMMENT_WORDS[rng.integers(0, len(_COMMENT_WORDS), n_cust)]),
    }

    o_orderdate = _dates(rng, n_ord)
    orders = {
        "o_orderkey": np.arange(1, n_ord + 1, dtype=np.int64),
        "o_custkey": rng.integers(1, n_cust + 1, n_ord).astype(np.int64),
        "o_orderstatus": np.array(["O", "F", "P"])[rng.integers(0, 3, n_ord)],
        "o_totalprice": np.round(rng.uniform(800.0, 500_000.0, n_ord), 2),
        "o_orderdate": o_orderdate,
        "o_shippriority": np.zeros(n_ord, dtype=np.int64),
    }

    per_order = rng.integers(1, 2 * n_li_avg, n_ord)
    l_orderkey = np.repeat(orders["o_orderkey"], per_order)
    n_li = len(l_orderkey)
    ship_delay = rng.integers(1, 122, n_li).astype("timedelta64[D]")
    base_date = np.repeat(o_orderdate, per_order).astype("datetime64[D]")
    l_shipdate = (base_date + ship_delay).astype(str)
    lineitem = {
        "l_id": np.arange(1, n_li + 1, dtype=np.int64),
        "l_orderkey": l_orderkey,
        "l_suppkey": rng.integers(1, n_supp + 1, n_li).astype(np.int64),
        "l_quantity": rng.integers(1, 51, n_li).astype(np.float64),
        "l_extendedprice": np.round(rng.uniform(900.0, 105_000.0, n_li), 2),
        "l_discount": np.round(rng.integers(0, 11, n_li) * 0.01, 2),
        "l_tax": np.round(rng.integers(0, 9, n_li) * 0.01, 2),
        "l_returnflag": np.array(["A", "N", "R"])[rng.integers(0, 3, n_li)],
        "l_linestatus": np.array(["O", "F"])[rng.integers(0, 2, n_li)],
        "l_shipdate": l_shipdate,
    }
    return {"region": region, "nation": nation, "supplier": supplier,
            "customer": customer, "orders": orders, "lineitem": lineitem}


def sqlite_mirror(data):
    """In-memory sqlite3 oracle over the SAME generated data (bigint ->
    integer, double -> real, positional insert in CREATE column order).
    One definition shared by tests/test_workload.py and
    tools/workload_smoke.py so 'matches sqlite' means one thing."""
    import sqlite3
    db = sqlite3.connect(":memory:")
    for name, ddl in SCHEMAS.items():
        db.execute(ddl.replace("bigint", "integer")
                   .replace("double", "real"))
        cols = list(data[name].keys())
        ph = ", ".join("?" * len(cols))
        db.executemany(f"insert into {name} values ({ph})",
                       zip(*(data[name][c].tolist() for c in cols)))
    return db


def canon_rows(rows):
    """Engine-vs-sqlite comparable form: floats canonicalized to 9
    significant digits (covers float64 noise and -0.0), NULL tagged
    unambiguously, everything else stringified.  Row ORDER is kept —
    the workload queries all have deterministic ORDER BY.  This is the
    STRICT equality tests and the CI smoke share; bench.py deliberately
    keeps its looser `_rows_match` (sorted, 1e-6 relative) for ALL its
    sections because real-TPU reductions reorder float sums beyond 9
    significant digits at SF>=0.1."""
    out = []
    for r in rows:
        key = []
        for v in r:
            if v is None:
                key.append("\x00NULL")
            elif isinstance(v, (int, float)):
                f = float(v)
                key.append(f"{0.0 if f == 0 else f:.9g}")
            else:
                key.append(str(v))
        out.append(tuple(key))
    return out


def load(session, sf: float = 1.0, seed: int = 7, data=None) -> dict:
    """Create schemas + columnar bulk-load (returns row counts).  Pass a
    pre-generated `data` dict to avoid regenerating (bench shares one
    dataset between this engine and the sqlite baseline)."""
    from ..columnar.store import bulk_load
    if data is None:
        data = generate(sf, seed)
    session.execute("create database if not exists tpch")
    session.execute("use tpch")
    counts = {}
    for name, ddl in SCHEMAS.items():
        session.execute(f"drop table if exists {name}")
        session.execute(ddl)
        info = session.infoschema().table_by_name("tpch", name)
        counts[name] = bulk_load(session.storage, info, data[name])
    return counts
