"""TPC-H data generation + schema + Q1/Q3/Q6 (BASELINE.json configs).

Numpy-vectorized generator with TPC-H-shaped cardinalities (SF=1:
6M lineitem / 1.5M orders / 150k customer), loaded through the columnar
bulk-ingest path (columnar/store.py).  Dates are 'YYYY-MM-DD' strings
(lexicographic compare == date compare), matching the engine's 3-family
type system (SURVEY §0.2 — no DATE type in the reference either).
"""
from __future__ import annotations

import numpy as np

SCHEMAS = {
    "customer": """create table customer (
        c_custkey bigint primary key,
        c_mktsegment varchar(10),
        c_nationkey bigint,
        c_acctbal double)""",
    "orders": """create table orders (
        o_orderkey bigint primary key,
        o_custkey bigint,
        o_orderstatus varchar(1),
        o_totalprice double,
        o_orderdate varchar(10),
        o_shippriority bigint)""",
    "lineitem": """create table lineitem (
        l_id bigint primary key,
        l_orderkey bigint,
        l_quantity double,
        l_extendedprice double,
        l_discount double,
        l_tax double,
        l_returnflag varchar(1),
        l_linestatus varchar(1),
        l_shipdate varchar(10))""",
}

Q1 = """select l_returnflag, l_linestatus,
    sum(l_quantity) as sum_qty,
    sum(l_extendedprice) as sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
    avg(l_quantity) as avg_qty,
    avg(l_extendedprice) as avg_price,
    avg(l_discount) as avg_disc,
    count(*) as count_order
from lineitem
where l_shipdate <= '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus"""

Q3 = """select l_orderkey,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING'
  and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < '1995-03-15'
  and l_shipdate > '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10"""

Q6 = """select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= '1994-01-01'
  and l_shipdate < '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24"""

QUERIES = {"Q1": Q1, "Q3": Q3, "Q6": Q6}

_SEGMENTS = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE",
                      "MACHINERY", "HOUSEHOLD"])
_EPOCH = np.datetime64("1992-01-01")


def _dates(rng, n, lo_days=0, hi_days=2405):
    days = rng.integers(lo_days, hi_days, n)
    return (_EPOCH + days.astype("timedelta64[D]")).astype("datetime64[D]").astype(str)


def generate(sf: float = 1.0, seed: int = 7):
    """Returns {table: {col: ndarray}} at scale factor sf."""
    rng = np.random.default_rng(seed)
    n_cust = int(150_000 * sf)
    n_ord = int(1_500_000 * sf)
    n_li_avg = 4  # ~6M lineitems at SF=1

    customer = {
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_mktsegment": _SEGMENTS[rng.integers(0, len(_SEGMENTS), n_cust)],
        "c_nationkey": rng.integers(0, 25, n_cust).astype(np.int64),
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2),
    }

    o_orderdate = _dates(rng, n_ord)
    orders = {
        "o_orderkey": np.arange(1, n_ord + 1, dtype=np.int64),
        "o_custkey": rng.integers(1, n_cust + 1, n_ord).astype(np.int64),
        "o_orderstatus": np.array(["O", "F", "P"])[rng.integers(0, 3, n_ord)],
        "o_totalprice": np.round(rng.uniform(800.0, 500_000.0, n_ord), 2),
        "o_orderdate": o_orderdate,
        "o_shippriority": np.zeros(n_ord, dtype=np.int64),
    }

    per_order = rng.integers(1, 2 * n_li_avg, n_ord)
    l_orderkey = np.repeat(orders["o_orderkey"], per_order)
    n_li = len(l_orderkey)
    ship_delay = rng.integers(1, 122, n_li).astype("timedelta64[D]")
    base_date = np.repeat(o_orderdate, per_order).astype("datetime64[D]")
    l_shipdate = (base_date + ship_delay).astype(str)
    lineitem = {
        "l_id": np.arange(1, n_li + 1, dtype=np.int64),
        "l_orderkey": l_orderkey,
        "l_quantity": rng.integers(1, 51, n_li).astype(np.float64),
        "l_extendedprice": np.round(rng.uniform(900.0, 105_000.0, n_li), 2),
        "l_discount": np.round(rng.integers(0, 11, n_li) * 0.01, 2),
        "l_tax": np.round(rng.integers(0, 9, n_li) * 0.01, 2),
        "l_returnflag": np.array(["A", "N", "R"])[rng.integers(0, 3, n_li)],
        "l_linestatus": np.array(["O", "F"])[rng.integers(0, 2, n_li)],
        "l_shipdate": l_shipdate,
    }
    return {"customer": customer, "orders": orders, "lineitem": lineitem}


def load(session, sf: float = 1.0, seed: int = 7, data=None) -> dict:
    """Create schemas + columnar bulk-load (returns row counts).  Pass a
    pre-generated `data` dict to avoid regenerating (bench shares one
    dataset between this engine and the sqlite baseline)."""
    from ..columnar.store import bulk_load
    if data is None:
        data = generate(sf, seed)
    session.execute("create database if not exists tpch")
    session.execute("use tpch")
    counts = {}
    for name, ddl in SCHEMAS.items():
        session.execute(f"drop table if exists {name}")
        session.execute(ddl)
        info = session.infoschema().table_by_name("tpch", name)
        counts[name] = bulk_load(session.storage, info, data[name])
    return counts
