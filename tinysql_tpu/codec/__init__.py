"""Key/row codecs (reference: util/codec, tablecodec, util/rowcodec)."""
from . import keycodec, tablecodec, rowcodec

__all__ = ["keycodec", "tablecodec", "rowcodec"]
