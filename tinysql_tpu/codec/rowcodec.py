"""Row value codec: {columnID: datum} <-> bytes.

Capability parity with reference util/rowcodec (v2 row format: column-id
directory + typed payloads, decoded straight into chunk columns —
rowcodec/decoder.go:355).  Layout:

  [u8 version=2][u16 ncols] then per column (sorted by id):
  [varint colID][u8 tag][payload]
  tag: 0=NULL, 1=int64(le), 2=float64(le), 3=str(u32 len + utf8)
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

from ..mytypes import Datum, FieldType, EvalType

_VERSION = 2
TAG_NULL, TAG_INT, TAG_REAL, TAG_STR = 0, 1, 2, 3


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    shift = v = 0
    while True:
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not (b & 0x80):
            return v, pos
        shift += 7


def encode_row(values: Dict[int, Datum]) -> bytes:
    out = bytearray()
    out.append(_VERSION)
    out += struct.pack("<H", len(values))
    for col_id in sorted(values):
        v = values[col_id]
        _write_varint(out, col_id)
        if v is None:
            out.append(TAG_NULL)
        elif isinstance(v, bool):
            out.append(TAG_INT)
            out += struct.pack("<q", int(v))
        elif isinstance(v, int):
            # two's-complement wrap into int64, matching Column.append
            u = v & ((1 << 64) - 1)
            out.append(TAG_INT)
            out += struct.pack("<q", u - (1 << 64) if u >= (1 << 63) else u)
        elif isinstance(v, float):
            out.append(TAG_REAL)
            out += struct.pack("<d", v)
        elif isinstance(v, str):
            raw = v.encode("utf-8")
            out.append(TAG_STR)
            out += struct.pack("<I", len(raw))
            out += raw
        else:
            raise TypeError(f"cannot row-encode {v!r}")
    return bytes(out)


def decode_row(buf: bytes) -> Dict[int, Datum]:
    if not buf:
        return {}
    if buf[0] != _VERSION:
        raise ValueError(f"bad row version {buf[0]}")
    (ncols,) = struct.unpack_from("<H", buf, 1)
    pos = 3
    out: Dict[int, Datum] = {}
    for _ in range(ncols):
        col_id, pos = _read_varint(buf, pos)
        tag = buf[pos]
        pos += 1
        if tag == TAG_NULL:
            out[col_id] = None
        elif tag == TAG_INT:
            (v,) = struct.unpack_from("<q", buf, pos)
            pos += 8
            out[col_id] = v
        elif tag == TAG_REAL:
            (v,) = struct.unpack_from("<d", buf, pos)
            pos += 8
            out[col_id] = v
        elif tag == TAG_STR:
            (n,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            out[col_id] = buf[pos:pos + n].decode("utf-8")
            pos += n
        else:
            raise ValueError(f"bad row tag {tag}")
    return out


def decode_row_to_datums(buf: bytes, col_ids: Sequence[int],
                         fts: Sequence[FieldType],
                         defaults: Optional[Sequence[Datum]] = None) -> List[Datum]:
    """Decode selected columns in order, filling defaults for absent ids —
    the chunk-decoder fast path (reference: rowcodec/decoder.go:355)."""
    m = decode_row(buf)
    out: List[Datum] = []
    for i, cid in enumerate(col_ids):
        if cid in m:
            v = m[cid]
            if v is not None and fts[i].eval_type is EvalType.INT and fts[i].is_unsigned and v < 0:
                v += 1 << 64
            out.append(v)
        else:
            out.append(defaults[i] if defaults else None)
    return out
