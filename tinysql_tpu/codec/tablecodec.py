"""Table/index key layout.

Capability parity with reference tablecodec/tablecodec.go:34-150 (including
the course-stub bodies :74 EncodeRowKeyWithHandle and :97 DecodeRecordKey,
implemented for real here):

  record key:  t{tableID}_r{handle}
  index key:   t{tableID}_i{indexID}{encoded values...}

tableID / indexID / handle are 8-byte memcomparable signed ints so ranges
over a table/index are contiguous in the keyspace.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..mytypes import Datum
from . import keycodec
from .keycodec import encode_i64_raw as _enc_i64, decode_i64_raw as _dec_i64

TABLE_PREFIX = b"t"
RECORD_PREFIX_SEP = b"_r"
INDEX_PREFIX_SEP = b"_i"


def encode_table_prefix(table_id: int) -> bytes:
    return TABLE_PREFIX + _enc_i64(table_id)


def encode_record_prefix(table_id: int) -> bytes:
    return encode_table_prefix(table_id) + RECORD_PREFIX_SEP


def encode_row_key(table_id: int, handle: int) -> bytes:
    """reference: tablecodec.go:74 (course stub) — t{tid}_r{handle}."""
    return encode_record_prefix(table_id) + _enc_i64(handle)


def encode_row_keys_batch(table_id: int, handles) -> list:
    """Batch-encode record keys for a handle array — the native
    memcomparable batch codec when available, python otherwise (hot in
    IndexLookUp stage 2: one key per handle per batch)."""
    import numpy as np
    from .. import native
    prefix = encode_record_prefix(table_id)
    h = np.asarray(handles, dtype=np.int64)
    enc = native.mc_encode_column(h, "int")
    if enc is not None:
        # skip the flag byte: record keys embed the raw big-endian payload
        return [prefix + enc[i, 1:].tobytes() for i in range(len(h))]
    return [encode_row_key(table_id, int(v)) for v in h]


def decode_record_key(key: bytes) -> Tuple[int, int]:
    """reference: tablecodec.go:97 (course stub) — inverse of encode_row_key."""
    if len(key) != 19 or key[:1] != TABLE_PREFIX or key[9:11] != RECORD_PREFIX_SEP:
        raise ValueError(f"invalid record key {key!r}")
    return _dec_i64(key[1:9]), _dec_i64(key[11:19])


def encode_index_prefix(table_id: int, index_id: int) -> bytes:
    return encode_table_prefix(table_id) + INDEX_PREFIX_SEP + _enc_i64(index_id)


def encode_index_key(table_id: int, index_id: int, values: Sequence[Datum],
                     handle: Optional[int] = None,
                     unsigned_flags: Optional[Sequence[bool]] = None) -> bytes:
    """Index key; for non-unique indexes the handle is appended to the key to
    disambiguate duplicates (reference: tables/index.go:103)."""
    key = encode_index_prefix(table_id, index_id) + keycodec.encode_key(values, unsigned_flags)
    if handle is not None:
        out = bytearray()
        keycodec.encode_int(out, handle)
        key += bytes(out)
    return key


def decode_index_key(key: bytes) -> Tuple[int, int, List[Datum]]:
    if key[:1] != TABLE_PREFIX or key[9:11] != INDEX_PREFIX_SEP:
        raise ValueError(f"invalid index key {key!r}")
    table_id = _dec_i64(key[1:9])
    index_id = _dec_i64(key[11:19])
    values = keycodec.decode_key(key[19:])
    return table_id, index_id, values


def is_record_key(key: bytes) -> bool:
    return len(key) >= 11 and key[:1] == TABLE_PREFIX and key[9:11] == RECORD_PREFIX_SEP


def is_index_key(key: bytes) -> bool:
    return len(key) >= 11 and key[:1] == TABLE_PREFIX and key[9:11] == INDEX_PREFIX_SEP


def decode_table_id(key: bytes) -> int:
    if key[:1] != TABLE_PREFIX or len(key) < 9:
        raise ValueError(f"invalid table key {key!r}")
    return _dec_i64(key[1:9])


def record_range(table_id: int) -> Tuple[bytes, bytes]:
    """[start, end) covering all records of a table."""
    p = encode_record_prefix(table_id)
    return p, p + b"\xff" * 9


def index_range(table_id: int, index_id: int) -> Tuple[bytes, bytes]:
    p = encode_index_prefix(table_id, index_id)
    return p, p + b"\xff" * 200
