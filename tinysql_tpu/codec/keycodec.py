"""Order-preserving (memcomparable) scalar key codec.

Capability parity with reference util/codec/codec.go:746 + number.go +
bytes.go: encoded byte strings compare (memcmp) in the same order as the
source datums, with NULL sorting first.  This is the foundation of every KV
key in the system (tablecodec, index keys, ranges).
"""
from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

from ..mytypes import Datum

NIL_FLAG = 0x00
BYTES_FLAG = 0x01
INT_FLAG = 0x03
UINT_FLAG = 0x04
FLOAT_FLAG = 0x05
MAX_FLAG = 0xFA

_SIGN_MASK = 0x8000000000000000
_GROUP = 8
_PAD = 0x00
_MARKER = 0xFF


def encode_i64_raw(v: int) -> bytes:
    """Flagless memcomparable int64 (shared with tablecodec key layout)."""
    return struct.pack(">Q", (v & 0xFFFFFFFFFFFFFFFF) ^ _SIGN_MASK)


def decode_i64_raw(b: bytes) -> int:
    (u,) = struct.unpack(">Q", b)
    u ^= _SIGN_MASK
    return u - (1 << 64) if u >= (1 << 63) else u


def encode_int(out: bytearray, v: int) -> None:
    out.append(INT_FLAG)
    out += encode_i64_raw(v)


def encode_uint(out: bytearray, v: int) -> None:
    out.append(UINT_FLAG)
    out += struct.pack(">Q", v & 0xFFFFFFFFFFFFFFFF)


def encode_float(out: bytearray, f: float) -> None:
    out.append(FLOAT_FLAG)
    if f == 0.0:
        f = 0.0  # normalize -0.0: SQL equality must give one key
    (u,) = struct.unpack(">Q", struct.pack(">d", f))
    if u & _SIGN_MASK:
        u ^= 0xFFFFFFFFFFFFFFFF
    else:
        u ^= _SIGN_MASK
    out += struct.pack(">Q", u)


def encode_bytes(out: bytearray, data: bytes) -> None:
    """8-byte-group escape encoding (reference: util/codec/bytes.go
    EncodeBytes): pad each group to 8 with 0x00 and append a marker byte
    0xFF - pad_count; full groups get marker 0xFF."""
    out.append(BYTES_FLAG)
    i = 0
    n = len(data)
    while True:
        group = data[i:i + _GROUP]
        pad = _GROUP - len(group)
        out += group
        out += bytes([_PAD]) * pad
        out.append(_MARKER - pad)
        i += _GROUP
        if pad > 0:
            break
        if i == n:
            # length is a multiple of 8: emit an all-pad trailing group
            out += bytes([_PAD]) * _GROUP
            out.append(_MARKER - _GROUP)
            break


def decode_bytes(buf: bytes, pos: int) -> Tuple[bytes, int]:
    data = bytearray()
    while True:
        if pos + _GROUP + 1 > len(buf):
            raise ValueError("truncated bytes encoding")
        group = buf[pos:pos + _GROUP]
        marker = buf[pos + _GROUP]
        pos += _GROUP + 1
        pad = _MARKER - marker
        if pad == 0:
            data += group
        elif 0 < pad <= _GROUP:
            if any(group[_GROUP - pad:]):
                # native mc_decode_bytes rejects non-zero padding; corrupt
                # keys must decode identically with or without the library
                raise ValueError("corrupt bytes encoding: non-zero padding")
            data += group[:_GROUP - pad]
            break
        else:
            raise ValueError(f"corrupt bytes-encoding marker {marker:#x}")
    return bytes(data), pos


def encode_datum(out: bytearray, v: Datum, unsigned: bool = False) -> None:
    if v is None:
        out.append(NIL_FLAG)
    elif isinstance(v, bool):
        encode_int(out, int(v))
    elif isinstance(v, int):
        if unsigned:
            encode_uint(out, v)
        else:
            encode_int(out, v)
    elif isinstance(v, float):
        encode_float(out, v)
    elif isinstance(v, str):
        encode_bytes(out, v.encode("utf-8", "surrogateescape"))
    elif isinstance(v, bytes):
        encode_bytes(out, v)
    else:
        raise TypeError(f"cannot key-encode {v!r}")


def encode_key(values: Sequence[Datum], unsigned_flags: Optional[Sequence[bool]] = None) -> bytes:
    out = bytearray()
    for i, v in enumerate(values):
        encode_datum(out, v, unsigned_flags[i] if unsigned_flags else False)
    return bytes(out)


def decode_one(buf: bytes, pos: int) -> Tuple[Datum, int]:
    if pos >= len(buf):
        raise ValueError("empty key buffer")
    flag = buf[pos]
    pos += 1
    if flag in (INT_FLAG, UINT_FLAG, FLOAT_FLAG) and pos + 8 > len(buf):
        raise ValueError("truncated key buffer")
    if flag == NIL_FLAG:
        return None, pos
    if flag == INT_FLAG:
        (u,) = struct.unpack_from(">Q", buf, pos)
        u ^= _SIGN_MASK
        v = u - (1 << 64) if u >= (1 << 63) else u
        return v, pos + 8
    if flag == UINT_FLAG:
        (u,) = struct.unpack_from(">Q", buf, pos)
        return u, pos + 8
    if flag == FLOAT_FLAG:
        (u,) = struct.unpack_from(">Q", buf, pos)
        if u & _SIGN_MASK:
            u ^= _SIGN_MASK
        else:
            u ^= 0xFFFFFFFFFFFFFFFF
        (f,) = struct.unpack(">d", struct.pack(">Q", u))
        return f, pos + 8
    if flag == BYTES_FLAG:
        b, pos = decode_bytes(buf, pos)
        # deterministic type: BYTES always decodes to str; surrogateescape
        # makes arbitrary binary round-trip losslessly through the str form
        return b.decode("utf-8", "surrogateescape"), pos
    raise ValueError(f"bad codec flag {flag:#x} at {pos - 1}")


def decode_key(buf: bytes) -> List[Datum]:
    out: List[Datum] = []
    pos = 0
    while pos < len(buf):
        v, pos = decode_one(buf, pos)
        out.append(v)
    return out
