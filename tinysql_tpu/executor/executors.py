"""Volcano executors over chunks (CPU path).

Capability parity with reference executor/: Executor iface Open/Next/Close
(executor.go:146-152), SelectionExec :346 (vectorized filter — the course
stub :396 implemented for real), TableReader (table_reader.go),
HashJoinExec (join.go — build :149 / probe :244 stubs implemented),
HashAggExec (aggregate.go — shuffle :355 / consume :425 stubs implemented),
SortExec/TopNExec (sort.go), ProjectionExec, LimitExec, TableDualExec.
The numpy-vectorized inner loops are the CPU fallback tier; the TPU tier
(executor/tpu_executors.py per-operator kernels, executor/devpipe.py
whole-subtree device pipelines) swaps in behind the same interface.
"""
from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import fail
from ..catalog.table import Table
from ..utils import interrupt
from ..chunk import Chunk, MAX_CHUNK_SIZE
from ..expression import Schema, vectorized_filter
from ..mytypes import EvalType, sort_key
from ..planner.builder import HANDLE_COL_NAME
from ..planner.physical import (PhysicalHashAgg, PhysicalHashJoin,
                                PhysicalIndexLookUpReader,
                                PhysicalIndexReader, PhysicalLimit,
                                PhysicalMergeJoin, PhysicalPlan,
                                PhysicalProjection, PhysicalSelection,
                                PhysicalSort, PhysicalTableDual,
                                PhysicalTableReader, PhysicalTopN)
from .aggfuncs import new_state


class ExecContext:
    """Per-statement execution context (reference: sessionctx threading)."""

    def __init__(self, txn, session_vars=None, infoschema=None, storage=None):
        self.txn = txn
        self.session_vars = session_vars or {}
        self.infoschema = infoschema
        self.storage = storage

    @property
    def max_chunk_size(self) -> int:
        return int(self.session_vars.get("tidb_max_chunk_size", MAX_CHUNK_SIZE))


class Executor:
    def __init__(self, schema: Schema, children: List["Executor"]):
        self.schema = schema
        self.children = children

    def field_types(self):
        return self.schema.field_types()

    def open(self, ctx: ExecContext) -> None:
        self.ctx = ctx
        for c in self.children:
            c.open(ctx)

    def next(self) -> Optional[Chunk]:
        raise NotImplementedError

    def close(self) -> None:
        for c in self.children:
            c.close()

    def drain(self) -> List[list]:
        rows = []
        while True:
            # THE root block boundary: statement kill and the
            # max_execution_time deadline land between blocks here (the
            # all-consuming operators below add their own inner checks);
            # execSlowNext lets chaos tests stretch any statement
            interrupt.check()
            fail.inject("execSlowNext")
            chk = self.next()
            if chk is None:
                break
            rows.extend(chk.to_rows())
        return rows


class TableReaderExec(Executor):
    """Direct scan via the txn (reference: table_reader.go); the distsql
    layer's coprocessor readers supersede this on the distributed path."""

    def __init__(self, plan: PhysicalTableReader):
        super().__init__(plan.schema, [])
        self.scan = plan.scan
        self._iter = None

    FAST_CHUNK = 1 << 16  # columnar-replica slice size

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        info = self.scan.table_info
        self._tbl = Table(info)
        # decode set: the real columns in schema order (handle -> None)
        self._decode_cols = []
        for c in self.scan.schema.columns:
            if c.name == HANDLE_COL_NAME:
                self._decode_cols.append(None)
            else:
                ci = info.find_column(c.name)
                assert ci is not None, f"column {c.name} missing in {info.name}"
                self._decode_cols.append(ci)
        self._real_cols = [ci for ci in self._decode_cols if ci is not None]
        self._replica = None
        self._pos = 0
        self._iter = None
        self._cop = None
        self._cop_rest = None  # (batch, cursor) of a partially-emitted batch
        self._local_agg = None
        self._hydrate = None
        dirty = (ctx.txn is not None and ctx.storage is not None
                 and self._txn_dirty(ctx.txn, info.id))
        self._range_sel = None
        # columnar replica fast path (columnar/store.py) — full scans only;
        # ranged scans seek the row store directly
        if ctx.storage is not None and self.scan.ranges is None:
            from ..columnar.store import replica_for_read
            rep = replica_for_read(ctx.storage, ctx.txn, info.id)
            if rep is not None and all(ci.id in rep.columns
                                       for ci in self._real_cols):
                self._replica = rep
                if self.scan.pushed_agg is not None:
                    self._local_agg = True  # partial agg over replica chunks
                return
        # ranged (pk-predicate) scans over a replica-backed table: the
        # bulk loader writes ONLY the replica, so seeking the row store
        # would return nothing (the PR 9 "l_id predicates return 0 rows"
        # bug) — serve the handle ranges from the replica instead.
        # Pushed aggregates ride the local partial-agg pass over the
        # gathered rows; pushed topn/limit are pre-cut hints the root
        # operators reapply, so serving them uncut stays correct.
        if ctx.storage is not None and self.scan.ranges is not None \
                and not dirty:
            from ..columnar.store import replica_for_read
            rep = replica_for_read(ctx.storage, ctx.txn, info.id)
            if rep is not None and all(ci.id in rep.columns
                                       for ci in self._real_cols):
                self._replica = rep
                self._range_sel = self._handle_range_positions(rep)
                if self.scan.pushed_agg is not None:
                    self._local_agg = True
                return
        if self.scan.pushed_agg is not None:
            # partial-agg reads: coprocessor path; a dirty txn falls back to
            # a local partial agg over the union-store scan (the UnionScan
            # analogue — own buffered writes must stay visible)
            if ctx.storage is not None and not dirty:
                self._cop = self._cop_select()
            else:
                self._iter = self._scan_iter(ctx.txn)
                self._local_agg = True
            return
        has_pushdown = (self.scan.filters or self.scan.ranges is not None
                        or self.scan.pushed_topn is not None
                        or self.scan.pushed_limit is not None)
        if ctx.storage is not None and not dirty and has_pushdown:
            # region scatter-gather with filter/topn/limit pushdown
            self._cop = self._cop_select()
            return
        self._iter = self._scan_iter(ctx.txn)
        if (ctx.storage is not None and not dirty
                and self.scan.ranges is None and self._real_cols):
            # pure full scan: hydrate the columnar replica as a side effect
            self._hydrate = {"handles": [], "rows": []}

    @staticmethod
    def _txn_dirty(txn, table_id: int) -> bool:
        from ..columnar.store import _txn_touches_table
        return _txn_touches_table(txn, table_id)

    def _scan_iter(self, txn):
        if self.scan.ranges is not None:
            return self._iter_ranges(txn)
        return self._tbl.iter_records(txn, cols=self._real_cols)

    def _cop_select(self):
        """Build the DAG request + key ranges and start the scatter-gather
        (reference: distsql.Select via RequestBuilder)."""
        from ..codec import tablecodec
        from ..distsql import DAGRequest, ScanInfo, select
        from ..distsql.exprpb import _ft_to_pb, exprs_to_pb
        info = self.scan.table_info
        pk = info.get_pk_handle_col()
        scan_info = ScanInfo(
            table_id=info.id,
            col_ids=[ci.id if ci is not None else -1
                     for ci in self._decode_cols],
            col_fts=[_ft_to_pb(c.ret_type)
                     for c in self.scan.schema.columns],
            col_defaults=[ci.default if ci is not None else None
                          for ci in self._decode_cols],
            handle_slots=[i for i, ci in enumerate(self._decode_cols)
                          if ci is None],
            pk_id=pk.id if pk is not None else None,
        )
        filters_pb = exprs_to_pb(self.scan.filters) if self.scan.filters \
            else None
        self._cop_filters_pushed = not self.scan.filters \
            or filters_pb is not None
        # topn/limit may only pre-cut AFTER all filters ran cop-side
        pre_cut_ok = self._cop_filters_pushed
        req = DAGRequest(
            start_ts=self.ctx.txn.start_ts,
            scan=scan_info,
            filters=filters_pb,
            agg=self.scan.pushed_agg,
            topn=self.scan.pushed_topn if pre_cut_ok else None,
            limit=self.scan.pushed_limit if pre_cut_ok else None,
        )
        if self.scan.ranges is not None:
            ranges = []
            for lo, hi in self.scan.ranges:
                ranges.append((tablecodec.encode_row_key(info.id, lo),
                               tablecodec.encode_row_key(info.id, hi)
                               + b"\x00"))
        else:
            ranges = [tablecodec.record_range(info.id)]
        conc = int(self.ctx.session_vars.get(
            "tidb_distsql_scan_concurrency", 15))
        return select(self.ctx.storage, req, ranges, conc)

    def _iter_ranges(self, txn):
        """Seek each [lo, hi] handle range directly (reference:
        distsql/request_builder.go handle-range table reads)."""
        from ..codec import tablecodec
        for lo, hi in self.scan.ranges:
            start = tablecodec.encode_row_key(self.scan.table_info.id, lo)
            end = tablecodec.encode_row_key(self.scan.table_info.id, hi) + b"\x00"
            for k, v in txn.iter_range(start, end):
                _, handle = tablecodec.decode_record_key(k)
                yield handle, self._tbl.decode_row(v, handle,
                                                   self._real_cols)

    def next(self) -> Optional[Chunk]:
        if self._cop is not None:
            return self._next_cop()
        if self._local_agg:
            return self._next_local_agg()
        if self._replica is not None:
            return self._apply_filters_or_none(self._next_fast_raw())
        return self._next_scan()

    def _apply_filters_or_none(self, chk):
        return None if chk is None else self._apply_filters(chk)

    def _next_cop(self) -> Optional[Chunk]:
        # one cop task returns a whole region's batch; emit it in
        # tidb_max_chunk_size slices so root drain-block boundaries
        # (kill / deadline checks, processlist progress) stay fine-
        # grained on large scans.  The leftover rides an integer cursor
        # (one slice copy per chunk, no quadratic re-slicing).  Pushed-
        # agg batches are tiny partial results and pass through whole.
        limit = max(self.ctx.max_chunk_size, 1)
        while True:
            if self._cop_rest is not None:
                rest, pos = self._cop_rest
                batch = rest[pos:pos + limit]
                pos += limit
                self._cop_rest = (rest, pos) if pos < len(rest) else None
            else:
                batch = next(self._cop, None)
                if batch is None:
                    self._cop = iter(())
                    return None
                if not batch:
                    continue
                if len(batch) > limit and self.scan.pushed_agg is None:
                    self._cop_rest = (batch, limit)
                    batch = batch[:limit]
            chk = Chunk(self.field_types(), cap=len(batch))
            for row in batch:
                chk.append_row(row)
            if (not self._cop_filters_pushed
                    and self.scan.pushed_agg is None):
                chk = self._apply_filters(chk)
                if chk.num_rows() == 0:
                    continue
            return chk

    def _next_local_agg(self) -> Optional[Chunk]:
        """Local partial aggregation over raw chunks — from the columnar
        replica or (dirty txn) the union-store scan.  Each slice gets one
        columnar pass (factorize + bincount straight into a Chunk, no
        per-row marshalling); per-slice partial groups merge at the root
        FINAL agg, which is also vectorized."""
        from ..distsql.copr import partial_agg_chunk
        limit = max(self.ctx.max_chunk_size, 4096)
        scan_fts = [c.ret_type for c in self.scan.schema.columns]
        while True:
            if self._replica is not None:
                raw = self._next_fast_raw()
                if raw is None:
                    return None
            else:
                if self._iter is None:
                    return None
                raw = Chunk(scan_fts, cap=limit)
                if self._fill_from_iter(raw, limit) == 0:
                    self._iter = None
                    return None
            if self.scan.filters:
                mask = self._filter_mask(raw, self.scan.filters)
                raw.set_sel(np.nonzero(mask)[0])
                raw = raw.compact()
            out = partial_agg_chunk(self.scan.pushed_agg, raw,
                                    self.field_types())
            if out is None or out.num_rows() == 0:
                continue
            return out

    def _handle_range_positions(self, rep) -> np.ndarray:
        """Replica row positions whose handle falls in the scan's
        [lo, hi] handle ranges (inclusive, like _iter_ranges).  Sorted
        handle arrays (the bulk-load/hydrate norm) binary-search; the
        general case falls back to boolean masking."""
        handles = rep.handles
        sorted_ = rep.memo(("handles_sorted",),
                           lambda: bool(len(handles) < 2
                                        or np.all(np.diff(handles) > 0)))
        parts = []
        for lo, hi in self.scan.ranges:
            if sorted_:
                a = int(np.searchsorted(handles, lo, side="left"))
                b = int(np.searchsorted(handles, hi, side="right"))
                parts.append(np.arange(a, b, dtype=np.int64))
            else:
                parts.append(np.nonzero((handles >= lo)
                                        & (handles <= hi))[0])
        if not parts:
            return np.empty(0, dtype=np.int64)
        return np.concatenate(parts)

    def take_raw_replica(self):
        """Hand the WHOLE replica to the caller as a zero-copy chunk view
        plus this scan's filters and the replica object (for device-side
        memoization), consuming the reader (fused device pipelines own the
        replica contract through this single method).
        Returns (chunk, filters, replica) or (None, None, None)."""
        rep = self._replica
        if rep is None or self.scan.pushed_agg is not None \
                or self._range_sel is not None:
            return None, None, None
        from ..chunk import Column as CCol
        cols = []
        for c, ci in zip(self.scan.schema.columns, self._decode_cols):
            if ci is None:
                cols.append(CCol.wrap_raw(c.ret_type, rep.handles))
            else:
                v, m = rep.columns[ci.id]
                cols.append(CCol.wrap_raw(c.ret_type, v, m))
        self._replica = None  # consumed: this reader must not re-serve
        return Chunk.from_columns(cols), list(self.scan.filters), rep

    def _next_fast_raw(self) -> Optional[Chunk]:
        """Next unfiltered slice of the columnar replica.  The slice is
        capped by tidb_max_chunk_size: drain-block boundaries are where
        statement kill / deadline checks land and where processlist
        observes progress, so one monolithic slice would make a large
        scan uninterruptible and invisible."""
        rep = self._replica
        sel = self._range_sel
        n_total = len(sel) if sel is not None else rep.n_rows
        if self._pos >= n_total:
            self._slice_range = None
            return None
        step = min(self.FAST_CHUNK, max(self.ctx.max_chunk_size, 1))
        lo, hi = self._pos, min(self._pos + step, n_total)
        self._pos = hi
        from ..chunk import Column as CCol
        cols = []
        if sel is not None:
            # ranged serve: gather the in-range rows (fancy-index copy —
            # pk ranges are small); string-code fast filters don't apply
            # (_slice_range stays None -> vectorized_filter)
            self._slice_range = None
            idx = sel[lo:hi]
            for c, ci in zip(self.scan.schema.columns, self._decode_cols):
                if ci is None:
                    cols.append(CCol.wrap_raw(c.ret_type,
                                              rep.handles[idx]))
                else:
                    v, m = rep.columns[ci.id]
                    cols.append(CCol.wrap_raw(c.ret_type, v[idx], m[idx]))
            return Chunk.from_columns(cols)
        self._slice_range = (lo, hi)
        for c, ci in zip(self.scan.schema.columns, self._decode_cols):
            if ci is None:
                cols.append(CCol.wrap_raw(c.ret_type, rep.handles[lo:hi]))
            else:
                v, m = rep.columns[ci.id]
                # zero-copy views: keeps <U string dtype so filters
                # compare in C (from_numpy would object-convert per batch)
                cols.append(CCol.wrap_raw(c.ret_type, v[lo:hi], m[lo:hi]))
        return Chunk.from_columns(cols)

    def _fill_from_iter(self, chk: Chunk, limit: int) -> int:
        """Drain up to `limit` (handle, row) pairs from the scan iterator
        into `chk`, interleaving the handle into its schema slots."""
        n = 0
        for handle, row in self._iter:
            vals = []
            it = iter(row)
            for ci in self._decode_cols:
                vals.append(handle if ci is None else next(it))
            chk.append_row(vals)
            if self._hydrate is not None:
                self._hydrate["handles"].append(handle)
                self._hydrate["rows"].append(row)
            n += 1
            if n >= limit:
                break
        return n

    def _next_scan(self) -> Optional[Chunk]:
        if self._iter is None:
            return None
        limit = self.ctx.max_chunk_size
        chk = Chunk(self.field_types(), cap=limit)
        if self._fill_from_iter(chk, limit) == 0:
            self._iter = None
            self._finish_hydrate()
            return None
        return self._apply_filters(chk)

    def _apply_filters(self, chk: Chunk) -> Chunk:
        if self.scan.filters:
            mask = self._filter_mask(chk, self.scan.filters)
            chk.set_sel(np.nonzero(mask)[0])
            chk = chk.compact()
        return chk

    def _filter_mask(self, chk: Chunk, conds) -> np.ndarray:
        """Filter mask over a replica slice or plain chunk.  On the
        replica path, `string Column <op> string Constant` conditions run
        as int compares over the replica's memoized dictionary codes
        (order-preserving; the SAME memo the TPU tier's _code_cmp uses) —
        the CPU analogue of the reference's storage-side selection."""
        rng = getattr(self, "_slice_range", None)
        rep = self._replica
        if rep is None or rng is None:
            return vectorized_filter(conds, chk)
        from .tpu_executors import (_code_cmp, _parse_string_cmp, _slot_id,
                                    rep_string_codes)
        lo_r, hi_r = rng
        mask = None
        residual = []
        for cond in conds:
            sc = _parse_string_cmp(chk, cond)
            if sc is None:
                residual.append(cond)
                continue
            col, op, val = sc
            sid = _slot_id(self, col.index)
            v, nl = rep.columns[sid]
            codes, card, _, uniques = rep_string_codes(rep, sid, v, nl)
            klo = int(np.searchsorted(uniques, val, side="left"))
            khi = int(np.searchsorted(uniques, val, side="right"))
            m = _code_cmp(np, op, codes[lo_r:hi_r], klo, khi, card)
            mask = m if mask is None else (mask & m)
        if residual:
            m = vectorized_filter(residual, chk)
            mask = m if mask is None else (mask & m)
        return mask

    def _finish_hydrate(self) -> None:
        """A completed full scan hydrates the columnar replica so the next
        analytical query skips row decode entirely."""
        h = self._hydrate
        self._hydrate = None
        if h is None:
            return
        from ..columnar.store import hydrate_from_scan
        handles = np.asarray(h["handles"], dtype=np.int64)
        arrays = {}
        for j, ci in enumerate(self._real_cols):
            vals = [r[j] for r in h["rows"]]
            null = np.array([v is None for v in vals], dtype=bool)
            et = ci.ft.eval_type
            if et is EvalType.STRING:
                arr = np.array(["" if v is None else v for v in vals],
                               dtype=str)  # fixed-width <U: C-speed filters
            else:
                dt = np.int64 if et is EvalType.INT else np.float64
                if et is EvalType.INT:
                    # unsigned values wrap two's-complement into the int64
                    # buffer, same as Column.append
                    vals = [0 if v is None else
                            (v - (1 << 64) if v >= (1 << 63) else v)
                            for v in vals]
                else:
                    vals = [0 if v is None else v for v in vals]
                arr = np.array(vals, dtype=dt)
            arrays[ci.id] = (arr, null)
        hydrate_from_scan(self.ctx.storage, self.ctx.txn,
                          self.scan.table_info, [c.id for c in self._real_cols],
                          arrays, handles)

    def close(self) -> None:
        self._iter = None
        self._cop = None
        self._cop_rest = None
        self._hydrate = None
        super().close()


def _iter_index_entries(txn, iscan):
    """Yield (index_values, handle) over the scan's ranges in index order
    (reference: tables/index.go Seek + distsql index-range reads)."""
    from ..codec import keycodec, tablecodec
    from ..planner.ranger import MAX, MIN
    info = iscan.table_info
    idx = iscan.index
    prefix = tablecodec.encode_index_prefix(info.id, idx.id)
    uns = []
    for ic in idx.columns:
        ci = info.find_column(ic.name)
        uns.append(bool(ci is not None and ci.ft.is_unsigned))
    n_cols = len(idx.columns)

    def enc(vals):
        return keycodec.encode_key(list(vals), uns[:len(vals)])

    for r in iscan.ranges:
        low = list(r.low)
        if low and low[-1] is MIN:
            # open lower bound from a comparison: NULL never satisfies it,
            # and NULL sorts first — start just past the null point
            lo_key = prefix + enc(low[:-1]) + bytes([keycodec.NIL_FLAG + 1])
        elif low:
            lo_key = prefix + enc(low) + (b"" if r.low_incl else b"\xff")
        else:
            lo_key = prefix
        high = list(r.high)
        if high and high[-1] is MAX:
            hi_key = prefix + enc(high[:-1]) + b"\xff"
        elif high:
            hi_key = prefix + enc(high) + (b"\xff" if r.high_incl else b"")
        else:
            hi_key = prefix + b"\xff"
        for k, v in txn.iter_range(lo_key, hi_key):
            vals = keycodec.decode_key(k[len(prefix):])
            if len(vals) > n_cols:  # handle rides in the key (non-unique
                handle = int(vals[n_cols])  # or unique-with-nulls)
                vals = vals[:n_cols]
            else:
                handle = int(v)  # unique index: handle in the value
            yield vals, handle


class IndexReaderExec(Executor):
    """Covering index scan: answers straight from index entries
    (reference: executor/distsql.go IndexReaderExecutor :166)."""

    def __init__(self, plan):
        super().__init__(plan.schema, [])
        self.iscan = plan.scan

    def open(self, ctx):
        super().open(ctx)
        self._iter = _iter_index_entries(ctx.txn, self.iscan)

    def next(self) -> Optional[Chunk]:
        if self._iter is None:
            return None
        limit = self.ctx.max_chunk_size
        chk = Chunk(self.field_types(), cap=limit)
        n = 0
        for vals, handle in self._iter:
            row = []
            for src in self.iscan.output_sources:
                row.append(handle if src[0] == "handle" else vals[src[1]])
            chk.append_row(row)
            n += 1
            if n >= limit:
                break
        if n == 0:
            self._iter = None
            return None
        if self.iscan.filters:
            mask = vectorized_filter(self.iscan.filters, chk)
            chk.set_sel(np.nonzero(mask)[0])
            chk = chk.compact()
        return chk

    def close(self) -> None:
        self._iter = None
        super().close()


class IndexLookUpExec(Executor):
    """Double read: stage 1 walks the index collecting handles, stage 2
    fetches rows by handle with `tidb_index_lookup_concurrency` workers,
    preserving index order (reference: IndexLookUpExecutor's index worker ->
    table workers pipeline, executor/distsql.go:237-370)."""

    def __init__(self, plan):
        super().__init__(plan.schema, [])
        self.iscan = plan.index_scan
        self.tscan = plan.table_scan

    def open(self, ctx):
        super().open(ctx)
        info = self.tscan.table_info
        self._tbl = Table(info)
        self._decode_cols = []
        for c in self.tscan.schema.columns:
            if c.name == HANDLE_COL_NAME:
                self._decode_cols.append(None)
            else:
                self._decode_cols.append(info.find_column(c.name))
        self._real_cols = [ci for ci in self._decode_cols if ci is not None]
        self._entries = _iter_index_entries(ctx.txn, self.iscan)
        self._pool = None

    def _fetch_batch(self, handles):
        """Stage 2: point-read `handles` concurrently, results in index
        order (reference table workers; 4 by default).  Row keys are
        batch-encoded (native memcomparable codec when available)."""
        from ..codec import tablecodec
        txn = self.ctx.txn
        workers = int(self.ctx.session_vars.get(
            "tidb_index_lookup_concurrency", 4))
        rows: List[Optional[list]] = [None] * len(handles)
        keys = tablecodec.encode_row_keys_batch(
            self.tscan.table_info.id, handles)

        def fetch(span):
            for j in range(*span):
                v = txn.get(keys[j])
                rows[j] = self._tbl.decode_row(v, handles[j],
                                               self._real_cols)
        if workers <= 1 or len(handles) < 64:
            fetch((0, len(handles)))
        else:
            if self._pool is None:
                import concurrent.futures as cf
                self._pool = cf.ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="kv-lookup")
            step = (len(handles) + workers - 1) // workers
            spans = [(i, min(i + step, len(handles)))
                     for i in range(0, len(handles), step)]
            list(self._pool.map(fetch, spans))
        return rows

    def next(self) -> Optional[Chunk]:
        if self._entries is None:
            return None
        limit = self.ctx.max_chunk_size
        handles = []
        for _, handle in self._entries:
            handles.append(handle)
            if len(handles) >= limit:
                break
        if not handles:
            self._entries = None
            return None
        rows = self._fetch_batch(handles)
        chk = Chunk(self.field_types(), cap=len(handles))
        for h, row in zip(handles, rows):
            vals = []
            it = iter(row)
            for ci in self._decode_cols:
                vals.append(h if ci is None else next(it))
            chk.append_row(vals)
        if self.tscan.filters:
            mask = vectorized_filter(self.tscan.filters, chk)
            chk.set_sel(np.nonzero(mask)[0])
            chk = chk.compact()
        return chk

    def close(self) -> None:
        self._entries = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        super().close()


class MemTableExec(Executor):
    """INFORMATION_SCHEMA virtual tables computed from the live schema
    (reference: infoschema/tables.go)."""

    def __init__(self, plan):
        super().__init__(plan.schema, [])
        self.table = plan.table
        self._done = False

    def open(self, ctx):
        super().open(ctx)
        self._done = False

    def next(self) -> Optional[Chunk]:
        if self._done:
            return None
        self._done = True
        from ..catalog.memtables import memtable_rows
        rows = memtable_rows(self.ctx.infoschema, self.table)
        chk = Chunk(self.field_types(), cap=max(len(rows), 1))
        for r in rows:
            chk.append_row(r)
        return chk


class SelectionExec(Executor):
    """Vectorized filter with sel-vector semantics (reference:
    executor.go:346-420; the course's stub :396)."""

    def __init__(self, plan: PhysicalSelection, child: Executor):
        super().__init__(plan.schema, [child])
        self.conditions = plan.conditions

    def next(self) -> Optional[Chunk]:
        while True:
            chk = self.children[0].next()
            if chk is None:
                return None
            chk = chk.compact()
            mask = vectorized_filter(self.conditions, chk)
            if not mask.any():
                continue
            chk.set_sel(np.nonzero(mask)[0])
            return chk.compact()


class ProjectionExec(Executor):
    """Vectorized projection (reference: projection.go — vectorized by
    construction here; the goroutine pipeline maps to device parallelism)."""

    def __init__(self, plan: PhysicalProjection, child: Executor):
        super().__init__(plan.schema, [child])
        self.exprs = plan.exprs

    def next(self) -> Optional[Chunk]:
        chk = self.children[0].next()
        if chk is None:
            return None
        chk = chk.compact()
        from ..chunk import Column as CCol
        cols = []
        for e, out_c in zip(self.exprs, self.schema.columns):
            v, null = e.vec_eval(chk)
            cols.append(CCol.from_numpy(out_c.ret_type, v, null))
        return Chunk.from_columns(cols) if cols else chk


class HashAggExec(Executor):
    """Hash aggregation (reference: aggregate.go two-stage parallel hash agg;
    single-threaded CPU tier here — the parallel partial/final split runs on
    the TPU/distributed tier via the same AggState partial protocol)."""

    def __init__(self, plan: PhysicalHashAgg, child: Executor):
        super().__init__(plan.schema, [child])
        self.plan = plan
        self._done = False

    def open(self, ctx):
        super().open(ctx)
        self._done = False

    def _vec_gate(self) -> bool:
        """All-numpy aggregation path: COMPLETE-mode, non-distinct
        count/sum/avg/min/max/first_row.  Accumulation order matches the
        row loop bit-for-bit (bincount adds in row order), so results are
        identical, just without the per-row Python."""
        from ..expression.aggregation import (AGG_AVG, AGG_COUNT,
                                              AGG_FIRST_ROW, AGG_MAX,
                                              AGG_MIN, AGG_SUM)
        ok = {AGG_COUNT, AGG_SUM, AGG_AVG, AGG_MIN, AGG_MAX, AGG_FIRST_ROW}
        for d in self.plan.aggs:
            # FINAL merges are vectorizable too: count/sum merge = add,
            # avg merges (sum, count) partial columns, min/max/first_row
            # merge = update
            if d.distinct:
                return False
            if d.name not in ok:
                return False
            if d.name in (AGG_MIN, AGG_MAX):
                a = d.args[0]
                # string / wrapped-unsigned compare orders need the
                # row-path semantics
                if a.eval_type is EvalType.STRING or _uns_of(a):
                    return False
        return True

    def _vec_agg(self) -> Optional[Chunk]:
        from ..chunk import Column as CCol
        from ..expression.aggregation import (AGG_AVG, AGG_COUNT,
                                              AGG_FIRST_ROW, AGG_MAX,
                                              AGG_MIN, AGG_SUM)
        plan = self.plan
        child = self.children[0]
        chunks = []
        while True:
            interrupt.check()
            chk = child.next()
            if chk is None:
                break
            chk = chk.compact()
            if chk.num_rows():
                chunks.append(chk)
        total = sum(c.num_rows() for c in chunks)
        if total == 0:
            if plan.group_by:
                return None
            # COUNT()=0 / SUM()=NULL single row over empty input
            states = [new_state(d) for d in plan.aggs]
            out = Chunk(self.field_types(), cap=1)
            out.append_row([states[i].result() if src == "agg" else None
                            for src, i in plan.output_map])
            return out

        def cat(expr):
            vs, ns = [], []
            for c in chunks:
                v, nl = expr.vec_eval(c)
                vs.append(np.asarray(v))
                ns.append(np.asarray(nl))
            return np.concatenate(vs), np.concatenate(ns)

        # ---- group ids: factorize each key column, combine, relabel in
        # first-occurrence order (matches the dict path's insertion order)
        kdata = [cat(e) for e in plan.group_by]
        gid = np.zeros(total, dtype=np.int64)
        for v, nl in kdata:
            if v.dtype == object or v.dtype.kind == "U":
                sv = np.where(nl, "", v).astype(str)
            else:
                sv = np.where(nl, v[0], v)
            _, inv = np.unique(sv, return_inverse=True)
            inv = inv.astype(np.int64)
            card = int(inv.max()) + 1
            code = np.where(nl, card, inv)
            _, gid = np.unique(gid * (card + 1) + code,
                               return_inverse=True)
            gid = gid.astype(np.int64)
        ug, first_idx, inv2 = np.unique(gid, return_index=True,
                                        return_inverse=True)
        order = np.argsort(first_idx, kind="stable")
        relabel = np.empty(len(ug), dtype=np.int64)
        relabel[order] = np.arange(len(ug), dtype=np.int64)
        gid = relabel[inv2.astype(np.int64)]
        first_idx = first_idx[order]
        ng = len(ug)

        def to_real(v, uns):
            fv = v.astype(np.float64)
            if uns and v.dtype == np.int64:
                fv = np.where(v < 0, fv + 2.0**64, fv)
            return fv

        from ..expression.aggregation import AggMode
        out_aggs = []
        for d in plan.aggs:
            name = d.name
            final = d.mode is AggMode.FINAL
            if name == AGG_COUNT:
                if final:
                    # merge: sum the partial counts (None partials skip)
                    v, nl = cat(d.args[0])
                    m = ~nl
                    acc = np.zeros(ng, dtype=np.int64)
                    np.add.at(acc, gid[m], v[m].astype(np.int64))
                    out_aggs.append((acc, np.zeros(ng, dtype=bool)))
                    continue
                m = np.ones(total, dtype=bool)
                for a in d.args:
                    _, nl = cat(a)
                    m &= ~nl
                cnt = np.bincount(gid[m], minlength=ng).astype(np.int64)
                out_aggs.append((cnt, np.zeros(ng, dtype=bool)))
            elif name == AGG_AVG and final:
                # FINAL avg over (sum, count) partial columns
                sm, snl = cat(d.args[0])
                cn, cnl = cat(d.args[1])
                m = ~cnl & (cn != 0)
                n_acc = np.zeros(ng, dtype=np.int64)
                np.add.at(n_acc, gid[m], cn[m].astype(np.int64))
                w = np.where(snl, 0.0, to_real(sm, False))
                s = np.bincount(gid[m], weights=w[m], minlength=ng)
                out_aggs.append((s / np.maximum(n_acc, 1), n_acc == 0))
            elif name in (AGG_SUM, AGG_AVG):
                # sum merge (FINAL) = sum update: one shared path
                a = d.args[0]
                v, nl = cat(a)
                m = ~nl
                cnt = np.bincount(gid[m], minlength=ng).astype(np.int64)
                if name == AGG_SUM \
                        and d.ret_type.eval_type is EvalType.INT:
                    acc = np.zeros(ng, dtype=np.int64)
                    np.add.at(acc, gid[m], v[m].astype(np.int64))
                    out_aggs.append((acc, cnt == 0))
                else:
                    s = np.bincount(gid[m], weights=to_real(v, _uns_of(a))[m],
                                    minlength=ng)
                    if name == AGG_AVG:
                        s = s / np.maximum(cnt, 1)
                    out_aggs.append((s, cnt == 0))
            elif name in (AGG_MIN, AGG_MAX):
                a = d.args[0]
                v, nl = cat(a)
                m = ~nl
                g2, v2 = gid[m], v[m]
                res = np.zeros(ng, dtype=v.dtype)
                rnull = np.ones(ng, dtype=bool)
                if len(g2):
                    o = np.argsort(g2, kind="stable")
                    g2s, v2s = g2[o], v2[o]
                    starts = np.nonzero(
                        np.r_[True, g2s[1:] != g2s[:-1]])[0]
                    red = (np.maximum if name == AGG_MAX
                           else np.minimum).reduceat(v2s, starts)
                    present = g2s[starts]
                    res[present] = red
                    rnull[present] = False
                out_aggs.append((res, rnull))
            else:  # AGG_FIRST_ROW
                v, nl = cat(d.args[0])
                out_aggs.append((v[first_idx], nl[first_idx]))

        out_cols = []
        for (src, idx), oc in zip(plan.output_map, self.schema.columns):
            if src == "agg":
                v, nl = out_aggs[idx]
            else:
                v, nl = kdata[idx][0][first_idx], kdata[idx][1][first_idx]
            out_cols.append(CCol.from_numpy(oc.ret_type, v, nl))
        return Chunk.from_columns(out_cols)

    def next(self) -> Optional[Chunk]:
        if self._done:
            return None
        self._done = True
        plan = self.plan
        if self._vec_gate():
            return self._vec_agg()
        groups: Dict[tuple, list] = {}
        gb_vals: Dict[tuple, list] = {}
        child = self.children[0]
        while True:
            interrupt.check()
            chk = child.next()
            if chk is None:
                break
            chk = chk.compact()
            n = chk.num_rows()
            # vectorized group key computation (unsigned ints live wrapped
            # in the int64 buffers — unwrap to semantic python values here)
            key_cols = [(*e.vec_eval(chk), _uns_of(e))
                        for e in plan.group_by]
            # agg arg values, vectorized
            arg_cols = []
            for d in plan.aggs:
                arg_cols.append([(*a.vec_eval(chk), _uns_of(a))
                                 for a in d.args])
            for i in range(n):
                key = tuple(_semantic(v, null, i, u)
                            for v, null, u in key_cols)
                st = groups.get(key)
                if st is None:
                    st = groups[key] = [new_state(d) for d in plan.aggs]
                    gb_vals[key] = list(key)
                for d_idx, d in enumerate(plan.aggs):
                    vals = [_semantic(v, null, i, u)
                            for v, null, u in arg_cols[d_idx]]
                    st[d_idx].update(vals)
        if not groups and not plan.group_by:
            # empty input, no GROUP BY: one row (COUNT()=0, SUM()=NULL)
            groups[()] = [new_state(d) for d in plan.aggs]
            gb_vals[()] = []
        out = Chunk(self.field_types(), cap=max(len(groups), 1))
        for key, states in groups.items():
            row = []
            for src, idx in plan.output_map:
                if src == "agg":
                    row.append(states[idx].result())
                else:
                    row.append(gb_vals[key][idx])
            out.append_row(row)
        return out if out.num_rows() else None


class HashJoinExec(Executor):
    """Hash join: build + probe (reference: join.go:31-350, course stubs
    :149/:244 implemented).  Build side = right child."""

    def __init__(self, plan: PhysicalHashJoin, left: Executor, right: Executor):
        super().__init__(plan.schema, [left, right])
        self.plan = plan
        self._built = False

    def open(self, ctx):
        super().open(ctx)
        self._built = False
        self._probe_buf = None

    def _native_fast_ok(self) -> bool:
        """Single int64 equi-key with matching signedness: the native
        open-addressing table (util/mvmap analogue) builds and probes on
        raw key buffers."""
        plan = self.plan
        if len(plan.left_keys) != 1:
            return False
        lk, rk = plan.left_keys[0], plan.right_keys[0]
        if lk.eval_type is not EvalType.INT or rk.eval_type is not EvalType.INT:
            return False
        return _uns_of(lk) == _uns_of(rk)

    def _build(self) -> None:
        from .. import native
        plan = self.plan
        build = self.children[1]
        self._build_rows: List[list] = []
        self._table: Dict[tuple, List[int]] = {}
        self._ht = None
        self._build_chunk: Optional[Chunk] = None
        use_native = self._native_fast_ok() and native.lib() is not None
        # fully-columnar path: native table + no per-row residual conds
        # means build AND probe stay vectorized end to end
        self._vec_ok = use_native and not plan.other_conditions \
            and plan.tp not in ("semi", "anti")
        if self._vec_ok:
            self._build_chunk = Chunk(
                [c.ret_type for c in self.children[1].schema.columns])
        # NOT IN null semantics need the build side's shape beyond the
        # hash table: total live rows (NULL keys included) and whether
        # any live row carried a NULL key
        self._build_n_live = 0
        self._build_has_null_key = False
        nat_keys: List[np.ndarray] = []
        while True:
            interrupt.check()
            chk = build.next()
            if chk is None:
                break
            chk = chk.compact()
            if plan.right_conditions:
                mask = vectorized_filter(plan.right_conditions, chk)
                chk.set_sel(np.nonzero(mask)[0])
                chk = chk.compact()
            self._build_n_live += chk.num_rows()
            if use_native:
                v, null = plan.right_keys[0].vec_eval(chk)
                self._build_has_null_key |= bool(np.asarray(null).any())
                keep = np.nonzero(~null)[0]  # NULL keys never equi-match
                nat_keys.append(np.asarray(v, dtype=np.int64)[keep])
                if self._vec_ok:
                    for dst, src in zip(self._build_chunk.columns,
                                        chk.columns):
                        dst.extend_take(src, keep)
                elif plan.tp in ("semi", "anti") \
                        and not plan.other_conditions:
                    pass  # membership probe reads only the hash table
                else:
                    for i in keep:
                        self._build_rows.append(chk.get_row(int(i)))
                continue
            keys = [(*e.vec_eval(chk), _uns_of(e)) for e in plan.right_keys]
            for i in range(chk.num_rows()):
                row = chk.get_row(i)
                key = tuple(_semantic(v, null, i, u) for v, null, u in keys)
                if any(k is None for k in key):
                    self._build_has_null_key = True
                    continue  # NULL never equi-matches
                idx = len(self._build_rows)
                self._build_rows.append(row)
                self._table.setdefault(key, []).append(idx)
        if use_native:
            bk = (np.concatenate(nat_keys) if nat_keys
                  else np.empty(0, dtype=np.int64))
            self._ht = native.I64HashTable(bk)
        self._n_right = len(self.children[1].schema.columns)
        self._built = True

    def next(self) -> Optional[Chunk]:
        if not self._built:
            self._build()
        plan = self.plan
        left = self.children[0]
        if plan.tp in ("semi", "anti"):
            return self._next_semi(left, plan)
        if self._ht is not None and self._vec_ok:
            return self._next_vec(left, plan)
        out_limit = self.ctx.max_chunk_size
        out = Chunk(self.field_types(), cap=out_limit)
        while True:
            chk = left.next()
            if chk is None:
                break
            chk = chk.compact()
            lmask = None
            if plan.left_conditions:
                mask = vectorized_filter(plan.left_conditions, chk)
                if plan.tp == "left":
                    # outer join: ON-clause left conds decide matching —
                    # a failing outer row null-extends instead of dropping
                    lmask = mask
                else:
                    chk.set_sel(np.nonzero(mask)[0])
                    chk = chk.compact()
            if self._ht is not None:
                v, null = plan.left_keys[0].vec_eval(chk)
                ids, counts = self._ht.probe(
                    np.asarray(v, dtype=np.int64), ~null)
                offsets = np.concatenate(([0], np.cumsum(counts)))
            else:
                keys = [(*e.vec_eval(chk), _uns_of(e))
                        for e in plan.left_keys]
            for i in range(chk.num_rows()):
                lrow = chk.get_row(i)
                if lmask is not None and not lmask[i]:
                    out.append_row(lrow + [None] * self._n_right)
                    continue
                if self._ht is not None:
                    matches = ids[offsets[i]:offsets[i + 1]]
                else:
                    key = tuple(_semantic(v, null, i, u)
                                for v, null, u in keys)
                    matches = [] if any(k is None for k in key) \
                        else self._table.get(key, [])
                matched = False
                for bi in matches:
                    joined = lrow + self._build_rows[bi]
                    if plan.other_conditions and not self._others_ok(joined):
                        continue
                    matched = True
                    out.append_row(joined)
                if not matched and plan.tp == "left":
                    out.append_row(lrow + [None] * self._n_right)
            if out.num_rows() >= out_limit:
                return out
        return out if out.num_rows() else None

    def _next_semi(self, left, plan) -> Optional[Chunk]:
        """Semi / anti join probe: emit LEFT rows only.  Covers keyed
        membership (IN / correlated EXISTS), the cartesian degenerate
        (uncorrelated EXISTS: any live build row matches every probe
        row), residual other_conditions (correlated non-equi), and the
        NULL-aware NOT IN ladder:

        - empty build side  -> anti keeps EVERY probe row (NULL too)
        - any NULL build key (null_aware) -> anti keeps NOTHING
        - NULL probe key (null_aware) -> dropped; plain anti keeps it
        """
        anti = plan.tp == "anti"
        na = anti and getattr(plan, "null_aware", False)
        out_limit = self.ctx.max_chunk_size
        out = Chunk(self.field_types(), cap=out_limit)
        while True:
            interrupt.check()
            chk = left.next()
            if chk is None:
                break
            chk = chk.compact()
            if plan.left_conditions:
                mask = vectorized_filter(plan.left_conditions, chk)
                chk.set_sel(np.nonzero(mask)[0])
                chk = chk.compact()
            n = chk.num_rows()
            if n == 0:
                continue
            if self._build_n_live == 0:
                if anti:  # NOT IN () / NOT EXISTS over empty: all pass
                    return chk
                continue
            if na and self._build_has_null_key:
                continue  # x NOT IN (..., NULL, ...) is never TRUE
            if self._ht is not None and not plan.other_conditions:
                # fully-columnar membership: probe counts -> boolean
                # keep -> one selection compact, no per-row marshalling
                v, null = plan.left_keys[0].vec_eval(chk)
                null = np.asarray(null)
                _ids, counts = self._ht.probe(
                    np.asarray(v, dtype=np.int64), ~null)
                matched = np.asarray(counts) > 0
                if anti:
                    keep = ~matched & (~null if na else
                                       np.ones(n, dtype=bool))
                else:
                    keep = matched
                sel = np.nonzero(keep)[0]
                if len(sel) == 0:
                    continue
                chk.set_sel(sel)
                return chk.compact()
            else:
                if self._ht is not None:
                    v, null = plan.left_keys[0].vec_eval(chk)
                    ids, counts = self._ht.probe(
                        np.asarray(v, dtype=np.int64), ~null)
                    offsets = np.concatenate(([0], np.cumsum(counts)))
                    nulls = np.asarray(null)
                else:
                    keys = [(*e.vec_eval(chk), _uns_of(e))
                            for e in plan.left_keys]
                for i in range(n):
                    lrow = chk.get_row(i)
                    if self._ht is not None:
                        probe_null = bool(nulls[i])
                        matches = ids[offsets[i]:offsets[i + 1]]
                    else:
                        key = tuple(_semantic(v, null, i, u)
                                    for v, null, u in keys)
                        probe_null = any(k is None for k in key)
                        matches = [] if probe_null \
                            else self._table.get(key, [])
                    hit = False
                    for bi in matches:
                        if plan.other_conditions and not self._others_ok(
                                lrow + self._build_rows[bi]):
                            continue
                        hit = True
                        break
                    if na and probe_null:
                        continue  # NULL NOT IN (non-empty) is NULL
                    if hit != anti:
                        out.append_row(lrow)
            if out.num_rows() >= out_limit:
                return out
        return out if out.num_rows() else None

    def _next_vec(self, left, plan) -> Optional[Chunk]:
        """Fully vectorized probe (the hot path the reference runs in its
        probe workers, join.go:325): native hash probe gives per-row match
        ids/counts; the joined chunk assembles by columnar fancy-indexing
        — np.repeat(probe) x gather(build) — with LEFT-join null extension
        appended as a block.  No per-row Python."""
        from ..chunk import Column as CCol
        fields = self.field_types()
        bcols = self._build_chunk.columns
        outer = plan.tp == "left"
        while True:
            chk = left.next()
            if chk is None:
                return None
            chk = chk.compact()
            n = chk.num_rows()
            if n == 0:
                continue
            lmask = None
            if plan.left_conditions:
                mask = vectorized_filter(plan.left_conditions, chk)
                if outer:
                    # ON-clause left conds decide matching — a failing
                    # outer row null-extends instead of dropping
                    lmask = mask
                else:
                    chk.set_sel(np.nonzero(mask)[0])
                    chk = chk.compact()
                    n = chk.num_rows()
                    if n == 0:
                        continue
            v, null = plan.left_keys[0].vec_eval(chk)
            ids, counts = self._ht.probe(np.asarray(v, dtype=np.int64),
                                         ~null)
            ids = np.asarray(ids, dtype=np.int64)
            counts = np.asarray(counts, dtype=np.int64)
            if lmask is not None:
                ids = ids[np.repeat(lmask, counts)]
                counts = np.where(lmask, counts, 0)
            pidx = np.repeat(np.arange(n, dtype=np.int64), counts)
            un = np.nonzero(counts == 0)[0] if outer \
                else np.empty(0, dtype=np.int64)
            n_un = len(un)
            if len(pidx) == 0 and n_un == 0:
                continue
            pairs = []
            for c in chk.columns:
                vv, mm = c.values(), c.null_mask()
                if n_un:
                    pairs.append((np.concatenate([vv[pidx], vv[un]]),
                                  np.concatenate([mm[pidx], mm[un]])))
                else:
                    pairs.append((vv[pidx], mm[pidx]))
            for c in bcols:
                vv, mm = c.values(), c.null_mask()
                va, ma = vv[ids], mm[ids]
                if n_un:
                    filler = (np.full(n_un, None, dtype=object)
                              if vv.dtype == object
                              else np.zeros(n_un, dtype=vv.dtype))
                    va = np.concatenate([va, filler])
                    ma = np.concatenate([ma, np.ones(n_un, dtype=bool)])
                pairs.append((va, ma))
            return Chunk.from_columns(
                [CCol.from_numpy(ft, va, ma)
                 for ft, (va, ma) in zip(fields, pairs)])

    def _others_ok(self, joined_row) -> bool:
        return _eval_other_conds(self.plan.other_conditions, joined_row)


def _uns_of(e) -> bool:
    """INT expression whose int64 buffer holds wrapped uint64 values."""
    return (e.eval_type is EvalType.INT
            and getattr(e.ret_type, "is_unsigned", False))


def _semantic(v, null, i: int, uns: bool):
    """Buffer cell -> semantic python value (unwraps wrapped unsigned)."""
    if null[i]:
        return None
    x = v[i].item() if hasattr(v[i], "item") else v[i]
    if uns and isinstance(x, int) and x < 0:
        x += 1 << 64
    return x


def _semantic_keys(expr, chk: Chunk) -> list:
    """Join-key column of `chk` as semantic python values (shared by the
    hash and merge join key paths)."""
    v, null = expr.vec_eval(chk)
    uns = _uns_of(expr)
    return [_semantic(v, null, i, uns) for i in range(chk.num_rows())]


def _eval_other_conds(conds, joined_row) -> bool:
    from ..expression import eval_bool_scalar
    return eval_bool_scalar(conds, joined_row)


class _RowCursor:
    """Row-at-a-time cursor over an executor's chunk stream, exposing the
    join key's semantic value per row; `side_conds` filter each chunk
    before it is exposed (the join's one-side conditions)."""

    def __init__(self, ex: Executor, key_expr, side_conds=None,
                 mask_mode: bool = False):
        self.ex = ex
        self.key_expr = key_expr
        self.side_conds = side_conds or []
        # mask_mode (outer side of an outer join): failing rows stay in the
        # stream with passes()==False so the join can null-extend them
        self.mask_mode = mask_mode
        self._chk = None
        self._keys = None
        self._mask = None
        self._i = 0
        self.done = False
        self._advance_chunk()

    def _advance_chunk(self) -> None:
        while True:
            chk = self.ex.next()
            if chk is None:
                self.done = True
                return
            chk = chk.compact()
            self._mask = None
            if self.side_conds and chk.num_rows():
                mask = vectorized_filter(self.side_conds, chk)
                if self.mask_mode:
                    self._mask = mask
                else:
                    chk.set_sel(np.nonzero(mask)[0])
                    chk = chk.compact()
            if chk.num_rows() == 0:
                continue
            self._chk = chk
            self._keys = _semantic_keys(self.key_expr, chk)
            self._i = 0
            return

    def key(self):
        return self._keys[self._i]

    def passes(self) -> bool:
        return self._mask is None or bool(self._mask[self._i])

    def row(self):
        return self._chk.get_row(self._i)

    def advance(self) -> None:
        self._i += 1
        if self._i >= self._chk.num_rows():
            self._advance_chunk()


class MergeJoinExec(Executor):
    """Sorted-input merge join with inner-group buffering (reference:
    executor/merge_join.go:31 — both inputs arrive in join-key order; the
    planner only picks this operator for clustered-pk-ordered scans)."""

    def __init__(self, plan, left: Executor, right: Executor):
        super().__init__(plan.schema, [left, right])
        self.plan = plan

    def open(self, ctx):
        super().open(ctx)
        self._lcur = None
        self._done = False

    def _others_ok(self, joined_row) -> bool:
        return _eval_other_conds(self.plan.other_conditions, joined_row)

    def next(self) -> Optional[Chunk]:
        if self._done:
            return None
        plan = self.plan
        if self._lcur is None:
            self._lcur = _RowCursor(self.children[0], plan.left_keys[0],
                                    plan.left_conditions,
                                    mask_mode=(plan.tp == "left"))
            self._rcur = _RowCursor(self.children[1], plan.right_keys[0],
                                    plan.right_conditions)
            self._n_right = len(self.children[1].schema.columns)
            self._rgroup_key = object()
            self._rgroup: List[list] = []
        out_limit = self.ctx.max_chunk_size
        out = Chunk(self.field_types(), cap=out_limit)
        lcur, rcur = self._lcur, self._rcur
        while not lcur.done and out.num_rows() < out_limit:
            lk = lcur.key()
            if not lcur.passes():
                # ON-clause outer-side cond failed: null-extend (left join)
                out.append_row(lcur.row() + [None] * self._n_right)
                lcur.advance()
                continue
            if lk is None:  # NULL keys never equi-match
                if plan.tp == "left":
                    out.append_row(lcur.row() + [None] * self._n_right)
                lcur.advance()
                continue
            # advance the buffered right group to lk
            if self._rgroup_key != lk:
                while not rcur.done and _key_lt(rcur.key(), lk):
                    rcur.advance()
                self._rgroup = []
                self._rgroup_key = lk
                while not rcur.done and rcur.key() == lk:
                    self._rgroup.append(rcur.row())
                    rcur.advance()
            matched = False
            for rrow in self._rgroup:
                joined = lcur.row() + rrow
                if plan.other_conditions and not self._others_ok(joined):
                    continue
                matched = True
                out.append_row(joined)
            if not matched and plan.tp == "left":
                out.append_row(lcur.row() + [None] * self._n_right)
            lcur.advance()
        if out.num_rows() == 0:
            self._done = True
            return None
        return out


def _key_lt(a, b) -> bool:
    """NULL sorts first (mirrors the key codec's ordering)."""
    if a is None:
        return b is not None
    if b is None:
        return False
    return a < b


def _sort_keys_for_rows(by, chk: Chunk):
    """Compute (columns of total-order keys, descending flags)."""
    cols = []
    descs = []
    for e, desc in by:
        v, null = e.vec_eval(chk)
        cols.append((v, null))
        descs.append(desc)
    return cols, descs


class SortExec(Executor):
    """Full in-memory sort (reference: sort.go:27-146, row-pointer
    indirection == argsort over key arrays)."""

    def __init__(self, plan: PhysicalSort, child: Executor):
        super().__init__(plan.schema, [child])
        self.by = plan.by
        self._out = None

    def open(self, ctx):
        super().open(ctx)
        self._out = None

    def _materialize(self):
        child = self.children[0]
        all_chk = Chunk(self.field_types(), cap=MAX_CHUNK_SIZE)
        while True:
            interrupt.check()
            chk = child.next()
            if chk is None:
                break
            all_chk.append_chunk(chk)
        n = all_chk.num_rows()
        if n == 0:
            self._out = iter([])
            return
        order = _argsort_chunk(self.by, all_chk)
        all_chk.set_sel(order)
        self._out = iter([all_chk.compact()])

    def next(self) -> Optional[Chunk]:
        if self._out is None:
            self._materialize()
        return next(self._out, None)


def _argsort_chunk(by, chk: Chunk) -> np.ndarray:
    """Stable multi-key argsort with NULLs-first MySQL semantics; numeric
    keys sort via numpy lexsort, strings via Python key sort."""
    n = chk.num_rows()
    keys = []
    any_str = False
    for e, desc in by:
        v, null = e.vec_eval(chk)
        if v.dtype == object:
            any_str = True
        elif v.dtype == np.int64 and e.ret_type.is_unsigned:
            # unsigned columns live two's-complement-wrapped in the int64
            # buffer; reinterpret so 2^64-1 sorts above 0
            v = v.view(np.uint64)
        keys.append((v, null, desc))
    if not any_str:
        # MySQL semantics: NULL sorts lowest (first in ASC, last in DESC).
        # lexsort: LAST array is most significant -> emit per-key
        # (value, null_rank) pairs walking the sort keys in reverse.
        arrs = []
        for v, null, desc in reversed(keys):
            vv = np.where(null, 0, v)  # neutralize NULL slots
            if desc:
                with np.errstate(over="ignore"):
                    if vv.dtype == np.uint64:
                        vv = np.iinfo(np.uint64).max - vv  # order-reversing
                    elif vv.dtype == np.int64:
                        vv = ~vv  # overflow-free (-v overflows at int64 min)
                    else:
                        vv = -vv
                rank = np.where(null, 1, 0).astype(np.int8)  # NULL last
            else:
                rank = np.where(null, 0, 1).astype(np.int8)  # NULL first
            arrs.append(vv)
            arrs.append(rank)
        return np.lexsort(arrs)
    # string keys: python sort
    def row_key(i):
        out = []
        for v, null, desc in keys:
            if null[i]:
                k = (0 if not desc else 2, 0)
            else:
                val = v[i]
                val = val.item() if hasattr(val, "item") else val
                sk = sort_key(val)
                if desc:
                    k = (1, _Neg(sk))
                else:
                    k = (1, sk)
            out.append(k)
        return out
    return np.array(sorted(range(n), key=row_key), dtype=np.int64)


class _Neg:
    """Reverses comparison order of a wrapped key."""
    __slots__ = ("k",)

    def __init__(self, k):
        self.k = k

    def __lt__(self, other):
        return other.k < self.k

    def __eq__(self, other):
        return self.k == other.k


class TopNExec(Executor):
    """Top-k (reference: sort.go:148-318 TopNExec heap)."""

    def __init__(self, plan: PhysicalTopN, child: Executor):
        super().__init__(plan.schema, [child])
        self.by = plan.by
        self.offset = plan.offset
        self.count = plan.count
        self._out = None

    def open(self, ctx):
        super().open(ctx)
        self._out = None

    def next(self) -> Optional[Chunk]:
        if self._out is None:
            child = self.children[0]
            all_chk = Chunk(self.field_types(), cap=MAX_CHUNK_SIZE)
            while True:
                interrupt.check()
                chk = child.next()
                if chk is None:
                    break
                all_chk.append_chunk(chk)
                # bound the buffer: keep only the current top
                # offset+count rows when it grows too large
                if all_chk.num_rows() >= 4 * max(self.offset + self.count, 256):
                    order = _argsort_chunk(self.by, all_chk)
                    all_chk.set_sel(order[: self.offset + self.count])
                    all_chk = all_chk.compact()
            if all_chk.num_rows():
                order = _argsort_chunk(self.by, all_chk)
                sel = order[self.offset: self.offset + self.count]
                all_chk.set_sel(sel)
                self._out = iter([all_chk.compact()] if len(sel) else [])
            else:
                self._out = iter([])
        return next(self._out, None)


class LimitExec(Executor):
    def __init__(self, plan: PhysicalLimit, child: Executor):
        super().__init__(plan.schema, [child])
        self.offset = plan.offset
        self.count = plan.count

    def open(self, ctx):
        super().open(ctx)
        self._skipped = 0
        self._emitted = 0

    def next(self) -> Optional[Chunk]:
        while self._emitted < self.count:
            chk = self.children[0].next()
            if chk is None:
                return None
            chk = chk.compact()
            n = chk.num_rows()
            start = 0
            if self._skipped < self.offset:
                take_skip = min(self.offset - self._skipped, n)
                self._skipped += take_skip
                start = take_skip
            avail = n - start
            if avail <= 0:
                continue
            take = min(avail, self.count - self._emitted)
            self._emitted += take
            chk.set_sel(np.arange(start, start + take))
            return chk.compact()
        return None


class TableDualExec(Executor):
    def __init__(self, plan: PhysicalTableDual):
        super().__init__(plan.schema, [])
        self.row_count = plan.row_count
        self._done = False

    def open(self, ctx):
        super().open(ctx)
        self._done = False

    def next(self) -> Optional[Chunk]:
        if self._done:
            return None
        self._done = True
        chk = Chunk(self.field_types(), cap=max(self.row_count, 1))
        if not self.schema.columns:
            chk.virtual_rows = self.row_count
        else:
            for _ in range(self.row_count):
                chk.append_row([None] * len(self.schema.columns))
        return chk


def build_executor(plan: PhysicalPlan, use_tpu: bool = False) -> Executor:
    """Physical plan -> executor tree (reference: executor/builder.go:69-117).
    With use_tpu, the big four operators come from the TPU tier when the
    plan's device enforcer marked them eligible.  Every executor is
    tagged with the plan node it was built from (``_obs_plan``) so
    obs/runtime_stats can key per-operator RuntimeStats for
    EXPLAIN ANALYZE without per-executor changes."""
    ex = _build_executor(plan, use_tpu)
    if getattr(ex, "_obs_plan", None) is None:
        ex._obs_plan = plan
    return ex


def _build_executor(plan: PhysicalPlan, use_tpu: bool = False) -> Executor:
    if use_tpu and getattr(plan, "use_tpu", False):
        from .tpu_executors import build_tpu_executor
        ex = build_tpu_executor(plan)
        if ex is not None:
            return ex
    if isinstance(plan, PhysicalTableReader):
        return TableReaderExec(plan)
    if isinstance(plan, PhysicalIndexReader):
        return IndexReaderExec(plan)
    if isinstance(plan, PhysicalIndexLookUpReader):
        return IndexLookUpExec(plan)
    from ..planner.physical import PhysicalMemTable
    if isinstance(plan, PhysicalMemTable):
        return MemTableExec(plan)
    if isinstance(plan, PhysicalSelection):
        return SelectionExec(plan, build_executor(plan.children[0], use_tpu))
    if isinstance(plan, PhysicalProjection):
        return ProjectionExec(plan, build_executor(plan.children[0], use_tpu))
    if isinstance(plan, PhysicalHashAgg):
        return HashAggExec(plan, build_executor(plan.children[0], use_tpu))
    if isinstance(plan, PhysicalMergeJoin):
        return MergeJoinExec(plan, build_executor(plan.children[0], use_tpu),
                             build_executor(plan.children[1], use_tpu))
    if isinstance(plan, PhysicalHashJoin):
        return HashJoinExec(plan, build_executor(plan.children[0], use_tpu),
                            build_executor(plan.children[1], use_tpu))
    if isinstance(plan, PhysicalSort):
        return SortExec(plan, build_executor(plan.children[0], use_tpu))
    if isinstance(plan, PhysicalTopN):
        return TopNExec(plan, build_executor(plan.children[0], use_tpu))
    if isinstance(plan, PhysicalLimit):
        return LimitExec(plan, build_executor(plan.children[0], use_tpu))
    if isinstance(plan, PhysicalTableDual):
        return TableDualExec(plan)
    raise ValueError(f"no executor for {type(plan).__name__}")
