"""ADMIN CHECK TABLE: verify record/index consistency.

Capability parity with reference util/admin (CheckIndicesCount /
ScanIndexData consistency checks used by executor admin statements).
"""
from __future__ import annotations

from ..catalog.model import SchemaState, TableInfo
from ..catalog.table import Index, Table
from ..codec import tablecodec


class AdminCheckError(Exception):
    pass


def check_table(storage, info: TableInfo) -> None:
    txn = storage.begin()
    try:
        tbl = Table(info)
        rows = {h: row for h, row in tbl.iter_records(txn)}
        for idx in tbl.indices:
            if idx.info.state != SchemaState.PUBLIC:
                continue
            lo, hi = tablecodec.index_range(info.id, idx.info.id)
            entries = list(txn.iter_range(lo, hi))
            if len(entries) != len(rows):
                raise AdminCheckError(
                    f"index '{idx.info.name}' has {len(entries)} entries, "
                    f"table has {len(rows)} rows")
            for k, v in entries:
                _, _, vals = tablecodec.decode_index_key(k)
                if idx.info.unique and v not in (b"0",):
                    handle = int(v)
                else:
                    handle = vals[-1]
                    vals = vals[:-1]
                if handle not in rows:
                    raise AdminCheckError(
                        f"index '{idx.info.name}' entry {vals!r} points to "
                        f"missing handle {handle}")
    finally:
        txn.rollback()
