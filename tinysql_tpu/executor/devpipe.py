"""Device-resident query pipelines, fused into ONE XLA program.

The TPU-first replacement for per-operator host round-trips: a supported
physical subtree (scans -> filters -> partial aggregates -> joins ->
topn/sort/limit/projection) compiles into a SINGLE jitted device program.
Intermediates never exist outside the XLA program (they are fusion
candidates for the compiler, not buffers); the only device->host transfer
of a query is the packed materialization of the final (usually tiny)
result, and for small results even the packing runs inside the same
program — one dispatch, one download.  This replaces the reference's
executor pipeline hot loops (probe loop executor/join.go:325, agg update
aggregate.go:307+) with gather/segment kernels, and its row-at-a-time
operator hand-off with masked static-shape device views.

Why fusion matters here: the device link bills ~40-70ms per program
dispatch (PROFILE.md §1); round 2 ran Q3 as five chained programs and
paid that five times.  Round 3 splits every node into host-side
``prepare`` (replica uploads, group indexes, position tables, parameter
tables — all memoized per replica version) and a pure traced ``emit``;
DevPipeExec composes the emits and jits the whole pipeline once per
(structure, shape) key.

Key design points (why this maps well onto TPU + XLA):

- **Static shapes everywhere.**  Every view is padded to a power-of-two
  bucket with a validity mask; data-dependent sizes never force a host
  sync or a recompile.  One program per (shape, structure) pair, reused
  across queries and constants (constants ride exprjit.ParamTable slots
  passed as runtime inputs).
- **Group index** (sort once per replica version, not per query): the
  high-cardinality GROUP BY path sorts the table by key ONCE, memoizes
  the order/boundaries on the replica (the clustered-index analogue of
  the reference's index access paths), and then a per-query aggregate is
  mask -> gather-to-sorted-order -> cumsum -> boundary-diff: exact for
  int64 (mod-2^64 wrap); for float64 the boundary diff folds the running
  prefix-sum's rounding into each group (error ~ eps x running total),
  bounded by the 1e-6-relative result-equality tests.  No per-query sort
  or scatter either way.
- **Join = dense position table + gather** (SURVEY §2.4: "build via
  scatter, probe via gather"): a unique build side keyed by a bounded
  int64 key becomes a dense key->row table (memoized on the replica for
  base-table keys; static per replica version for group-index keys);
  probing is one gather + validity checks.  Non-unique build sides ride
  the same group index as a CSR layout (sorted order + group boundaries,
  reference join.go:244 / util/mvmap multiplicity semantics): probe maps
  key -> group, per-group valid counts come from one cumsum, and a
  two-phase expansion (scatter row starts + running-max fill) lands the
  variable-multiplicity output in a static bucket sized by a host-side
  upper bound.
- Strings ride order-preserving dictionary codes on device (decode on
  materialize only), so string group keys, sort keys, and equality
  filters all stay on the TPU.
"""
from __future__ import annotations

import contextvars
import os
import queue
import threading
import time

from typing import Callable, Dict, List, Optional

import numpy as np

from .. import fail as _fail
from ..obs import context as _obs
from ..utils import interrupt as _interrupt

from ..chunk import Chunk, Column as CCol
from ..expression import Column as ExprColumn, Constant
from ..expression.aggregation import AGG_COUNT, AGG_SUM
from ..mytypes import EvalType
from ..ops import kernels, progcache
from ..ops.exprjit import (ParamTable, compile_expr_params, is_jittable,
                           stable_shape_key)
from ..planner.physical import (PhysicalHashAgg, PhysicalHashJoin,
                                PhysicalLimit, PhysicalMergeJoin,
                                PhysicalProjection, PhysicalSelection,
                                PhysicalSort, PhysicalTableReader,
                                PhysicalTopN)

MAX_DENSE_RANGE = 1 << 25   # dense key->pos tables up to 32M slots (128MB)
MAX_EXPAND = 1 << 23        # CSR-join output bucket cap (8M rows)

# structural node keys that have actually been compiled into some fused
# pipeline — introspection surface for tests and the multichip dryrun.
# Guarded (qlint CC7xx triage): concurrent pool workers and the prewarm
# worker both publish keys; set.update over an iterable is NOT atomic
_CNK_MU = threading.Lock()
COMPILED_NODE_KEYS: set = set()


def _note_compiled(kparts) -> None:
    with _CNK_MU:
        COMPILED_NODE_KEYS.update(kparts)


# =========================================================================
# async block pipeline: host-staging / device-compute overlap
# =========================================================================

def pipeline_depth(session_vars=None) -> int:
    """Staging-queue depth for the async block pipeline: how many staged
    blocks may be in flight ahead of the consumer (the double-buffer
    bound on transient device slots).  0 = synchronous inline staging —
    no thread, the exact sequential order, byte-identical results.
    Resolution: TINYSQL_PIPELINE_DEPTH env (tests/CI kill-switch) >
    tidb_pipeline_depth sysvar > default 2 (double-buffered)."""
    env = os.environ.get("TINYSQL_PIPELINE_DEPTH")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            return 0
    if session_vars is not None:
        try:
            return max(0, int(session_vars.get("tidb_pipeline_depth", 2)
                              or 0))
        except Exception:
            return 2
    return 2


#: end-of-stream marker on the staging queue
_PIPE_DONE = object()


class BlockPipeline:
    """Bounded-depth staging queue: ONE producer thread runs
    ``stage_fn(item)`` for each item IN ORDER — the host half of a block
    (slice, decode/encode, pad, enqueue the H2D upload) — while the
    consumer iterates the staged results in the same order and dispatches
    device compute.  With JAX's async dispatch the device runs block k's
    kernel while the stage thread prepares block k+1's uploads; the only
    sync points are each block's result materialization (the drain).

    ``depth <= 0`` degrades to synchronous inline staging with NO thread:
    the same calls in the same order, so results are byte-identical with
    the pipeline on or off (the TINYSQL_PIPELINE_DEPTH=0 contract).

    Error contract: an exception inside ``stage_fn`` is captured, the
    producer stops, and the exception re-raises ON THE CALLER at the
    point the failed block would have been consumed — blocks staged
    before it still deliver.  Abandoning the iterator (break / caller
    exception) cancels the producer and joins the thread; ``close()`` is
    idempotent.  Host syncs inside ``stage_fn`` defeat the overlap —
    qlint TS106 flags them statically."""

    def __init__(self, stage_fn: Callable, items, depth: int = 2):
        self._stage = stage_fn
        self._items = list(items)
        self._sync = depth <= 0
        self._mu = threading.Lock()
        self._stage_s = 0.0
        self._hwm = 0
        self._cancel = threading.Event()
        self._q = None
        self._thread = None
        if not self._sync:
            self._q = queue.Queue(maxsize=max(1, depth))
            # the producer runs inside a COPY of the creator's context:
            # the active QueryObs scope, current-operator attribution,
            # and span parent all carry across the thread boundary, so
            # stage spans/counters land on the query (and operator) that
            # built the pipeline (obs/context.py)
            cctx = contextvars.copy_context()
            # "devpipe-stage" is the conprof role vocabulary
            # (obs/conprof.ROLE_PREFIXES): the producer classifies as
            # role `devpipe` in continuous_profiling / race-stress /
            # py-spy output
            self._thread = threading.Thread(
                target=cctx.run, args=(self._run,),
                name="devpipe-stage", daemon=True)
            self._thread.start()

    def _stage_timed(self, item):
        t0 = time.time()
        # both run inside the creator's copied context: a statement kill
        # or deadline stops the producer between blocks, and the staging
        # failpoint exercises the error-delivery contract below
        _interrupt.check()
        _fail.inject("devpipeStageError")
        with _obs.span("stage", cat="pipeline"):
            out = self._stage(item)
        dt = time.time() - t0
        with self._mu:
            self._stage_s += dt
        return out

    # ---- producer -------------------------------------------------------
    def _run(self) -> None:
        try:
            for item in self._items:
                if self._cancel.is_set():
                    return
                out = self._stage_timed(item)
                if not self._put((out, None)):
                    return
        except BaseException as exc:  # delivered to the consumer
            self._put((None, exc))
            return
        self._put(_PIPE_DONE)

    def _put(self, entry) -> bool:
        """Cancellation-aware bounded put: a consumer that stopped
        pulling must never leave this thread parked on a full queue."""
        while not self._cancel.is_set():
            try:
                self._q.put(entry, timeout=0.05)
            except queue.Full:
                continue
            with self._mu:
                self._hwm = max(self._hwm, self._q.qsize())
            return True
        return False

    # ---- consumer -------------------------------------------------------
    def __iter__(self):
        if self._sync:
            for item in self._items:
                yield self._stage_timed(item)
            return
        try:
            while True:
                entry = self._q.get()
                if entry is _PIPE_DONE:
                    break
                out, exc = entry
                if exc is not None:
                    raise exc
                yield out
        finally:
            self.close()

    def close(self) -> None:
        """Cancel the producer and join its thread (idempotent)."""
        self._cancel.set()
        if self._thread is None:
            return
        while True:  # wake a producer parked on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=10)

    def stats(self) -> dict:
        """{"blocks", "stage_s", "depth_hwm"} — feed kernels.pipe_record
        AFTER the consumer loop (the producer is joined by then)."""
        with self._mu:
            return {"blocks": len(self._items),
                    "stage_s": self._stage_s,
                    "depth_hwm": self._hwm}


class _PipeBuilder:
    """Collects the fused program's runtime inputs and structural cache
    key while the node tree prepares.  Input ORDER is deterministic for a
    given key (prepare is a deterministic tree walk), so a cache-hit
    pipeline can re-bind fresh inputs positionally."""
    __slots__ = ("inputs", "kparts")

    def __init__(self):
        self.inputs: List = []
        self.kparts: List = []

    def add(self, arr) -> int:
        self.inputs.append(arr)
        return len(self.inputs) - 1

    def params(self, pt: ParamTable):
        pi, pf = pt.arrays()
        return self.add(pi), self.add(pf)

    def key(self, part) -> None:
        self.kparts.append(part)


class _TView:
    """Trace-time view: ``emit(args) -> (valid, [(vals, null), ...])``
    over the fused program's positional inputs, plus the host-side
    column metadata (ret_type, string decode table) and bucket size."""
    __slots__ = ("emit", "nb", "meta")

    def __init__(self, emit: Callable, nb: int, meta: List[tuple]):
        self.emit = emit
        self.nb = nb
        self.meta = meta


# =========================================================================
# group index: the sorted-replica clustered index
# =========================================================================

class GroupIndex:
    """Per (replica version, key columns) sorted order + group boundaries.
    order[i] = original row of sorted position i; groups are contiguous
    runs; ends[g] = last sorted position of group g (host int64 [ng]);
    keycols[j] = (values[ng], null[ng]) per key column (NULL keys form
    one group; a multi-column key groups by the TUPLE).  The single-int-
    key index additionally exposes gkeys/lo/hi + the dense pos_table the
    join build sides ride."""
    __slots__ = ("order", "ends", "keycols", "n_groups", "lo", "hi")

    def __init__(self, key_cols: List[tuple]):
        # lexsort: last key is primary -> feed (vals, nulls) pairs in
        # reverse column order, nulls after their values so each column
        # sorts non-null-first.  Values under a null mask are garbage:
        # mask them to 0 so the sort (and the boundary diff below) never
        # splits the NULL group on them.
        ops = []
        svs = []
        for vals, nulls in key_cols:
            svs.append((np.where(nulls, 0, vals), nulls))
        for mv, nl in reversed(svs):
            ops.append(mv)
            ops.append(nl)
        order = np.lexsort(tuple(ops))
        n = len(order)
        svs = [(mv[order], nl[order]) for mv, nl in svs]
        if n == 0:
            self.order = order
            self.ends = np.empty(0, dtype=np.int64)
            self.keycols = [(np.empty(0, dtype=v.dtype),
                             np.empty(0, dtype=bool)) for v, _ in key_cols]
            self.n_groups = 0
            self.lo = self.hi = 0
            return
        boundary = np.zeros(n, dtype=bool)
        boundary[0] = True
        for sv, sn in svs:
            # a value diff only splits groups when NEITHER row is NULL:
            # all NULL keys form ONE group (kernels._group_agg_kernel
            # applies the same guard)
            boundary[1:] |= ((sv[1:] != sv[:-1]) & ~(sn[1:] & sn[:-1])) \
                | (sn[1:] != sn[:-1])
        starts = np.nonzero(boundary)[0]
        ends = np.empty(len(starts), dtype=np.int64)
        ends[:-1] = starts[1:] - 1
        ends[-1] = n - 1
        self.order = order
        self.ends = ends
        self.keycols = [(sv[ends], sn[ends]) for sv, sn in svs]
        self.n_groups = len(ends)
        if len(key_cols) == 1 and self.gkeys.dtype == np.int64:
            nn = self.gkeys[~self.gkey_null]
            self.lo = int(nn.min()) if len(nn) else 0
            self.hi = int(nn.max()) if len(nn) else 0
        else:
            self.lo = self.hi = 0

    @property
    def gkeys(self) -> np.ndarray:
        return self.keycols[0][0]

    @property
    def gkey_null(self) -> np.ndarray:
        return self.keycols[0][1]

    def pos_table(self) -> Optional[np.ndarray]:
        """Dense key -> group index (int32), -1 for absent keys; None when
        the key range is too wide for a dense table (single-int-key
        indexes only)."""
        if len(self.keycols) != 1 or self.gkeys.dtype != np.int64:
            return None
        rng = self.hi - self.lo + 1
        if rng > MAX_DENSE_RANGE:
            return None
        tbl = np.full(rng, -1, dtype=np.int32)
        live = ~self.gkey_null
        tbl[self.gkeys[live] - self.lo] = np.nonzero(live)[0]
        return tbl

    def raw_counts(self) -> np.ndarray:
        """Rows per group (host int64 [ng]) — the pre-filter group sizes
        the CSR join uses for its expansion upper bound."""
        if self.n_groups == 0:
            return np.empty(0, dtype=np.int64)
        prev = np.concatenate(([np.int64(-1)], self.ends[:-1]))
        return self.ends - prev

    def sorted_gid(self) -> np.ndarray:
        """Group id per SORTED position (host int64 [n]) — the lane the
        segment-min/max kernels reduce over."""
        return np.repeat(np.arange(self.n_groups, dtype=np.int64),
                         self.raw_counts())


def _group_index(rep, sids: tuple, key_cols: List[tuple]) -> GroupIndex:
    """sids: tuple of stable slot ids (one per key column)."""
    return rep.memo(("groupindex", sids), lambda: GroupIndex(key_cols))


def _col_bounds(rep, sid, vals, nulls):
    """Host min/max of a replica int column's non-null values."""
    def build():
        nn = vals[~nulls]
        if len(nn) == 0:
            return None
        return int(nn.min()), int(nn.max())
    return rep.memo(("bounds", sid), build)


def _rep_pos_table(rep, sid, vals, nulls):
    """Dense key -> row index table for a UNIQUE replica column (the
    planner proves uniqueness: pk / single-column unique index)."""
    def build():
        b = _col_bounds(rep, sid, vals, nulls)
        if b is None:
            return None
        lo, hi = b
        rng = hi - lo + 1
        if rng > MAX_DENSE_RANGE:
            return None
        tbl = np.full(rng, -1, dtype=np.int32)
        live = ~nulls
        tbl[vals[live] - lo] = np.nonzero(live)[0].astype(np.int32)
        return lo, hi, tbl
    return rep.memo(("postable", sid), build)


# =========================================================================
# compiled nodes
# =========================================================================

class _Ctx:
    """Per-query compile context."""

    def __init__(self, exec_ctx, mesh=None):
        self.exec_ctx = exec_ctx
        self.mesh = mesh  # multi-chip mesh (tidb_mesh_parallel) or None


def mesh_if_enabled(session_vars):
    from ..parallel import dist
    return dist.session_mesh(session_vars)


def _jn():
    return kernels.jnp()


def _dev_upload(rep, key, build_np):
    # counted H2D (kernels.h2d): replica-memoized, so the transfer is
    # charged once per (replica, key) — to whichever query materializes it
    return rep.memo(key, lambda: kernels.h2d(build_np()))


class _ReplicaLeaf:
    """Full-table scan from the columnar replica: device columns are
    version-memoized uploads; scan filters become the validity mask
    (traced inline into the fused program)."""

    def __init__(self, reader_exec, plan):
        self.ex = reader_exec
        self.plan = plan
        self._rep = None  # set at prepare(): take_raw_replica consumes
        self._chk = None

    @staticmethod
    def compile(plan: PhysicalTableReader, ctx: _Ctx):
        from .executors import TableReaderExec
        scan = plan.scan
        if scan.ranges is not None or scan.pushed_agg is not None \
                or scan.pushed_topn is not None \
                or scan.pushed_limit is not None:
            return None
        ex = TableReaderExec(plan)
        ex.open(ctx.exec_ctx)
        if ex._replica is None:
            ex.close()
            return None
        from .tpu_executors import _block_budget
        budget = _block_budget(getattr(ctx.exec_ctx, "session_vars", {}))
        if budget > 0 and ex._replica.n_rows > budget:
            # table exceeds the device buffer budget: whole-column
            # residency is off the table — the per-op tier's block-wise
            # aggregate (partial-state carry) serves it instead
            ex.close()
            return None
        return _ReplicaLeaf(ex, plan)

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        from .tpu_executors import (_build_device_mask, _rep_string_dict,
                                    _slot_id)
        chk, filters, rep = self.ex.take_raw_replica()
        if chk is None:
            return None
        self._rep = rep
        self._chk = chk
        n = chk.full_rows()
        nb = kernels.bucket(max(n, 1))
        jn = _jn()
        pt = ParamTable()
        dm = _build_device_mask(self.ex, rep, chk, filters, pt)
        if dm is None:
            return None
        mask_fn, mask_key, _needed = dm
        params = pt.arrays()
        slots = []
        meta: List[tuple] = []
        dts = []
        for idx, c in enumerate(chk.columns):
            v = c.values()
            m = c.null_mask()
            sid = _slot_id(self.ex, idx)
            dn = _dev_upload(rep, ("devn", sid, nb),
                             lambda m=m: kernels.pad1(m, nb, True))
            if v.dtype == object or v.dtype.kind == "U":
                got = _rep_string_dict(rep, sid, chk, idx)
                codes, _card, _, uniques = got
                dv = _dev_upload(rep, ("devcodes", sid, nb),
                                 lambda c_=codes: kernels.pad1(c_, nb))
                meta.append((c.ft, uniques))
                dts.append("s")
            else:
                dv = _dev_upload(rep, ("devv", sid, nb),
                                 lambda v=v: kernels.pad1(v, nb))
                meta.append((c.ft, None))
                dts.append("f" if v.dtype == np.float64 else "i")
            slots.append((pb.add(dv), pb.add(dn)))
        pi, pf = params
        ip = pb.add(np.asarray(pi))
        fp = pb.add(np.asarray(pf))
        pb.key(("leaf", mask_key, nb, tuple(dts)))

        def emit(args):
            pairs = [(args[iv], args[im]) for iv, im in slots]
            valid = mask_fn(pairs, (args[ip], args[fp]), jn.arange(nb))
            return valid, pairs
        return _TView(emit, nb, meta)

    # host info the parent join/agg stages need (valid after prepare())
    def replica(self):
        return self._rep if self._rep is not None else self.ex._replica

    def chunk(self):
        return self._chk

    def close(self):
        self.ex.close()


class _HostLeaf:
    """Any unsupported subtree: run its regular executor, upload the
    materialized chunk (H2D is cheap; this is the CPU->TPU boundary).
    Numeric columns only — a string column here would need a per-query
    dictionary build, which defeats the point."""

    def __init__(self, child_exec, plan):
        self.ex = child_exec
        self.plan = plan
        self._chk = None

    @staticmethod
    def compile(plan, ctx: _Ctx):
        for c in plan.schema.columns:
            if c.ret_type.eval_type is EvalType.STRING:
                return None
        if _contains_join(plan):
            # an unsupported JOIN subtree as a host leaf would nest
            # another DevPipeExec inside (materialize + re-upload per
            # layer); bail the whole pipeline instead — the per-operator
            # executors handle that shape without the extra round trips
            return None
        from .executors import build_executor
        ex = build_executor(plan, True)
        if ex is None:
            return None
        ex.open(ctx.exec_ctx)
        return _HostLeaf(ex, plan)

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        from .tpu_executors import _drain_chunk
        chk = _drain_chunk(self.ex, self.ex.field_types()).compact()
        self._chk = chk
        n = chk.num_rows()
        nb = kernels.bucket(max(n, 1))
        slots = []
        meta = []
        dts = []
        for c, oc in zip(chk.columns, self.plan.schema.columns):
            v = c.values()
            m = c.null_mask()
            slots.append((pb.add(kernels.pad1(v, nb)),
                          pb.add(kernels.pad1(m, nb, True))))
            meta.append((oc.ret_type, None))
            dts.append("f" if v.dtype == np.float64 else "i")
        vi = pb.add(kernels.pad1(np.ones(n, dtype=bool), nb))
        pb.key(("host", nb, tuple(dts)))

        def emit(args):
            return args[vi], [(args[a], args[b]) for a, b in slots]
        return _TView(emit, nb, meta)

    def chunk(self):
        return self._chk

    def close(self):
        self.ex.close()


def _assemble_agg_specs(plan):
    """Shared descriptor lowering for the device aggregation nodes:
    returns (specs, slots) or None.  specs[k] = (kind, expr|None) with
    kind in count_star/count/sum/sum0/min/max — sum0 is a SUM of partial
    COUNT states, 0 over empty input instead of NULL; slots[i] maps
    descriptor i to ("one", k) or ("avg", k_sum, k_cnt) — avg decomposes
    into sum+count with the quotient taken in-kernel (reference
    partial-state split, aggregation/descriptor.go)."""
    from ..expression.aggregation import (AGG_AVG, AGG_MAX, AGG_MIN,
                                          AggMode)
    from ..expression.builtins import new_function
    specs: List[tuple] = []
    slots: List[tuple] = []
    for d in plan.aggs:
        if d.distinct:
            return None
        if d.mode is AggMode.FINAL:
            # FINAL merges partial STATES (reference aggfuncs mode split):
            # count -> SUM of partial counts; avg -> sum(sums)/sum(counts);
            # sum/min/max merge with themselves
            if d.name == AGG_COUNT and is_jittable(d.args[0]):
                # sum0: COUNT merged from partial states is 0 over empty
                # input, never NULL (unlike SUM)
                specs.append(("sum0", d.args[0]))
                slots.append(("one", len(specs) - 1))
            elif d.name == AGG_AVG and len(d.args) == 2 \
                    and all(is_jittable(a) for a in d.args):
                a0 = d.args[0]
                if a0.eval_type is not EvalType.REAL:
                    a0 = new_function("cast_real", [a0])
                specs.append(("sum", a0))
                specs.append(("sum", d.args[1]))
                slots.append(("avg", len(specs) - 2, len(specs) - 1))
            elif d.name == AGG_SUM and is_jittable(d.args[0]):
                a = d.args[0]
                if (d.ret_type.eval_type is EvalType.REAL
                        and a.eval_type is not EvalType.REAL):
                    a = new_function("cast_real", [a])
                specs.append(("sum", a))
                slots.append(("one", len(specs) - 1))
            elif d.name in (AGG_MIN, AGG_MAX) and is_jittable(d.args[0]) \
                    and not (d.args[0].eval_type is EvalType.INT
                             and getattr(d.args[0].ret_type,
                                         "is_unsigned", False)):
                specs.append((("min" if d.name == AGG_MIN else "max"),
                              d.args[0]))
                slots.append(("one", len(specs) - 1))
            else:
                return None
            continue
        if d.name == AGG_COUNT and isinstance(d.args[0], Constant) \
                and d.args[0].value is not None:
            specs.append(("count_star", None))
            slots.append(("one", len(specs) - 1))
        elif d.name == AGG_COUNT and is_jittable(d.args[0]):
            specs.append(("count", d.args[0]))
            slots.append(("one", len(specs) - 1))
        elif d.name == AGG_SUM and is_jittable(d.args[0]):
            a = d.args[0]
            if (d.ret_type.eval_type is EvalType.REAL
                    and a.eval_type is not EvalType.REAL):
                a = new_function("cast_real", [a])
            specs.append(("sum", a))
            slots.append(("one", len(specs) - 1))
        elif d.name == AGG_AVG and is_jittable(d.args[0]):
            a = d.args[0]
            ar = a if a.eval_type is EvalType.REAL \
                else new_function("cast_real", [a])
            specs.append(("sum", ar))
            specs.append(("count", a))
            slots.append(("avg", len(specs) - 2, len(specs) - 1))
        elif d.name in (AGG_MIN, AGG_MAX) and is_jittable(d.args[0]):
            a = d.args[0]
            if (a.eval_type is EvalType.INT
                    and getattr(a.ret_type, "is_unsigned", False)):
                return None  # unsigned order map: CPU/per-op tiers
            specs.append((("min" if d.name == AGG_MIN else "max"), a))
            slots.append(("one", len(specs) - 1))
        else:
            return None
    return specs, slots


def _agg_out_map(plan):
    """schema slot -> ("agg", descriptor i) | ("gb", key j), or None."""
    out_map = []
    for src, i in getattr(plan, "output_map", []):
        out_map.append(("agg", i) if src == "agg" else ("gb", i))
    if len(out_map) != len(plan.schema.columns):
        return None
    return out_map


def _mm_fill(jn, dtype, kind: str):
    if dtype == jn.int64:
        return (jn.iinfo(jn.int64).max if kind == "min"
                else jn.iinfo(jn.int64).min)
    return jn.inf if kind == "min" else -jn.inf


def _spec_results(jn, spec_kinds, arg_fns, pairs, pr, valid, gmask,
                  gvals, seg_sum, seg_mm, presence, n_out):
    """Shared per-spec aggregation loop for both device group-by nodes
    (the subtle NULL-when-empty / avg-pairing semantics live ONCE here).
    gmask/gvals gather a lane into sorted order; seg_sum reduces a sorted
    lane to [n_out]; seg_mm(av_s, live_s, kind) likewise for min/max."""
    res = []
    for kind, af in zip(spec_kinds, arg_fns):
        if kind == "count_star":
            res.append((presence, jn.zeros(n_out, dtype=bool)))
            continue
        av, an = af(pairs, pr)
        live_s = gmask(valid & ~an)
        cnt = seg_sum(live_s.astype(jn.int64))
        if kind == "count":
            res.append((cnt, jn.zeros(n_out, dtype=bool)))
        elif kind in ("sum", "sum0"):
            res.append((seg_sum(jn.where(live_s, gvals(av), 0)),
                        jn.zeros(n_out, dtype=bool) if kind == "sum0"
                        else cnt == 0))
        else:  # min / max
            fill = _mm_fill(jn, av.dtype, kind)
            res.append((seg_mm(jn.where(live_s, gvals(av), fill),
                               live_s, kind), cnt == 0))
    return res


def _slot_outputs(jn, res, slots):
    """Descriptor outputs from spec results: direct, or the avg quotient
    (NULL when the count is zero)."""
    outs = []
    for slot in slots:
        if slot[0] == "one":
            outs.append(res[slot[1]])
        else:
            sv, _ = res[slot[1]]
            cv, _ = res[slot[2]]
            outs.append((sv / jn.maximum(cv, 1).astype(sv.dtype),
                         cv == 0))
    return outs


def _gb_key_ok(e) -> bool:
    """Group keys the device nodes handle: plain columns — signed ints,
    reals, or strings (dictionary codes on device)."""
    if not isinstance(e, ExprColumn):
        return False
    if e.eval_type is EvalType.INT \
            and getattr(e.ret_type, "is_unsigned", False):
        return False
    return True


class _AggIndexNode:
    """GROUP BY directly over the columnar replica, via the group index:
    mask -> gather to sorted order -> cumsum -> boundary diff.  Multi-
    column keys group by the tuple (strings ride dictionary codes); the
    index is built ONCE per (replica version, key set) and memoized, so a
    per-query aggregate is one fused program over [nb] with a tiny [ngb]
    output.  Replaces the reference's partial-agg hash table
    (aggregate.go:355 shuffle) for reader-rooted aggregates."""

    def __init__(self, leaf: _ReplicaLeaf, plan, key_cols, specs, slots,
                 out_map):
        self.leaf = leaf
        self.plan = plan
        self.key_cols = key_cols    # [ExprColumn]
        self.specs = specs
        self.slots = slots
        self.out_map = out_map      # schema slot -> ("agg", i) | ("gb", j)
        self.gidx: Optional[GroupIndex] = None
        self._sids: Optional[tuple] = None

    @staticmethod
    def compile(plan: PhysicalHashAgg, ctx: _Ctx):
        if not plan.group_by:
            return None
        if not all(_gb_key_ok(e) for e in plan.group_by):
            return None
        child = plan.children[0]
        if not isinstance(child, PhysicalTableReader):
            return None
        leaf = _ReplicaLeaf.compile(child, ctx)
        if leaf is None:
            return None
        got = _assemble_agg_specs(plan)
        out_map = _agg_out_map(plan)
        if got is None or out_map is None:
            leaf.close()
            return None
        specs, slots = got
        return _AggIndexNode(leaf, plan, list(plan.group_by), specs, slots,
                             out_map)

    def _host_key_cols(self, rep):
        """[(vals, nulls)] per key column (codes for strings), their
        stable slot ids, and decode tables."""
        from .tpu_executors import _rep_string_dict, _slot_id
        chk = self.leaf.chunk()
        key_cols, sids, decodes = [], [], []
        for e in self.key_cols:
            idx = e.index
            sid = _slot_id(self.leaf.ex, idx)
            if sid == "handle":
                kv = rep.handles
                km = np.zeros(rep.n_rows, dtype=bool)
                decode = None
            elif e.eval_type is EvalType.STRING:
                got = _rep_string_dict(rep, sid, chk, idx)
                if got is None:
                    return None
                kv = got[0]
                km = chk.columns[idx].null_mask()
                decode = got[3]
            else:
                kv, km = rep.columns[sid]
                decode = None
            key_cols.append((kv, km))
            sids.append(sid)
            decodes.append(decode)
        return key_cols, tuple(sids), decodes

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        tv = self.leaf.prepare(pb)
        if tv is None:
            return None
        rep = self.leaf.replica()
        got = self._host_key_cols(rep)
        if got is None:
            return None
        key_cols, sids, decodes = got
        gidx = _group_index(rep, sids, key_cols)
        self.gidx = gidx
        self._sids = sids
        ng = gidx.n_groups
        ngb = kernels.bucket(max(ng, 1))
        nb = tv.nb
        jn = _jn()
        io = pb.add(_dev_upload(rep, ("gi_order", sids, nb),
                                lambda: kernels.pad1(gidx.order, nb)))
        ie = pb.add(_dev_upload(rep, ("gi_ends", sids, ngb),
                                lambda: kernels.pad1(
                                    gidx.ends, ngb,
                                    fill=max(rep.n_rows - 1, 0))))
        gb_slots = []
        for j, (gk, gn) in enumerate(gidx.keycols):
            ik = pb.add(_dev_upload(rep, ("gi_gkeys", sids, j, ngb),
                                    lambda gk=gk: kernels.pad1(gk, ngb)))
            ikn = pb.add(_dev_upload(rep, ("gi_gknull", sids, j, ngb),
                                     lambda gn=gn: kernels.pad1(gn, ngb,
                                                                True)))
            gb_slots.append((ik, ikn))
        need_mm = any(k in ("min", "max") for k, _ in self.specs)
        isg = None
        if need_mm:
            # group id per sorted position, sentinel ngb on padding —
            # the segment-min/max lane
            isg = pb.add(_dev_upload(
                rep, ("gi_sgid", sids, nb),
                lambda: kernels.pad1(gidx.sorted_gid(), nb, fill=ngb)))
        pt = ParamTable()
        pt.add_int(ng)
        pt.add_int(rep.n_rows)
        arg_fns = []
        keys = []
        for kind, a in self.specs:
            if a is None:
                arg_fns.append(None)
                keys.append(kind)
            else:
                arg_fns.append(compile_expr_params(a, pt))
                keys.append(f"{kind}:{stable_shape_key(a)}")
        ip, fp = pb.params(pt)
        # the cache key must pin EVERYTHING the traced closure depends
        # on: key column ids + dtypes (int vs float key lanes retrace),
        # the descriptor->spec slot mapping, and the output column map
        kdts = tuple((str(s), str(gk.dtype))
                     for s, (gk, _) in zip(sids, gidx.keycols))
        pb.key(("aggindex", tuple(keys), kdts, tuple(self.slots),
                tuple(self.out_map), nb, ngb))
        spec_kinds = [k for k, _ in self.specs]
        slots = self.slots
        out_map = self.out_map
        schema_cols = self.plan.schema.columns

        def emit(args):
            j = kernels.jax()
            valid, pairs = tv.emit(args)
            order, ends = args[io], args[ie]
            pr = (args[ip], args[fp])
            # padded sorted positions map to row 0 via the padded order
            # array — they MUST be masked or row 0 is counted once per
            # padding slot
            in_table = jn.arange(nb) < pr[0][1]
            valid_s = valid[order] & in_table
            prev = jn.concatenate([jn.full((1,), -1, dtype=jn.int64),
                                   ends[:-1]])
            prev_safe = jn.maximum(prev, 0)

            def seg(x_s):
                c = jn.cumsum(x_s)
                hi = c[ends]
                lo = jn.where(prev >= 0, c[prev_safe],
                              jn.zeros((), dtype=x_s.dtype))
                return hi - lo

            def seg_mm(av_s, live_s, kind):
                gl = jn.where(live_s, args[isg], ngb)
                op = j.ops.segment_min if kind == "min" \
                    else j.ops.segment_max
                return op(av_s, gl, num_segments=ngb + 1)[:ngb]
            presence = seg(valid_s.astype(jn.int64))
            res = _spec_results(
                jn, spec_kinds, arg_fns, pairs, pr, valid,
                gmask=lambda b: b[order] & in_table,
                gvals=lambda v: v[order],
                seg_sum=seg, seg_mm=seg_mm, presence=presence, n_out=ngb)
            outs = _slot_outputs(jn, res, slots)
            gvalid = (jn.arange(ngb) < pr[0][0]) & (presence > 0)
            cols = []
            for m in out_map:
                if m[0] == "agg":
                    cols.append(outs[m[1]])
                else:
                    cols.append((args[gb_slots[m[1]][0]],
                                 args[gb_slots[m[1]][1]]))
            return gvalid, cols
        meta = []
        for oc, m in zip(schema_cols, out_map):
            decode = decodes[m[1]] if m[0] == "gb" else None
            meta.append((oc.ret_type, decode))
        return _TView(emit, ngb, meta)

    def build_key_info(self):
        """(lo, hi, pos_table np) for the parent join — static per
        replica version (single-int-key indexes only)."""
        rep = self.leaf.replica()
        got = self._host_key_cols(rep)
        if got is None:
            return None
        _, sids, _ = got

        def mk():
            tbl = self.gidx.pos_table()
            if tbl is None:
                return None
            return self.gidx.lo, self.gidx.hi, tbl
        return rep.memo(("gi_postable", sids), mk)

    def key_slot(self) -> int:
        """Schema slot of the (single) group key in the output view."""
        if len(self.key_cols) != 1:
            return -1
        for i, slot in enumerate(self.out_map):
            if slot[0] == "gb":
                return i
        return -1

    def close(self):
        self.leaf.close()


class _JoinNode:
    """Equi-join on int keys — a single key directly, or several keys
    combined into one COMPOSITE lane (sum((k_i - lo_i) * stride_i), a
    bijection over the bounded cross range).  Device layouts:

    - unique build (planner-proven pk/unique, or a group-index partial
      agg): dense key -> row position table + one gather per build
      column — probe-shaped output, no expansion.
    - general multiplicity (reference join.go:244 / util/mvmap): the
      build replica's group index is a CSR layout; probe maps key ->
      group through the dense table, per-group VALID counts come from a
      cumsum over the sorted validity, and the variable-size output
      lands in a static bucket via scatter-starts + running-max fill.
    """

    def __init__(self, probe, build, probe_keys, build_keys, tp,
                 probe_is_left, plan, mesh=None, mult=False,
                 session_vars=None):
        self.probe = probe
        self.build = build
        self.probe_keys = list(probe_keys)
        self.build_keys = list(build_keys)
        self.probe_key = self.probe_keys[0]
        self.build_key = self.build_keys[0]
        self.nk = len(self.probe_keys)
        self.tp = tp
        self.probe_is_left = probe_is_left
        self.plan = plan
        self.mesh = mesh
        self.mult = mult
        self.session_vars = session_vars or {}
        self.n_mesh = int(mesh.devices.size) if mesh is not None else 0

    @staticmethod
    def compile(plan: PhysicalHashJoin, ctx: _Ctx):
        if isinstance(plan, PhysicalMergeJoin):
            return None
        if plan.tp not in ("inner", "left", "semi", "anti"):
            return None
        if not plan.left_keys or plan.other_conditions \
                or len(plan.left_keys) != len(plan.right_keys):
            return None
        for k in list(plan.left_keys) + list(plan.right_keys):
            if not isinstance(k, ExprColumn) \
                    or k.eval_type is not EvalType.INT \
                    or getattr(k.ret_type, "is_unsigned", False):
                return None
        if getattr(plan, "left_conditions", None) \
                or getattr(plan, "right_conditions", None):
            return None  # side conds live in Selections below by now
        nk = len(plan.left_keys)
        lk, rk = plan.left_keys[0], plan.right_keys[0]
        mult = False
        if plan.tp in ("semi", "anti"):
            # semi/anti = a VALIDITY filter on the probe view (no shape
            # change, no gather): the build side's dense pos-table
            # answers membership.  One row per key in that table means
            # the build must be planner-proven unique; the NOT IN
            # null-aware ladder needs build-shape scalars the fused
            # program doesn't carry — both fall to the per-op executor.
            if nk != 1 or getattr(plan, "null_aware", False) \
                    or not getattr(plan, "right_unique", False):
                return None
            build = _compile_node(plan.children[1], ctx)
            if build is None:
                return None
            if not _has_build_key_info(build, rk):
                _close_node(build)
                return None
            probe = _compile_node(plan.children[0], ctx)
            if probe is None:
                _close_node(build)
                return None
            return _JoinNode(probe, build, [lk], [rk], plan.tp, True,
                             plan, mesh=ctx.mesh,
                             session_vars=getattr(ctx.exec_ctx,
                                                  "session_vars", None))
        if nk > 1:
            # multi-key: composite lane over a dense range, leaf/sel
            # build sides only; non-unique key sets ride the same CSR
            # expansion as single keys, over the composite lane
            build_side, probe_side = 1, 0
            build_keys = list(plan.right_keys)
            probe_keys = list(plan.left_keys)
            mult = not getattr(plan, "right_unique", False)
        elif getattr(plan, "right_unique", False):
            build_side, probe_side = 1, 0
            build_keys, probe_keys = [rk], [lk]
        elif getattr(plan, "left_unique", False) and plan.tp == "inner":
            build_side, probe_side = 0, 1
            build_keys, probe_keys = [lk], [rk]
        else:
            # general multiplicity: build stays the right child (the
            # probe must stay the outer side of a LEFT join), CSR over
            # the build replica's group index
            build_side, probe_side = 1, 0
            build_keys, probe_keys = [rk], [lk]
            mult = True
        build = _compile_node(plan.children[build_side], ctx)
        if build is None:
            return None
        ok = _leafish(build) is not None if (nk > 1 or mult) \
            else _has_build_key_info(build, build_keys[0])
        if not ok:
            _close_node(build)
            return None
        probe = _compile_node(plan.children[probe_side], ctx)
        if probe is None:
            _close_node(build)
            return None
        return _JoinNode(probe, build, probe_keys, build_keys,
                         plan.tp, probe_side == 0, plan, mesh=ctx.mesh,
                         mult=mult,
                         session_vars=getattr(ctx.exec_ctx,
                                              "session_vars", None))

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        btv = self.build.prepare(pb)
        if btv is None:
            return None
        ptv = self.probe.prepare(pb)
        if ptv is None:
            return None
        if self.tp in ("semi", "anti"):
            return self._prepare_semi(pb, btv, ptv)
        if self.mult:
            return self._prepare_mult(pb, btv, ptv)
        if self.nk > 1:
            return self._prepare_unique_multi(pb, btv, ptv)
        return self._prepare_unique(pb, btv, ptv)

    # ---- semi / anti: membership folds into probe validity -------------

    def _prepare_semi(self, pb, btv, ptv) -> Optional[_TView]:
        """Semi/anti join as a validity AND over the probe view: probe
        key -> build pos-table -> live?  The probe's pairs pass through
        untouched, so an entire Q5-style join chain with an interleaved
        semijoin stays ONE traced program."""
        info = _prepare_build_key_info(self.build, self.build_key, pb)
        if info is None:
            return None
        lo, hi, it, tbl_len = info
        jn = _jn()
        nb = ptv.nb
        nbb = btv.nb
        pk_slot = self.probe_key.index
        anti = self.tp == "anti"
        pt = ParamTable()
        pt.add_int(lo)
        pt.add_int(hi)
        ip, fp = pb.params(pt)
        pb.key(("semijoin", anti, nb, nbb, tbl_len, pk_slot,
                len(ptv.meta), len(btv.meta)))

        def emit(args):
            bvalid, _bpairs = btv.emit(args)
            pvalid, ppairs = ptv.emit(args)
            kp, knull = ppairs[pk_slot]
            pr = (args[ip], args[fp])
            lo_p, hi_p = pr[0][0], pr[0][1]
            inr = (kp >= lo_p) & (kp <= hi_p) & ~knull
            pos0 = jn.clip(kp - lo_p, 0, tbl_len - 1)
            pos = jn.where(inr, args[it][pos0].astype(jn.int64), -1)
            match = (pos >= 0) & bvalid[jn.clip(pos, 0, nbb - 1)]
            # anti (NOT EXISTS shape, never null-aware here): a NULL
            # probe key matches nothing and therefore SURVIVES
            valid_out = pvalid & (~match if anti else match)
            return valid_out, list(ppairs)
        return _TView(emit, nb, ptv.meta)

    # ---- multi-key unique build: composite lane + dense table ----------

    def _host_raw_key_cols(self, node, keys):
        """Raw host (vals, nulls) per key over a leaf/sel chain, plus the
        (replica, stable slot ids)."""
        leaf = _leafish(node)
        if leaf is None:
            return None
        rep = leaf.replica()
        if rep is None:
            return None
        from .tpu_executors import _slot_id
        cols, sids = [], []
        for k in keys:
            sid = _slot_id(leaf.ex, k.index)
            if sid == "handle":
                kv = rep.handles
                km = np.zeros(rep.n_rows, dtype=bool)
            else:
                kv, km = rep.columns[sid]
            if kv.dtype != np.int64:
                return None
            cols.append((kv, km))
            sids.append(sid)
        return rep, tuple(sids), cols

    def _prepare_unique_multi(self, pb, btv, ptv) -> Optional[_TView]:
        got = self._host_raw_key_cols(self.build, self.build_keys)
        if got is None:
            return None
        rep, sids, cols = got
        # per replica version: the full-column min/max scans + composite
        # lane build amortize like the single-key bounds/pos tables
        spec = rep.memo(("composite_spec", sids),
                        lambda: _composite_spec(cols))
        if spec is None:
            return None
        los, his, strides, comp, null_any, total = spec
        jn = _jn()
        nb, nbb = ptv.nb, btv.nb
        pk_slots = tuple(k.index for k in self.probe_keys)
        outer = self.tp == "left"
        probe_is_left = self.probe_is_left

        def mk():
            # dense composite -> build row (uniqueness over the key SET
            # is planner-proven; rows with any NULL key never match)
            tbl = np.full(total, -1, dtype=np.int32)
            live = ~null_any
            tbl[comp[live]] = np.nonzero(live)[0].astype(np.int32)
            return tbl
        it = pb.add(_dev_upload(rep, ("postable_multi", sids), mk))
        pt = ParamTable()
        for lo, hi, st in zip(los, his, strides):
            pt.add_int(lo)
            pt.add_int(hi)
            pt.add_int(st)
        ip, fp = pb.params(pt)
        pb.key(("joinmk", nb, nbb, total, pk_slots, outer, probe_is_left,
                len(btv.meta), len(ptv.meta)))

        def emit(args):
            bvalid, bpairs = btv.emit(args)
            pvalid, ppairs = ptv.emit(args)
            pr = (args[ip], args[fp])
            ok = pvalid
            comp_t = jn.zeros(nb, dtype=jn.int64)
            for j, slot in enumerate(pk_slots):
                kv, kn = ppairs[slot]
                lo_ = pr[0][3 * j]
                hi_ = pr[0][3 * j + 1]
                st_ = pr[0][3 * j + 2]
                ok = ok & (kv >= lo_) & (kv <= hi_) & ~kn
                comp_t = comp_t + (kv - lo_) * st_
            pos0 = jn.clip(comp_t, 0, total - 1)
            pos = jn.where(ok, args[it][pos0].astype(jn.int64), -1)
            pos_safe = jn.clip(pos, 0, nbb - 1)
            match = (pos >= 0) & bvalid[pos_safe]
            valid_out = pvalid if outer else (pvalid & match)
            gathered = [(bv[pos_safe], bn[pos_safe] | ~match)
                        for bv, bn in bpairs]
            if probe_is_left:
                return valid_out, list(ppairs) + gathered
            return valid_out, gathered + list(ppairs)
        if probe_is_left:
            meta = ptv.meta + btv.meta
        else:
            meta = btv.meta + ptv.meta
        return _TView(emit, nb, meta)

    # ---- unique build side: dense pos table + gather -------------------

    def _host_key_lane(self, node, key: ExprColumn):
        """The raw (pre-filter) host values of a node view's key lane
        (unpadded — _shuffle_cap_of pads to the device bucket), the live
        row count, and a (rep, memo_key) handle for replica-backed lanes
        so the capacity histogram memoizes per replica version.  None =
        shape without host-visible keys (fall back to broadcast)."""
        if isinstance(node, _SelNode):
            return self._host_key_lane(node.child, key)
        if isinstance(node, _ReplicaLeaf):
            rep = node.replica()
            if rep is None:
                return None
            from .tpu_executors import _slot_id
            sid = _slot_id(node.ex, key.index)
            kv = rep.handles if sid == "handle" else rep.columns[sid][0]
            if kv.dtype != np.int64:
                return None
            return kv, rep.n_rows, (rep, ("shufcap", sid))
        if isinstance(node, _AggIndexNode):
            if node.gidx is None or node.key_slot() != key.index:
                return None
            gk = node.gidx.gkeys
            if gk.dtype != np.int64:
                return None
            rep = node.leaf.replica()
            return gk, node.gidx.n_groups, (rep, ("shufcap_gi",
                                                  node._sids))
        if isinstance(node, _HostLeaf):
            chk = node.chunk()
            if chk is None:
                return None
            v = chk.columns[key.index].values()
            if v.dtype != np.int64:
                return None
            return v, chk.num_rows(), None  # per-query data: no memo
        return None

    @staticmethod
    def _shuffle_cap_of(lane, nbucket: int, n: int) -> int:
        from ..parallel import dist
        kv, n_rows, memo = lane

        def calc():
            return dist.shuffle_cap(kernels.pad1(kv, nbucket), n, n_rows)
        if memo is None:
            return calc()
        rep, mkey = memo
        return rep.memo(mkey + (nbucket, n), calc)

    @staticmethod
    def _broadcast_default() -> int:
        """The sysvar's shipped default — a session value differing from
        it is an explicit operator override.  Read from DEFAULT_SYSVARS
        (one definition; lazy import avoids the session<->executor
        cycle)."""
        from ..session.session import DEFAULT_SYSVARS
        return int(DEFAULT_SYSVARS["tidb_broadcast_build_max_rows"])

    def _shuffle_wanted(self, nb: int, nbb: int, mesh) -> bool:
        """Broadcast-vs-shuffle strategy (reference P4 north star).  The
        PLANNER decides by cost (device.py _mesh_join_strategy: broadcast
        bytes x mesh size vs one-pass shuffle volume, estRows from
        ANALYZE stats — the task.go:146 GetCost pattern); the
        tidb_broadcast_build_max_rows knob applies only when set away
        from its default (manual override, VERDICT r4 next-4)."""
        if mesh is None:
            return False
        n = int(mesh.devices.size)
        if n & (n - 1) or nb % n or nbb % n:
            return False
        default = self._broadcast_default()
        try:
            thresh = int(self.session_vars.get(
                "tidb_broadcast_build_max_rows", default))
        except Exception:
            return False
        if thresh != default:
            return nbb > thresh  # explicit knob override
        strategy = getattr(self.plan, "mesh_strategy", None)
        if strategy == "shuffle":
            return True
        # a plan-time "broadcast" stays subject to the RUNTIME budget:
        # estRows can be stale while nbb is the actual build bucket —
        # replicating an unexpectedly-huge build side to every shard is
        # the memory blow-up the budget protects against
        return nbb > thresh

    def _prepare_unique_shuffle(self, pb, btv, ptv, mesh) \
            -> Optional[_TView]:
        """Partitioned-build mesh join: all_to_all BOTH sides by key hash
        over the mesh axis, then each shard joins its partition locally
        (sort + searchsorted).  No shard ever holds the whole build side."""
        from ..parallel import dist
        jn = _jn()
        n = int(mesh.devices.size)
        nb, nbb = ptv.nb, btv.nb
        got_p = self._host_key_lane(self.probe, self.probe_key)
        got_b = self._host_key_lane(self.build, self.build_key)
        if got_p is None or got_b is None:
            return None
        pn_rows = got_p[1]
        bn_rows = got_b[1]
        capp = self._shuffle_cap_of(got_p, nb, n)
        capb = self._shuffle_cap_of(got_b, nbb, n)
        # skew gates, BOTH sides: a clustered hash would make one shard's
        # receive buffer rival the whole table — broadcast is strictly
        # better there (counted: tinysql_shard_skew_retries_total)
        from ..ops import shardops
        if n * n * capp > max(MAX_EXPAND, 2 * nb):
            shardops.record_skew_retry()
            return None
        if n * n * capb > max(MAX_EXPAND, 2 * nbb):
            shardops.record_skew_retry()
            return None
        pt = ParamTable()
        pt.add_int(pn_rows)
        pt.add_int(bn_rows)
        ip, fp = pb.params(pt)
        pk_slot = self.probe_key.index
        bk_slot = self.build_key.index
        outer = self.tp == "left"
        probe_is_left = self.probe_is_left
        npc, nbc = len(ptv.meta), len(btv.meta)
        pb.key(("joinshuf", nb, nbb, capp, capb, pk_slot, bk_slot, outer,
                probe_is_left, nbc, npc, n))
        # shard-exchange economics: the all_to_all lane volume this
        # program moves per dispatch (value+null byte per slot, plus the
        # validity lane) and one round at the receive-buffer HWM
        shardops.record_exchange(n * capp * (9 * npc + 1)
                                 + n * capb * (9 * nbc + 1))
        shardops.note_round(max(n * capp, n * capb))

        def kernel(ppairs, pvalid, bpairs, bvalid, pr):
            from jax import lax
            mp, mb = nb // n, nbb // n
            si = lax.axis_index("shard").astype(jn.int64)
            gp = si * mp + jn.arange(mp)
            gb_ = si * mb + jn.arange(mb)
            dp = dist.hash_dest_traced(jn, ppairs[pk_slot][0], n, gp,
                                       pr[0][0])
            db = dist.hash_dest_traced(jn, bpairs[bk_slot][0], n, gb_,
                                       pr[0][1])
            p_lanes = []
            for v, m_ in ppairs:
                p_lanes += [(v, jn.zeros((), dtype=v.dtype)), (m_, True)]
            p_lanes.append((pvalid, False))
            p_recv = dist.exchange_lanes(jn, p_lanes, dp, capp, n)
            b_lanes = []
            for v, m_ in bpairs:
                b_lanes += [(v, jn.zeros((), dtype=v.dtype)), (m_, True)]
            b_lanes.append((bvalid, False))
            b_recv = dist.exchange_lanes(jn, b_lanes, db, capb, n)
            P_ = [(p_recv[2 * i], p_recv[2 * i + 1]) for i in range(npc)]
            pv_r = p_recv[-1]
            B_ = [(b_recv[2 * i], b_recv[2 * i + 1]) for i in range(nbc)]
            bv_r = b_recv[-1]
            BN = n * capb
            bk_r, bkn_r = B_[bk_slot]
            pk_r, pkn_r = P_[pk_slot]
            hit, brow = dist.local_unique_join(
                jn, bk_r, bv_r & ~bkn_r, pk_r, BN)
            matched = hit & ~pkn_r & pv_r
            valid_out = pv_r if outer else matched
            bcols = [(bv2[brow], bn2[brow] | ~matched) for bv2, bn2 in B_]
            return valid_out, P_, bcols

        from ..parallel.dist import shard_map_fn
        shard_map, P = shard_map_fn()
        pspec = [(P("shard"), P("shard"))] * npc
        bspec = [(P("shard"), P("shard"))] * nbc
        sharded = shard_map(
            kernel, mesh=mesh,
            in_specs=(pspec, P("shard"), bspec, P("shard"), (P(), P())),
            out_specs=(P("shard"),
                       [(P("shard"), P("shard"))] * npc,
                       [(P("shard"), P("shard"))] * nbc))

        def emit(args):
            bvalid, bpairs = btv.emit(args)
            pvalid, ppairs = ptv.emit(args)
            valid_out, pcols, bcols = sharded(ppairs, pvalid, bpairs,
                                              bvalid,
                                              (args[ip], args[fp]))
            if probe_is_left:
                return valid_out, list(pcols) + list(bcols)
            return valid_out, list(bcols) + list(pcols)
        if probe_is_left:
            meta = ptv.meta + btv.meta
        else:
            meta = btv.meta + ptv.meta
        return _TView(emit, n * n * capp, meta)

    def _prepare_unique(self, pb, btv, ptv) -> Optional[_TView]:
        from ..parallel import dist as _dist
        if self._shuffle_wanted(ptv.nb, btv.nb,
                                self.mesh if _dist.shardable(ptv.nb,
                                                             self.mesh)
                                else None):
            out = self._prepare_unique_shuffle(pb, btv, ptv, self.mesh)
            if out is not None:
                return out  # else: broadcast below
        info = _prepare_build_key_info(self.build, self.build_key, pb)
        if info is None:
            return None
        lo, hi, it, tbl_len = info
        jn = _jn()
        nb = ptv.nb
        nbb = btv.nb
        pk_slot = self.probe_key.index
        pt = ParamTable()
        pt.add_int(lo)
        pt.add_int(hi)
        ip, fp = pb.params(pt)
        outer = self.tp == "left"
        # multi-chip: shard the PROBE side over the mesh, broadcast the
        # build table + build view (SURVEY §2.11 P4: partition one side,
        # probe rides ICI-local gathers, no cross-chip traffic per row)
        from ..parallel import dist
        mesh = self.mesh if dist.shardable(nb, self.mesh) else None
        n_mesh = self.n_mesh if mesh is not None else 0
        probe_is_left = self.probe_is_left
        pb.key(("join", nb, nbb, tbl_len, pk_slot, outer, probe_is_left,
                len(btv.meta), len(ptv.meta), n_mesh))

        def kernel(ppairs, pvalid, bpairs, bvalid, tbl, pr):
            kp, knull = ppairs[pk_slot]
            lo_p, hi_p = pr[0][0], pr[0][1]
            inr = (kp >= lo_p) & (kp <= hi_p) & ~knull
            pos0 = jn.clip(kp - lo_p, 0, tbl_len - 1)
            pos = jn.where(inr, tbl[pos0].astype(jn.int64), -1)
            pos_safe = jn.clip(pos, 0, nbb - 1)
            match = (pos >= 0) & bvalid[pos_safe]
            if outer:
                valid_out = pvalid
            else:
                valid_out = pvalid & match
            gathered = []
            for bv, bn in bpairs:
                gv = bv[pos_safe]
                gn = bn[pos_safe] | ~match
                gathered.append((gv, gn))
            return valid_out, gathered

        if mesh is not None:
            from ..parallel.dist import shard_map_fn
            shard_map, P = shard_map_fn()
            pspec = [(P("shard"), P("shard"))] * len(ptv.meta)
            bspec = [(P(), P())] * len(btv.meta)
            sharded = shard_map(
                kernel, mesh=mesh,
                in_specs=(pspec, P("shard"), bspec, P(), P(),
                          (P(), P())),
                out_specs=(P("shard"),
                           [(P("shard"), P("shard"))] * len(btv.meta)))
        else:
            sharded = kernel

        def emit(args):
            bvalid, bpairs = btv.emit(args)
            pvalid, ppairs = ptv.emit(args)
            valid_out, gathered = sharded(ppairs, pvalid, bpairs, bvalid,
                                          args[it],
                                          (args[ip], args[fp]))
            if probe_is_left:
                return valid_out, list(ppairs) + gathered
            return valid_out, gathered + list(ppairs)
        if probe_is_left:
            meta = ptv.meta + btv.meta
        else:
            meta = btv.meta + ptv.meta
        return _TView(emit, nb, meta)

    # ---- general multiplicity: CSR over the build group index ----------

    def _prepare_mult(self, pb, btv, ptv) -> Optional[_TView]:
        from .tpu_executors import _slot_id
        leaf = _leafish(self.build)
        rep = leaf.replica()
        if rep is None:
            return None
        cspec = None
        if self.nk > 1:
            # multi-key CSR: group index over the composite lane
            got = self._host_raw_key_cols(self.build, self.build_keys)
            if got is None:
                return None
            rep, sids0, bcols_host = got
            cspec = rep.memo(("composite_spec", sids0),
                             lambda: _composite_spec(bcols_host))
            if cspec is None:
                return None
            kv, km = cspec[3], cspec[4]
            sids = ("comp",) + sids0
        else:
            sid = _slot_id(leaf.ex, self.build_key.index)
            if sid == "handle":
                kv, km = rep.handles, np.zeros(rep.n_rows, dtype=bool)
            else:
                kv, km = rep.columns[sid]
            sids = (sid,)
        gidx = _group_index(rep, sids, [(kv, km)])

        def mk():
            tbl = gidx.pos_table()
            return None if tbl is None else (gidx.lo, gidx.hi, tbl)
        got = rep.memo(("gi_postable", sids), mk)
        if got is None:
            return None
        lo, hi, tbl = got
        raw = gidx.raw_counts()
        outer = self.tp == "left"
        # mesh: shard the PROBE side, broadcast the CSR structures; the
        # per-shard expansion bucket needs host-exact per-shard bounds
        from ..parallel import dist
        mesh = self.mesh if dist.shardable(ptv.nb, self.mesh) else None
        n_mesh = int(mesh.devices.size) if mesh is not None else 0
        per_probe = self._per_probe_counts(raw, tbl, lo, hi, ptv, outer,
                                           cspec=cspec)
        if mesh is not None and per_probe is None:
            mesh = None  # no host probe keys: per-shard bound unknowable
            n_mesh = 0
        ob = self._expand_bucket(raw, ptv, outer, per_probe,
                                 shards=max(n_mesh, 1))
        if ob is None and mesh is not None:
            # probe skew blew the per-shard bound: retry unsharded
            # before abandoning the device pipeline (counted:
            # tinysql_shard_skew_retries_total feeds the imbalance rule)
            from ..ops import shardops
            shardops.record_skew_retry()
            mesh = None
            n_mesh = 0
            ob = self._expand_bucket(raw, ptv, outer, per_probe)
        if ob is None:
            return None
        jn = _jn()
        nb = ptv.nb           # probe bucket
        nbb = btv.nb          # build bucket == leaf bucket (sel keeps nb)
        ng = gidx.n_groups
        ngb = kernels.bucket(max(ng, 1))
        tbl_len = int(tbl.shape[0])
        pk_slots = tuple(k.index for k in self.probe_keys)
        io = pb.add(_dev_upload(rep, ("gi_order", sids, nbb),
                                lambda: kernels.pad1(gidx.order, nbb)))
        ie = pb.add(_dev_upload(rep, ("gi_ends", sids, ngb),
                                lambda: kernels.pad1(
                                    gidx.ends, ngb,
                                    fill=max(rep.n_rows - 1, 0))))
        it = pb.add(_dev_upload(rep, ("gi_postable_dev", sids),
                                lambda: tbl))
        pt = ParamTable()
        pt.add_int(ng)
        pt.add_int(rep.n_rows)
        pt.add_int(lo)
        pt.add_int(hi)
        if cspec is not None:
            for klo, khi, kst in zip(cspec[0], cspec[1], cspec[2]):
                pt.add_int(klo)
                pt.add_int(khi)
                pt.add_int(kst)
        ip, fp = pb.params(pt)
        probe_is_left = self.probe_is_left
        nk = self.nk
        npc, nbc = len(ptv.meta), len(btv.meta)
        nb_loc = nb // n_mesh if n_mesh else nb
        pb.key(("joinm", nb, nbb, ngb, ob, tbl_len, pk_slots, outer,
                probe_is_left, nbc, npc, n_mesh))

        def kernel(ppairs, pvalid, bpairs, bvalid, order, ends, tbl_d,
                   pr):
            from jax import lax
            nb = nb_loc  # per-shard probe rows (== global when no mesh)
            ng_p, nrows_p, lo_p, hi_p = (pr[0][0], pr[0][1], pr[0][2],
                                         pr[0][3])
            # per-group VALID counts from one cumsum over sorted validity
            in_table = jn.arange(nbb) < nrows_p
            vs = bvalid[order] & in_table
            c = jn.cumsum(vs.astype(jn.int64))
            gmask = jn.arange(ngb) < ng_p
            prev = jn.concatenate([jn.full((1,), -1, dtype=jn.int64),
                                   ends[:-1]])
            prev_safe = jn.maximum(prev, 0)
            start_c = jn.where(prev >= 0, c[prev_safe], 0)
            vcnt = jn.where(gmask, c[ends] - start_c, 0)
            # compacted sorted order: comp[j] = row of j-th valid entry
            vidx = jn.nonzero(vs, size=nbb, fill_value=0)[0]
            comp = order[vidx]
            # probe -> group -> multiplicity (multi-key probes compute
            # the composite lane from per-key params)
            if nk > 1:
                ok = pvalid
                kp = jn.zeros(nb, dtype=jn.int64)
                for j, slot in enumerate(pk_slots):
                    kvj, knj = ppairs[slot]
                    klo = pr[0][4 + 3 * j]
                    khi = pr[0][4 + 3 * j + 1]
                    kst = pr[0][4 + 3 * j + 2]
                    ok = ok & (kvj >= klo) & (kvj <= khi) & ~knj
                    kp = kp + (kvj - klo) * kst
                inr = ok & (kp >= lo_p) & (kp <= hi_p)
                kp = jn.clip(kp, lo_p, hi_p)
            else:
                kp, knull = ppairs[pk_slots[0]]
                inr = (kp >= lo_p) & (kp <= hi_p) & ~knull & pvalid
            pos0 = jn.clip(kp - lo_p, 0, tbl_len - 1)
            g = jn.where(inr, tbl_d[pos0].astype(jn.int64), -1)
            gsafe = jn.clip(g, 0, ngb - 1)
            m = jn.where(g >= 0, vcnt[gsafe], 0)
            if outer:
                cnt = jn.where(pvalid, jn.maximum(m, 1), 0)
            else:
                cnt = m
            offs = jn.cumsum(cnt) - cnt   # exclusive prefix
            total = offs[-1] + cnt[-1]
            # two-phase expansion: scatter each probe row's id at its
            # output start, running-max fill assigns every output slot
            tgt = jn.where(cnt > 0, offs, ob)  # ob = dropped (OOB)
            base = jn.zeros(ob, dtype=jn.int64).at[tgt].set(
                jn.arange(nb) + 1, mode="drop")
            pidx = lax.cummax(base, axis=0) - 1
            valid_out = (pidx >= 0) & (jn.arange(ob) < total)
            ps = jn.clip(pidx, 0, nb - 1)
            k = jn.arange(ob) - offs[ps]
            gj = g[ps]
            gjs = jn.clip(gj, 0, ngb - 1)
            matched = (gj >= 0) & (k < m[ps]) & valid_out
            brow = comp[jn.clip(start_c[gjs] + k, 0, nbb - 1)]
            pcols = [(pv[ps], pn[ps]) for pv, pn in ppairs]
            bcols = [(bv[brow], bn[brow] | ~matched) for bv, bn in bpairs]
            return valid_out, pcols, bcols

        if mesh is not None:
            # probe side sharded over the mesh, CSR structures broadcast
            # (each shard expands its own probe block into its own
            # per-shard bucket — SURVEY §2.11 P4)
            from ..parallel.dist import shard_map_fn
            shard_map, P = shard_map_fn()
            sharded = shard_map(
                kernel, mesh=mesh,
                in_specs=([(P("shard"), P("shard"))] * npc, P("shard"),
                          [(P(), P())] * nbc, P(), P(), P(), P(),
                          (P(), P())),
                out_specs=(P("shard"),
                           [(P("shard"), P("shard"))] * npc,
                           [(P("shard"), P("shard"))] * nbc))
        else:
            sharded = kernel

        def emit(args):
            bvalid, bpairs = btv.emit(args)
            pvalid, ppairs = ptv.emit(args)
            valid_out, pcols, bcols = sharded(
                ppairs, pvalid, bpairs, bvalid, args[io], args[ie],
                args[it], (args[ip], args[fp]))
            if probe_is_left:
                return valid_out, list(pcols) + list(bcols)
            return valid_out, list(bcols) + list(pcols)
        if probe_is_left:
            meta = ptv.meta + btv.meta
        else:
            meta = btv.meta + ptv.meta
        return _TView(emit, ob * max(n_mesh, 1), meta)

    def _per_probe_counts(self, raw, tbl, lo, hi, ptv, outer, cspec=None):
        """Host per-probe-row match-count UPPER bounds (pre-filter group
        sizes; filters only shrink), padded to the probe bucket — feeds
        both the global and the per-shard expansion bounds.  None when
        the probe side has no host-visible keys."""
        from .tpu_executors import _slot_id
        pkv = pkm = None
        if cspec is not None:
            got = self._host_raw_key_cols(self.probe, self.probe_keys)
            if got is not None:
                _, _, pcols = got
                los, his, strides = cspec[0], cspec[1], cspec[2]
                pkm = np.zeros(len(pcols[0][0]), dtype=bool)
                pkv = np.zeros(len(pcols[0][0]), dtype=np.int64)
                for (kvj, kmj), klo, khi, kst in zip(pcols, los, his,
                                                     strides):
                    pkm |= kmj | (kvj < klo) | (kvj > khi)
                    pkv += (np.clip(kvj, klo, khi) - klo) * kst
        else:
            pleaf = _leafish(self.probe)
            if pleaf is not None:
                prep = pleaf.replica()
                if prep is not None:
                    psid = _slot_id(pleaf.ex, self.probe_key.index)
                    if psid == "handle":
                        pkv = prep.handles
                        pkm = np.zeros(prep.n_rows, dtype=bool)
                    else:
                        pkv, pkm = prep.columns[psid]
        if pkv is None:
            return None
        inr = (~pkm) & (pkv >= lo) & (pkv <= hi)
        gsafe = np.where(inr, pkv - lo, 0)
        g = np.where(inr, tbl[gsafe], -1)
        per = np.where(g >= 0, raw[np.clip(g, 0, max(len(raw) - 1, 0))],
                       0)
        if outer:
            per = np.maximum(per, 1)
        return kernels.pad1(per.astype(np.int64), ptv.nb)

    def _expand_bucket(self, raw, ptv, outer, per_probe, shards: int = 1):
        """Static (per-shard) output bucket for the CSR expansion.  None
        = too large, fall off the device pipeline."""
        if per_probe is None:
            mx = int(raw.max()) if len(raw) else 0
            bound = ptv.nb * max(mx, 1 if outer else 0)
        elif shards > 1:
            blk = ptv.nb // shards
            bound = int(per_probe.reshape(shards, blk).sum(axis=1).max())
        else:
            bound = int(per_probe.sum())
        if bound * shards > MAX_EXPAND:
            return None
        return kernels.bucket(max(bound, 1))

    def close(self):
        _close_node(self.probe)
        _close_node(self.build)


class _SortGroupNode:
    """GROUP BY above an arbitrary device view (join outputs included,
    VERDICT r3 #1): in-kernel lexsort by the key lanes (valid rows first),
    boundary diff -> group leaders, next-leader positions by a reverse
    cummin scan, then every sum/count is a cumsum + two gathers over the
    leader windows — no scatter on the hot path (SURVEY §7 "hash tables
    on TPU": sort-based grouping; reference aggregate.go:355 shuffle).
    min/max ride segment ops over the group-number lane.  Output view:
    group g at slot g of the child-sized bucket, valid = g < n_groups."""

    def __init__(self, child, key_cols, specs, slots, out_map, plan):
        self.child = child
        self.key_cols = key_cols
        self.specs = specs
        self.slots = slots
        self.out_map = out_map
        self.plan = plan

    @staticmethod
    def compile(plan: PhysicalHashAgg, ctx: _Ctx):
        if not plan.group_by:
            return None
        if not all(_gb_key_ok(e) for e in plan.group_by):
            return None
        got = _assemble_agg_specs(plan)
        out_map = _agg_out_map(plan)
        if got is None or out_map is None:
            return None
        specs, slots = got
        child = _compile_node(plan.children[0], ctx)
        if child is None:
            return None
        return _SortGroupNode(child, list(plan.group_by), specs, slots,
                              out_map, plan)

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        tv = self.child.prepare(pb)
        if tv is None:
            return None
        jn = _jn()
        nb = tv.nb
        key_idx = []
        decodes = []
        for e in self.key_cols:
            if e.index >= len(tv.meta):
                return None
            decode = tv.meta[e.index][1]
            if e.eval_type is EvalType.STRING and decode is None:
                return None  # string key without device codes
            key_idx.append(e.index)
            decodes.append(decode)
        pt = ParamTable()
        arg_fns = []
        keys = []
        for kind, a in self.specs:
            if a is None:
                arg_fns.append(None)
                keys.append(kind)
            else:
                arg_fns.append(compile_expr_params(a, pt))
                keys.append(f"{kind}:{stable_shape_key(a)}")
        ip, fp = pb.params(pt)
        pb.key(("sortgroup", tuple(keys), tuple(key_idx),
                tuple(self.slots), tuple(self.out_map), nb,
                len(tv.meta)))
        spec_kinds = [k for k, _ in self.specs]
        slots = self.slots
        out_map = self.out_map
        schema_cols = self.plan.schema.columns
        nkeys = len(key_idx)

        def emit(args):
            from jax import lax
            j = kernels.jax()
            valid, pairs = tv.emit(args)
            pr = (args[ip], args[fp])
            kvs = [pairs[i] for i in key_idx]
            perm = jn.lexsort(_sort_ops(jn, kvs, (False,) * nkeys, valid))
            valid_s = valid[perm]
            skeys = [(v[perm], m[perm]) for v, m in kvs]
            idx = jn.arange(nb)
            # leader = valid row starting a new key run (invalid rows
            # sort last, so groups of valid rows are contiguous)
            diff = jn.zeros(nb, dtype=bool).at[0].set(True)
            for sv, sn in skeys:
                d = ((sv[1:] != sv[:-1]) & ~(sn[1:] & sn[:-1])) \
                    | (sn[1:] != sn[:-1])
                diff = diff.at[1:].set(diff[1:] | d)
            prev_invalid = jn.concatenate(
                [jn.ones(1, dtype=bool), ~valid_s[:-1]])
            lead = valid_s & (diff | prev_invalid)
            gnum = jn.cumsum(lead.astype(jn.int64))       # 1-based
            ng = gnum[-1]
            sgid = jn.where(valid_s, gnum - 1, nb)        # per sorted pos
            # group end for the leader at i: next leader position - 1
            lp = jn.where(lead, idx, nb)
            nxt = lax.cummin(lp[::-1])[::-1]              # next leader >= i
            nxt_after = jn.concatenate([nxt[1:],
                                        jn.full((1,), nb, dtype=nxt.dtype)])
            end = jn.clip(nxt_after - 1, 0, nb - 1)

            lead_pos = jn.nonzero(lead, size=nb, fill_value=0)[0]

            def seg(x_s):
                # window sum [i, end_i] gathered at the leaders;
                # contributions are pre-masked so the last group's window
                # absorbing the invalid tail adds zero
                c = jn.cumsum(x_s)
                c0 = jn.concatenate([jn.zeros(1, dtype=x_s.dtype), c[:-1]])
                return (c[end] - c0)[lead_pos]

            def seg_mm(av_s, live_s, kind):
                gl = jn.where(live_s, sgid, nb)
                op = j.ops.segment_min if kind == "min" \
                    else j.ops.segment_max
                return op(av_s, gl, num_segments=nb + 1)[:nb]
            presence = seg(valid_s.astype(jn.int64))
            res = _spec_results(
                jn, spec_kinds, arg_fns, pairs, pr, valid,
                gmask=lambda b: b[perm], gvals=lambda v: v[perm],
                seg_sum=seg, seg_mm=seg_mm, presence=presence, n_out=nb)
            outs = _slot_outputs(jn, res, slots)
            gvalid = jn.arange(nb) < ng
            cols = []
            for m in out_map:
                if m[0] == "agg":
                    cols.append(outs[m[1]])
                else:
                    sv, sn = skeys[m[1]]
                    cols.append((sv[lead_pos], sn[lead_pos] | ~gvalid))
            return gvalid, cols
        meta = []
        for oc, m in zip(schema_cols, out_map):
            decode = decodes[m[1]] if m[0] == "gb" else None
            meta.append((oc.ret_type, decode))
        return _TView(emit, nb, meta)

    def close(self):
        _close_node(self.child)


class _ScalarAggNode:
    """Global (no GROUP BY) aggregation over any device view — masked
    reductions, one output row at slot 0 of a minimal bucket.  Keeps
    scalar aggregates above joins device-resident (reference
    aggregate.go:482 always-parallel Next, degenerate single group),
    including FINAL partial-state merges from agg pushdown."""

    def __init__(self, child, specs, slots, plan):
        self.child = child
        self.specs = specs
        self.slots = slots
        self.plan = plan

    @staticmethod
    def compile(plan: PhysicalHashAgg, ctx: _Ctx):
        if plan.group_by:
            return None
        got = _assemble_agg_specs(plan)
        if got is None:
            return None
        specs, slots = got
        out_map = _agg_out_map(plan)
        if out_map is None or any(m[0] != "agg" for m in out_map):
            return None
        child = _compile_node(plan.children[0], ctx)
        if child is None:
            return None
        node = _ScalarAggNode(child, specs, slots, plan)
        node.out_map = out_map
        return node

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        tv = self.child.prepare(pb)
        if tv is None:
            return None
        jn = _jn()
        ob = 16  # minimal bucket; the one result row sits at slot 0
        pt = ParamTable()
        arg_fns = []
        keys = []
        for kind, a in self.specs:
            if a is None:
                arg_fns.append(None)
                keys.append(kind)
            else:
                arg_fns.append(compile_expr_params(a, pt))
                keys.append(f"{kind}:{stable_shape_key(a)}")
        ip, fp = pb.params(pt)
        pb.key(("scalaragg", tuple(keys), tuple(self.slots),
                tuple(self.out_map), tv.nb, len(tv.meta)))
        spec_kinds = [k for k, _ in self.specs]
        slots = self.slots
        out_map = self.out_map
        schema_cols = self.plan.schema.columns

        def at0(x):
            return jn.zeros(ob, dtype=x.dtype).at[0].set(x)

        def emit(args):
            valid, pairs = tv.emit(args)
            pr = (args[ip], args[fp])
            # the shared per-spec loop with degenerate reducers: one
            # global segment, result at slot 0 (semantics live ONCE in
            # _spec_results)
            res = _spec_results(
                jn, spec_kinds, arg_fns, pairs, pr, valid,
                gmask=lambda b: b, gvals=lambda v: v,
                seg_sum=lambda x_s: at0(jn.sum(x_s)),
                seg_mm=lambda av_s, live_s, kind: at0(
                    (jn.min if kind == "min" else jn.max)(av_s)),
                presence=at0(jn.sum(valid.astype(jn.int64))), n_out=ob)
            outs = _slot_outputs(jn, res, slots)
            gvalid = jn.arange(ob) == 0  # exactly one result row
            return gvalid, [outs[m[1]] for m in out_map]
        meta = [(oc.ret_type, None) for oc in schema_cols]
        return _TView(emit, ob, meta)

    def close(self):
        _close_node(self.child)


def _leafish(node) -> Optional[_ReplicaLeaf]:
    """The underlying replica leaf of a leaf/selection chain (selection
    preserves the schema, so column offsets map straight through)."""
    if isinstance(node, _ReplicaLeaf):
        return node
    if isinstance(node, _SelNode):
        return _leafish(node.child)
    return None


def _has_build_key_info(node, build_key) -> bool:
    if isinstance(node, _AggIndexNode):
        return node.key_slot() == build_key.index
    if isinstance(node, (_ReplicaLeaf,)):
        return True  # bounds checked at prepare time
    if isinstance(node, (_SelNode,)):
        return _has_build_key_info(node.child, build_key)
    if isinstance(node, _ProjNode):
        # identity output: row space unchanged, key lives at the child
        # slot the projection reads (a subquery's final projection)
        e = node.exprs[build_key.index]
        return isinstance(e, ExprColumn) \
            and _has_build_key_info(node.child, e)
    return False


def _prepare_build_key_info(node, build_key, pb: _PipeBuilder):
    """(lo, hi, input index of the device pos-table, table length) mapping
    build-key value -> build view row."""
    if isinstance(node, _AggIndexNode):
        got = node.build_key_info()
        if got is None:
            return None
        lo, hi, tbl = got
        rep = node.leaf.replica()
        from .tpu_executors import _slot_id
        sids = (_slot_id(node.leaf.ex, node.key_cols[0].index),)
        d = _dev_upload(rep, ("gi_postable_dev", sids), lambda: tbl)
        return lo, hi, pb.add(d), int(tbl.shape[0])
    if isinstance(node, _SelNode):
        return _prepare_build_key_info(node.child, build_key, pb)
    if isinstance(node, _ProjNode):
        e = node.exprs[build_key.index]
        if not isinstance(e, ExprColumn):
            return None
        return _prepare_build_key_info(node.child, e, pb)
    if isinstance(node, _ReplicaLeaf):
        rep = node.replica()
        if rep is None:
            return None
        from .tpu_executors import _slot_id
        sid = _slot_id(node.ex, build_key.index)
        if sid == "handle":
            kv, km = rep.handles, np.zeros(rep.n_rows, dtype=bool)
        else:
            kv, km = rep.columns[sid]
        got = _rep_pos_table(rep, sid, kv, km)
        if got is None:
            return None
        lo, hi, tbl = got
        d = _dev_upload(rep, ("postable_dev", sid), lambda: tbl)
        return lo, hi, pb.add(d), int(tbl.shape[0])
    return None


def _composite_spec(cols):
    """Multi-key composite lane: per-key (lo, hi, stride) such that
    comp = sum((k_i - lo_i) * stride_i) is a bijection over the cross
    range — the device-friendly replacement for a multi-column hash key
    (reference join key tuples, util/mvmap multi-part keys).  None when
    the combined dense range exceeds MAX_DENSE_RANGE."""
    los, his = [], []
    total = 1
    for kv, km in cols:
        nn = kv[~km]
        if len(nn):
            lo, hi = int(nn.min()), int(nn.max())
        else:
            lo = hi = 0
        span = hi - lo + 1
        if span <= 0 or total > MAX_DENSE_RANGE // span:
            return None
        total *= span
        los.append(lo)
        his.append(hi)
    strides = []
    st = 1
    for lo, hi in reversed(list(zip(los, his))):
        strides.append(st)
        st *= hi - lo + 1
    strides.reverse()
    comp = np.zeros(len(cols[0][0]), dtype=np.int64)
    null_any = np.zeros(len(cols[0][0]), dtype=bool)
    for (kv, km), lo, hi, stride in zip(cols, los, his, strides):
        comp += (np.clip(kv, lo, hi) - lo) * stride
        null_any |= km
    return los, his, strides, comp, null_any, total


class _SelNode:
    """Filter over a device view: conditions AND into the validity mask."""

    def __init__(self, child, conds, plan):
        self.child = child
        self.conds = conds
        self.plan = plan

    @staticmethod
    def compile(plan: PhysicalSelection, ctx: _Ctx):
        if not all(is_jittable(c) for c in plan.conditions):
            return None
        child = _compile_node(plan.children[0], ctx)
        if child is None:
            return None
        return _SelNode(child, plan.conditions, plan)

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        tv = self.child.prepare(pb)
        if tv is None:
            return None
        pt = ParamTable()
        fns = [compile_expr_params(c, pt) for c in self.conds]
        keys = tuple(stable_shape_key(c) for c in self.conds)
        ip, fp = pb.params(pt)
        pb.key(("sel", keys, tv.nb, len(tv.meta)))

        def emit(args):
            valid, pairs = tv.emit(args)
            pr = (args[ip], args[fp])
            m = valid
            for f in fns:
                v, null = f(pairs, pr)
                m = m & (v != 0) & ~null
            return m, pairs
        return _TView(emit, tv.nb, tv.meta)

    def close(self):
        _close_node(self.child)


class _ProjNode:
    """Projection over a device view; string columns pass through as
    bare column references (codes + decode)."""

    def __init__(self, child, exprs, plan):
        self.child = child
        self.exprs = exprs
        self.plan = plan

    @staticmethod
    def compile(plan: PhysicalProjection, ctx: _Ctx):
        for e in plan.exprs:
            if is_jittable(e):
                continue
            if isinstance(e, ExprColumn) and e.eval_type is EvalType.STRING:
                continue
            return None
        child = _compile_node(plan.children[0], ctx)
        if child is None:
            return None
        return _ProjNode(child, plan.exprs, plan)

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        tv = self.child.prepare(pb)
        if tv is None:
            return None
        pt = ParamTable()
        fns = []
        keys = []
        meta = []
        for e, oc in zip(self.exprs, self.plan.schema.columns):
            if isinstance(e, ExprColumn):
                fns.append(("col", e.index))
                keys.append(f"@{e.index}")
                meta.append((oc.ret_type, tv.meta[e.index][1]))
            else:
                fns.append(("fn", compile_expr_params(e, pt)))
                keys.append(stable_shape_key(e))
                meta.append((oc.ret_type, None))
        ip, fp = pb.params(pt)
        pb.key(("proj", tuple(keys), tv.nb, len(tv.meta)))

        def emit(args):
            valid, pairs = tv.emit(args)
            pr = (args[ip], args[fp])
            outs = []
            for kind, f in fns:
                if kind == "col":
                    outs.append(pairs[f])
                else:
                    outs.append(f(pairs, pr))
            return valid, outs
        return _TView(emit, tv.nb, meta)

    def close(self):
        _close_node(self.child)


def _sort_ops(jn, keys, descs, valid):
    """lexsort operand list: requested keys (NULL first asc / last desc),
    invalid rows last.  keys = [(vals, null)] — ints/codes/floats."""
    ops = []
    for i in range(len(keys) - 1, -1, -1):
        v, m = keys[i]
        desc = descs[i]
        vv = jn.where(m, 0, v)
        if desc:
            # ~v is the overflow-free order-reversing bijection on int64
            vv = ~vv if vv.dtype == jn.int64 else -vv
            rank = jn.where(m, 1, 0).astype(jn.int8)  # NULL last
        else:
            rank = jn.where(m, 0, 1).astype(jn.int8)  # NULL first
        ops.append(vv)
        ops.append(rank)
    ops.append(jn.where(valid, 0, 1).astype(jn.int8))  # invalid last
    return ops


class _OrderNode:
    """TopN (static offset/count slice after lexsort — valid rows sort
    first, so perm[offset : offset+count_bucket] IS the answer) or full
    Sort over a view.

    Under `tidb_mesh_parallel` a TopN runs distributed (the mesh analogue
    of the reference's per-region TopN pushdown + root merge,
    /root/reference/store/mockstore/mocktikv/topn.go:1-139 +
    planner/core/task.go:392-452): each shard lexsorts its partition and
    keeps its top (offset+count) candidates, an all_gather moves the
    k x n_shards survivors over ICI, and a replicated merge sort slices
    the final window.  A global-row-index tiebreak makes the result
    bit-identical to the single-device stable sort."""

    def __init__(self, child, by, offset, count, plan, mesh=None):
        self.child = child
        self.by = by
        self.off = offset        # None = full sort
        self.count = count
        self.plan = plan
        self.mesh = mesh

    @staticmethod
    def compile(plan, ctx: _Ctx):
        by = plan.by
        for e, _ in by:
            if is_jittable(e):
                continue
            if isinstance(e, ExprColumn) and e.eval_type is EvalType.STRING:
                continue
            return None
        child = _compile_node(plan.children[0], ctx)
        if child is None:
            return None
        off = count = None
        if isinstance(plan, PhysicalTopN):
            off, count = plan.offset, plan.count
        return _OrderNode(child, by, off, count, plan, mesh=ctx.mesh)

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        tv = self.child.prepare(pb)
        if tv is None:
            return None
        jn = _jn()
        pt = ParamTable()
        fns = []
        keys = []
        for e, desc in self.by:
            if isinstance(e, ExprColumn):
                fns.append(("col", e.index))
                keys.append(f"@{e.index}:{desc}")
            else:
                fns.append(("fn", compile_expr_params(e, pt)))
                keys.append(f"{stable_shape_key(e)}:{desc}")
        descs = tuple(d for _, d in self.by)
        if self.off is None:
            off, kb = 0, tv.nb
        else:
            off = min(self.off, tv.nb)
            kb = min(kernels.bucket(max(self.count, 1)) + off, tv.nb)
        count = self.count
        ip, fp = pb.params(pt)

        from ..parallel import dist
        mesh = self.mesh if (self.off is not None
                             and dist.shardable(tv.nb, mesh=self.mesh)
                             ) else None
        if mesh is not None:
            return self._prepare_mesh(pb, tv, fns, tuple(keys), descs, off,
                                      kb, count, ip, fp, mesh)
        pb.key(("order", tuple(keys), off, kb, count, tv.nb,
                len(tv.meta)))

        def emit(args):
            valid, pairs = tv.emit(args)
            pr = (args[ip], args[fp])
            kvs = []
            for kind, f in fns:
                if kind == "col":
                    kvs.append(pairs[f])
                else:
                    kvs.append(f(pairs, pr))
            perm = jn.lexsort(_sort_ops(jn, kvs, descs, valid))
            take = perm[off:kb]
            out_valid = valid[take]
            if count is not None:
                # valid rows sort first, so the taken valid rows are a
                # prefix; cap it at `count`
                out_valid = out_valid & (jn.arange(kb - off) < count)
            outs = [(v[take], m[take]) for v, m in pairs]
            return out_valid, outs
        return _TView(emit, kb - off, tv.meta)

    def _prepare_mesh(self, pb, tv, fns, key_ids, descs, off, kb, count,
                      ip, fp, mesh):
        """Distributed TopN: per-shard top-(off+count) + all_gather merge.
        Column sort keys alias the payload lanes, so only computed ('fn')
        keys travel as extra lanes — the merge re-reads column keys from
        the gathered payload instead of gathering them twice."""
        jn = _jn()
        from jax import lax
        n = int(mesh.devices.size)
        per = tv.nb // n
        kc = min(kb, per)  # per-shard candidate count
        pb.key(("order_mesh", key_ids, off, kb,
                count, tv.nb, len(tv.meta), n, kc))

        def pick_kvs(fn_kvs, pairs):
            out = []
            it = iter(fn_kvs)
            for kind, f in fns:
                out.append(pairs[f] if kind == "col" else next(it))
            return out

        def kernel(fn_kvs, valid, pairs):
            # per-shard [per] lanes; global row index = the stable-sort
            # tiebreak that reproduces the single-device order exactly
            si = lax.axis_index("shard").astype(jn.int64)
            gidx = si * per + jn.arange(per, dtype=jn.int64)
            kvs = pick_kvs(fn_kvs, pairs)
            perm = jn.lexsort([gidx] + _sort_ops(jn, kvs, descs, valid))
            take = perm[:kc]
            lanes = ([(kv[0][take], kv[1][take]) for kv in fn_kvs]
                     + [(v[take], m[take]) for v, m in pairs])
            g_valid = lax.all_gather(valid[take], "shard", tiled=True)
            g_gidx = lax.all_gather(gidx[take], "shard", tiled=True)
            g_lanes = [(lax.all_gather(v, "shard", tiled=True),
                        lax.all_gather(m, "shard", tiled=True))
                       for v, m in lanes]
            g_fn_kvs = g_lanes[:len(fn_kvs)]
            g_pairs = g_lanes[len(fn_kvs):]
            g_kvs = pick_kvs(g_fn_kvs, g_pairs)
            perm2 = jn.lexsort([g_gidx]
                               + _sort_ops(jn, g_kvs, descs, g_valid))
            take2 = perm2[off:kb]
            out_valid = g_valid[take2]
            if count is not None:
                out_valid = out_valid & (jn.arange(kb - off) < count)
            outs = [(v[take2], m[take2]) for v, m in g_pairs]
            return out_valid, outs

        from ..parallel.dist import shard_map_fn, shard_map_unchecked
        _, P = shard_map_fn()

        def emit(args):
            valid, pairs = tv.emit(args)
            pr = (args[ip], args[fp])
            fn_kvs = [f(pairs, pr) for kind, f in fns if kind == "fn"]
            npairs = len(pairs)
            sharded = shard_map_unchecked(
                kernel, mesh=mesh,
                in_specs=([(P("shard"), P("shard"))] * len(fn_kvs),
                          P("shard"),
                          [(P("shard"), P("shard"))] * npairs),
                out_specs=(P(), [(P(), P())] * npairs))
            return sharded(fn_kvs, valid, list(pairs))
        return _TView(emit, kb - off, tv.meta)

    def close(self):
        _close_node(self.child)


class _LimitNode:
    def __init__(self, child, plan):
        self.child = child
        self.plan = plan

    @staticmethod
    def compile(plan: PhysicalLimit, ctx: _Ctx):
        child = _compile_node(plan.children[0], ctx)
        if child is None:
            return None
        return _LimitNode(child, plan)

    def prepare(self, pb: _PipeBuilder) -> Optional[_TView]:
        tv = self.child.prepare(pb)
        if tv is None:
            return None
        jn = _jn()
        pt = ParamTable()
        pt.add_int(self.plan.offset)
        pt.add_int(self.plan.offset + self.plan.count)
        ip, fp = pb.params(pt)
        pb.key(("limit", tv.nb))

        def emit(args):
            valid, pairs = tv.emit(args)
            pr = (args[ip], args[fp])
            rank = jn.cumsum(valid.astype(jn.int64))
            return valid & (rank > pr[0][0]) & (rank <= pr[0][1]), pairs
        return _TView(emit, tv.nb, tv.meta)

    def close(self):
        _close_node(self.child)


def _close_node(node):
    if node is not None and hasattr(node, "close"):
        node.close()


def _compile_node(plan, ctx: _Ctx):
    """Compile a plan subtree to a device node, or wrap it as a host
    leaf.  Returns None only for structural impossibilities at the
    ROOT of the requested subtree (callers fall back entirely)."""
    node = _compile_device(plan, ctx)
    if node is not None:
        return node
    return _HostLeaf.compile(plan, ctx)


def _compile_device(plan, ctx: _Ctx):
    if isinstance(plan, PhysicalTableReader):
        return _ReplicaLeaf.compile(plan, ctx)
    if isinstance(plan, PhysicalHashAgg):
        if not plan.group_by:
            return _ScalarAggNode.compile(plan, ctx)
        node = _AggIndexNode.compile(plan, ctx)
        if node is None:
            node = _SortGroupNode.compile(plan, ctx)
        return node
    if isinstance(plan, PhysicalHashJoin):
        return _JoinNode.compile(plan, ctx)
    if isinstance(plan, PhysicalSelection):
        return _SelNode.compile(plan, ctx)
    if isinstance(plan, PhysicalProjection):
        return _ProjNode.compile(plan, ctx)
    if isinstance(plan, (PhysicalTopN, PhysicalSort)):
        return _OrderNode.compile(plan, ctx)
    if isinstance(plan, PhysicalLimit):
        return _LimitNode.compile(plan, ctx)
    return None


def _contains_join(plan) -> bool:
    if isinstance(plan, PhysicalHashJoin) \
            and not isinstance(plan, PhysicalMergeJoin):
        return True
    return any(_contains_join(c) for c in plan.children)


def _contains_grouped_agg(plan) -> bool:
    if isinstance(plan, PhysicalHashAgg) and plan.group_by:
        return True
    return any(_contains_grouped_agg(c) for c in plan.children)


# =========================================================================
# materialization: host chunk from the packed download
# =========================================================================

def _to_chunk(host_pairs, meta) -> Chunk:
    cols = []
    for (v, m), (ret_type, decode) in zip(host_pairs, meta):
        if decode is not None:
            card = len(decode)
            safe = np.where(m | (v < 0) | (v >= card), 0, v)
            out = np.asarray(decode)[safe].astype(object)
            out[m] = None
            cols.append(CCol.from_numpy(ret_type, out, m))
        else:
            vv = v
            if ret_type.eval_type is EvalType.REAL \
                    and vv.dtype != np.float64:
                vv = vv.astype(np.float64)
            cols.append(CCol.from_numpy(ret_type, vv, m))
    return Chunk.from_columns(cols)


# =========================================================================
# executor wrapper
# =========================================================================

class DevPipeExec:
    """Volcano-compatible wrapper: compiles the subtree at open(), runs
    the fused device program once at first next().  Falls back to the
    regular TPU/CPU executors when compilation bails (structurally or at
    run time)."""

    def __init__(self, plan, fallback_builder: Callable):
        self.plan = plan
        self.schema = plan.schema
        self.children = []
        self._fallback_builder = fallback_builder
        self._fallback = None
        self._node = None
        self._done = False

    def field_types(self):
        return [c.ret_type for c in self.plan.schema.columns]

    def open(self, ctx):
        self.ctx = ctx
        self._done = False
        if not self._enabled(ctx):
            self._node = None
            self._open_fallback(ctx)
            return
        if self._spill_pressure(ctx):
            # memory-adaptive execution (ops/spill.py): the fused device
            # pipeline holds whole tables resident and has no spill
            # path — under quota pressure (or spillForceAll) the
            # statement routes to the per-operator executors, whose
            # join/agg/sort/topn spill routes bound the working set
            self._node = None
            self._open_fallback(ctx)
            return
        if not _contains_join(self.plan) \
                and _contains_grouped_agg(self.plan) \
                and mesh_if_enabled(ctx.session_vars) is not None:
            # agg-only pipelines under tidb_mesh_parallel ride the per-op
            # SHARDED fused aggregate (psum partial merge over the mesh);
            # devpipe's agg node is single-device.  Join pipelines and
            # plain scan+TopN stay here: the join and TopN nodes have
            # their own mesh (shard_map) paths.
            self._node = None
            self._open_fallback(ctx)
            return
        cctx = _Ctx(ctx, mesh=mesh_if_enabled(ctx.session_vars))
        try:
            self._node = _compile_device(self.plan, cctx)
        except Exception:
            self._bail(ctx, "compile")
            self._node = None
        if self._node is None:
            self._open_fallback(ctx)

    def _spill_pressure(self, ctx) -> bool:
        """Should this statement spill?  Same decision the per-operator
        tier makes (ops/spill.would_spill — the side-effect-free probe:
        no spillForceAll fire consumed, no throwaway SpillContext),
        priced per node with the SAME per-row costs the per-operator
        gates use (join: both sides × _JOIN_ROW_BYTES; everything else:
        the nominal pre-drain price) — if any operator under here would
        run partitioned, the whole pipeline steps aside."""
        from ..ops import spill
        from ..utils import memory as _memory
        from .tpu_executors import _JOIN_ROW_BYTES, _probe_row_bytes

        def est_of(p) -> float:
            return float(getattr(p, "stats_row_count", 0.0) or 0.0)

        def max_bytes(p) -> float:
            if isinstance(p, PhysicalHashJoin) \
                    and not isinstance(p, PhysicalMergeJoin):
                # the join gate prices BOTH sides (it materializes both)
                b = sum(est_of(c) for c in p.children) * _JOIN_ROW_BYTES
            else:
                # measured replica row width when one exists, else the
                # nominal pre-drain price — identical to the
                # per-operator probe (_would_spill_here)
                b = est_of(p) * _probe_row_bytes(
                    p, getattr(ctx, "storage", None))
            for c in getattr(p, "children", ()):
                b = max(b, max_bytes(c))
            return b

        # would_spill prices est_rows × row_bytes; pass the maximum
        # node cost as bytes directly
        return spill.would_spill(_memory.current(), max_bytes(self.plan), 1)

    @staticmethod
    def _forced(ctx) -> bool:
        raw = ctx.session_vars.get("tidb_devpipe", -1)
        return raw is not None and int(raw) == 1

    @staticmethod
    def _bail(ctx, stage: str):
        """A devpipe exception degrades to the per-operator tier — loudly:
        re-raise under tidb_devpipe=1 (tests force the pipeline and must
        see kernel bugs), warn-log otherwise so the regression is visible
        in the slow-query/debug log."""
        if DevPipeExec._forced(ctx):
            raise  # noqa: PLE0704 — re-raise the active exception
        import logging
        logging.getLogger("tinysql_tpu").warning(
            "devpipe %s failed, per-operator fallback", stage,
            exc_info=True)

    @staticmethod
    def _enabled(ctx) -> bool:
        """Pipelines win where transfers dominate (real devices).  On the
        XLA:CPU backend the compact numpy per-operator tier is faster, so
        auto mode engages only off-cpu; tests force with tidb_devpipe=1."""
        raw = ctx.session_vars.get("tidb_devpipe", -1)
        mode = -1 if raw is None else int(raw)
        if mode == 0:
            return False
        if mode == 1:
            return True
        try:
            return kernels.jax().default_backend() != "cpu"
        except Exception:
            return False

    def _open_fallback(self, ctx):
        self._fallback = self._fallback_builder(self.plan)
        qobs = getattr(self, "_obs_qobs", None)
        if qobs is not None:
            # the per-operator fallback tree is built lazily (after
            # instrument_tree walked the executor tree), so a pipeline
            # bail-out instruments it here with the same query scope
            from ..obs.runtime_stats import instrument_tree
            instrument_tree(self._fallback, qobs)
        self._fallback.open(ctx)

    def next(self) -> Optional[Chunk]:
        if self._fallback is not None:
            return self._fallback.next()
        if self._done:
            return None
        self._done = True
        try:
            out = self._run_pipeline()
        except Exception:
            self._bail(self.ctx, "run")
            out = None  # device died mid-run: fall back whole
        if out is None:
            # runtime bail (replica vanished, device error): rebuild on
            # the per-operator executors, which carry their own fallbacks
            _close_node(self._node)
            self._node = None
            self._open_fallback(self.ctx)
            return self._fallback.next()
        return out if out.num_rows() else None

    def _run_pipeline(self) -> Optional[Chunk]:
        """Prepare the node tree (host work + input collection), then run
        the WHOLE pipeline as one jitted program.  Small outputs fold the
        result packing into the same program: one dispatch, one D2H."""
        pb = _PipeBuilder()
        tv = self._node.prepare(pb)
        if tv is None:
            return None
        jn = _jn()
        nb = tv.nb
        ncols = len(tv.meta)
        small = nb <= kernels.SMALL_PACK
        # the input dtype/shape signature joins the key as a structural
        # backstop: a node key that under-pins its closure could otherwise
        # share a cached program whose retrace clobbers the mutable pack
        # schema (jit holds one trace per signature, the schema list holds
        # only the LAST trace's layout)
        sig = tuple((str(getattr(a, "dtype", type(a))),
                     tuple(getattr(a, "shape", ())))
                    for a in pb.inputs)
        key = ("pipe", small, tuple(pb.kparts), sig)
        if small:
            def build_small():
                schema: list = []
                emit = tv.emit

                def mega(args):
                    valid, cols = emit(args)
                    flat = [valid]
                    for v, m in cols:
                        flat.append(v)
                        flat.append(m)
                    return kernels.pack_arrays(schema, flat)
                _note_compiled(pb.kparts)
                return kernels.counted_jit(mega), schema
            fn, schema = progcache.get(key, build_small)
            vals = kernels.unpack_flat(fn(pb.inputs), schema)
            keep = np.nonzero(vals[0])[0]
            host = [(vals[1 + 2 * i][keep], vals[2 + 2 * i][keep])
                    for i in range(ncols)]
        else:
            def build_big():
                emit = tv.emit

                def mega(args):
                    valid, cols = emit(args)
                    return [valid] + [x for vm in cols for x in vm]
                _note_compiled(pb.kparts)
                return kernels.counted_jit(mega)
            fn = progcache.get(key, build_big)
            res = fn(pb.inputs)
            valid, items = res[0], list(res[1:])

            def build_count():
                return kernels.counted_jit(
                    lambda v: jn.sum(v.astype(jn.int64)))
            cfn = progcache.get(("nvalid", nb), build_count)
            n_valid = int(kernels.d2h(cfn(valid)))
            if n_valid == 0:
                host = [(np.empty(0, dtype=np.int64),
                         np.empty(0, dtype=bool))] * ncols
            else:
                ob = min(kernels.bucket(n_valid), nb)
                _ids, vals = kernels._present_pack(
                    valid.astype(jn.int64), items, ob)
                host = [(vals[2 * i][:n_valid], vals[2 * i + 1][:n_valid])
                        for i in range(ncols)]
        return _to_chunk(host, tv.meta)

    def drain(self) -> List[list]:
        rows = []
        while True:
            _interrupt.check()
            _fail.inject("execSlowNext")
            chk = self.next()
            if chk is None:
                break
            rows.extend(chk.to_rows())
        return rows

    def close(self):
        if self._fallback is not None:
            self._fallback.close()
        _close_node(self._node)
