"""Aggregate function state machines.

Capability parity with reference executor/aggfuncs/ (AggFunc iface
aggfuncs.go:63 — Alloc/Update/Merge/Append — with per-mode builders
builder.go, impls func_count.go/func_sum.go/func_avg.go/func_max_min.go/
func_first_row.go).  States support COMPLETE (rows->result),
PARTIAL1 (rows->partial) and FINAL (partials->result) so the same machinery
drives single-chip, parallel, and distributed (psum-merged) aggregation.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ..expression import AggFuncDesc, AggMode
from ..expression.aggregation import (AGG_AVG, AGG_COUNT, AGG_FIRST_ROW,
                                      AGG_MAX, AGG_MIN, AGG_SUM)
from ..mytypes import Datum, EvalType, coerce_for_compare, to_real, wrap_i64


class AggState:
    """Per-group accumulator."""

    def update(self, vals: List[Datum]) -> None:  # one input row's arg values
        raise NotImplementedError

    def merge(self, partial: List[Datum]) -> None:  # partial-result columns
        raise NotImplementedError

    def partial(self) -> List[Datum]:
        raise NotImplementedError

    def result(self) -> Datum:
        raise NotImplementedError


class CountState(AggState):
    __slots__ = ("n", "distinct", "seen")

    def __init__(self, distinct=False):
        self.n = 0
        self.distinct = distinct
        self.seen = set() if distinct else None

    def update(self, vals):
        if any(v is None for v in vals):
            return
        if self.distinct:
            key = tuple(vals)
            if key in self.seen:
                return
            self.seen.add(key)
        self.n += 1

    def merge(self, partial):
        if partial[0] is not None:
            self.n += partial[0]

    def partial(self):
        return [self.n]

    def result(self):
        return self.n


class SumState(AggState):
    __slots__ = ("total", "has", "is_int", "distinct", "seen")

    def __init__(self, is_int: bool, distinct=False):
        self.total = 0 if is_int else 0.0
        self.has = False
        self.is_int = is_int
        self.distinct = distinct
        self.seen = set() if distinct else None

    def update(self, vals):
        v = vals[0]
        if v is None:
            return
        if self.distinct:
            if v in self.seen:
                return
            self.seen.add(v)
        if self.is_int:
            self.total = wrap_i64(self.total + int(v))
        else:
            self.total += to_real(v)
        self.has = True

    def merge(self, partial):
        v = partial[0]
        if v is None:
            return
        if self.is_int:
            self.total = wrap_i64(self.total + int(v))
        else:
            self.total += to_real(v)
        self.has = True

    def partial(self):
        return [self.total if self.has else None]

    def result(self):
        return self.total if self.has else None


class AvgState(AggState):
    """COMPLETE-mode avg; in distributed mode avg is split into sum+count
    partials and a FINAL avg over two columns (aggregation.py split)."""
    __slots__ = ("total", "n", "distinct", "seen")

    def __init__(self, distinct=False):
        self.total = 0.0
        self.n = 0
        self.distinct = distinct
        self.seen = set() if distinct else None

    def update(self, vals):
        v = vals[0]
        if v is None:
            return
        if self.distinct:
            if v in self.seen:
                return
            self.seen.add(v)
        self.total += to_real(v)
        self.n += 1

    def merge(self, partial):
        # partial = [sum, count]
        if partial[1]:
            self.total += to_real(partial[0] or 0.0)
            self.n += partial[1]

    def partial(self):
        return [self.total if self.n else None, self.n]

    def result(self):
        return self.total / self.n if self.n else None


class FinalAvgState(AggState):
    """FINAL avg over (sum, count) partial columns."""
    __slots__ = ("total", "n")

    def __init__(self):
        self.total = 0.0
        self.n = 0

    def update(self, vals):  # vals = [sum_partial, count_partial]
        self.merge(vals)

    def merge(self, partial):
        if partial[1]:
            self.total += to_real(partial[0] or 0.0)
            self.n += int(partial[1])

    def partial(self):
        return [self.total if self.n else None, self.n]

    def result(self):
        return self.total / self.n if self.n else None


class MaxMinState(AggState):
    __slots__ = ("best", "is_max")

    def __init__(self, is_max: bool):
        self.best: Optional[Datum] = None
        self.is_max = is_max

    def update(self, vals):
        v = vals[0]
        if v is None:
            return
        if self.best is None:
            self.best = v
            return
        a, b = coerce_for_compare(v, self.best)
        if (a > b) == self.is_max and a != b:
            self.best = v

    def merge(self, partial):
        self.update(partial)

    def partial(self):
        return [self.best]

    def result(self):
        return self.best


class FirstRowState(AggState):
    __slots__ = ("value", "seen")

    def __init__(self):
        self.value = None
        self.seen = False

    def update(self, vals):
        if not self.seen:
            self.value = vals[0]
            self.seen = True

    def merge(self, partial):
        self.update(partial)

    def partial(self):
        return [self.value]

    def result(self):
        return self.value


def new_state(desc: AggFuncDesc) -> AggState:
    """reference: aggfuncs/builder.go Build (by name + mode)."""
    name = desc.name
    if name == AGG_COUNT:
        if desc.mode is AggMode.FINAL:
            s = CountState()
            s.update = s.merge  # final count sums partial counts
            return s
        return CountState(desc.distinct)
    if name == AGG_SUM:
        is_int = desc.ret_type.eval_type is EvalType.INT
        if desc.mode is AggMode.FINAL:
            s = SumState(is_int)
            s.update = s.merge
            return s
        return SumState(is_int, desc.distinct)
    if name == AGG_AVG:
        if desc.mode is AggMode.FINAL:
            return FinalAvgState()
        return AvgState(desc.distinct)
    if name == AGG_MAX:
        return MaxMinState(True)
    if name == AGG_MIN:
        return MaxMinState(False)
    if name == AGG_FIRST_ROW:
        return FirstRowState()
    raise ValueError(f"unknown aggregate {name!r}")
