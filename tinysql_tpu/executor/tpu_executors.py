"""TPU executor tier: the north-star operators.

Capability parity with BASELINE.json: TPU-backed HashAgg / HashJoin /
Sort / TopN / Projection / Selection registered behind the same volcano
interface as the CPU tier — marshalling chunk columns to device arrays
(SURVEY §2.9 note: Column {data, null} maps 1:1 onto array + mask), running
ops/kernels.py sort/segment kernels, and materializing results back.

String group/sort keys ride order-preserving dictionary codes built on the
host (np.unique), so TPC-H-style char keys still hit the device path.
"""
from __future__ import annotations

import time

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..chunk import Chunk, Column as CCol, MAX_CHUNK_SIZE
from ..expression import vectorized_filter
from ..expression.aggregation import (AGG_AVG, AGG_COUNT, AGG_FIRST_ROW,
                                      AGG_MAX, AGG_MIN, AGG_SUM)
from ..mytypes import EvalType, new_real_type
from ..ops import kernels, progcache
from ..planner.physical import (PhysicalHashAgg, PhysicalHashJoin,
                                PhysicalProjection, PhysicalSelection,
                                PhysicalSort, PhysicalTopN)
from .executors import Executor, build_executor


def _batching_active() -> bool:
    """Is a batch round's collect/replay leg live on this context?
    (ops/batching.active — lazy import so the executor stays importable
    without the serving layer initialized)"""
    from ..ops import batching
    return batching.active()


def _drain_chunk(ex: Executor, fields, soft: bool = False) -> Chunk:
    """``soft=True`` (spill-mode callers): the whole drain — child
    per-chunk allocations AND the accumulator growth — charges through
    the tracker's soft path (utils/memory.soft_scope).  A spill-mode
    operator's input materialization inherently overshoots the quota on
    a cold scan (no replica to serve zero-copy views); the partitioner
    takes the accumulated chunk over and releases it immediately after,
    and any nested operator inside the drained subtree sees the same
    watermark-crossed tracker, so its own spill gate fires.  Hard
    enforcement resumes at the first charge outside the scope."""
    from ..utils import memory as _memory
    from contextlib import nullcontext
    with (_memory.soft_scope() if soft else nullcontext()):
        first = ex.next()
        if first is None:
            return Chunk(fields, cap=MAX_CHUNK_SIZE)
        nxt = ex.next()
        if nxt is None:
            # single-chunk children (every device-tier operator) hand
            # their output over without a copy — this also keeps
            # DeviceColumn (late-materialization) chunks on device
            return first.compact()
        out = Chunk(fields, cap=MAX_CHUNK_SIZE)
        out.append_chunk(first)
        out.append_chunk(nxt)
        while True:
            chk = ex.next()
            if chk is None:
                break
            out.append_chunk(chk)
        return out


def _block_budget(session_vars) -> int:
    """tidb_device_block_rows, defensively parsed — the ONE reader all
    block-wise paths (agg, join, sort, topn, devpipe leaf) share."""
    try:
        return int(session_vars.get("tidb_device_block_rows", 0) or 0)
    except Exception:
        return 0


def _mesh_for(ctx, nb: int, plan=None):
    """Execution mesh for one sharded dispatch, or None = single-device.
    Gates, in order: session opt-in (tidb_mesh_parallel), the planner's
    estRows-driven shard count when annotated (plan.mesh_shards from
    planner/device.py — 1 is the degenerate 'stay single-device' case,
    >=2 clips to a cached submesh), and the runtime row-bucket gate
    (dist.shardable) on the ACTUAL padded row count."""
    from ..parallel import dist
    mesh = dist.session_mesh(ctx.session_vars)
    if mesh is None:
        return None
    want = int(getattr(plan, "mesh_shards", 0) or 0)
    if want == 1:
        return None
    if want >= 2:
        mesh = dist.sized_mesh(min(want, dist.mesh_shards(mesh)))
    return mesh if dist.shardable(nb, mesh) else None


def _spill_run_rows(sctx, n: int, row_bytes: int) -> int:
    """Run length for the external sort/top-k: what the resident budget
    holds, floored (tiny budgets must not devolve into per-row runs) and
    — under spillForceAll with no real quota — capped so small inputs
    still produce multiple runs for the store to prove itself on."""
    rows = int(sctx.budget // max(row_bytes, 1))
    if sctx.spill_all:
        rows = min(rows, max(n // 4, 256))
    return max(min(rows, n), 256)


def _est_rows_of(plan_child) -> float:
    return float(getattr(plan_child, "stats_row_count", 0.0) or 0.0)


def _maybe_spill_ctx(ctx, est_rows: float, actual_rows: int,
                     row_bytes: int, label: str):
    """Memory-adaptive execution gate shared by join/agg/sort/topn: a
    live ops/spill.SpillContext when this operator should run its
    partitioned spill path (spillForceAll, watermark crossed, or the
    planner's estRows pricing the operator's materialization over the
    watermark headroom), else None.  Partition-count choice rides the
    PLANNER estimate — the statement decides its fan-out before
    materializing — with the actual row count as the no-stats
    fallback."""
    from ..ops import spill
    from ..utils import memory as _memory
    if est_rows <= 0:
        est_rows = float(actual_rows)
    return spill.maybe_context(ctx.session_vars, _memory.current(),
                               max(est_rows, float(actual_rows)),
                               row_bytes, label)


#: per-row pricing for the join's proactive estRows trigger: what a row
#: of the charged working set costs (a compacted side is ~4 numeric
#: columns + null masks); agg and sort price their own rows from the
#: actual argument/key layout
_JOIN_ROW_BYTES = 36

#: nominal per-row pricing for the sort/topn PRE-drain softness check —
#: the FALLBACK when no measured width exists (obs/memprof.py
#: measured_row_bytes replaces it with the table's replica truth): the
#: would-this-spill probe prices one 8-byte key + null + rowid
_NOMINAL_ROW_BYTES = 17


def _plan_base_table_id(plan) -> int:
    """Table id of the single base table feeding ``plan`` (walks reader
    wrappers and unary operators down to the scan; 0 when the subtree is
    not scan-rooted — joins, memtables)."""
    node = plan
    for _ in range(32):
        scan = getattr(node, "scan", None) or \
            getattr(node, "table_scan", None)
        if scan is not None:
            node = scan
        info = getattr(node, "table_info", None)
        if info is not None:
            return int(info.id)
        kids = getattr(node, "children", None)
        if not kids or len(kids) != 1:
            return 0
        node = kids[0]
    return 0


def _probe_row_bytes(plan, storage=None) -> int:
    """Measured per-row width for the pre-drain spill probe: the base
    table's replica truth (obs/memprof.py — device-memoized column
    bytes over rows) when a replica exists, else the nominal constant.
    The measured number prices what a drained row of THIS table really
    costs, so `would_spill` flips where the ledger alone would not."""
    from ..obs import memprof
    tid = _plan_base_table_id(plan)
    if tid <= 0:
        return _NOMINAL_ROW_BYTES
    return memprof.measured_row_bytes(tid, _NOMINAL_ROW_BYTES,
                                      storage=storage)


def _would_spill_here(ctx, plan) -> bool:
    """Side-effect-free pre-drain probe for sort/topn: the real spill
    gate runs after materialization (it needs the actual key layout), but
    the drain's accumulator copies must already charge soft when the gate
    is going to say yes — otherwise a cold scan bigger than the quota
    dies before the external sort can spill a single run."""
    from ..ops import spill
    from ..utils import memory as _memory
    return spill.would_spill(_memory.current(),
                             _est_rows_of(plan.children[0]),
                             _probe_row_bytes(plan.children[0],
                                              getattr(ctx, "storage",
                                                      None)))


def _mask_compact_threshold() -> float:
    """Below this selectivity, compacting beats masking.  On real TPUs
    masked full-table kernels win almost always (stable shapes = one
    compile; throughput absorbs the extra rows); on the CPU backend the
    extra rows are pure cost, so compact much more aggressively."""
    try:
        return 0.3 if kernels.jax().default_backend() == "tpu" else 0.75
    except Exception:
        return 0.3


def _take_replica_masked(ex: Executor, extra_conds=None):
    """Single owner of the raw-replica intake: (chunk, mask, replica) with
    scan filters plus `extra_conds` folded into one mask (None when no
    conditions), or (None, None, None) when the child cannot serve raw.

    String comparisons against constants rewrite to integer compares over
    replica-memoized dictionary codes (ordered np.unique) — built once per
    replica version, they turn e.g. TPC-H date-range filters from <U
    string compares into int64 compares."""
    from .executors import TableReaderExec
    if not isinstance(ex, TableReaderExec):
        return None, None, None
    chk, filters, rep = ex.take_raw_replica()
    if chk is None:
        return None, None, None
    conds = list(filters) + list(extra_conds or [])
    if not conds:
        return chk, None, rep
    return chk, _fold_filter_masks(ex, rep, chk, conds), rep


def _fold_filter_masks(ex, rep, chk, conds):
    """AND-fold host masks for `conds`: string compares ride dictionary
    codes, the residual goes through vectorized_filter.  Shared by the
    replica intake and the fused-agg host-mask fallback."""
    mask = None
    residual = []
    for c in conds:
        m = _string_cmp_mask(ex, rep, chk, c)
        if m is None:
            residual.append(c)
        else:
            mask = m if mask is None else (mask & m)
    if residual:
        rm = vectorized_filter(residual, chk)
        mask = rm if mask is None else (mask & rm)
    return mask


_STR_CMP_OPS = {"=", "!=", "<", "<=", ">", ">="}


def _parse_string_cmp(chk, cond):
    """Recognize `string Column <op> string Constant` (either order).
    Returns (col, op, value) with the op flipped for constant-first, or
    None."""
    from ..expression import Column as ExprColumn, Constant, ScalarFunction
    from ..mytypes import EvalType as ET
    if not (isinstance(cond, ScalarFunction)
            and cond.name in _STR_CMP_OPS and len(cond.args) == 2):
        return None
    a, b = cond.args
    flip = False
    if isinstance(b, ExprColumn) and isinstance(a, Constant):
        a, b = b, a
        flip = True
    if not (isinstance(a, ExprColumn) and isinstance(b, Constant)):
        return None
    if a.eval_type is not ET.STRING or not isinstance(b.value, str):
        return None
    if chk.columns[a.index].values().dtype.kind != "U":
        return None
    op = cond.name
    if flip:
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
              "=": "=", "!=": "!="}[op]
    return a, op, b.value


def _code_cmp_fn(idx: int, op: str, lo_s: int, hi_s: int, card_s: int):
    """Device closure: string compare as an int compare over the slot's
    dictionary-code column, with per-query [lo, hi) bounds + NULL code as
    runtime params."""
    def f(cols, params):
        jn = kernels.jnp()
        code, null = cols[idx]
        pi = params[0]
        r = _code_cmp(jn, op, code, pi[lo_s], pi[hi_s], pi[card_s],
                      null=null)
        return r.astype(jn.int64), jn.zeros_like(null)
    return f


def _build_device_mask(ex, rep, chk, conds, pt):
    """Compile scan filters into an on-device mask program over the fused
    kernels' dev_cols.  Returns (mask_fn, key, needed) — needed is a set
    of (slot index, "codes" | "full") the program reads — or None when
    some condition cannot run on device (host mask fallback).  ``pt`` is
    the query's shared ParamTable (aggregate arguments append to the
    same vector): the live row count takes a slot first (padding guard),
    then per-condition constants — so changing any literal in the family
    never recompiles.  NOTE a None return may leave consumed slots in
    ``pt``; callers discard and rebuild it (slot order is part of the
    cached program's contract)."""
    from ..ops.exprjit import (compile_expr_params, is_jittable,
                               stable_shape_key)
    row_slot = pt.add_int(chk.full_rows())
    fns = []
    keys = []
    needed = set()
    for cond in conds:
        sc = _parse_string_cmp(chk, cond)
        if sc is not None:
            col, op, val = sc
            idx = col.index
            got = _rep_string_dict(rep, _slot_id(ex, idx), chk, idx)
            if got is None:
                return None
            _codes, card, _, uniques = got
            lo = int(np.searchsorted(uniques, val, side="left"))
            hi = int(np.searchsorted(uniques, val, side="right"))
            fns.append(_code_cmp_fn(idx, op, pt.add_int(lo),
                                    pt.add_int(hi), pt.add_int(card)))
            keys.append(f"strcmp@{idx}:{op}")
            needed.add((idx, "codes"))
        elif is_jittable(cond):
            fns.append(compile_expr_params(cond, pt))
            keys.append(stable_shape_key(cond))
            for c in cond.collect_columns():
                needed.add((c.index, "full"))
        else:
            return None

    def mask_fn(cols, params, row_idx):
        m = row_idx < params[0][row_slot]
        for f in fns:
            v, null = f(cols, params)
            m = m & (v != 0) & ~null
        return m
    return mask_fn, tuple(keys), needed


def rep_string_codes(rep, sid, v, null):
    """Ordered dictionary codes for a string replica column, memoized per
    replica version: (codes int64 [n] with NULL -> card, card, base=0,
    uniques).  ONE builder for every consumer of the ("keycodes", ...)
    memo slot (TPU group keys, device masks, CPU string filters) so the
    cached tuple shape can never drift between tiers."""
    def build():
        safe = np.where(null, "", v)
        uniques, codes = np.unique(safe.astype(str), return_inverse=True)
        codes = np.where(null, len(uniques), codes).astype(np.int64)
        return codes, len(uniques), 0, uniques
    return rep.memo(("keycodes", sid, True, False), build)


def _rep_string_dict(rep, sid, chk, idx):
    col = chk.columns[idx]
    return rep_string_codes(rep, sid, col.values(), col.null_mask())


def _slot_id(ex, idx: int):
    """Stable replica-memo id for a schema slot (column id or the
    handle)."""
    ci = ex._decode_cols[idx]
    return ci.id if ci is not None else "handle"


def _code_cmp(np_or_jnp, op: str, code, lo, hi, card, null=None):
    """The dictionary-code compare ladder over [lo, hi) bounds — one
    implementation serving both the host (numpy) and device (jnp traced)
    paths."""
    live = code != card  # NULL code = card: comparisons exclude it
    if null is not None:
        live = live & ~null
    if op == "=":
        r = (code >= lo) & (code < hi)
    elif op == "!=":
        r = (code < lo) | (code >= hi)
    elif op == "<":
        r = code < lo
    elif op == "<=":
        r = code < hi
    elif op == ">":
        r = code >= hi
    else:  # >=
        r = code >= lo
    return r & live


def _string_cmp_mask(ex, rep, chk, cond):
    """Try to evaluate `cond` (string Column vs string Constant compare)
    through dictionary codes; returns a bool mask or None."""
    sc = _parse_string_cmp(chk, cond)
    if sc is None:
        return None
    a, op, val = sc
    codes, card, _, uniques = _rep_string_dict(rep, _slot_id(ex, a.index),
                                               chk, a.index)
    lo = int(np.searchsorted(uniques, val, side="left"))
    hi = int(np.searchsorted(uniques, val, side="right"))
    return _code_cmp(np, op, codes, lo, hi, card)


def _compact_if_selective(chk: Chunk, mask):
    """Selective filters compact (less kernel work); permissive ones stay
    masked (stable bucket shape = one TPU compile per table size).
    String columns compact LAZILY (LazyTakeColumn): copying a <U date
    column costs ~5x an int64 copy, and a join above usually needs only
    its final few rows — the gather defers to that cardinality."""
    from ..chunk.column import LazyTakeColumn
    if (mask is not None and mask.size
            and mask.mean() < _mask_compact_threshold()):
        sel = np.nonzero(mask)[0]
        cols = []
        for c in chk.columns:
            v = c._data
            if v is not None and (v.dtype == object or v.dtype.kind == "U"):
                cols.append(LazyTakeColumn(c, sel))
            else:
                cols.append(c.take(sel))
        return Chunk.from_columns(cols), None
    if mask is not None and not mask.size:
        return chk, None  # empty chunk: nothing to mask
    return chk, mask


def _child_input(ex: Executor, soft: bool = False) -> Chunk:
    """Materialize a child's full output: TableReaders on the columnar
    replica hand over zero-copy column views (filters applied by selection
    compaction) instead of slicing + re-appending chunk by chunk.
    ``soft=True``: spill-mode caller — the accumulation/compaction copies
    are soft-charged (see :func:`_drain_chunk`)."""
    chk, mask, _rep = _take_replica_masked(ex)
    if chk is not None:
        if mask is not None:
            chk.set_sel(np.nonzero(mask)[0])
            chk = chk.compact()
        return chk
    out = _drain_chunk(ex, ex.field_types(), soft=soft)
    if soft:
        from ..utils import memory as _memory
        with _memory.soft_scope():
            return out.compact()
    return out.compact()


def _count_mask_program(slot: int):
    """COUNT(col) consumes only the column's null mask; the value half of
    the device pair may be absent (string columns upload masks only)."""
    def fn(cols, params):
        null = cols[slot][1]
        return null, null
    return fn


def _lower_agg_args(arg_exprs, pt):
    """Aggregate-argument entries -> ((cols, params) programs, shape-keyed
    program_key tuple).  ONE lowering for the whole-table fused path and
    the block-pipeline path: the cache-key contract (same key => same
    ParamTable slot layout) spans both, so they must never diverge.
    Constants ride ``pt`` — a changed literal is a program-cache HIT."""
    from ..ops.exprjit import compile_expr_params, stable_shape_key
    progs = []
    pk_parts = []
    for a in arg_exprs:
        if isinstance(a, tuple):
            progs.append(_count_mask_program(a[1]))
            pk_parts.append(f"mask@{a[1]}")
        elif a is None:
            progs.append(None)
            pk_parts.append("-")
        else:
            progs.append(compile_expr_params(a, pt))
            pk_parts.append(stable_shape_key(a))
    return progs, tuple(pk_parts)


def _composite_key_lanes(lkeys, lchk, rkeys, rchk):
    """Multi-key equi-join keys -> ONE int64 lane per side via JOINT
    factorization (np.unique over both sides' stacked key tuples):
    equal tuples get equal codes, distinct tuples distinct codes —
    collision-free for any value range, unlike stride composites.  A
    tuple with ANY NULL component never equi-matches (null mask OR).
    Returns ((lk, lnull), (rk, rnull)) host arrays for the single-key
    kernels."""
    def stack(keys, chk):
        pairs = [e.vec_eval(chk) for e in keys]
        vals = np.stack([np.asarray(v).astype(np.int64, copy=False)
                         for v, _ in pairs], axis=1)
        null = np.zeros(len(vals), dtype=bool)
        for _, m in pairs:
            null |= np.asarray(m)
        return vals, null
    lv, lnull = stack(lkeys, lchk)
    rv, rnull = stack(rkeys, rchk)
    both = np.concatenate([lv, rv], axis=0)
    _, inv = np.unique(both, axis=0, return_inverse=True)
    inv = np.asarray(inv, dtype=np.int64).ravel()
    return (inv[:len(lv)], lnull), (inv[len(lv):], rnull)


def _encode_key(e, chk: Chunk) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Evaluate a group/sort key over the chunk -> (codes, null, decode).
    Strings become order-preserving dictionary codes; decode maps code ->
    original value (None for numerics)."""
    v, null = e.vec_eval(chk)
    if v.dtype == object or v.dtype.kind == "U":
        safe = np.where(null, "", v)
        uniques, codes = np.unique(safe.astype(str), return_inverse=True)
        return codes.astype(np.int64), null, uniques
    if v.dtype == np.int64 and getattr(e.ret_type, "is_unsigned", False):
        # unsigned values live two's-complement-wrapped in the int64 buffer;
        # XOR with the sign bit maps unsigned order onto signed int64 order
        # (bijective, so it's equally valid as a group key)
        v = v ^ np.int64(-2**63)
    return v, null, None


class TPUHashAggExec(Executor):
    """Group-by as device segment-reduce (SURVEY §2.11 P5 TPU counterpart)."""

    def __init__(self, plan: PhysicalHashAgg, child: Executor):
        super().__init__(plan.schema, [child])
        self.plan = plan
        self._done = False

    def open(self, ctx):
        super().open(ctx)
        self._done = False

    def _raw_replica_input(self, compact: bool = True):
        """Fused fast path: the child is a TableReader serving from the
        columnar replica — take the FULL table as a zero-copy chunk view
        and turn the scan filters into a device-side valid mask, skipping
        chunk slicing, host compaction, and append copies entirely (the
        filter+aggregate fusion XLA is built for).  ``compact=False``
        (spill mode) keeps even selective filters as masks: the charged
        compaction copy is exactly the working set the quota is trying
        to bound, and the partitioned path selects live rows itself."""
        chk, mask, _rep = _take_replica_masked(self.children[0])
        if chk is None:
            return None, None
        # low-selectivity GROUPED aggregates sort faster over a compacted
        # input; scalar aggregates never sort, so they keep the fused mask
        if self.plan.group_by and compact:
            chk, mask = _compact_if_selective(chk, mask)
        return chk, mask

    @staticmethod
    def _try_segment_layout(keys, n: int):
        """If every group key has known small cardinality (dictionary codes
        for strings; narrow value range for ints), lay the keys out as one
        composite segment id.  Returns (gid, cards, bases) or None.  Each
        key gets one extra bin for NULL."""
        if n == 0:
            return None
        cards = []
        bases = []
        effs = []
        total = 1  # final value = the composite segment count
        for v, null, decode in keys:
            if decode is not None:
                card = len(decode)
                eff = np.where(null, card, v)
                base = 0
            elif v.dtype == np.int64:
                nn = v[~null]
                if len(nn) == 0:
                    card, base = 0, 0
                    eff = np.full(n, 0, dtype=np.int64)
                else:
                    vmin, vmax = int(nn.min()), int(nn.max())
                    card = vmax - vmin + 1
                    if card > kernels.seg_limit(n):
                        return None
                    base = vmin
                    eff = np.where(null, card, v - vmin)
            else:
                return None  # float keys: sort-based path
            total *= card + 1
            if total > kernels.seg_limit(n):
                return None
            cards.append(card)
            bases.append(base)
            effs.append(eff.astype(np.int64))
        gid = np.zeros(n, dtype=np.int64)
        for eff, card in zip(effs, cards):
            gid = gid * (card + 1) + eff
        return gid, cards, bases, total

    # ---- fully fused device path ------------------------------------------
    def _try_fused_device(self):
        """The flagship aggregation path: device-resident padded columns
        (memoized on the replica), ON-DEVICE argument evaluation via the
        exprjit lowering, host filter mask as the only per-query upload,
        one XLA program end to end.  Returns an output Chunk or None to
        fall back."""
        from .executors import TableReaderExec
        from ..ops.exprjit import is_jittable
        plan = self.plan
        child = self.children[0]
        if not isinstance(child, TableReaderExec):
            return None
        rep = getattr(child, "_replica", None)
        if rep is None or child.scan.pushed_agg is not None:
            return None
        from ..expression import Column as ExprColumn, Constant

        # ---- eligibility + spec/arg-program assembly --------------------
        specs: List[Tuple[str, bool]] = []
        arg_exprs: List = []      # jittable expr | ("mask", slot) | None
        slots: List[tuple] = []
        from ..expression.aggregation import AggMode
        for d in plan.aggs:
            if d.distinct:
                return None
            if d.mode is AggMode.FINAL and d.name == AGG_COUNT:
                a = d.args[0]
                if not is_jittable(a):
                    return None
                # sum0: merged COUNT is 0 over empty input, never NULL
                specs.append(("sum0", True))
                arg_exprs.append(a)
                slots.append(("dev", len(specs) - 1))
            elif d.mode is AggMode.FINAL and d.name == AGG_AVG:
                a0, a1 = d.args
                if not (is_jittable(a0) and is_jittable(a1)):
                    return None
                if a0.eval_type is not EvalType.REAL:
                    from ..expression.builtins import new_function
                    a0 = new_function("cast_real", [a0])
                specs.append(("sum", True))
                arg_exprs.append(a0)
                specs.append(("sum", True))
                arg_exprs.append(a1)
                slots.append(("avg", len(specs) - 2, len(specs) - 1))
            elif d.name == AGG_COUNT:
                a = d.args[0]
                if isinstance(a, Constant) and a.value is not None:
                    specs.append(("count_star", False))
                    arg_exprs.append(None)
                    slots.append(("dev", len(specs) - 1))
                elif isinstance(a, ExprColumn):
                    specs.append(("count", True))
                    arg_exprs.append(("mask", a.index))
                    slots.append(("dev", len(specs) - 1))
                elif is_jittable(a):
                    specs.append(("count", True))
                    arg_exprs.append(a)
                    slots.append(("dev", len(specs) - 1))
                else:
                    return None
            elif d.name == AGG_SUM:
                a = d.args[0]
                if not is_jittable(a):
                    return None
                if (d.ret_type.eval_type is EvalType.REAL
                        and a.eval_type is not EvalType.REAL):
                    from ..expression.builtins import new_function
                    a = new_function("cast_real", [a])
                specs.append(("sum", True))
                arg_exprs.append(a)
                slots.append(("dev", len(specs) - 1))
            elif d.name == AGG_AVG:
                a = d.args[0]
                if not is_jittable(a):
                    return None
                from ..expression.builtins import new_function
                ar = a if a.eval_type is EvalType.REAL \
                    else new_function("cast_real", [a])
                specs.append(("sum", True))
                arg_exprs.append(ar)
                specs.append(("count", True))
                arg_exprs.append(a)
                slots.append(("avg", len(specs) - 2, len(specs) - 1))
            elif d.name in (AGG_MAX, AGG_MIN):
                a = d.args[0]
                if not is_jittable(a):
                    return None
                if (a.eval_type is EvalType.INT
                        and getattr(a.ret_type, "is_unsigned", False)):
                    return None  # unsigned order map: sort path handles
                specs.append((("max" if d.name == AGG_MAX else "min"), True))
                arg_exprs.append(a)
                slots.append(("dev_mm", len(specs) - 1, False))
            elif d.name == AGG_FIRST_ROW:
                if not isinstance(d.args[0], ExprColumn):
                    return None
                slots.append(("first", d.args[0]))
            else:
                return None

        # group keys must be plain Columns (codes memoized on the replica)
        for e in plan.group_by:
            if not isinstance(e, ExprColumn):
                return None

        chk, filters, rep = child.take_raw_replica()
        if chk is None:
            return None  # nothing consumed: reader bails identically
        n = chk.full_rows()
        nb = kernels.bucket(max(n, 1))
        jn = kernels.jnp()
        # stable per-slot ids: replica memos are shared across queries with
        # different column pruning, so slot INDEXES must never key them
        slot_ids = [ci.id if ci is not None else "handle"
                    for ci in child._decode_cols]

        # ---- per-key codes (memoized per replica) -----------------------
        key_layouts = []
        for e in plan.group_by:
            lay = self._rep_key_codes(rep, e, chk, slot_ids[e.index])
            if lay is None:
                child._replica = rep  # un-consume for the fallback path
                return None
            key_layouts.append(lay)
        n_segments = 1
        for _, card, _, _ in key_layouts:
            n_segments *= card + 1
        if n_segments > kernels.seg_limit(n) and plan.group_by:
            child._replica = rep
            return None

        # ---- block-wise execution (SURVEY §5.7): tables above the device
        # buffer budget stream through HBM in row blocks; partial states
        # carry on host between blocks
        budget = _block_budget(self.ctx.session_vars)
        if budget > 0 and n > budget:
            out = self._fused_blockwise(chk, rep, child, filters,
                                        specs, arg_exprs, slots,
                                        key_layouts, n_segments, n, budget)
            if out is not None:
                return out
            child._replica = rep
            return None

        # ---- CPU-backend host twin for SCATTER-BOUND group-bys: above
        # SEG_UNROLL segments the device kernel scatter-adds, which
        # XLA:CPU runs serially, while np.bincount with REPLICA-MEMOIZED
        # argument columns is the host-optimal kernel.  Below that
        # threshold the fused device program still wins ON THIS BACKEND
        # (measured: Q1 0.73s fused vs 1.47s host — its on-device
        # args/mask avoid numpy's materialized temporaries; PROFILE.md
        # §6).  Runs BEFORE the device-mask build so the twin never pays
        # for a device filter program it would discard.
        if (plan.group_by and n_segments > kernels.SEG_UNROLL
                and kernels.host_kernels_ok()
                and self._mesh_if_enabled(nb) is None
                and self._host_groupby_ok(specs, slots, arg_exprs)):
            out = self._fused_host_groupby(chk, child, rep, filters,
                                           specs, arg_exprs, slots,
                                           key_layouts, n_segments, n,
                                           slot_ids)
            if out is not None:
                return out

        # ---- filter mask: on-device program when every condition lowers
        # (constants as runtime params — zero recompiles across constant
        # changes, ~100-byte upload); host numpy + nb-bool upload otherwise.
        # ONE ParamTable serves the mask AND the aggregate arguments: the
        # whole fused program's constants ride a single runtime vector.
        from ..ops.exprjit import ParamTable
        pt = ParamTable()
        dev_mask = _build_device_mask(child, rep, chk, filters, pt)
        if dev_mask is None:
            pt = ParamTable()  # discard half-consumed mask slots
            fmask = _fold_filter_masks(child, rep, chk, filters) \
                if filters else None
            mask_needed = set()
        else:
            mask_fn, mask_prog_key, mask_needed = dev_mask
            fmask = None

        # ---- device columns (memoized per replica + bucket) -------------
        needed = set(mask_needed)
        for a in arg_exprs:
            if isinstance(a, tuple):
                needed.add((a[1], "mask"))
            elif a is not None:
                for c in a.collect_columns():
                    needed.add((c.index, "full"))
        dev_cols = [None] * len(chk.columns)
        for idx, kind in needed:
            col = chk.columns[idx]
            v = col.values()
            m = col.null_mask()
            sid = slot_ids[idx]
            if kind == "codes":
                # string filter rides dictionary codes; the value half of
                # the slot carries the code column
                got = _rep_string_dict(rep, sid, chk, idx)
                codes = got[0]
                dv = rep.memo(("devcodes", sid, nb),
                              lambda c=codes: kernels.h2d_pad(c, nb))
            elif v.dtype == object or v.dtype.kind == "U":
                if kind == "full":
                    child._replica = rep
                    return None  # string values in a compute expr
                dv = None
            else:
                dv = rep.memo(("devv", sid, nb),
                              lambda v=v: kernels.h2d_pad(v, nb))
            dn = rep.memo(("devn", sid, nb),
                          lambda m=m: kernels.h2d_pad(m, nb, True))
            if dev_cols[idx] is None or dv is not None:
                dev_cols[idx] = (dv, dn)

        # aggregate-argument programs: params-compiled against the SAME
        # ParamTable as the mask, program cache keyed by expression SHAPE
        progs, program_key = _lower_agg_args(arg_exprs, pt)
        params = pt.arrays()

        # ---- mask spec for the kernels ----------------------------------
        if dev_mask is not None:
            mask_spec = ("dev", mask_fn, mask_prog_key)
        else:
            mask = np.zeros(nb, dtype=bool)
            mask[:n] = fmask if fmask is not None else True
            mask_spec = ("host", kernels.h2d(mask))

        # ---- run --------------------------------------------------------
        if not plan.group_by:
            out_keys = []
            mesh = self._mesh_if_enabled(nb)
            if mesh is not None:
                # partial->final over the mesh, and STILL batchable: the
                # stacked variant vmaps B queries over the N-shard
                # program (B x N in one dispatch)
                from ..ops import shardops
                out_aggs, first_orig = \
                    shardops.fused_scalar_aggregate_sharded(
                        mesh, dev_cols, specs, progs, n, nb, mask_spec,
                        program_key=program_key, params=params,
                        batchable=True)
            else:
                # batchable: THE single-shot dispatch cross-query
                # micro-batching coalesces (ops/batching.py) — blockwise
                # / passthrough variants stay solo
                out_aggs, first_orig = kernels.fused_scalar_aggregate(
                    dev_cols, specs, progs, n, nb, mask_spec,
                    program_key=program_key, params=params,
                    batchable=True)
        else:
            gid_dev = rep.memo(
                ("gid_dev", tuple(slot_ids[e.index]
                                  for e in plan.group_by), nb),
                lambda: kernels.h2d_pad(
                    self._compose_gid(key_layouts, n), nb))
            mesh = self._mesh_if_enabled(nb)
            if mesh is not None:
                present, out_aggs, first_orig = \
                    kernels.fused_segment_aggregate_sharded(
                        mesh, dev_cols, gid_dev, n_segments, specs, progs,
                        n, mask_spec, program_key=program_key,
                        params=params)
            elif self._can_device_passthrough(plan, slots, key_layouts) \
                    and not _batching_active():
                # a live batch round prefers the batchable fused path
                # below: members must park (collect) and consume
                # (replay) along the SAME route, and the keep variant's
                # per-member device assembly cannot ride a stacked
                # dispatch
                ids, live, out_aggs_d, np_, ob = \
                    kernels.fused_segment_aggregate_keep(
                        dev_cols, gid_dev, n_segments, specs, progs,
                        mask_spec, program_key=program_key, params=params)
                return self._assemble_device_output(
                    plan, slots, key_layouts, ids, live, out_aggs_d, np_)
            else:
                present, out_aggs, first_orig = \
                    kernels.fused_segment_aggregate(
                        dev_cols, gid_dev, n_segments, specs, progs, n,
                        mask_spec, program_key=program_key, params=params,
                        batchable=True)
            out_keys = self._decode_present(present, key_layouts)
        return self._assemble_output(chk, plan, slots, out_keys, out_aggs,
                                     first_orig,
                                     [l[3] for l in key_layouts])

    def _fused_blockwise(self, chk, rep, child, filters, specs,
                         arg_exprs, slots, key_layouts, n_segments: int,
                         n: int, budget: int, fmask=None):
        """Block-wise fused aggregation (SURVEY §5.7 long-context
        analogue; reference chunked iteration + RequiredRows): row blocks
        of `budget` upload transiently (NOT replica-memoized — the whole
        point is the table does not fit), the fused segment/scalar kernel
        reduces each block on device, and per-segment partial states
        (sum/count add, min/max fold, first-row min, presence union)
        carry on host between blocks — the aggregate's partial/final mode
        split applied across TIME instead of across workers.

        PIPELINED: block staging (slice + pad + H2D enqueue) runs on the
        BlockPipeline thread while the device reduces the previous block
        and the main thread folds its partials — host work and device
        work overlap instead of alternating (tidb_pipeline_depth /
        TINYSQL_PIPELINE_DEPTH=0 restores the serial order; the fold
        order is block order either way, so results are identical)."""
        from ..ops.exprjit import ParamTable
        from .devpipe import BlockPipeline, pipeline_depth
        jn = kernels.jnp()
        # host filter mask over the full table; reuse the caller's when
        # it already folded one (the dev-mask path leaves it None)
        if fmask is None and filters:
            fmask = _fold_filter_masks(child, rep, chk, filters)
        # argument programs: params-compiled so a changed literal reuses
        # the block kernel
        pt = ParamTable()
        progs, program_key = _lower_agg_args(arg_exprs, pt)
        params = pt.arrays()
        needed = set()
        for a in arg_exprs:
            if isinstance(a, tuple):
                needed.add((a[1], "mask"))
            elif a is not None:
                for c in a.collect_columns():
                    needed.add((c.index, "full"))
        # eligibility BEFORE the pipeline spins up: a string column in a
        # compute expression bails the whole path, never a single block
        for idx, kind in needed:
            v = chk.columns[idx].values()
            if kind == "full" and (v.dtype == object or v.dtype.kind == "U"):
                return None
        gid_full = self._compose_gid(key_layouts, n) if key_layouts \
            else None
        ns = n_segments if key_layouts else 1
        bb = kernels.bucket(budget)
        seen = np.zeros(ns, dtype=bool)
        first_acc = np.full(ns, np.iinfo(np.int64).max, dtype=np.int64)
        acc: list = [None] * len(specs)

        def ensure_acc(i, kind, dtype):
            if acc[i] is not None:
                return acc[i]
            if kind in ("count_star", "count", "sum", "sum0"):
                av = np.zeros(ns, dtype=dtype)
            elif kind == "min":
                av = np.full(ns, np.inf if dtype == np.float64
                             else np.iinfo(np.int64).max, dtype=dtype)
            else:
                av = np.full(ns, -np.inf if dtype == np.float64
                             else np.iinfo(np.int64).min, dtype=dtype)
            acc[i] = (av, np.ones(ns, dtype=bool))
            return acc[i]

        def stage(start):
            """Host half of one block: slice, pad, ENQUEUE the uploads.
            Runs on the pipeline thread while the device reduces the
            previous block (no host syncs here — qlint TS106)."""
            end = min(start + budget, n)
            m_rows = end - start
            dev_cols = [None] * len(chk.columns)
            for idx, kind in needed:
                col = chk.columns[idx]
                v = col.values()
                m_ = col.null_mask()
                if v.dtype == object or v.dtype.kind == "U":
                    dv = None  # mask-only slot (COUNT over a string col)
                else:
                    dv = kernels.h2d_pad(v[start:end], bb)
                dn = kernels.h2d_pad(m_[start:end], bb, True)
                if dev_cols[idx] is None or dv is not None:
                    dev_cols[idx] = (dv, dn)
            bmask = np.zeros(bb, dtype=bool)
            bmask[:m_rows] = fmask[start:end] if fmask is not None \
                else True
            mask_spec = ("host", kernels.h2d(bmask))
            gid_b = kernels.h2d_pad(gid_full[start:end], bb) \
                if key_layouts else None
            return start, m_rows, dev_cols, mask_spec, gid_b

        t_pipe = time.time()
        dispatch_s = drain_s = 0.0
        pipe = BlockPipeline(stage, range(0, n, budget),
                             depth=pipeline_depth(self.ctx.session_vars))
        for start, m_rows, dev_cols, mask_spec, gid_b in pipe:
            t0 = time.time()
            if key_layouts:
                present, outs, first = kernels.fused_segment_aggregate(
                    dev_cols, gid_b, ns, specs, progs, m_rows, mask_spec,
                    program_key=program_key, params=params)
            else:
                # scalar contract (_unpack_scalar_agg): zero-or-one-row
                # arrays; an empty block contributes nothing
                outs, first = kernels.fused_scalar_aggregate(
                    dev_cols, specs, progs, m_rows, bb, mask_spec,
                    program_key=program_key, params=params)
                present = np.zeros(len(first), dtype=np.int64)
                outs = [(np.asarray(v_), np.asarray(m_))
                        for v_, m_ in outs]
            dispatch_s += time.time() - t0
            t0 = time.time()
            if len(present) == 0:
                continue
            seen[present] = True
            first_acc[present] = np.minimum(first_acc[present],
                                            np.asarray(first) + start)
            for i, ((v_, m_), (kind, _)) in enumerate(zip(outs, specs)):
                v_ = np.asarray(v_)
                m_ = np.asarray(m_)
                live = ~m_
                if not live.any():
                    continue
                av, am = ensure_acc(i, kind, v_.dtype)
                ids = np.asarray(present)[live]
                vv = v_[live]
                if kind in ("count_star", "count", "sum", "sum0"):
                    av[ids] += vv
                elif kind == "min":
                    av[ids] = np.minimum(av[ids], vv)
                else:
                    av[ids] = np.maximum(av[ids], vv)
                am[ids] = False
            drain_s += time.time() - t0
        ps = pipe.stats()
        kernels.pipe_record(blocks=ps["blocks"], stage_s=ps["stage_s"],
                            dispatch_s=dispatch_s, drain_s=drain_s,
                            wall_s=time.time() - t_pipe,
                            depth_hwm=ps["depth_hwm"])
        if self.plan.group_by:
            present_ids = np.nonzero(seen)[0]
        else:
            # a scalar aggregate over zero rows still returns one row
            # (COUNT=0, SUM=NULL)
            present_ids = np.arange(1)
            if not seen[0]:
                first_acc[0] = 0
        out_aggs = []
        for i, (kind, _) in enumerate(specs):
            if acc[i] is None:
                dt = np.int64 if kind != "sum" else np.float64
                av = np.zeros(ns, dtype=dt)
                am = np.ones(ns, dtype=bool)
                if kind in ("count_star", "count", "sum0"):
                    am = np.zeros(ns, dtype=bool)  # COUNT of nothing = 0
                acc[i] = (av, am)
            av, am = acc[i]
            if kind in ("count_star", "count", "sum0"):
                am = np.zeros_like(am)  # counts are never NULL
            out_aggs.append((av[present_ids], am[present_ids]))
        out_keys = self._decode_present(present_ids, key_layouts) \
            if key_layouts else []
        first_orig = np.where(
            first_acc[present_ids] == np.iinfo(np.int64).max, 0,
            first_acc[present_ids])
        return self._assemble_output(chk, self.plan, slots, out_keys,
                                     out_aggs, first_orig,
                                     [l[3] for l in key_layouts])

    def _mesh_if_enabled(self, nb: int):
        """Multi-chip mesh for the sharded aggregate when the session asks
        for it (SET @@tidb_mesh_parallel = 1) and the bucket divides over
        the devices (power-of-two buckets over power-of-two meshes)."""
        return _mesh_for(self.ctx, nb, self.plan)

    @staticmethod
    def _rep_key_codes(rep, e, chk, slot_id):
        """(codes np[int64], card, base, decode) memoized on the replica,
        keyed by the column's stable id (NOT the query-local offset)."""
        idx = e.index
        col = chk.columns[idx]
        v = col.values()
        null = col.null_mask()
        is_string = v.dtype == object or v.dtype.kind == "U"
        uns = (not is_string and v.dtype == np.int64
               and getattr(e.ret_type, "is_unsigned", False))
        if is_string:
            # shared with the filter rewrite: one dictionary per column
            return _rep_string_dict(rep, slot_id, chk, idx)

        def build():
            w = (v ^ np.int64(-2**63)) if uns else v
            if w.dtype != np.int64:
                return None
            nn = w[~null]
            if len(nn) == 0:
                return (np.zeros(len(w), dtype=np.int64), 0, 0, None)
            vmin, vmax = int(nn.min()), int(nn.max())
            card = vmax - vmin + 1
            if card > kernels.seg_limit(len(w)):
                return None
            codes = np.where(null, card, w - vmin).astype(np.int64)
            return codes, card, vmin, None
        return rep.memo(("keycodes", slot_id, is_string, uns), build)

    @staticmethod
    def _compose_gid(key_layouts, n: int) -> np.ndarray:
        gid = np.zeros(n, dtype=np.int64)
        for codes, card, _, _ in key_layouts:
            gid = gid * (card + 1) + codes
        return gid

    @staticmethod
    def _decode_present(present, key_layouts):
        out_keys = []
        strides = []
        s = 1
        for _, card, _, _ in reversed(key_layouts):
            strides.append(s)
            s *= card + 1
        strides.reverse()
        for (codes, card, base, decode), stride in zip(key_layouts, strides):
            code = (present // stride) % (card + 1)
            is_null = code == card
            vals = np.where(is_null, 0, code + base)
            out_keys.append((vals.astype(np.int64), is_null))
        return out_keys

    def next(self) -> Optional[Chunk]:
        if self._done:
            return None
        self._done = True
        plan = self.plan
        # memory-adaptive aggregation: under spill pressure the fused
        # whole-table paths step aside and the generic path below runs
        # its partitioned spill route (grouped aggregates only — scalar
        # aggregate state is O(1) and never worth spilling)
        sctx = None
        if plan.group_by:
            # per-row partition payload: gid + rid + each arg's
            # (value, null) pair
            row_bytes = 16 + sum(9 for _ in plan.aggs) * 2
            sctx = _maybe_spill_ctx(self.ctx,
                                    _est_rows_of(plan.children[0]), 0,
                                    row_bytes, "agg")
        if sctx is None:
            fused = self._try_fused_device()
            if fused is not None:
                return fused
        chk, filter_mask = self._raw_replica_input(compact=sctx is None)
        if chk is None:
            soft = sctx is not None
            chk = _drain_chunk(self.children[0],
                               self.children[0].field_types(), soft=soft)
            if soft:
                from ..utils import memory as _memory
                with _memory.soft_scope():
                    chk = chk.compact()
            else:
                chk = chk.compact()
        n = chk.full_rows()

        # ---- keys (dictionary-encode strings) -------------------------
        keys = [_encode_key(e, chk) for e in plan.group_by]
        key_cols = [(v, m) for v, m, _ in keys]

        # ---- agg specs --------------------------------------------------
        # device does count/sum/min/max; avg = sum+count pair;
        # first_row is gathered host-side by representative row id
        specs: List[Tuple[str, bool]] = []
        arg_cols: List[Tuple[np.ndarray, np.ndarray]] = []
        slots: List[tuple] = []  # how to produce each desc's result

        def add_arg(e, cast_real=False, order_map=False,
                    null_only=False) -> bool:
            """Returns True when the arg was XOR-sign-bit mapped (unsigned
            min/max ordering) so the caller can un-map the result."""
            v, m = e.vec_eval(chk)
            if null_only or v.dtype == object or v.dtype.kind == "U":
                # COUNT only consumes the null mask; string values (and any
                # non-numeric dtype) must not reach the device
                v = np.zeros(len(m), dtype=np.int64)
            uns = (e.eval_type is EvalType.INT
                   and getattr(e.ret_type, "is_unsigned", False))
            was_mapped = False
            if cast_real and v.dtype != np.float64:
                r = v.astype(np.float64)
                if uns and v.dtype == np.int64:
                    # unwrap wrapped uint64 into its real value
                    r = np.where(v < 0, r + 2.0**64, r)
                v = r
            elif order_map and uns and v.dtype == np.int64:
                # min/max compare on device: XOR maps unsigned order onto
                # signed int64 order; un-mapped in agg_result
                v = v ^ np.int64(-2**63)
                was_mapped = True
            arg_cols.append((v, m))
            return was_mapped

        from ..expression.aggregation import AggMode
        for d in plan.aggs:
            # FINAL mode merges PARTIAL states (agg pushdown through join):
            # count partials SUM; avg partials are a (sum, count) column
            # pair; sum/min/max/first_row merge with their own op
            if d.mode is AggMode.FINAL and d.name == AGG_COUNT:
                specs.append(("sum0", True))  # merged COUNT: 0, not NULL
                add_arg(d.args[0])
                slots.append(("dev", len(specs) - 1))
            elif d.mode is AggMode.FINAL and d.name == AGG_AVG:
                specs.append(("sum", True))
                add_arg(d.args[0], cast_real=True)
                specs.append(("sum", True))
                add_arg(d.args[1])
                slots.append(("avg", len(specs) - 2, len(specs) - 1))
            elif d.name == AGG_COUNT:
                from ..expression import Constant
                a = d.args[0]
                if isinstance(a, Constant) and a.value is not None:
                    specs.append(("count_star", False))
                    slots.append(("dev", len(specs) - 1))
                else:
                    specs.append(("count", True))
                    add_arg(a, null_only=True)
                    slots.append(("dev", len(specs) - 1))
            elif d.name == AGG_SUM:
                specs.append(("sum", True))
                add_arg(d.args[0],
                        cast_real=d.ret_type.eval_type is EvalType.REAL)
                slots.append(("dev", len(specs) - 1))
            elif d.name == AGG_AVG:
                specs.append(("sum", True))
                add_arg(d.args[0], cast_real=True)
                specs.append(("count", True))
                add_arg(d.args[0], null_only=True)
                slots.append(("avg", len(specs) - 2, len(specs) - 1))
            elif d.name in (AGG_MAX, AGG_MIN):
                specs.append((("max" if d.name == AGG_MAX else "min"), True))
                was_mapped = add_arg(d.args[0], order_map=True)
                slots.append(("dev_mm", len(specs) - 1, was_mapped))
            elif d.name == AGG_FIRST_ROW:
                slots.append(("first", d.args[0]))
            else:  # pragma: no cover — enforcer gates
                raise ValueError(d.name)

        if not plan.group_by:
            # global aggregate: sort-free masked reductions (sctx is
            # only ever opened under plan.group_by)
            out_keys = []
            out_aggs, first_orig = kernels.scalar_aggregate(
                specs, arg_cols, n, filter_mask=filter_mask)
        else:
            seg = self._try_segment_layout(keys, n)
            if seg is not None:
                # known small cardinality: sort-free segment reductions
                gid, cards, bases, n_segments = seg
                if sctx is None:
                    # reactive re-check: materializing the input above
                    # may have crossed the watermark after the early
                    # (pre-materialization) decision said no
                    sctx = _maybe_spill_ctx(
                        self.ctx, _est_rows_of(plan.children[0]), n,
                        16 + 18 * len(arg_cols), "agg")
                if sctx is not None:
                    # partitioned partial aggregation: groups hash to
                    # partitions whole, partials merge at drain —
                    # per-group accumulation order (and float sums) are
                    # exactly the unpartitioned kernel's
                    from ..ops import spill
                    with sctx:
                        present, out_aggs, first_orig = \
                            spill.partitioned_segment_aggregate(
                                sctx, gid, n_segments, specs, arg_cols,
                                n, filter_mask=filter_mask)
                    sctx = None
                else:
                    present, out_aggs, first_orig = \
                        kernels.segment_group_aggregate(
                            gid, n_segments, specs, arg_cols, n,
                            filter_mask=filter_mask)
                out_keys = []
                strides = []
                s = 1
                for c in reversed(cards):
                    strides.append(s)
                    s *= c + 1
                strides.reverse()
                for i, (c, base) in enumerate(zip(cards, bases)):
                    code = (present // strides[i]) % (c + 1)
                    is_null = code == c
                    vals = np.where(is_null, 0, code + base)
                    out_keys.append((vals.astype(np.int64), is_null))
            else:
                # sort-based grouping (float keys / huge cardinality):
                # no partitioned route — release the unused spill scope
                if sctx is not None:
                    sctx.close()
                out_keys, out_aggs, first_orig = kernels.group_aggregate(
                    key_cols, specs, arg_cols, n, filter_mask=filter_mask)
        return self._assemble_output(chk, plan, slots, out_keys, out_aggs,
                                     first_orig, [d for _, _, d in keys])

    @staticmethod
    def _host_groupby_ok(specs, slots, arg_exprs) -> bool:
        """Host-twin eligibility: bincount-able specs only (min/max need
        ufunc.at, which loses to the device kernel), no first_row
        gathers, and no exact int64 SUMs (float64 accumulation caps at
        the 2^53 mantissa) — checked UPFRONT so an ineligible query
        never pays O(n) twin work before bailing."""
        for (kind, _), a in zip(specs, arg_exprs):
            if kind not in ("sum", "sum0", "count", "count_star"):
                return False
            if (kind == "sum" and a is not None
                    and not isinstance(a, tuple)
                    and a.eval_type is EvalType.INT):
                return False
        return all(sl[0] != "first" for sl in slots)

    @staticmethod
    def _host_arg_key(a, slot_ids) -> tuple:
        """Replica-memo key for an argument expression: the shape key
        plus the STABLE column ids its offsets refer to — replicas are
        shared across queries with different column pruning, so the
        offsets inside stable_key alone would collide (the slot-id
        invariant at the top of _try_fused_device)."""
        from ..ops.exprjit import stable_key
        cols = sorted({c.index for c in a.collect_columns()})
        return ("hostarg", stable_key(a),
                tuple((i, slot_ids[i]) for i in cols))

    def _fused_host_groupby(self, chk, child, rep, filters, specs,
                            arg_exprs, slots, key_layouts,
                            n_segments: int, n: int, slot_ids):
        """numpy twin of the scatter-bound fused segment aggregate (CPU
        backend): host filter mask + replica-MEMOIZED argument columns +
        np.bincount per spec over the composite group ids.  Returns an
        output chunk, or None to fall back to the device kernels."""
        fmask = _fold_filter_masks(child, rep, chk, filters) \
            if filters else None
        gid = key_layouts[0][0] if len(key_layouts) == 1 else rep.memo(
            ("gid_host", tuple(slot_ids[e.index]
                               for e in self.plan.group_by)),
            lambda: self._compose_gid(key_layouts, n))
        ns = n_segments
        kernels.host_dispatch()  # the twin IS the kernel on this backend
        g_valid = gid if fmask is None else gid[fmask]
        presence = np.bincount(g_valid, minlength=ns)
        present = np.nonzero(presence > 0)[0]
        out_aggs = []
        for (kind, _has_arg), a in zip(specs, arg_exprs):
            if kind == "count_star":
                out_aggs.append((presence[present].astype(np.int64),
                                 np.zeros(len(present), dtype=bool)))
                continue
            if isinstance(a, tuple):  # ("mask", slot): COUNT(col)
                m = chk.columns[a[1]].null_mask()
                vals = None
            else:
                # memoized per (replica version, expression shape, the
                # STABLE ids of its columns): the twin's economics depend
                # on never re-evaluating args per query
                vals, m = rep.memo(self._host_arg_key(a, slot_ids),
                                   lambda a=a: a.vec_eval(chk))
            live = ~np.asarray(m, dtype=bool)
            if fmask is not None:
                live = live & fmask
            gl = gid[live]
            if kind == "count":
                c = np.bincount(gl, minlength=ns)
                out_aggs.append((c[present].astype(np.int64),
                                 np.zeros(len(present), dtype=bool)))
                continue
            # sum / sum0: float64 accumulation — exact for counts and
            # doubles (int64 SUMs were rejected upfront by the gate)
            v = np.asarray(vals)[live]
            if v.dtype != np.float64:
                v = v.astype(np.float64)
            ssum = np.bincount(gl, weights=v, minlength=ns)
            if kind == "sum0":  # merged COUNT: 0 over empty, never NULL
                out_aggs.append((ssum[present].astype(np.int64),
                                 np.zeros(len(present), dtype=bool)))
            else:
                c = np.bincount(gl, minlength=ns)
                out_aggs.append((ssum[present], (c == 0)[present]))
        out_keys = self._decode_present(present, key_layouts)
        first_orig = np.zeros(len(present), dtype=np.int64)
        return self._assemble_output(chk, self.plan, slots, out_keys,
                                     out_aggs, first_orig,
                                     [l[3] for l in key_layouts])

    def _can_device_passthrough(self, plan, slots, key_layouts) -> bool:
        """Late-materialization gate (VERDICT r4 next-2): the aggregate's
        output chunk stays device-resident (DeviceColumn) when every
        output can be produced by traced ops — numeric group keys without
        a string decode table or unsigned order-map, and dev/avg/min-max
        slots (first_row gathers host-side by representative row)."""
        if not plan.group_by:
            return False
        try:
            if int(self.ctx.session_vars.get(
                    "tidb_device_passthrough", 1) or 0) == 0:
                return False
        except Exception:
            pass
        for sl in slots:
            if sl[0] == "dev" or sl[0] == "avg":
                continue
            if sl[0] == "dev_mm" and not sl[2]:
                continue
            return False
        for lay, e in zip(key_layouts, plan.group_by):
            if lay[3] is not None:  # string dictionary decode
                return False
            if getattr(e.ret_type, "is_unsigned", False):
                return False
        return True

    def _assemble_device_output(self, plan, slots, key_layouts, ids, live,
                                out_aggs, np_):
        """Device-resident output chunk: ONE jitted program decodes group
        ids back to key values and finishes the slots (avg divide, REAL
        cast), producing bucket-padded (values, null) pairs wrapped as
        DeviceColumns.  Nothing lands on host until a host consumer asks
        (a device join above consumes the pairs directly)."""
        from ..chunk import DeviceColumn
        jn = kernels.jnp()
        ob = int(ids.shape[0])
        strides = []
        s = 1
        for _, card, _, _ in reversed(key_layouts):
            strides.append(s)
            s *= card + 1
        strides.reverse()
        # (card, base, stride) per key ride as RUNTIME params — stats
        # shifts (inserts widening a key's min/max) must not recompile
        # the decode kernel (same rule as the device-mask params)
        lay = np.array([(card, base, stride)
                        for (_, card, base, _), stride
                        in zip(key_layouts, strides)], dtype=np.int64)
        slot_sig = []
        for src, idx in plan.output_map:
            if src == "agg":
                sl = slots[idx]
                real = (plan.aggs[idx].ret_type.eval_type
                        is EvalType.REAL)
                slot_sig.append((sl[0], sl[1],
                                 sl[2] if sl[0] == "avg" else None, real))
            else:
                slot_sig.append(("gb", idx, None, False))
        key = ("devout", ob, len(key_layouts), tuple(slot_sig),
               tuple(str(v.dtype) for v, _ in out_aggs))

        def build():
            def kernel(ids_in, live_in, aggs, lay_in):
                outs = []
                for kind, i, extra, real in slot_sig:
                    if kind == "gb":
                        card = lay_in[i, 0]
                        base = lay_in[i, 1]
                        stride = lay_in[i, 2]
                        code = (ids_in // stride) % (card + 1)
                        nullk = (code == card) | ~live_in
                        outs.append((jn.where(nullk, 0, code + base),
                                     nullk))
                    elif kind == "avg":
                        sv, sm = aggs[i]
                        cv, _ = aggs[extra]
                        outs.append((sv / jn.maximum(cv, 1),
                                     sm | (cv == 0)))
                    else:  # dev / dev_mm (unsigned excluded by the gate)
                        v, m = aggs[i]
                        if real and v.dtype != jn.float64:
                            v = v.astype(jn.float64)
                        outs.append((v, m))
                return outs
            return kernels.counted_jit(kernel)
        fn = progcache.get(key, build)
        outs = fn(ids, live, list(out_aggs), kernels.h2d(lay))
        cols = []
        for (src, idx), (v, m) in zip(plan.output_map, outs):
            ft = (plan.aggs[idx].ret_type if src == "agg"
                  else plan.group_by[idx].ret_type)
            col = DeviceColumn(ft, v, m, np_)
            if src == "gb" and len(key_layouts) == 1:
                # single-key groups: present ids ascend, and id = code =
                # value - base, so live non-null key values ascend — a
                # join building on this column skips its sort
                col.sorted_live = True
            cols.append(col)
        return Chunk.from_columns(cols)

    def _assemble_output(self, chk, plan, slots, out_keys, out_aggs,
                         first_orig, decodes):
        """Materialize the output chunk from kernel results (shared by the
        fused, segment, scalar, and sort-based aggregation paths)."""
        ng = len(first_orig)

        # empty input + no GROUP BY: single default row (COUNT=0, SUM=NULL)
        if ng == 0 and not plan.group_by:
            from .aggfuncs import new_state
            out = Chunk(self.field_types(), cap=1)
            states = [new_state(d) for d in plan.aggs]
            row = []
            for src, idx in plan.output_map:
                row.append(states[idx].result() if src == "agg" else None)
            out.append_row(row)
            return out

        def agg_result(i: int) -> CCol:
            d = plan.aggs[i]
            slot = slots[i]
            if slot[0] in ("dev", "dev_mm"):
                v, m = out_aggs[slot[1]]
                if slot[0] == "dev_mm" and slot[2]:
                    v = v ^ np.int64(-2**63)  # undo unsigned order map
                if d.ret_type.eval_type is EvalType.REAL and v.dtype != np.float64:
                    v = v.astype(np.float64)
                return CCol.from_numpy(d.ret_type, v, m)
            if slot[0] == "avg":
                sv, sm = out_aggs[slot[1]]
                cv, _ = out_aggs[slot[2]]
                cnt = np.maximum(cv, 1)
                return CCol.from_numpy(d.ret_type, sv / cnt, sm | (cv == 0))
            # first_row: gather by representative row id (any type)
            col_expr = slot[1]
            v, m = col_expr.vec_eval(chk)
            return CCol.from_numpy(d.ret_type, v[first_orig], m[first_orig])

        def gb_result(i: int) -> CCol:
            decode = decodes[i]
            e = plan.group_by[i]
            if decode is not None:
                vals = np.empty(ng, dtype=object)
                kvals = out_keys[i][0]
                for r in range(ng):
                    vals[r] = str(decode[kvals[r]])  # np.str_ -> str
                return CCol.from_numpy(e.ret_type, vals, out_keys[i][1])
            kv, km = out_keys[i]
            if (kv.dtype == np.int64 and e.eval_type is EvalType.INT
                    and getattr(e.ret_type, "is_unsigned", False)):
                kv = kv ^ np.int64(-2**63)  # undo the unsigned order map
            return CCol.from_numpy(e.ret_type, kv, km)

        cols = []
        for src, idx in plan.output_map:
            cols.append(agg_result(idx) if src == "agg" else gb_result(idx))
        return Chunk.from_columns(cols)


class TPUHashJoinExec(Executor):
    """Equi-join as device sort + searchsorted + expansion (SURVEY §2.11 P4
    TPU counterpart: build via sorted scatter, probe via gather)."""

    def __init__(self, plan: PhysicalHashJoin, left: Executor, right: Executor):
        super().__init__(plan.schema, [left, right])
        self.plan = plan
        self._done = False

    def open(self, ctx):
        super().open(ctx)
        self._done = False

    def _side_input(self, i: int, side_conds, compact: bool = True):
        """(chunk, mask, replica): replica-backed readers keep RAW rows
        with scan and side filters folded into a mask; other children
        materialize compacted with side conds applied.  ``compact=False``
        (spill mode) keeps selective filters as masks — the partitioned
        match takes validity masks directly, and the compaction copy is
        charged working set the quota is trying to bound."""
        ex = self.children[i]
        chk, mask, rep = _take_replica_masked(ex, side_conds)
        if chk is not None:
            if compact:
                chk, mask = _compact_if_selective(chk, mask)
            return chk, mask, (rep if mask is not None else None)
        # compact=False == spill mode: this materialization is the very
        # transient the partitioner is about to take over, so its copies
        # charge soft (a cold scan larger than the quota must not die
        # before the spill layer sees a single row)
        chk = _child_input(ex, soft=not compact)
        if side_conds:
            m = vectorized_filter(side_conds, chk)
            chk.set_sel(np.nonzero(m)[0])
            if compact:
                chk = chk.compact()
            else:
                from ..utils import memory as _memory
                with _memory.soft_scope():
                    chk = chk.compact()
        return chk, None, None

    def next(self) -> Optional[Chunk]:
        if self._done:
            return None
        self._done = True
        plan = self.plan
        if plan.tp in ("semi", "anti"):
            return self._semi_next()
        outer = plan.tp == "left"
        # Outer join: ON-clause left conds decide MATCHING (failing outer
        # rows null-extend), so they must NOT fold into lvalid (the kernel
        # drops invalid rows).  Instead poison the key null-mask: a NULL
        # key matches nothing, and the outer path emits unmatched valid
        # rows once with right index -1.
        on_left = plan.left_conditions if outer else []
        right_unique = getattr(plan, "right_unique", False)
        left_unique = getattr(plan, "left_unique", False)
        probe_side = 1 if (left_unique and plan.tp == "inner"
                           and not right_unique) else 0
        # memory-adaptive spill decision BEFORE materializing the sides:
        # in spill mode selective filters stay masks over zero-copy
        # replica views instead of charged compaction copies.  The
        # estimate prices BOTH sides (the join materializes both)
        est = _est_rows_of(plan.children[0]) + _est_rows_of(
            plan.children[1])
        sctx = _maybe_spill_ctx(self.ctx, est, 0, _JOIN_ROW_BYTES,
                                "join")
        lchk, lmask, lrep = self._side_input(
            0, [] if on_left else plan.left_conditions,
            compact=sctx is None)
        rchk, rmask, rrep = self._side_input(
            1, plan.right_conditions, compact=sctx is None)
        if sctx is None:
            # reactive re-check: materialization may have crossed the
            # watermark the early (estimate-driven) decision missed
            sctx = _maybe_spill_ctx(
                self.ctx, est,
                lchk.full_rows() + rchk.full_rows(),
                _JOIN_ROW_BYTES, "join")
        # block-wise probe streaming (SURVEY §5.7; VERDICT r4 next-3):
        # when the PROBE side exceeds tidb_device_block_rows, its key
        # column uploads transiently per block against the resident build
        # structure — the table never becomes fully device-resident
        budget = _block_budget(self.ctx.session_vars)
        probe_chk = lchk if probe_side == 0 else rchk
        stream = (budget > 0 and probe_chk.full_rows() > budget
                  and sctx is None)

        # every join branch has a numpy twin on the CPU backend
        # (kernels.host_kernels_ok honors TINYSQL_DEVICE_JOIN_ONLY):
        # route keys to host there; device-resident/memoized otherwise
        host_keys = kernels.host_kernels_ok()

        # multi-key equi-joins ride ONE composite int64 lane (joint
        # factorization over both sides — collision-free by
        # construction), then the single-key kernels apply unchanged
        composite = len(plan.left_keys) > 1
        if composite:
            stream = False

        from .devpipe import BlockPipeline, pipeline_depth
        depth = pipeline_depth(self.ctx.session_vars)

        def keys_of(side, expr, chk, rep):
            if stream and side == probe_side:
                v, m = expr.vec_eval(chk)  # host: no full-column upload
                return np.asarray(v), np.asarray(m)
            return self._key_arrays(expr, chk, rep, side,
                                    host_keys=host_keys)

        key_exprs = (plan.left_keys[0], plan.right_keys[0])
        side_chks = (lchk, rchk)
        side_reps = (lrep, rrep)
        build_side = 1 - probe_side
        if composite:
            (lk, lnull), (rk, rnull) = _composite_key_lanes(
                plan.left_keys, lchk, plan.right_keys, rchk)
        elif stream and depth > 0:
            # build-side ingestion overlaps probe staging (the
            # reference's build/probe worker split, join.go:149/:244
            # completed for real): the build keys' replica-memoized
            # uploads run on the pipeline thread while the probe side's
            # key column extracts here
            bpipe = BlockPipeline(
                lambda side: keys_of(side, key_exprs[side],
                                     side_chks[side], side_reps[side]),
                [build_side], depth=1)
            try:
                pk_pair = keys_of(probe_side, key_exprs[probe_side],
                                  side_chks[probe_side],
                                  side_reps[probe_side])
                bk_pair = list(bpipe)[0]  # drain: joins the thread
            finally:
                bpipe.close()  # probe failure must not leak the stager
            if probe_side == 0:
                (lk, lnull), (rk, rnull) = pk_pair, bk_pair
            else:
                (lk, lnull), (rk, rnull) = bk_pair, pk_pair
        else:
            lk, lnull = keys_of(0, key_exprs[0], lchk, lrep)
            rk, rnull = keys_of(1, key_exprs[1], rchk, rrep)
        if on_left:
            on_mask = vectorized_filter(on_left, lchk)
            # poison only the NULL mask (values may stay replica-memoized
            # on device); a padded device mask re-lands on host, padding
            # rows are already null=True
            lnull = np.asarray(lnull)
            if lnull.shape[0] != on_mask.shape[0]:
                fail = np.zeros(lnull.shape[0], dtype=bool)
                fail[:on_mask.shape[0]] = ~on_mask
                lnull = lnull | fail
            else:
                lnull = lnull | ~on_mask
        if lk.dtype != rk.dtype:
            lk = np.asarray(lk).astype(np.float64)
            rk = np.asarray(rk).astype(np.float64)
        def stream_match(fn, pk, pn, n_probe, pmask, bkey, n_build,
                         bmask, **kw):
            """Probe-block loop: fn per block of `budget` rows with the
            block's validity slice; probe-side indices re-base by the
            block start.  Stable block shapes = one compiled program.

            PIPELINED: the staging thread slices the next probe block
            (and pre-uploads its padded key arrays when the device match
            kernel will run) while the current block's match executes;
            results concatenate in block order, so depth 0 (synchronous)
            is byte-identical."""
            dev_stage = not (kernels.host_kernels_ok()
                             and isinstance(bkey[0], np.ndarray))
            jn = kernels.jnp() if dev_stage else None

            def stage(s_):
                e_ = min(s_ + budget, n_probe)
                m = e_ - s_
                kv, kn = pk[s_:e_], pn[s_:e_]
                if dev_stage:
                    blk = kernels.bucket(max(m, 1))
                    kv = kernels.h2d_pad(kv, blk)
                    kn = kernels.h2d_pad(kn, blk, True)
                pm = None if pmask is None else pmask[s_:e_]
                return s_, (kv, kn), m, pm

            pis, bis = [], []
            t_pipe = time.time()
            dispatch_s = 0.0
            pipe = BlockPipeline(stage, range(0, n_probe, budget),
                                 depth=depth)
            for s_, kpair, m, pm in pipe:
                t0 = time.time()
                pi_b, bi_b = fn(kpair, m, bkey, n_build, lvalid=pm,
                                rvalid=bmask, **kw)
                dispatch_s += time.time() - t0
                pis.append(pi_b + s_)
                bis.append(bi_b)
            ps = pipe.stats()
            kernels.pipe_record(blocks=ps["blocks"],
                                stage_s=ps["stage_s"],
                                dispatch_s=dispatch_s,
                                wall_s=time.time() - t_pipe,
                                depth_hwm=ps["depth_hwm"])
            if not pis:
                z = np.empty(0, dtype=np.int64)
                return z, z
            return np.concatenate(pis), np.concatenate(bis)

        # memory-adaptive hybrid hash join (ops/spill.py): under quota
        # pressure (or spillForceAll) the build side partitions by key
        # hash with cold partitions in the host spill store; probe rows
        # route to their partition; overflowing partitions recursively
        # repartition.  Output order is the unpartitioned kernels' exact
        # contract, so the branch is transparent to everything above.
        if sctx is not None:
            li, ri = self._spill_join(
                sctx, (lk, lnull), (rk, rnull), lchk, rchk, lmask, rmask,
                probe_side, right_unique, left_unique, outer)
        elif right_unique:
            # unique build side: expansion-free probe, no size sync
            bs = (not composite
                  and self._sorted_build(plan.right_keys[0], rchk))
            if stream:
                li, ri = stream_match(
                    kernels.unique_join_match, lk, lnull,
                    lchk.full_rows(), lmask, (rk, rnull),
                    rchk.full_rows(), rmask,
                    outer=(plan.tp == "left"), build_sorted=bs)
            else:
                out = None
                mesh = _mesh_for(
                    self.ctx, kernels.bucket(max(lchk.full_rows(), 1)),
                    plan)
                if mesh is not None and isinstance(lk, np.ndarray) \
                        and isinstance(rk, np.ndarray):
                    # partitioned build/probe over the mesh (shard =
                    # spill partition); None (skew, odd dtypes) falls
                    # through to the single-device kernel
                    from ..ops import shardops
                    out = shardops.unique_join_match_sharded(
                        mesh, (lk, lnull), lchk.full_rows(),
                        (rk, rnull), rchk.full_rows(),
                        outer=(plan.tp == "left"),
                        lvalid=lmask, rvalid=rmask)
                if out is not None:
                    li, ri = out
                else:
                    li, ri = kernels.unique_join_match(
                        (lk, lnull), lchk.full_rows(), (rk, rnull),
                        rchk.full_rows(), outer=(plan.tp == "left"),
                        lvalid=lmask, rvalid=rmask, build_sorted=bs)
        elif left_unique and plan.tp == "inner":
            bs = (not composite
                  and self._sorted_build(plan.left_keys[0], lchk))
            if stream:
                ri, li = stream_match(
                    kernels.unique_join_match, rk, rnull,
                    rchk.full_rows(), rmask, (lk, lnull),
                    lchk.full_rows(), lmask, outer=False,
                    build_sorted=bs)
            else:
                out = None
                mesh = _mesh_for(
                    self.ctx, kernels.bucket(max(rchk.full_rows(), 1)),
                    plan)
                if mesh is not None and isinstance(lk, np.ndarray) \
                        and isinstance(rk, np.ndarray):
                    from ..ops import shardops
                    out = shardops.unique_join_match_sharded(
                        mesh, (rk, rnull), rchk.full_rows(),
                        (lk, lnull), lchk.full_rows(), outer=False,
                        lvalid=rmask, rvalid=lmask)
                if out is not None:
                    ri, li = out
                else:
                    ri, li = kernels.unique_join_match(
                        (rk, rnull), rchk.full_rows(), (lk, lnull),
                        lchk.full_rows(), outer=False,
                        lvalid=rmask, rvalid=lmask, build_sorted=bs)
        elif stream:
            li, ri = stream_match(
                kernels.join_match, lk, lnull, lchk.full_rows(), lmask,
                (rk, rnull), rchk.full_rows(), rmask,
                outer=(plan.tp == "left"))
        else:
            li, ri = kernels.join_match((lk, lnull), lchk.full_rows(),
                                        (rk, rnull), rchk.full_rows(),
                                        outer=(plan.tp == "left"),
                                        lvalid=lmask, rvalid=rmask)
        # gather output columns — LAZILY for inner joins: a parent join
        # or TopN composes the index chain and each payload column lands
        # once, at the final (smallest) cardinality
        from ..chunk.column import LazyTakeColumn
        unmatched = ri < 0
        ri_safe = np.where(unmatched, 0, ri)
        lazy = plan.tp != "left" and not unmatched.any()
        cols: List[CCol] = []
        for c in lchk.columns:
            cols.append(LazyTakeColumn(c, li) if lazy else c.take(li))
        for c in rchk.columns:
            if lazy:
                cols.append(LazyTakeColumn(c, ri_safe))
                continue
            taken = c.take(ri_safe)
            if unmatched.any():
                taken.null_mask()[unmatched] = True
            cols.append(taken)
        out = Chunk.from_columns(cols)
        if plan.other_conditions:
            mask = vectorized_filter(plan.other_conditions, out)
            if plan.tp == "left":
                # failed other-cond on matched rows -> NULL-extended row
                # must survive only if NO match passes; handled by
                # re-checking per left row
                keep = self._outer_fixup(li, ri, mask, lchk, out)
                out.set_sel(np.nonzero(keep)[0])
            else:
                out.set_sel(np.nonzero(mask)[0])
            out = out.compact()
        return out if out.num_rows() else None

    def _outer_fixup(self, li, ri, mask, lchk, out) -> np.ndarray:
        """LEFT JOIN + other-conditions: a left row keeps exactly its
        passing matches, or one NULL-extended row if none pass."""
        n_left = lchk.num_rows()
        passing = np.zeros(n_left, dtype=bool)
        matched_rows = ri >= 0
        np.logical_or.at(passing, li[matched_rows & mask],
                         True)
        keep = np.zeros(len(li), dtype=bool)
        # keep matched rows that pass
        keep |= matched_rows & mask
        # left rows with no passing match: keep ONE row, null-extended
        no_pass = ~passing
        seen = set()
        for idx in range(len(li)):
            l = li[idx]
            if no_pass[l] and l not in seen:
                seen.add(l)
                keep[idx] = True
                # null-extend the right side of this surviving row
                for c in out.columns[len(lchk.columns):]:
                    c.null_mask()[idx] = True
        return keep


    def _semi_next(self) -> Optional[Chunk]:
        """Semi / anti join on device: a membership test over the build
        (subquery) side via kernels.semi_join_match — the sort +
        searchsorted machinery the join kernels already ride — emitting
        surviving LEFT rows only.  Under quota pressure the membership
        derives from the spilled partitioned inner join instead (matches
        are partition-local under key hashing, so presence/absence is
        decidable per partition)."""
        from ..chunk.column import LazyTakeColumn
        plan = self.plan
        anti = plan.tp == "anti"
        null_aware = anti and getattr(plan, "null_aware", False)
        est = _est_rows_of(plan.children[0]) + _est_rows_of(
            plan.children[1])
        sctx = _maybe_spill_ctx(self.ctx, est, 0, _JOIN_ROW_BYTES,
                                "join")
        lchk, lmask, lrep = self._side_input(0, plan.left_conditions,
                                             compact=sctx is None)
        rchk, rmask, rrep = self._side_input(1, plan.right_conditions,
                                             compact=sctx is None)
        if sctx is None:
            sctx = _maybe_spill_ctx(
                self.ctx, est,
                lchk.full_rows() + rchk.full_rows(),
                _JOIN_ROW_BYTES, "join")
        mesh = None if sctx is not None or len(plan.left_keys) > 1 else \
            _mesh_for(self.ctx, kernels.bucket(max(lchk.full_rows(), 1)),
                      plan)
        # partitioned semijoin scatters HOST key lanes with the spill
        # partitioner — device-resident keys would round-trip anyway
        host_keys = kernels.host_kernels_ok() or mesh is not None
        if len(plan.left_keys) > 1:
            (lk, lnull), (rk, rnull) = _composite_key_lanes(
                plan.left_keys, lchk, plan.right_keys, rchk)
        else:
            lk, lnull = self._key_arrays(plan.left_keys[0], lchk, lrep,
                                         0, host_keys=host_keys)
            rk, rnull = self._key_arrays(plan.right_keys[0], rchk, rrep,
                                         1, host_keys=host_keys)
        if getattr(lk, "dtype", None) != getattr(rk, "dtype", None) \
                and isinstance(lk, np.ndarray) \
                and isinstance(rk, np.ndarray):
            lk = np.asarray(lk).astype(np.float64)
            rk = np.asarray(rk).astype(np.float64)
        if sctx is not None:
            li = self._spill_semi(sctx, (lk, lnull), (rk, rnull), lchk,
                                  rchk, lmask, rmask, anti, null_aware)
        else:
            li = None
            if mesh is not None:
                from ..ops import shardops
                li = shardops.semi_join_match_sharded(
                    mesh, (lk, lnull), lchk.full_rows(), (rk, rnull),
                    rchk.full_rows(), anti=anti, null_aware=null_aware,
                    lvalid=lmask, rvalid=rmask)
            if li is None:
                li = kernels.semi_join_match(
                    (lk, lnull), lchk.full_rows(), (rk, rnull),
                    rchk.full_rows(), anti=anti, null_aware=null_aware,
                    lvalid=lmask, rvalid=rmask)
        if len(li) == 0:
            return None
        cols: List[CCol] = [LazyTakeColumn(c, li) for c in lchk.columns]
        return Chunk.from_columns(cols)

    def _spill_semi(self, sctx, lpair, rpair, lchk, rchk, lmask, rmask,
                    anti: bool, null_aware: bool) -> np.ndarray:
        """Spill-mode membership: the empty/NULL-set ladder decides
        host-side; otherwise the partitioned inner join supplies matched
        probe rows (equal keys colocate per partition, so membership is
        partition-local) and semi/anti derive from the matched set."""
        from ..ops import spill
        lk = np.asarray(lpair[0])
        lnull = np.asarray(lpair[1], dtype=bool)
        rk = np.asarray(rpair[0])
        rnull = np.asarray(rpair[1], dtype=bool)
        n_left = lchk.full_rows()
        n_right = rchk.full_rows()
        lv = np.ones(n_left, dtype=bool) if lmask is None \
            else np.asarray(lmask[:n_left], dtype=bool)
        rv = np.ones(n_right, dtype=bool) if rmask is None \
            else np.asarray(rmask[:n_right], dtype=bool)
        if int(rv.sum()) == 0:
            sctx.close()
            keep = lv if anti else np.zeros(n_left, dtype=bool)
            return np.nonzero(keep)[0].astype(np.int64)
        if anti and null_aware and bool((rv & rnull[:n_right]).any()):
            sctx.close()
            return np.empty(0, dtype=np.int64)
        unique_build = getattr(self.plan, "right_unique", False)

        def match(pp, n_p, bp, n_b):
            if unique_build:
                return kernels.unique_join_match(pp, n_p, bp, n_b,
                                                 outer=False)
            return kernels.join_match(pp, n_p, bp, n_b, outer=False)

        with sctx:
            mi, _ = spill.partitioned_join(
                sctx, (lk, lnull), n_left, (rk, rnull), n_right, match,
                outer=False, probe_valid=lmask, build_valid=rmask)
        matched = np.zeros(n_left, dtype=bool)
        matched[mi] = True
        if anti:
            keep = lv & ~matched
            if null_aware:
                keep &= ~lnull[:n_left]
        else:
            keep = matched
        return np.nonzero(keep)[0].astype(np.int64)

    def _spill_join(self, sctx, lpair, rpair, lchk, rchk, lmask, rmask,
                    probe_side: int, right_unique: bool,
                    left_unique: bool, outer: bool):
        """Partitioned spill-mode matching: host key arrays (device-
        resident replica keys land once — np.asarray — instead of
        living whole on device), per-partition match through the
        UNCHANGED kernel entry points (the compiled programs and their
        progcache entries are shared with the unpartitioned path)."""
        from ..ops import spill
        lk = np.asarray(lpair[0])
        lnull = np.asarray(lpair[1], dtype=bool)
        rk = np.asarray(rpair[0])
        rnull = np.asarray(rpair[1], dtype=bool)
        unique_build = right_unique if probe_side == 0 else left_unique

        def match(pp, n_p, bp, n_b):
            if unique_build:
                return kernels.unique_join_match(pp, n_p, bp, n_b,
                                                 outer=False)
            return kernels.join_match(pp, n_p, bp, n_b, outer=False)

        with sctx:
            if probe_side == 0:
                return spill.partitioned_join(
                    sctx, (lk, lnull), lchk.full_rows(),
                    (rk, rnull), rchk.full_rows(), match, outer=outer,
                    probe_valid=lmask, build_valid=rmask)
            ri, li = spill.partitioned_join(
                sctx, (rk, rnull), rchk.full_rows(),
                (lk, lnull), lchk.full_rows(), match, outer=False,
                probe_valid=rmask, build_valid=lmask)
            return li, ri

    @staticmethod
    def _sorted_build(key_expr, chk) -> bool:
        """True when the build key column provably ascends among live
        rows (a device-resident single-key aggregate output): the join
        kernel then skips its argsort."""
        from ..chunk import DeviceColumn
        from ..expression import Column as ExprColumn
        if not isinstance(key_expr, ExprColumn):
            return False
        col = chk.columns[key_expr.index]
        return (isinstance(col, DeviceColumn) and col._data is None
                and col.sorted_live)

    def _key_arrays(self, key_expr, chk, rep, side, host_keys=False):
        """Join key (values, null) — for a bare Column over an uncompacted
        replica, PADDED DEVICE arrays memoized on the replica (no re-upload
        per query); device-resident for a DeviceColumn child (an aggregate
        output that never landed on host); numpy otherwise.  `host_keys`
        (a unique-join on the CPU backend) lands keys on host instead —
        XLA:CPU "device" buffers are host memory, so landing is a memcpy
        and the numpy match twin beats the serial device kernels."""
        from ..chunk import DeviceColumn
        from ..expression import Column as ExprColumn
        from .executors import TableReaderExec
        if isinstance(key_expr, ExprColumn):
            col = chk.columns[key_expr.index]
            if isinstance(col, DeviceColumn) and col._data is None:
                if host_keys:
                    return key_expr.vec_eval(chk)
                return col.device_pair()
        if rep is not None and isinstance(key_expr, ExprColumn):
            child = self.children[side]
            if isinstance(child, TableReaderExec):
                if host_keys:
                    # the raw replica views are free on host
                    return key_expr.vec_eval(chk)
                ci = child._decode_cols[key_expr.index]
                sid = ci.id if ci is not None else "handle"
                nb = kernels.bucket(max(chk.full_rows(), 1))
                jn = kernels.jnp()
                col = chk.columns[key_expr.index]
                v = col.values()
                m = col.null_mask()
                if v.dtype != object and v.dtype.kind != "U":
                    dv = rep.memo(("devv", sid, nb),
                                  lambda v=v: kernels.h2d_pad(v, nb))
                    dn = rep.memo(("devn", sid, nb),
                                  lambda m=m: kernels.h2d_pad(m, nb, True))
                    return dv, dn
        return key_expr.vec_eval(chk)


class TPUSortExec(Executor):
    def __init__(self, plan: PhysicalSort, child: Executor):
        super().__init__(plan.schema, [child])
        self.plan = plan
        self._out = None

    def open(self, ctx):
        super().open(ctx)
        self._out = None

    def next(self) -> Optional[Chunk]:
        if self._out is None:
            chk = _child_input(self.children[0],
                               soft=_would_spill_here(self.ctx, self.plan))
            n = chk.num_rows()
            if n == 0:
                self._out = iter([])
            else:
                keys = [(_encode_key(e, chk)[:2]) for e, _ in self.plan.by]
                keys = [(v, m) for v, m in keys]
                descs = [d for _, d in self.plan.by]
                row_bytes = sum(np.asarray(v).dtype.itemsize + 1
                                for v, _ in keys) + 8
                sctx = _maybe_spill_ctx(
                    self.ctx, _est_rows_of(self.plan.children[0]), n,
                    row_bytes, "sort")
                if sctx is not None and \
                        _spill_run_rows(sctx, n, row_bytes) >= n:
                    # the whole key set fits one run: an external sort
                    # would just write-and-reload a single run file
                    sctx.close()
                    sctx = None
                budget = _block_budget(self.ctx.session_vars)
                if sctx is not None:
                    # external sort: spilled sorted runs + k-way merge
                    # (exact full-lexsort permutation; ops/spill.py)
                    from ..ops import spill
                    with sctx:
                        perm = spill.external_sort_permutation(
                            sctx, keys, descs, n,
                            _spill_run_rows(sctx, n, row_bytes))
                elif budget > 0 and n > budget:
                    # above the device budget a full ORDER BY sorts on
                    # host (same semantics): whole-key residency would
                    # violate tidb_device_block_rows
                    perm = kernels.host_sort_permutation(keys, descs, n)
                else:
                    perm = None
                    mesh = _mesh_for(self.ctx,
                                     kernels.bucket(max(n, 1)), self.plan)
                    if mesh is not None:
                        # per-shard sort + exact device rank merge;
                        # None (multi-key, unscorable) falls through
                        from ..ops import shardops
                        perm = shardops.sort_permutation_sharded(
                            mesh, keys, descs, n)
                    if perm is None:
                        perm = kernels.sort_permutation(keys, descs, n)
                chk.set_sel(perm)
                self._out = iter([chk.compact()])
        return next(self._out, None)


class TPUTopNExec(Executor):
    def __init__(self, plan: PhysicalTopN, child: Executor):
        super().__init__(plan.schema, [child])
        self.plan = plan
        self._out = None

    def open(self, ctx):
        super().open(ctx)
        self._out = None

    def next(self) -> Optional[Chunk]:
        if self._out is None:
            chk = _child_input(self.children[0],
                               soft=_would_spill_here(self.ctx, self.plan))
            n = chk.num_rows()
            if n == 0:
                self._out = iter([])
            else:
                keys = [(_encode_key(e, chk)[:2]) for e, _ in self.plan.by]
                descs = [d for _, d in self.plan.by]
                k = self.plan.offset + self.plan.count
                row_bytes = sum(np.asarray(v).dtype.itemsize + 1
                                for v, _ in keys) + 8
                sctx = _maybe_spill_ctx(
                    self.ctx, _est_rows_of(self.plan.children[0]), n,
                    row_bytes, "topn")
                if sctx is not None and \
                        _spill_run_rows(sctx, n, row_bytes) >= n:
                    # single-run input: nothing to carry between runs
                    sctx.close()
                    sctx = None
                budget = _block_budget(self.ctx.session_vars)
                if sctx is not None:
                    # run-file top-k: the candidate carry lives in the
                    # spill store between runs (ops/spill.py)
                    from ..ops import spill
                    with sctx:
                        perm = spill.external_topk(
                            sctx, keys, descs, n, k,
                            _spill_run_rows(sctx, n, row_bytes))
                elif budget > 0 and n > budget:
                    perm = self._blockwise_topk(keys, descs, n, k, budget)
                else:
                    perm = None
                    mesh = _mesh_for(self.ctx,
                                     kernels.bucket(max(n, 1)), self.plan)
                    if mesh is not None:
                        # per-shard top-k + replicated tournament merge
                        from ..ops import shardops
                        perm = shardops.top_k_sharded(
                            mesh, keys, descs, n, k)
                    if perm is None:
                        perm = kernels.top_k(keys, descs, n, k)
                sel = perm[self.plan.offset:]
                chk.set_sel(sel)
                self._out = iter([chk.compact()] if len(sel) else [])
        return next(self._out, None)

    @staticmethod
    def _blockwise_topk(keys, descs, n: int, k: int,
                        budget: int) -> np.ndarray:
        """Block-wise top-k (SURVEY §5.7; VERDICT r4 next-3): each block
        of `budget` rows yields its local top-k candidates (device
        buffers bounded by the block bucket), the carried candidate set
        merges with each block's winners, and a final top-k over the
        <= 2k survivors picks the answer — partial TopN state across
        TIME, the streaming analogue of the reference's per-region TopN
        (mocktikv/topn.go) merged at the root (task.go:392-452)."""
        if 2 * k > budget:
            # the <=2k candidate pools would themselves exceed the device
            # budget: selection runs fully on host instead
            return np.asarray(
                kernels.host_sort_permutation(keys, descs, n)[:k])
        cand = np.empty(0, dtype=np.int64)
        for s_ in range(0, n, budget):
            e_ = min(s_ + budget, n)
            bkeys = [(v[s_:e_], m[s_:e_]) for v, m in keys]
            ids = np.asarray(kernels.top_k(bkeys, descs, e_ - s_,
                                           k)) + s_
            pool = np.concatenate([cand, ids])
            pkeys = [(v[pool], m[pool]) for v, m in keys]
            order = np.asarray(kernels.top_k(pkeys, descs, len(pool), k))
            cand = pool[order]
        return cand


class TPUProjectionExec(Executor):
    """Expression trees fused by XLA into elementwise device kernels."""

    def __init__(self, plan: PhysicalProjection, child: Executor):
        super().__init__(plan.schema, [child])
        self.plan = plan
        self._fn = None
        self._params = None

    def _compiled(self):
        if self._fn is None:
            # shared params-compiled program (ops/progcache): executors
            # are rebuilt per query, so a per-instance @jit wrapper would
            # retrace EVERY query — qlint TS104, the ~40-70ms-per-
            # dispatch bug class PROFILE.md §1 prices
            from ..ops.exprjit import (ParamTable, compile_expr_params,
                                       stable_shape_key)
            key = ("proj",) + tuple(stable_shape_key(e)
                                    for e in self.plan.exprs)
            pt = ParamTable()
            fns = [compile_expr_params(e, pt) for e in self.plan.exprs]
            self._params = [kernels.h2d(a) for a in pt.arrays()]

            def build():
                def kernel(cols, params, fns=fns):
                    return [f(cols, params) for f in fns]
                return kernels.counted_jit(kernel)
            self._fn = progcache.get(key, build)
        return self._fn

    def next(self) -> Optional[Chunk]:
        chk = self.children[0].next()
        if chk is None:
            return None
        chk = chk.compact()
        if not chk.columns:
            # zero-column (TableDual) input: host numpy path handles
            # virtual row counts; nothing to gain on device
            from ..chunk import Column as HostCol
            cols = []
            for e, oc in zip(self.plan.exprs, self.plan.schema.columns):
                v, m = e.vec_eval(chk)
                cols.append(HostCol.from_numpy(oc.ret_type, v, m))
            return Chunk.from_columns(cols)
        cols_dev = _marshal(chk)
        outs = self._compiled()(cols_dev, tuple(self._params))
        # ONE counted pull for every output stream — per-pair np.asarray
        # was 2N hidden uncounted downloads (transfer-audit find)
        flat = []
        for v, m in outs:
            flat.extend((v, m))
        host = kernels.d2h_many(flat) if flat else []
        out_cols = []
        for i, oc in enumerate(self.plan.schema.columns):
            out_cols.append(CCol.from_numpy(oc.ret_type, host[2 * i],
                                            host[2 * i + 1]))
        return Chunk.from_columns(out_cols)


class TPUSelectionExec(Executor):
    def __init__(self, plan: PhysicalSelection, child: Executor):
        super().__init__(plan.schema, [child])
        self.plan = plan
        self._fn = None
        self._params = None

    def _compiled(self):
        if self._fn is None:
            # params-compiled program shared at module level: constants
            # ride runtime param slots (exprjit.ParamTable), so queries
            # differing only in literals reuse ONE compiled program — no
            # per-literal cache growth, no jit dispatch-cache miss from a
            # fresh wrapper per query (executors are rebuilt per query).
            from ..ops.exprjit import (ParamTable, compile_expr_params,
                                       stable_shape_key)
            key = ("filter",) + tuple(stable_shape_key(c)
                                      for c in self.plan.conditions)
            pt = ParamTable()
            fns = [compile_expr_params(c, pt) for c in self.plan.conditions]
            self._params = [kernels.h2d(a) for a in pt.arrays()]

            def build():
                jn = kernels.jnp()

                def kernel(cols, params, fns=fns):
                    n = cols[0][0].shape[0] if cols else 0
                    mask = jn.ones((n,), dtype=bool)
                    for f in fns:
                        v, null = f(cols, params)
                        mask = mask & (v != 0) & ~null
                    return mask
                return kernels.counted_jit(kernel)
            self._fn = progcache.get(key, build)
        return self._fn

    def next(self) -> Optional[Chunk]:
        while True:
            chk = self.children[0].next()
            if chk is None:
                return None
            chk = chk.compact()
            if chk.num_rows() == 0:
                continue
            if not chk.columns:
                mask = vectorized_filter(self.plan.conditions, chk)
            else:
                # counted pull: raw np.asarray here was a hidden
                # uncounted d2h on the hot filter loop (DF801)
                mask = kernels.d2h(
                    self._compiled()(_marshal(chk), tuple(self._params)))
            if not mask.any():
                continue
            chk.set_sel(np.nonzero(mask)[0])
            return chk.compact()


def _marshal(chk: Chunk):
    """Chunk columns -> device (values, null) pairs.  String columns are
    never touched by device exprs (enforcer), but must still occupy their
    index slot — pass zeros."""
    jnp = kernels.jnp()
    out = []
    n = chk.num_rows()
    for c in chk.columns:
        v = c.values()
        # uploads count (DF802): raw jnp.asarray bypassed h2d_transfers
        if v.dtype == object:
            out.append((jnp.zeros(n, dtype=jnp.int64),
                        kernels.h2d(c.null_mask())))
        else:
            out.append((kernels.h2d(v), kernels.h2d(c.null_mask())))
    return out


def build_tpu_executor(plan) -> Optional[Executor]:
    """TPU-tier builder.  Subtrees containing a supported join or a
    grouped aggregate compile into a device-resident pipeline (devpipe)
    with the per-operator executors as fallback; lone operators use the
    per-op executors (whose fused paths are already single-program)."""
    from .devpipe import DevPipeExec, _contains_grouped_agg, _contains_join
    if _contains_join(plan) or _contains_grouped_agg(plan):
        return DevPipeExec(plan, _build_tpu_op)
    return _build_tpu_op(plan)


def _build_tpu_op(plan) -> Optional[Executor]:
    ex = _build_tpu_op_inner(plan)
    if ex is not None and getattr(ex, "_obs_plan", None) is None:
        ex._obs_plan = plan  # per-operator stats key (obs/runtime_stats)
    return ex


def _build_tpu_op_inner(plan) -> Optional[Executor]:
    if isinstance(plan, PhysicalHashAgg):
        return TPUHashAggExec(plan, build_executor(plan.children[0], True))
    if isinstance(plan, PhysicalHashJoin):
        # multi-key joins collapse into ONE composite int64 lane (joint
        # factorization) and ride the same single-key kernels
        return TPUHashJoinExec(plan, build_executor(plan.children[0], True),
                               build_executor(plan.children[1], True))
    if isinstance(plan, PhysicalTopN):
        return TPUTopNExec(plan, build_executor(plan.children[0], True))
    if isinstance(plan, PhysicalSort):
        return TPUSortExec(plan, build_executor(plan.children[0], True))
    if isinstance(plan, PhysicalProjection):
        return TPUProjectionExec(plan, build_executor(plan.children[0], True))
    if isinstance(plan, PhysicalSelection):
        return TPUSelectionExec(plan, build_executor(plan.children[0], True))
    return None
