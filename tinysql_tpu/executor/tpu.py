"""TPU executor tier — placeholder until the ops/ kernels land.

Capability slot for the north-star BASELINE.json: TPU-backed HashJoin /
HashAgg / Sort / Projection registered behind the same build_executor
switch, chosen by the planner's device enforcer.
"""
from __future__ import annotations

from typing import Optional


def try_build_tpu(plan) -> Optional[object]:
    from ..planner.physical import (PhysicalHashAgg, PhysicalHashJoin,
                                    PhysicalSort, PhysicalTopN)
    if getattr(plan, "use_tpu", False):
        from .tpu_executors import build_tpu_executor
        return build_tpu_executor(plan)
    return None
