"""DML write executors: INSERT / REPLACE / DELETE.

Capability parity with reference executor/insert.go + insert_common.go
(value evaluation, defaults, autoid), replace.go (delete-then-insert on
duplicate), delete.go, batch_checker.go (dup-key detection).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..catalog.autoid import Allocator
from ..catalog.model import TableInfo
from ..catalog.table import DuplicateKeyError, Table
from ..codec import tablecodec
from ..expression import Constant, Schema
from ..kv.errors import KeyNotFound
from ..mytypes import FLAG_AUTO_INCREMENT, FLAG_NOT_NULL, Datum, cast_datum
from ..parser import ast


class WriteError(Exception):
    pass


def get_allocator(storage, tid: int) -> Allocator:
    cache = getattr(storage, "_allocators", None)
    if cache is None:
        cache = storage._allocators = {}
    a = cache.get(tid)
    if a is None:
        a = cache[tid] = Allocator(storage, tid)
    return a


class InsertExec:
    """reference: executor/insert.go InsertExec + replace.go ReplaceExec."""

    def __init__(self, session, stmt: ast.InsertStmt, info: TableInfo,
                 db_name: str):
        self.session = session
        self.stmt = stmt
        self.info = info
        self.db_name = db_name
        self.affected = 0

    def execute(self, txn) -> int:
        info = self.info
        tbl = Table(info, get_allocator(self.session.storage, info.id))
        cols = info.public_columns()
        by_name = {c.name.lower(): c for c in cols}
        if self.stmt.columns:
            target = []
            for name in self.stmt.columns:
                c = by_name.get(name.lower())
                if c is None:
                    raise WriteError(f"Unknown column '{name}' in 'field list'")
                target.append(c)
        else:
            target = cols

        rows: List[List[Datum]] = []
        if self.stmt.select is not None:
            src_rows = self.session._run_select_plan(self.stmt.select, txn)
            for r in src_rows:
                if len(r) != len(target):
                    raise WriteError("Column count doesn't match value count")
                rows.append(self._complete_row(tbl, target, list(r)))
        else:
            for lst in self.stmt.lists:
                if len(lst) != len(target):
                    raise WriteError("Column count doesn't match value count "
                                     f"at row {len(rows) + 1}")
                vals = [self._eval_insert_expr(e, target[i])
                        for i, e in enumerate(lst)]
                rows.append(self._complete_row(tbl, target, vals))

        for row in rows:
            if self.stmt.is_replace:
                self._replace_row(txn, tbl, row)
            else:
                self._check_duplicates(txn, tbl, row)
                tbl.add_record(txn, row)
            self.affected += 1
        return self.affected

    def _check_duplicates(self, txn, tbl: Table, row: List[Datum]) -> None:
        """Eager dup detection so INSERT fails at the statement, not at
        commit (reference: executor/batch_checker.go getKeysNeedCheck);
        the prewrite check remains the backstop for concurrent races."""
        pk = self.info.get_pk_handle_col()
        if pk is not None and row[pk.offset] is not None:
            h = int(row[pk.offset])
            try:
                txn.get(tablecodec.encode_row_key(self.info.id, h))
                raise DuplicateKeyError(self.info.name, "PRIMARY", [h])
            except KeyNotFound:
                pass
        for idx in tbl.indices:
            if idx.info.unique and idx.exists_conflict(txn, row) is not None:
                raise DuplicateKeyError(self.info.name, idx.info.name,
                                        idx._index_values(row))

    # ---- helpers --------------------------------------------------------
    def _eval_insert_expr(self, e: ast.ExprNode, col) -> Datum:
        if isinstance(e, ast.DefaultExpr):
            return col.default
        return self.session.eval_const_expr(e)

    def _complete_row(self, tbl: Table, target, vals: List[Datum]) -> List[Datum]:
        """Order values by column offset, fill defaults/autoid, check
        NOT NULL (reference: insert_common.go getRow/fillRow)."""
        info = self.info
        by_offset: Dict[int, Datum] = {}
        for c, v in zip(target, vals):
            by_offset[c.offset] = v
        row: List[Datum] = []
        for c in info.public_columns():
            v = by_offset.get(c.offset, c.default)
            if v is None and (c.ft.flag & FLAG_AUTO_INCREMENT):
                v = tbl.allocator.alloc()
            elif v is not None and (c.ft.flag & FLAG_AUTO_INCREMENT):
                v = cast_datum(v, c.ft)
                tbl.allocator.rebase(int(v))
            if v is None and c.ft.not_null:
                if c.offset in by_offset:
                    raise WriteError(f"Column '{c.name}' cannot be null")
                raise WriteError(f"Field '{c.name}' doesn't have a default value")
            row.append(cast_datum(v, c.ft) if v is not None else None)
        return row

    def _replace_row(self, txn, tbl: Table, row: List[Datum]) -> None:
        """REPLACE: remove any row conflicting on pk or unique keys, then
        insert (reference: replace.go removeRow + addRecord)."""
        info = self.info
        removed = True
        while removed:
            removed = False
            pk = info.get_pk_handle_col()
            if pk is not None and row[pk.offset] is not None:
                h = int(row[pk.offset])
                try:
                    old = tbl.row(txn, h)
                except KeyNotFound:
                    old = None
                if old is not None:
                    tbl.remove_record(txn, h, old)
                    removed = True
            for idx in tbl.indices:
                if not idx.info.unique:
                    continue
                h = idx.exists_conflict(txn, row)
                if h is not None:
                    old = tbl.row(txn, h)
                    tbl.remove_record(txn, h, old)
                    removed = True
        tbl.add_record(txn, row)


class UpdateExec:
    """reference: executor/update.go UpdateExec — read-modify-write over
    the scanned qualifying rows (the plan carries the hidden handle
    column), riding the SAME row-store + 2PC prewrite/commit path as
    INSERT/DELETE, so every transactional guarantee (and failpoint) of
    that path covers UPDATE for free."""

    def __init__(self, session, info: TableInfo, assigns):
        # assigns: [(ColumnInfo, Expression bound to scan-schema offsets)]
        self.session = session
        self.info = info
        self.assigns = assigns
        self.affected = 0

    def execute(self, txn, rows: List[list]) -> int:
        tbl = Table(self.info, get_allocator(self.session.storage,
                                             self.info.id))
        pk = self.info.get_pk_handle_col()
        for row in rows:
            handle = row[-1]
            old = row[:-1]
            new = list(old)
            for ci, expr in self.assigns:
                # MySQL single-table UPDATE evaluates assignments left to
                # right, each seeing the values already assigned
                v = expr.eval(new + [handle])
                if v is None and ci.ft.not_null:
                    raise WriteError(f"Column '{ci.name}' cannot be null")
                new[ci.offset] = cast_datum(v, ci.ft) if v is not None \
                    else None
            if new == old:
                continue  # no-op assignment: nothing to write
            if pk is not None and new[pk.offset] != handle:
                # handle change: the row MOVES in the keyspace
                new_handle = int(new[pk.offset])
                try:
                    txn.get(tablecodec.encode_row_key(self.info.id,
                                                      new_handle))
                    raise DuplicateKeyError(self.info.name, "PRIMARY",
                                            [new_handle])
                except KeyNotFound:
                    pass
                # eager 1062 at STATEMENT time, same as the in-place
                # branch — not deferred to commit-time prewrite
                self._check_unique(txn, tbl, old, new, handle)
                tbl.remove_record(txn, handle, old)
                tbl.add_record(txn, new)
            else:
                self._check_unique(txn, tbl, old, new, handle)
                tbl.update_record(txn, handle, old, new)
            self.affected += 1
        return self.affected

    def _check_unique(self, txn, tbl: Table, old, new,
                      handle: int) -> None:
        for idx in tbl.indices:
            if not idx.info.unique:
                continue
            if idx._index_values(old) == idx._index_values(new):
                continue  # key unchanged: no new conflict possible
            h = idx.exists_conflict(txn, new)
            if h is not None and h != handle:
                raise DuplicateKeyError(self.info.name, idx.info.name,
                                        idx._index_values(new))


class DeleteExec:
    """reference: executor/delete.go — scan qualifying rows (plan includes
    the hidden handle column), remove each."""

    def __init__(self, session, info: TableInfo):
        self.session = session
        self.info = info
        self.affected = 0

    def execute(self, txn, rows: List[list]) -> int:
        tbl = Table(self.info)
        for row in rows:
            handle = row[-1]
            tbl.remove_record(txn, handle, row[:-1])
            self.affected += 1
        return self.affected
