"""TCP server speaking the MySQL protocol (reference: server/server.go
NewServer :121 / Run :155 accept loop / per-conn goroutine :225, and
server/conn.go clientConn.Run :541, dispatch :667, handleQuery :821,
writeResultset :931).

One thread per connection (the per-connection-goroutine analogue, SURVEY
§2.11 P1); each connection owns a Session over the shared storage.
"""
from __future__ import annotations

import logging
import socket
import threading
import time
import weakref
from typing import Dict, Optional

from ..session.session import ResultSet, Session
from . import protocol as p
from .packetio import PacketIO

log = logging.getLogger("tinysql_tpu.server")

#: live servers (weak — registry dies with the server): the
#: ``tinysql_conn_*`` gauges aggregate open/idle/active connections
#: across every live server the same way pool gauges do for pools.
_SERVERS: "weakref.WeakSet" = weakref.WeakSet()
_SERVERS_MU = threading.Lock()


def conn_gauges() -> dict:
    """Aggregate connection gauges across every live server (the
    ``tinysql_conn_open/idle/active`` ring-metric feed).  A connection
    is *active* while its session has a statement executing or queued;
    everything else — parked aio file objects and legacy threads
    blocked in read alike — is *idle*."""
    out = {"open": 0, "idle": 0, "active": 0}
    with _SERVERS_MU:
        servers = list(_SERVERS)
    for srv in servers:
        with srv._mu:
            ccs = list(srv.conns.values())
        for cc in ccs:
            sess = cc.session
            out["open"] += 1
            if getattr(sess, "stmt_running", False) or \
                    getattr(sess, "stmt_state", "") == "queued":
                out["active"] += 1
            else:
                out["idle"] += 1
    return out


def _err_packet_for(e: Exception) -> bytes:
    """Map a statement error onto the wire: typed errors carry their own
    MySQL code/sqlstate (QueryKilled 1317, QueryTimeout 3024,
    MemQuotaExceeded 8175, coded SessionErrors); everything else is the
    generic 1105."""
    return p.err_packet(getattr(e, "mysql_code", 1105), str(e),
                        getattr(e, "sqlstate", "HY000"))


class ClientConn:
    def __init__(self, server: "Server", conn: socket.socket):
        self.server = server
        self.sock = conn
        self.io = PacketIO(conn)
        self.tls = False
        self.session = Session(server.storage, domain=server.domain)
        # the wire thread-id IS the session's process-unique conn id, so
        # the id a client sees in the handshake is a valid KILL target
        self.conn_id = self.session.conn_id
        self.alive = True
        # prepared statements: id -> [sql_parts, types] (binary protocol)
        self._stmts: dict = {}
        self._next_stmt_id = 1

    # ---- handshake (reference: conn.go:117,418 — with the scramble
    # verification full TiDB does and tinysql stripped) -------------------
    def greeting_caps(self) -> int:
        caps = p.SERVER_CAPS
        if self.server.ssl_ctx is not None:
            caps |= p.CLIENT_SSL
        return caps

    def handshake(self) -> bool:
        salt = p.new_salt()
        self.io.write_packet(p.handshake_v10(self.conn_id, salt,
                                             self.greeting_caps()))
        try:
            payload = self.io.read_packet()
        except (ConnectionError, OSError):
            return False
        return self.finish_handshake(salt, payload)

    def finish_handshake(self, salt: bytes, payload: bytes) -> bool:
        """Everything after the greeting round-trip: optional TLS
        upgrade, response parse, scramble verification, initial USE.
        Split out so the aio front end (which frames the first response
        itself, nonblocking) shares one auth path with the legacy
        blocking read above."""
        import struct
        from . import auth
        try:
            # SSLRequest (reference: conn.go:448-455 readOptionalSSLRequest
            # + upgradeToTLS :1070): the protocol-41 SSLRequest is the
            # 32-byte response prefix (caps, max-packet, charset, filler)
            # with CLIENT_SSL set and NO username — the client then
            # renegotiates over TLS and sends the full response.
            if (self.server.ssl_ctx is not None and len(payload) <= 32
                    and struct.unpack_from("<I", payload, 0)[0]
                    & p.CLIENT_SSL):
                seq = self.io.sequence
                self.sock = self.server.ssl_ctx.wrap_socket(
                    self.sock, server_side=True)
                self.io = PacketIO(self.sock)
                self.io.sequence = seq
                self.tls = True
                payload = self.io.read_packet()
            resp = p.parse_handshake_response(payload)
        except (ConnectionError, IndexError, ValueError, struct.error,
                OSError):
            return False  # not a MySQL client (or bad TLS); close quietly
        try:
            stored = auth.lookup_auth_string(self.server.storage,
                                             resp["user"])
        except Exception as e:  # auth lookup failure != dead server thread
            log.warning("conn-%d auth lookup error: %s", self.conn_id, e)
            self.io.write_packet(p.err_packet(1105, "auth lookup failed"))
            return False
        if stored is None or not auth.check_scramble(resp["auth"], salt,
                                                     stored):
            using = "YES" if resp["auth"] else "NO"
            self.io.write_packet(p.err_packet(
                1045, f"Access denied for user '{resp['user']}'@'%' "
                      f"(using password: {using})", "28000"))
            return False
        if resp["db"]:
            try:
                self.session.execute(f"use `{resp['db']}`")
            except Exception as e:
                self.io.write_packet(p.err_packet(1049, str(e), "42000"))
                return False
        self.user = resp["user"]
        self.session.user = resp["user"]  # PROCESSLIST identity
        self.io.write_packet(p.ok_packet())
        return True

    # ---- command loop (reference: conn.go:541,667) ----------------------
    def dispatch_command(self, cmd: int, payload: bytes) -> None:
        """One non-QUIT command's dispatch + response, shared by the
        legacy thread loop below and the aio front end (which frames
        commands itself and intercepts COM_QUERY for async pool
        submission before ever calling here)."""
        if cmd == p.COM_PING:
            self.io.write_packet(p.ok_packet())
        elif cmd == p.COM_INIT_DB:
            db = payload.decode("utf-8", "replace")
            self._run_sql(f"use `{db}`")
        elif cmd == p.COM_QUERY:
            self._run_sql(payload.decode("utf-8", "replace"))
        elif cmd == p.COM_FIELD_LIST:
            self._handle_field_list(payload)
        elif cmd == p.COM_STMT_PREPARE:
            self._handle_stmt_prepare(payload)
        elif cmd == p.COM_STMT_EXECUTE:
            self._handle_stmt_execute(payload)
        elif cmd == p.COM_STMT_CLOSE:
            import struct
            self._stmts.pop(
                struct.unpack_from("<I", payload, 0)[0], None)
            # COM_STMT_CLOSE sends no response
        else:
            self.io.write_packet(
                p.err_packet(1047, f"unknown command {cmd}"))

    def run(self, pre=None) -> None:
        """The per-connection thread body.  ``pre=(salt, payload)``
        resumes a handshake whose greeting round-trip already happened
        on the event loop (the aio front end's TLS handoff)."""
        try:
            ok = self.finish_handshake(*pre) if pre is not None \
                else self.handshake()
            if not ok:
                return
            while self.alive:
                self.io.reset_sequence()
                try:
                    data = self.io.read_packet()
                except ConnectionError:
                    return
                if not data:
                    continue
                cmd, payload = data[0], data[1:]
                if cmd == p.COM_QUIT:
                    return
                try:
                    self.dispatch_command(cmd, payload)
                except ConnectionError:
                    return
                except Exception as e:  # one bad command != dead conn
                    log.warning("conn-%d command error: %s",
                                self.conn_id, e)
                    try:
                        self.io.write_packet(_err_packet_for(e))
                    except OSError:
                        return
                if self.session.killed:
                    # plain KILL <id>: the connection drops after the
                    # current command's response went out
                    return
        finally:
            try:
                self.session.rollback_txn()
            except Exception:
                pass
            self.sock.close()
            self.server.remove_conn(self.conn_id)

    def _handle_field_list(self, payload: bytes) -> None:
        """COM_FIELD_LIST (reference conn.go:846 handleFieldList): table
        name up to NUL, optional field wildcard after; respond with one
        column definition per table column (empty default value) + EOF."""
        from ..catalog.infoschema import DatabaseNotExist, TableNotExist
        name = payload.split(b"\x00", 1)[0].decode("utf-8", "replace")
        db = self.session.current_db
        if not db:
            self.io.write_packet(p.err_packet(1046, "No database selected",
                                              "3D000"))
            return
        try:
            # fresh domain schema, NOT the session's statement pin: a
            # COM_FIELD_LIST never runs a statement, so the pin would
            # otherwise serve a stale column list across others' DDL
            info = self.session.domain.info_schema().table_by_name(db,
                                                                   name)
        except DatabaseNotExist:
            self.io.write_packet(p.err_packet(
                1049, f"Unknown database '{db}'", "42000"))
            return
        except TableNotExist:
            self.io.write_packet(p.err_packet(
                1146, f"Table '{db}.{name}' doesn't exist", "42S02"))
            return
        self.io.begin_buffer()
        try:
            for col in info.columns:
                self.io.write_packet(p.column_def(col.name, col.ft,
                                                  with_default=True))
            self.io.write_packet(p.eof_packet())
        finally:
            self.io.flush()

    # ---- prepared statements (binary protocol) --------------------------
    # The client-visible surface of the reference's binary resultset path
    # (conn.go:879 writeResultset binary=true, util.go:171 dumpBinaryRow):
    # prepare splits on '?' placeholders, execute decodes binary params,
    # substitutes literals, and streams the resultset in BINARY rows.
    MAX_PREPARED_STMTS = 1024  # per connection (max_prepared_stmt_count)

    def _handle_stmt_prepare(self, payload: bytes) -> None:
        if len(self._stmts) >= self.MAX_PREPARED_STMTS:
            self.io.write_packet(p.err_packet(
                1461, "Can't create more than "
                f"{self.MAX_PREPARED_STMTS} prepared statements", "42000"))
            return
        sql = payload.decode("utf-8", "replace")
        parts = p.split_placeholders(sql)
        n_params = len(parts) - 1
        # result-column metadata WITHOUT executing: plan the statement
        # with NULL in the placeholders (param types are unknown at
        # prepare time — MySQL's own prepare metadata does the same)
        cols = fts = None
        try:
            from ..parser import parse
            probe = parse("NULL".join(parts))
            if len(probe) == 1:
                meta = self.session.select_metadata(probe[0])
                if meta is not None:
                    cols, fts = meta
        except Exception:
            cols = fts = None
        sid = self._next_stmt_id
        self._next_stmt_id += 1
        self._stmts[sid] = [parts, None]
        self.io.begin_buffer()
        try:
            self.io.write_packet(p.prepare_ok(sid, n_params,
                                              len(cols) if cols else 0))
            for _ in range(n_params):
                self.io.write_packet(p.column_def("?", None))
            if n_params:
                self.io.write_packet(p.eof_packet())
            if cols:
                for name, ft in zip(cols, fts):
                    self.io.write_packet(p.column_def(name, ft))
                self.io.write_packet(p.eof_packet())
        finally:
            self.io.flush()

    def _handle_stmt_execute(self, payload: bytes) -> None:
        import struct
        sid = struct.unpack_from("<I", payload, 0)[0]
        ent = self._stmts.get(sid)
        if ent is None:
            self.io.write_packet(p.err_packet(
                1243, f"Unknown prepared statement handler ({sid})",
                "HY000"))
            return
        parts, prev_types = ent
        _, vals, types = p.decode_execute_params(payload, len(parts) - 1,
                                                 prev_types)
        ent[1] = types
        try:
            sql = parts[0] + "".join(p.literal(v) + seg
                                     for v, seg in zip(vals, parts[1:]))
        except ValueError as e:
            self.io.write_packet(p.err_packet(1367, str(e), "22007"))
            return
        from ..parser import parse
        stmts = parse(sql)
        if len(stmts) != 1:
            self.io.write_packet(p.err_packet(
                1064, "prepared statement must be a single statement",
                "42000"))
            return
        rs = self.server.pool.run(self.session, stmts[0], sql)
        if isinstance(rs, ResultSet):
            self._write_resultset(rs, binary=True)
        else:
            self.io.write_packet(p.ok_packet(
                affected=self.session.last_affected))

    def _run_sql(self, sql: str) -> None:
        """Execute statement-by-statement so each gets its own response,
        chained with SERVER_MORE_RESULTS_EXISTS (reference: conn.go
        handleQuery's multi-statement loop)."""
        from ..parser import parse
        try:
            stmts = parse(sql)
        except Exception as e:
            self.io.write_packet(p.err_packet(1064, str(e), "42000"))
            return
        for i, stmt in enumerate(stmts):
            more = i + 1 < len(stmts)
            label = sql if len(stmts) == 1 else \
                f"{sql[:200]} [stmt {i + 1}/{len(stmts)}]"
            try:
                # the full-lifecycle entry, via the bounded statement
                # pool (admission control + same-digest coalescing;
                # control statements bypass it inside pool.run): wire
                # statements get QueryObs scopes, summary/slow-log
                # records, and processlist info
                rs = self.server.pool.run(self.session, stmt, label)
            except Exception as e:
                log.debug("query error: %s", e)
                self.io.write_packet(_err_packet_for(e))
                return  # error aborts the remaining statements
            if isinstance(rs, ResultSet):
                self._write_resultset(rs, more)
            else:
                self.io.write_packet(p.ok_packet(
                    affected=self.session.last_affected,
                    more_results=more))

    def _write_resultset(self, rs: ResultSet, more: bool = False,
                         binary: bool = False) -> None:
        """Text rows for COM_QUERY, binary rows for COM_STMT_EXECUTE
        (reference conn.go:931,977 writeChunks text/binary split)."""
        from .packetio import lenenc_int
        self.io.begin_buffer()  # whole resultset -> one sendall
        try:
            self.io.write_packet(lenenc_int(len(rs.columns)))
            fields = rs.fields or [None] * len(rs.columns)
            for name, ft in zip(rs.columns, fields):
                self.io.write_packet(p.column_def(name, ft))
            self.io.write_packet(p.eof_packet())
            for row in rs.rows:
                self.io.write_packet(p.binary_row(row, fields) if binary
                                     else p.text_row(row))
            self.io.write_packet(p.eof_packet(more_results=more))
        finally:
            self.io.flush()


class Server:
    def __init__(self, storage, host: str = "127.0.0.1", port: int = 4000,
                 lease_s: float = 0.05, ssl_cert: str = "",
                 ssl_key: str = ""):
        self.storage = storage
        # mid-handshake TLS upgrade (reference: server/conn.go:448-455,
        # upgradeToTLS :1070) — advertised via CLIENT_SSL only when a
        # cert/key pair is configured
        self.ssl_ctx = None
        if ssl_cert and ssl_key:
            import ssl as _ssl
            ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(ssl_cert, ssl_key)
            self.ssl_ctx = ctx
        # one schema-cache domain PER SERVER (reference: domain singleton
        # per tidb-server process) with a background reload ticker so the
        # DDL syncer barrier sees this server catch up
        from ..domain import Domain
        self.domain = Domain(storage, lease_s=lease_s, background=True)
        # stats-driven auto-prewarm (session/prewarm.py): a background
        # worker that AOT-compiles the hottest digest families from
        # statements_summary off the query path — the serving-side cure
        # for the 15s+ first-run XLA compile.  Gated at runtime by the
        # GLOBAL tidb_auto_prewarm sysvar (re-read every cycle).
        from ..session.prewarm import PrewarmWorker
        self.prewarm = PrewarmWorker(storage, domain=self.domain)
        # bounded statement execution + admission control + same-digest
        # micro-batching (server/pool.py, server/admission.py) — the
        # high-throughput serving path (ROADMAP open item 2)
        from .pool import StatementPool
        self.pool = StatementPool(storage)
        # time-series metrics sampler (obs/tsring.py): snapshots every
        # registered counter/gauge source into the bounded ring behind
        # information_schema.metrics_history / metrics_summary and the
        # inspection engine, paced by the GLOBAL tidb_metrics_interval
        from ..obs.tsring import Sampler
        self.metrics_sampler = Sampler(storage)
        # continuous host profiler (obs/conprof.py): a background
        # stack sampler walking sys._current_frames() at the GLOBAL
        # tidb_conprof_rate (Hz, 0 = off), feeding
        # information_schema.continuous_profiling, /debug/conprof,
        # statements_summary CPU attribution, and the cpu-saturation /
        # profiler-overhead inspection rules
        from ..obs.conprof import ConprofSampler
        self.conprof_sampler = ConprofSampler(storage)
        # continuous heap profiler (obs/memprof.py): tracemalloc-based
        # allocation-site sampler paced by tidb_memprof_rate (Hz, 0 =
        # off + tracing stopped), feeding /debug/heap, the memory_state
        # reconciliation series, statements_summary heap attribution,
        # and the heap-growth / mem-untracked inspection rules
        from ..obs.memprof import MemprofSampler
        self.memprof_sampler = MemprofSampler(storage)
        # durable flight recorder (obs/flight.py): stamps this boot's
        # incarnation identity and — when the storage has a data dir —
        # appends crc-framed observability segments every
        # tidb_flight_interval, loads prior incarnations read-only, and
        # arms the atexit/faulthandler black-box flush.  Volatile
        # storage: identity only, zero flight movement.
        from ..obs.flight import FlightWriter
        self.flight_writer = FlightWriter(storage)
        self.host = host
        self.port = port
        self.sock: Optional[socket.socket] = None
        self.conns: Dict[int, ClientConn] = {}
        self._mu = threading.Lock()
        self._closed = threading.Event()
        # event-loop front end (server/aio.py): created lazily on the
        # first connection accepted while tidb_wire_mode = 'aio', so a
        # legacy-mode server spawns zero aio threads
        self._aio = None
        with _SERVERS_MU:
            _SERVERS.add(self)

    def start(self) -> int:
        """Bind + accept loop in a background thread; returns bound port."""
        from .auth import ensure_user_table
        ensure_user_table(self.storage)  # idempotent system-table bootstrap
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((self.host, self.port))
        self.port = self.sock.getsockname()[1]
        self.sock.listen(128)
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="mysql-accept")
        t.start()
        self.prewarm.start()
        self.metrics_sampler.start()
        self.conprof_sampler.start()
        self.memprof_sampler.start()
        self.flight_writer.start()
        # device-time truth knobs are process-global module state applied
        # at SET time (session/session.py) — a fresh server re-applies
        # whatever GLOBAL scope the storage carries
        try:
            g = getattr(self.storage, "_global_vars", {})
            from ..ops import profiler
            profiler.set_rate(float(
                g.get("tidb_device_profile_rate", 0) or 0))
            from ..obs import inspect as obs_inspect
            obs_inspect.set_slo_p99_ms(float(
                g.get("tidb_slo_p99_ms", 0) or 0))
            wal = self.storage.mvcc.wal
            if wal is not None and g.get("tidb_wal_fsync"):
                wal.set_fsync_policy(str(g["tidb_wal_fsync"]))
        except Exception:
            log.warning("device-profile knob re-apply failed",
                        exc_info=True)
        log.info("listening on %s:%d", self.host, self.port)
        return self.port

    def _max_connections(self) -> int:
        from .pool import read_global_int
        return read_global_int(self.storage,
                               "tidb_max_server_connections", 0)

    def wire_mode(self) -> str:
        """The live GLOBAL ``tidb_wire_mode``: ``legacy`` =
        thread-per-connection, ``aio`` = event-loop front end.  Read
        per accepted connection, so a mid-server flip applies to every
        NEW connection while established ones keep their mode."""
        from .pool import read_global_str
        return read_global_str(self.storage, "tidb_wire_mode",
                               "legacy").strip().lower()

    def aio_frontend(self):
        """The event-loop front end, started on first use."""
        with self._mu:
            fe = self._aio
            if fe is None:
                from .aio import AioFrontEnd
                fe = self._aio = AioFrontEnd(self)
        fe.start()
        return fe

    def _accept_loop(self) -> None:
        from . import admission
        while not self._closed.is_set():
            try:
                conn, addr = self.sock.accept()
            except OSError:
                return
            cap = self._max_connections()
            with self._mu:
                n_open = len(self.conns)
            # the connection-admission gate (server/admission.py): the
            # 1040 verdict and its accept/shed accounting live with the
            # 1041 statement gate, and run AT ACCEPT — before any
            # handshake work — in both wire modes
            if not admission.check_connect(n_open, cap):
                # MySQL refuses over-cap connects with ERR 1040 as the
                # FIRST packet (no handshake) — the unbounded accept
                # loop was a trivial DoS before this gate
                try:
                    PacketIO(conn).write_packet(p.err_packet(
                        1040, "Too many connections", "08004"))
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            cc = ClientConn(self, conn)
            with self._mu:
                self.conns[cc.conn_id] = cc
            if self.wire_mode() == "aio":
                # event-loop front end: the connection parks as a
                # registered file object — no thread is ever spawned
                self.aio_frontend().adopt(cc)
            else:
                threading.Thread(target=cc.run, daemon=True,
                                 name=f"conn-{cc.conn_id}").start()

    def remove_conn(self, cid: int) -> None:
        with self._mu:
            self.conns.pop(cid, None)

    def close(self) -> None:
        """Graceful drain (reference: server.go:155-283)."""
        self._closed.set()
        # shutdown drain: give in-flight pooled statements a bounded
        # window to complete (and their responses to flush) BEFORE the
        # front ends are torn down — the WAL checkpoint below must cover
        # every statement the wire acked.  Wedged statements (armed
        # sleeps, kills in flight) fall through to today's cancel path.
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            try:
                snap = self.pool.snapshot()
                if not snap.get("running") and not snap.get("queued"):
                    break
            except Exception:
                break
            time.sleep(0.01)  # qlint: disable=CC701 -- bounded drain poll at shutdown, no lock held
        with self._mu:
            fe = self._aio
        if fe is not None:
            fe.close()
        self.pool.close()
        self.prewarm.close()
        self.metrics_sampler.close()
        self.conprof_sampler.close()
        self.memprof_sampler.close()
        # flight black box: force-flush the final segment (last trace
        # ring + processlist) AFTER the samplers stop — their windows
        # are settled — and BEFORE the WAL checkpoint below, so a clean
        # shutdown marks this incarnation's record final.  Both wire
        # modes end here (the aio front end closed above).
        self.flight_writer.close()
        self.domain.close()
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        with self._mu:
            for cc in list(self.conns.values()):
                cc.alive = False
                try:
                    cc.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        # graceful-close durability parity (BOTH wire modes end here):
        # fsync the WAL tail and fold it into a checkpoint, so a clean
        # shutdown leaves the data dir checkpoint-clean.  Best effort —
        # a failed checkpoint leaves the unrotated log authoritative,
        # and a shared storage may already be closed by another server.
        flush = getattr(self.storage, "flush_and_checkpoint", None)
        if flush is not None:
            try:
                flush()
            except Exception:
                log.warning("wal checkpoint on close failed",
                            exc_info=True)
