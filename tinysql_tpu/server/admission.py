"""Admission control for the statement-execution pool.

The serving contract (ROADMAP open item 2): heavy multi-client load
must degrade by QUEUEING then SHEDDING — typed, retryable errors — and
never by unbounded thread/memory growth or a wedged accept loop.  The
gate runs at submit time in ``server/pool.py`` and folds the live
signals the engine already publishes:

- **pool queue depth** vs ``tidb_stmt_pool_queue_depth`` — the primary
  backpressure signal;
- **aggregate memory pressure**: the sum of every running statement's
  MemTracker bytes (PR 4 quotas; the always-installed tracker feeding
  ``processlist.mem_bytes``) vs ``tidb_admission_mem_limit`` — when the
  in-flight set already holds that much, new work is shed instead of
  queued behind statements that may OOM-abort anyway;
- **device cooldown** (``ops/degrade.py``): while planning is pinned to
  CPU after a device loss, the effective queue cap is HALVED — the CPU
  tier drains slower, so the same queue represents more latency; shed
  earlier rather than build a deeper backlog;
- the ``admissionQueueFull`` failpoint, which forces the queue-full
  verdict for chaos drills.

A rejection is MySQL error 1041 (ER_OUT_OF_RESOURCES) with an explicit
retry hint — clients are expected to back off and retry, exactly like
TiDB's server-busy shedding.

Counter-write discipline: ``STATS`` is written only through
:func:`_count` in this module (qlint OB401/OB402 — admission.py is an
owning module); /metrics renders the snapshot.
"""
from __future__ import annotations

import threading
from typing import Dict

from .. import fail

#: process-total admission verdicts: admitted = began executing,
#: queued = waited in the pool queue first, rejected = shed with 1041.
#: queue_wait_s_sum accumulates every pooled statement's measured wait
#: for a worker (pool claim time minus submit time) — the pool-side
#: half of the per-statement queue_wait attribution, so
#: statements_summary's sum_queue_wait_ms can be reconciled against the
#: serving tier's own accounting over any metrics_history window
STATS = {"admitted": 0, "queued": 0, "rejected": 0,
         "queue_wait_s_sum": 0.0}

#: process-total CONNECTION admission verdicts (the 1040 gate at
#: accept, both wire modes): accepts = connections handed to a front
#: end, sheds = connects refused with ERR 1040 before any handshake.
#: The ``tinysql_conn_accepts/sheds_total`` ring metrics read this —
#: the connection-pressure inspection rule's evidence.
CONN_STATS = {"accepts": 0, "sheds": 0}
_mu = threading.Lock()


def _count(key: str, n: int = 1) -> None:
    with _mu:
        STATS[key] = STATS.get(key, 0) + n


def _count_conn(key: str, n: int = 1) -> None:
    with _mu:
        CONN_STATS[key] = CONN_STATS.get(key, 0) + n


def stats_snapshot() -> Dict[str, int]:
    with _mu:
        return dict(STATS)


def conn_stats_snapshot() -> Dict[str, int]:
    with _mu:
        return dict(CONN_STATS)


def reset_stats() -> None:
    """Tests only."""
    with _mu:
        for k in STATS:
            STATS[k] = 0
        for k in CONN_STATS:
            CONN_STATS[k] = 0


class AdmissionRejected(Exception):
    """MySQL 1041 ER_OUT_OF_RESOURCES: the server is shedding load.
    The message always carries the retry hint — rejection is a
    backpressure signal, not a statement failure."""

    mysql_code = 1041
    sqlstate = "HY000"

    def __init__(self, reason: str):
        super().__init__(
            f"server overloaded ({reason}); retry later with backoff")
        self.reason = reason


def aggregate_stmt_mem() -> int:
    """Live bytes held by RUNNING statements across every registered
    session (the processlist feed's MemTracker sum)."""
    from ..utils import interrupt
    total = 0
    for _cid, sess in interrupt.sessions():
        if getattr(sess, "stmt_running", False):
            mt = getattr(sess, "_stmt_mem", None)
            if mt is not None:
                total += mt.consumed
    return total


def effective_queue_cap(queue_cap: int) -> int:
    """The configured cap, halved (min 1) while the backend is pinned to
    CPU by device-loss cooldown."""
    from ..ops import degrade
    if queue_cap > 0 and degrade.cpu_pinned():
        return max(1, queue_cap // 2)
    return queue_cap


def check_admit(queue_len: int, queue_cap: int,
                mem_limit: int = 0) -> None:
    """Raise :class:`AdmissionRejected` when the statement must be shed;
    plain return means it may run or queue.  The caller holds the pool
    lock, so ``queue_len`` is exact."""
    if fail.eval_point("admissionQueueFull"):
        _count("rejected")
        raise AdmissionRejected("admission queue full [failpoint]")
    cap = effective_queue_cap(queue_cap)
    if cap > 0 and queue_len >= cap:
        from ..ops import degrade
        note = " during device-loss cooldown" if cap != queue_cap \
            and degrade.cpu_pinned() else ""
        _count("rejected")
        raise AdmissionRejected(
            f"statement queue full: {queue_len} waiting, cap {cap}{note}")
    if mem_limit > 0:
        used = aggregate_stmt_mem()
        if used >= mem_limit:
            _count("rejected")
            raise AdmissionRejected(
                f"statement memory pressure: {used} bytes in flight, "
                f"tidb_admission_mem_limit {mem_limit}")


def check_connect(open_count: int, cap: int) -> bool:
    """The CONNECTION-admission verdict at accept time (both wire
    modes): True admits (counted), False means the accept loop must
    refuse with ERR 1040 as the first packet (counted as a shed).
    ``cap`` is ``tidb_max_server_connections`` (0 = unlimited)."""
    if cap > 0 and open_count >= cap:
        _count_conn("sheds")
        return False
    _count_conn("accepts")
    return True


def count_admitted() -> None:
    _count("admitted")


def count_queued() -> None:
    _count("queued")


def record_queue_wait(seconds: float) -> None:
    """One claimed entry's measured wait for a worker (called by the
    pool at claim time, queued and immediately-admitted entries both —
    an 'admitted' wait is just very small)."""
    _count("queue_wait_s_sum", float(seconds))
