"""MySQL protocol payloads: handshake, OK/ERR/EOF, column definitions,
text-protocol resultset rows (reference: server/conn.go writeInitialHandshake
:117, readOptionalSSLRequestAndHandshakeResponse :418, writeOK/writeError,
writeResultset :931-1050).
"""
from __future__ import annotations

import os
import struct
from typing import List, Optional

from ..mytypes import EvalType, FieldType
from .packetio import lenenc_int, lenenc_str, read_lenenc_int, read_nul_str

SERVER_VERSION = "5.7.25-tinysql-tpu-1.0"

# capability flags (subset)
CLIENT_LONG_PASSWORD = 1 << 0
CLIENT_FOUND_ROWS = 1 << 1
CLIENT_LONG_FLAG = 1 << 2
CLIENT_CONNECT_WITH_DB = 1 << 3
CLIENT_PROTOCOL_41 = 1 << 9
CLIENT_TRANSACTIONS = 1 << 13
CLIENT_SECURE_CONNECTION = 1 << 15
CLIENT_MULTI_STATEMENTS = 1 << 16
CLIENT_MULTI_RESULTS = 1 << 17
CLIENT_PLUGIN_AUTH = 1 << 19

SERVER_CAPS = (CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS | CLIENT_LONG_FLAG
               | CLIENT_CONNECT_WITH_DB | CLIENT_PROTOCOL_41
               | CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION
               | CLIENT_MULTI_STATEMENTS | CLIENT_MULTI_RESULTS
               | CLIENT_PLUGIN_AUTH)

SERVER_STATUS_AUTOCOMMIT = 0x0002
SERVER_MORE_RESULTS_EXISTS = 0x0008

# MySQL column types
TYPE_LONGLONG = 0x08
TYPE_DOUBLE = 0x05
TYPE_VAR_STRING = 0xFD

# commands
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_PING = 0x0E


def handshake_v10(conn_id: int, salt: bytes) -> bytes:
    out = bytearray()
    out.append(10)  # protocol version
    out += SERVER_VERSION.encode() + b"\x00"
    out += struct.pack("<I", conn_id)
    out += salt[:8] + b"\x00"
    out += struct.pack("<H", SERVER_CAPS & 0xFFFF)
    out.append(0x21)  # charset utf8
    out += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
    out += struct.pack("<H", (SERVER_CAPS >> 16) & 0xFFFF)
    out.append(21)  # auth plugin data len
    out += b"\x00" * 10
    out += salt[8:20] + b"\x00"
    out += b"mysql_native_password\x00"
    return bytes(out)


def parse_handshake_response(payload: bytes) -> dict:
    caps = struct.unpack_from("<I", payload, 0)[0]
    pos = 4 + 4 + 1 + 23  # caps, max packet, charset, reserved
    user, pos = read_nul_str(payload, pos)
    if caps & CLIENT_SECURE_CONNECTION:
        alen = payload[pos]
        auth = payload[pos + 1:pos + 1 + alen]
        pos += 1 + alen
    else:
        auth, pos = read_nul_str(payload, pos)
    db = b""
    if caps & CLIENT_CONNECT_WITH_DB and pos < len(payload):
        db, pos = read_nul_str(payload, pos)
    return {"caps": caps, "user": user.decode(), "db": db.decode(),
            "auth": bytes(auth)}


def ok_packet(affected: int = 0, last_insert_id: int = 0,
              warnings: int = 0, more_results: bool = False) -> bytes:
    status = SERVER_STATUS_AUTOCOMMIT | (
        SERVER_MORE_RESULTS_EXISTS if more_results else 0)
    return (b"\x00" + lenenc_int(affected) + lenenc_int(last_insert_id)
            + struct.pack("<H", status)
            + struct.pack("<H", warnings))


def err_packet(code: int, message: str, state: str = "HY000") -> bytes:
    return (b"\xff" + struct.pack("<H", code) + b"#" + state.encode()[:5]
            + message.encode("utf-8", "replace"))


def eof_packet(warnings: int = 0, more_results: bool = False) -> bytes:
    status = SERVER_STATUS_AUTOCOMMIT | (
        SERVER_MORE_RESULTS_EXISTS if more_results else 0)
    return (b"\xfe" + struct.pack("<H", warnings)
            + struct.pack("<H", status))


def _mysql_type(ft: Optional[FieldType]):
    if ft is None:
        return TYPE_VAR_STRING, 0x21
    et = ft.eval_type
    if et is EvalType.INT:
        return TYPE_LONGLONG, 0x3F  # binary charset for numerics
    if et is EvalType.REAL:
        return TYPE_DOUBLE, 0x3F
    return TYPE_VAR_STRING, 0x21


def column_def(name: str, ft: Optional[FieldType]) -> bytes:
    tp, charset = _mysql_type(ft)
    flags = ft.flag if ft is not None else 0
    out = bytearray()
    out += lenenc_str(b"def")          # catalog
    out += lenenc_str(b"")             # schema
    out += lenenc_str(b"")             # table
    out += lenenc_str(b"")             # org_table
    out += lenenc_str(name.encode())   # name
    out += lenenc_str(name.encode())   # org_name
    out.append(0x0C)                   # fixed-length fields marker
    out += struct.pack("<H", charset)
    out += struct.pack("<I", (ft.flen if ft is not None and ft.flen > 0
                              else 255))
    out.append(tp)
    out += struct.pack("<H", flags & 0xFFFF)
    out.append(0)                      # decimals
    out += b"\x00\x00"
    return bytes(out)


def text_row(values: List[object]) -> bytes:
    out = bytearray()
    for v in values:
        if v is None:
            out += b"\xfb"
        else:
            if isinstance(v, float):
                s = repr(v)
            else:
                s = str(v)
            out += lenenc_str(s.encode("utf-8", "surrogateescape"))
    return bytes(out)


def new_salt() -> bytes:
    # printable, non-zero bytes per protocol convention
    return bytes((b % 93) + 33 for b in os.urandom(20))
