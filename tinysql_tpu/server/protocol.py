"""MySQL protocol payloads: handshake, OK/ERR/EOF, column definitions,
text-protocol resultset rows (reference: server/conn.go writeInitialHandshake
:117, readOptionalSSLRequestAndHandshakeResponse :418, writeOK/writeError,
writeResultset :931-1050).
"""
from __future__ import annotations

import os
import struct
from typing import List, Optional

from ..mytypes import EvalType, FieldType
from .packetio import lenenc_int, lenenc_str, read_lenenc_int, read_nul_str

SERVER_VERSION = "5.7.25-tinysql-tpu-1.0"

# capability flags (subset)
CLIENT_LONG_PASSWORD = 1 << 0
CLIENT_FOUND_ROWS = 1 << 1
CLIENT_LONG_FLAG = 1 << 2
CLIENT_CONNECT_WITH_DB = 1 << 3
CLIENT_PROTOCOL_41 = 1 << 9
CLIENT_SSL = 1 << 11
CLIENT_TRANSACTIONS = 1 << 13
CLIENT_SECURE_CONNECTION = 1 << 15
CLIENT_MULTI_STATEMENTS = 1 << 16
CLIENT_MULTI_RESULTS = 1 << 17
CLIENT_PLUGIN_AUTH = 1 << 19

SERVER_CAPS = (CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS | CLIENT_LONG_FLAG
               | CLIENT_CONNECT_WITH_DB | CLIENT_PROTOCOL_41
               | CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION
               | CLIENT_MULTI_STATEMENTS | CLIENT_MULTI_RESULTS
               | CLIENT_PLUGIN_AUTH)

SERVER_STATUS_AUTOCOMMIT = 0x0002
SERVER_MORE_RESULTS_EXISTS = 0x0008

# MySQL column types
TYPE_LONGLONG = 0x08
TYPE_DOUBLE = 0x05
TYPE_VAR_STRING = 0xFD

# commands
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_CLOSE = 0x19


def handshake_v10(conn_id: int, salt: bytes,
                  caps: int = SERVER_CAPS) -> bytes:
    out = bytearray()
    out.append(10)  # protocol version
    out += SERVER_VERSION.encode() + b"\x00"
    out += struct.pack("<I", conn_id)
    out += salt[:8] + b"\x00"
    out += struct.pack("<H", caps & 0xFFFF)
    out.append(0x21)  # charset utf8
    out += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
    out += struct.pack("<H", (caps >> 16) & 0xFFFF)
    out.append(21)  # auth plugin data len
    out += b"\x00" * 10
    out += salt[8:20] + b"\x00"
    out += b"mysql_native_password\x00"
    return bytes(out)


def parse_handshake_response(payload: bytes) -> dict:
    caps = struct.unpack_from("<I", payload, 0)[0]
    pos = 4 + 4 + 1 + 23  # caps, max packet, charset, reserved
    user, pos = read_nul_str(payload, pos)
    if caps & CLIENT_SECURE_CONNECTION:
        alen = payload[pos]
        auth = payload[pos + 1:pos + 1 + alen]
        pos += 1 + alen
    else:
        auth, pos = read_nul_str(payload, pos)
    db = b""
    if caps & CLIENT_CONNECT_WITH_DB and pos < len(payload):
        db, pos = read_nul_str(payload, pos)
    return {"caps": caps, "user": user.decode(), "db": db.decode(),
            "auth": bytes(auth)}


def ok_packet(affected: int = 0, last_insert_id: int = 0,
              warnings: int = 0, more_results: bool = False) -> bytes:
    status = SERVER_STATUS_AUTOCOMMIT | (
        SERVER_MORE_RESULTS_EXISTS if more_results else 0)
    return (b"\x00" + lenenc_int(affected) + lenenc_int(last_insert_id)
            + struct.pack("<H", status)
            + struct.pack("<H", warnings))


def err_packet(code: int, message: str, state: str = "HY000") -> bytes:
    return (b"\xff" + struct.pack("<H", code) + b"#" + state.encode()[:5]
            + message.encode("utf-8", "replace"))


def eof_packet(warnings: int = 0, more_results: bool = False) -> bytes:
    status = SERVER_STATUS_AUTOCOMMIT | (
        SERVER_MORE_RESULTS_EXISTS if more_results else 0)
    return (b"\xfe" + struct.pack("<H", warnings)
            + struct.pack("<H", status))


def _mysql_type(ft: Optional[FieldType]):
    if ft is None:
        return TYPE_VAR_STRING, 0x21
    et = ft.eval_type
    if et is EvalType.INT:
        return TYPE_LONGLONG, 0x3F  # binary charset for numerics
    if et is EvalType.REAL:
        return TYPE_DOUBLE, 0x3F
    return TYPE_VAR_STRING, 0x21


def column_def(name: str, ft: Optional[FieldType],
               with_default: bool = False) -> bytes:
    """Column definition 41.  with_default appends the (empty) default-
    value field COM_FIELD_LIST responses carry (reference conn.go:846
    handleFieldList: zero DefaultValueLength to keep clients happy)."""
    tp, charset = _mysql_type(ft)
    flags = ft.flag if ft is not None else 0
    out = bytearray()
    out += lenenc_str(b"def")          # catalog
    out += lenenc_str(b"")             # schema
    out += lenenc_str(b"")             # table
    out += lenenc_str(b"")             # org_table
    out += lenenc_str(name.encode())   # name
    out += lenenc_str(name.encode())   # org_name
    out.append(0x0C)                   # fixed-length fields marker
    out += struct.pack("<H", charset)
    out += struct.pack("<I", (ft.flen if ft is not None and ft.flen > 0
                              else 255))
    out.append(tp)
    out += struct.pack("<H", flags & 0xFFFF)
    out.append(0)                      # decimals
    out += b"\x00\x00"
    if with_default:
        out += lenenc_int(0)           # empty default value
    return bytes(out)


def text_row(values: List[object]) -> bytes:
    out = bytearray()
    for v in values:
        if v is None:
            out += b"\xfb"
        else:
            if isinstance(v, float):
                s = repr(v)
            else:
                s = str(v)
            out += lenenc_str(s.encode("utf-8", "surrogateescape"))
    return bytes(out)


def binary_row(values: List[object],
               fields: Optional[List[Optional[FieldType]]] = None) -> bytes:
    """Binary-protocol resultset row (reference server/util.go:171
    dumpBinaryRow): 0x00 header, NULL bitmap with a 2-bit offset, then
    per-column wire values — int64 little-endian, float64 IEEE bits,
    strings length-encoded."""
    ncols = len(values)
    nmap = bytearray((ncols + 7 + 2) // 8)
    body = bytearray()
    fts = fields if fields is not None and len(fields) == ncols \
        else [None] * ncols
    for i, (v, ft) in enumerate(zip(values, fts)):
        if v is None:
            pos = i + 2
            nmap[pos // 8] |= 1 << (pos % 8)
            continue
        et = ft.eval_type if ft is not None else None
        if et is EvalType.INT or (et is None and isinstance(v, int)
                                  and not isinstance(v, bool)):
            # two's-complement longlong covers signed and unsigned
            body += struct.pack("<Q", int(v) & 0xFFFFFFFFFFFFFFFF)
        elif et is EvalType.REAL or (et is None and isinstance(v, float)):
            body += struct.pack("<d", float(v))
        else:
            body += lenenc_str(str(v).encode("utf-8", "surrogateescape"))
    return b"\x00" + bytes(nmap) + bytes(body)


def prepare_ok(stmt_id: int, n_params: int, n_cols: int = 0) -> bytes:
    """COM_STMT_PREPARE response header packet."""
    return (b"\x00" + struct.pack("<I", stmt_id)
            + struct.pack("<H", n_cols) + struct.pack("<H", n_params)
            + b"\x00" + struct.pack("<H", 0))


def split_placeholders(sql: str) -> List[str]:
    """Split sql on '?' placeholders OUTSIDE quoted strings/identifiers
    and comments (same comment syntax the lexer strips: '-- ', '#',
    '/*...*/'); len(result) - 1 is the parameter count."""
    parts = []
    cur = []
    quote = None
    n = len(sql)
    i = 0
    while i < n:
        ch = sql[i]
        if quote:
            cur.append(ch)
            if ch == "\\" and quote != "`" and i + 1 < n:
                cur.append(sql[i + 1])
                i += 2
                continue
            if ch == quote:
                quote = None
            i += 1
            continue
        if ch == "#" or (ch == "-" and (sql[i:i + 3] in ("-- ", "--\t",
                                                         "--\n")
                                        or sql[i:i + 2] == "--"
                                        and i + 2 == n)):
            j = sql.find("\n", i)
            j = n if j < 0 else j
            cur.append(sql[i:j])
            i = j
            continue
        if ch == "/" and sql[i:i + 2] == "/*":
            j = sql.find("*/", i + 2)
            j = n - 2 if j < 0 else j
            cur.append(sql[i:j + 2])
            i = j + 2
            continue
        if ch in ("'", '"', "`"):
            quote = ch
            cur.append(ch)
        elif ch == "?":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    parts.append("".join(cur))
    return parts


def decode_execute_params(payload: bytes, n_params: int,
                          prev_types: Optional[list]):
    """COM_STMT_EXECUTE payload -> (stmt_id, values, types).  Binary
    protocol parameter block: NULL bitmap (no offset), new-params-bound
    flag, type pairs, then wire values (longlong/double/lenenc subset —
    the engine's three type families)."""
    stmt_id = struct.unpack_from("<I", payload, 0)[0]
    pos = 9  # id(4) + flags(1) + iteration_count(4)
    if n_params == 0:
        return stmt_id, [], prev_types
    nmap_len = (n_params + 7) // 8
    nmap = payload[pos:pos + nmap_len]
    pos += nmap_len
    bound = payload[pos]
    pos += 1
    if bound:
        types = [(payload[pos + 2 * i], payload[pos + 2 * i + 1])
                 for i in range(n_params)]
        pos += 2 * n_params
    else:
        types = prev_types
    if types is None:
        raise ValueError("no parameter types bound")
    vals: List[object] = []
    for i, (tp, flag) in enumerate(types):
        if nmap[i // 8] & (1 << (i % 8)):
            vals.append(None)
            continue
        unsigned = bool(flag & 0x80)
        if tp == 0x01:    # TINY
            v = payload[pos] if unsigned \
                else struct.unpack_from("<b", payload, pos)[0]
            pos += 1
        elif tp in (0x02, 0x0D):  # SHORT / YEAR
            v = struct.unpack_from("<H" if unsigned else "<h",
                                   payload, pos)[0]
            pos += 2
        elif tp in (0x03, 0x09):  # LONG / INT24
            v = struct.unpack_from("<I" if unsigned else "<i",
                                   payload, pos)[0]
            pos += 4
        elif tp == 0x08:  # LONGLONG
            v = struct.unpack_from("<Q" if unsigned else "<q",
                                   payload, pos)[0]
            pos += 8
        elif tp == 0x04:  # FLOAT
            v = struct.unpack_from("<f", payload, pos)[0]
            pos += 4
        elif tp == 0x05:  # DOUBLE
            v = struct.unpack_from("<d", payload, pos)[0]
            pos += 8
        elif tp == 0x06:  # NULL
            v = None
        elif tp in (0x0F, 0xFC, 0xFD, 0xFE):  # VARCHAR/BLOB/VAR_STRING/STRING
            ln, pos = read_lenenc_int(payload, pos)
            v = payload[pos:pos + ln].decode("utf-8", "surrogateescape")
            pos += ln
        else:
            raise ValueError(f"unsupported parameter type 0x{tp:02x}")
        vals.append(v)
    return stmt_id, vals, types


def literal(v: object) -> str:
    """Render a decoded parameter as a SQL literal for substitution."""
    import math
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        if not math.isfinite(v):
            # 'inf'/'nan' are not SQL literals; reject cleanly rather
            # than surface a confusing parse error
            raise ValueError("non-finite double parameter")
        return repr(v)
    s = str(v).replace("\\", "\\\\").replace("'", "\\'")
    return f"'{s}'"


def new_salt() -> bytes:
    # printable, non-zero bytes per protocol convention
    return bytes((b % 93) + 33 for b in os.urandom(20))
