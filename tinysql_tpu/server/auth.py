"""mysql_native_password authentication (reference: server/conn.go:418
openSessionAndDoAuth — tinysql STRIPS the scramble check that full TiDB
performs there; this build restores it: privilege/auth CheckScrambledPassword
semantics against a bootstrapped mysql.user table).

Scheme: the server sends a 20-byte salt in the v10 handshake; the client
responds with  token = SHA1(pw) XOR SHA1(salt + SHA1(SHA1(pw))).  The
server stores only  '*' + HEX(SHA1(SHA1(pw)))  (MySQL's PASSWORD() hash),
recovers SHA1(pw) from the token, and re-hashes to compare.
"""
from __future__ import annotations

import hashlib


def hash_password(password: str) -> str:
    """MySQL PASSWORD(): '*' + HEX(SHA1(SHA1(pw))); '' stays ''."""
    if not password:
        return ""
    h = hashlib.sha1(hashlib.sha1(password.encode()).digest()).hexdigest()
    return "*" + h.upper()


def scramble(password: str, salt: bytes) -> bytes:
    """Client-side token (used by tests' raw-socket client)."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    x = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, x))


def check_scramble(token: bytes, salt: bytes, stored: str) -> bool:
    """Server-side verification against the stored PASSWORD() hash."""
    if not stored:
        return len(token) == 0  # empty password accepts only empty token
    if len(token) != 20 or len(stored) != 41 or not stored.startswith("*"):
        return False
    try:
        h2 = bytes.fromhex(stored[1:])  # SHA1(SHA1(pw))
    except ValueError:
        return False
    x = hashlib.sha1(salt + h2).digest()
    h1 = bytes(a ^ b for a, b in zip(token, x))  # candidate SHA1(pw)
    return hashlib.sha1(h1).digest() == h2


def ensure_user_table(storage) -> None:
    """Bootstrap mysql.user with a passwordless root (reference:
    session/bootstrap.go:126 creates the mysql.* system tables)."""
    from ..session.session import Session
    s = Session(storage)
    try:
        s.execute("create database if not exists mysql")
        s.execute("create table if not exists mysql.user ("
                  "user varchar(32) primary key, "
                  "authentication_string varchar(64))")
        if not s.query("select count(*) from mysql.user").rows[0][0]:
            s.execute("insert into mysql.user values ('root', '')")
    finally:
        s.rollback_txn()


def lookup_auth_string(storage, user: str):
    """Stored hash for `user`, or None when the user does not exist.
    The username is matched in PYTHON, never interpolated into SQL — a
    crafted username must not be able to escape a string literal."""
    from ..session.session import Session
    s = Session(storage)
    try:
        rows = s.query(
            "select user, authentication_string from mysql.user").rows
    finally:
        s.rollback_txn()
    for u, h in rows:
        if u == user:
            return h if h is not None else ""
    return None
