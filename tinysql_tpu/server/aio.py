"""C10k event-loop wire front end (ROADMAP item 3).

Thread-per-connection puts the serving ceiling at CONNECTION count:
every parked client pins a reader thread, so "millions of users" dies
at a few thousand OS threads long before the statement pool saturates.
This module multiplexes all connections accepted while
``tidb_wire_mode = 'aio'`` onto a bounded set of event-loop threads
(``tidb_aio_loops``, role ``aio``): idle connections park as registered
file objects in a ``selectors`` poll set, and complete COM_QUERY
statements are handed to the existing ``server/pool.py`` StatementPool
through the SAME admission gate (1041 shed + retry hint at submit; the
1040 connection cap runs at accept in ``server.py`` for both modes).

Division of labor per connection (one ``_AioConn`` state machine,
loop-thread-confined — no per-connection locks):

- **handshake / framing** — nonblocking: the greeting goes out at
  adoption, response packets are reassembled from whatever byte
  boundaries ``recv`` delivers (incl. 0xFFFFFF continuation frames),
  and a half-open peer that stalls mid-frame (or mid-handshake) is
  reaped after ``tidb_aio_frame_timeout_ms`` — the slowloris guard.
  TLS clients are handed off to a legacy ``conn-<id>`` thread at the
  SSLRequest packet (blocking wrap + blocking command loop); the loop
  itself never parks TLS sockets.
- **COM_QUERY** — async: each pooled statement is submitted with
  ``StatementPool.submit(on_done=...)``; the loop thread performs the
  submit, so the entry's ``contextvars.copy_context()`` captures the
  loop-side obs scope exactly like a connection thread would (CC704's
  cross-hop contract), and queue/batch wait attribution lands in
  statements_summary unchanged.  Completion is posted back over the
  loop's self-pipe; resultset encoding and all socket writes stay on
  the loop.  Control statements (SET / SHOW / KILL / BEGIN / DDL ...)
  execute inline on the loop — the control plane outlives a wedged
  pool, the ``admissionDelay`` drill's contract.
- **prepared statements / COM_FIELD_LIST / COM_INIT_DB** — reuse
  ``ClientConn.dispatch_command`` inline (COM_STMT_EXECUTE runs its
  pool leg blocking on the loop; the async path is COM_QUERY's).
- **KILL** — ``utils/interrupt.kill`` notifies the front end's
  observer; the victim's loop wakes via self-pipe and a killed IDLE
  connection closes within one tick — there is no blocked reader
  thread to notice otherwise.  A killed QUEUED statement is cancelled
  with ``cancel_if_queued`` (never occupies a worker); a RUNNING one
  aborts through the statement's own interrupt checks, and the
  connection drops after the in-flight command's response (plain-KILL
  parity with the legacy loop).

Every serving invariant survives the hop: sessions register in the
conn-id/process registries at adoption (``processlist`` shows parked
connections as Sleep rows), ``server.conns`` carries the ClientConn for
KILL targeting and drain, and storm results are byte-identical to the
thread-per-connection path (tests/test_aio.py).
"""
from __future__ import annotations

import logging
import os
import selectors
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..session.session import ResultSet
from ..utils import interrupt
from ..utils.interrupt import QueryKilled
from . import protocol as p
from .packetio import MAX_PAYLOAD, PacketIO
from .server import ClientConn, _err_packet_for

log = logging.getLogger("tinysql_tpu.aio")

#: fallback wake granularity (seconds): kill wakes and completions
#: arrive immediately over the self-pipe; the tick only paces the
#: slowloris sweep and the killed-while-unwatched backstop
_TICK_S = 0.1

#: outbound-buffer high-water mark (bytes): past it the loop stops
#: reading AND stops executing buffered commands for that connection
#: until the peer drains — the nonblocking twin of the backpressure a
#: legacy thread got for free from a blocking ``sendall``.  Without
#: this, one slow-reading client pipelining large resultsets grows
#: server memory without bound
WBUF_HWM = 1 << 20


class _ConnWriter:
    """``sendall`` target for a connection's PacketIO: protocol encoders
    (ok/err packets, resultset writers) land bytes in the connection's
    outbound buffer; the loop flushes it nonblocking."""

    __slots__ = ("_conn",)

    def __init__(self, conn: "_AioConn"):
        self._conn = conn

    def sendall(self, data: bytes) -> None:
        self._conn.wbuf += data


class _AioConn:
    """One multiplexed connection's state, confined to its loop thread.

    ``state``: handshake -> ready <-> running -> closing -> closed.
    ``ready`` with an empty read buffer IS the parked-idle state — the
    connection costs one registered file object and zero threads.
    """

    __slots__ = ("cc", "sock", "salt", "state", "rbuf", "wbuf", "parts",
                 "last_rx", "stmts", "idx", "sql", "entry", "events",
                 "pumping")

    def __init__(self, cc: ClientConn):
        self.cc = cc
        self.sock = cc.sock
        self.salt = b""
        self.state = "handshake"
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.parts: List[bytes] = []  # 0xFFFFFF continuation payloads
        self.last_rx = time.monotonic()
        self.stmts: list = []
        self.idx = 0
        self.sql = ""
        self.entry = None  # in-flight pool entry (async COM_QUERY leg)
        self.events = 0
        self.pumping = False


class _Loop:
    """One event-loop thread: a selector over parked connections plus a
    self-pipe carrying adoptions, statement completions, and kill wakes
    from other threads.  All connection state is mutated here only."""

    def __init__(self, fe: "AioFrontEnd", idx: int):
        self.fe = fe
        self.sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        # data=None marks the wake pipe in the ready list
        self.sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._mu = threading.Lock()
        self._inbox: deque = deque()
        self.conns: Dict[int, _AioConn] = {}
        self._closed = False
        self._last_tick = 0.0
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name=f"aio-loop-{idx}")

    # ---- cross-thread mailbox -------------------------------------------
    def post(self, item) -> None:
        """Enqueue work from any thread and wake the selector."""
        if self._closed:
            # the loop is gone, so a deferred session finalization
            # (_close_conn with an in-flight entry at shutdown) would
            # otherwise be lost — the worker is done with the session
            # once its completion posts here, so roll back on the
            # posting thread instead
            if item[0] == "done":
                conn, entry = item[1]
                if conn.state == "closed" and conn.entry is entry:
                    conn.entry = None
                    self._finalize_session(conn)
            return
        with self._mu:
            self._inbox.append(item)
        self._wake()

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"\x00")
        except (BlockingIOError, OSError):
            pass  # pipe full = a wake is already pending; or closing

    def close(self) -> None:
        self._closed = True
        self._wake()

    # ---- loop body -------------------------------------------------------
    def _run(self) -> None:
        while not self._closed:
            try:
                events = self.sel.select(timeout=_TICK_S)
            except OSError:
                break
            if self._closed:
                break
            self._drain_inbox()
            for key, mask in events:
                if key.data is None:
                    try:
                        os.read(self._wake_r, 4096)
                    except (BlockingIOError, OSError):
                        pass
                    continue
                conn = key.data
                if mask & selectors.EVENT_WRITE and conn.state != "closed":
                    self._flush(conn)
                if mask & selectors.EVENT_READ and conn.state != "closed":
                    self._on_readable(conn)
            self._tick()
        # drain: handle completions already posted, close every parked
        # connection (rollback + deregister), then drain once more —
        # closing a connection with an in-flight entry cancels it, and
        # that cancellation's completion lands in the inbox.  Entries
        # completing after this point hit post()'s closed-loop path.
        self._drain_inbox()
        for conn in list(self.conns.values()):
            self._close_conn(conn)
        self._drain_inbox()
        try:
            self.sel.close()
        except OSError:
            pass
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass

    def _drain_inbox(self) -> None:
        while True:
            with self._mu:
                if not self._inbox:
                    return
                kind, arg = self._inbox.popleft()
            if kind == "new":
                self._adopt(arg)
            elif kind == "done":
                self._on_stmt_done(*arg)
            elif kind == "kill":
                self._on_kill(arg)

    def _tick(self) -> None:
        """Per-tick sweep: slowloris frame timeouts + the killed-session
        backstop (the self-pipe wake is the fast path; this bounds the
        worst case at one tick).  Paced to _TICK_S regardless of how
        often select() returns — under load the ready list keeps the
        loop hot, and an O(conns) sweep per event batch would burn the
        one thread that serializes all I/O."""
        from .pool import read_global_int
        now = time.monotonic()
        if now - self._last_tick < _TICK_S:
            return
        self._last_tick = now
        tmo_s = read_global_int(self.fe.server.storage,
                                "tidb_aio_frame_timeout_ms", 10000) / 1e3
        for conn in list(self.conns.values()):
            if conn.state == "closed":
                continue
            sess = conn.cc.session
            # 'closing' is covered too: a killed victim whose response
            # sits unflushed against a stalled peer had a full tick to
            # drain — force the close rather than leak the socket
            if conn.state in ("handshake", "ready", "closing") \
                    and sess.killed:
                self._close_conn(conn)
                continue
            if conn.state == "running" and conn.entry is not None \
                    and (sess.guard.killed or sess.killed):
                self.fe.server.pool.cancel_if_queued(conn.entry,
                                                     QueryKilled())
            if tmo_s > 0 and now - conn.last_rx > tmo_s and (
                    conn.state == "handshake"
                    or (conn.state == "ready"
                        and (conn.rbuf or conn.parts))
                    # write-side stall: a closing connection whose err
                    # packet / final response the peer never reads
                    or (conn.state == "closing" and conn.wbuf)):
                log.info("aio conn-%d reaped: stalled in state %s for "
                         ">%.0fms (slowloris guard)", conn.cc.conn_id,
                         conn.state, tmo_s * 1e3)
                self._close_conn(conn)

    # ---- adoption / teardown --------------------------------------------
    def _adopt(self, cc: ClientConn) -> None:
        conn = _AioConn(cc)
        cc.io = PacketIO(_ConnWriter(conn))
        conn.salt = p.new_salt()
        try:
            conn.sock.setblocking(False)
            cc.io.write_packet(p.handshake_v10(cc.conn_id, conn.salt,
                                               cc.greeting_caps()))
            self.sel.register(conn.sock, selectors.EVENT_READ, conn)
        except (OSError, ValueError):
            self._discard(conn)
            return
        conn.events = selectors.EVENT_READ
        self.conns[cc.conn_id] = conn
        self._flush(conn)

    def _discard(self, conn: _AioConn) -> None:
        """Teardown for a connection that never registered."""
        conn.state = "closed"
        try:
            conn.sock.close()
        except OSError:
            pass
        self.fe.server.remove_conn(conn.cc.conn_id)

    def _close_conn(self, conn: _AioConn) -> None:
        if conn.state == "closed":
            return
        conn.state = "closed"
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        self.conns.pop(conn.cc.conn_id, None)
        # deregister BEFORE the peer-visible close: the moment close()
        # sends FIN a client can observe the drop and ask the server
        # about this conn_id (processlist, the KILL-idle acceptance
        # test) — a registry row outliving its socket reads as a leak
        self.fe.server.remove_conn(conn.cc.conn_id)
        try:
            conn.sock.close()
        except OSError:
            pass
        entry = conn.entry
        if entry is not None:
            # an in-flight statement still owns the session (a pool
            # worker may be executing it): a legacy connection thread
            # would block in pool.run until completion before rolling
            # back — the async twin cancels/aborts it and DEFERS the
            # session teardown to the completion callback, so rollback
            # never races the worker on the same session
            if not self.fe.server.pool.cancel_if_queued(entry,
                                                        QueryKilled()):
                guard = getattr(conn.cc.session, "guard", None)
                if guard is not None:
                    guard.kill()  # the peer is gone; abort fast
            return
        self._finalize_session(conn)

    def _finalize_session(self, conn: _AioConn) -> None:
        try:
            conn.cc.session.rollback_txn()
        except Exception:
            pass

    # ---- socket I/O ------------------------------------------------------
    def _set_events(self, conn: _AioConn, want: int) -> None:
        if want == conn.events or conn.state == "closed":
            return
        try:
            self.sel.modify(conn.sock, want, conn)
            conn.events = want
        except (KeyError, ValueError, OSError):
            self._close_conn(conn)

    def _flush(self, conn: _AioConn) -> None:
        if conn.state == "closed":
            return
        while conn.wbuf:
            try:
                n = conn.sock.send(conn.wbuf)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close_conn(conn)
                return
            if n <= 0:
                break
            del conn.wbuf[:n]
        if conn.wbuf:
            # backpressure: past the high-water mark stop READING from
            # the peer too — a client that won't drain its responses
            # must not keep feeding the server new commands
            read_ev = 0 if len(conn.wbuf) > WBUF_HWM \
                else selectors.EVENT_READ
            self._set_events(conn, read_ev | selectors.EVENT_WRITE)
        else:
            self._set_events(conn, selectors.EVENT_READ)
            if conn.state == "closing":
                self._close_conn(conn)
            elif conn.state == "ready" and conn.rbuf \
                    and not conn.pumping:
                # commands parked behind the high-water mark resume
                # once the peer drained the buffer
                self._pump(conn)

    def _on_readable(self, conn: _AioConn) -> None:
        try:
            data = conn.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)  # peer closed; rollback + deregister
            return
        conn.rbuf += data
        conn.last_rx = time.monotonic()
        self._pump(conn)
        self._flush(conn)

    def _next_packet(self, conn: _AioConn):
        """Extract one complete MySQL packet from the read buffer, or
        None while a frame is still partial — THE reassembly point for
        statements split across reads.  Oversized payloads follow the
        0xFFFFFF continuation rule (server/packetio.py)."""
        while True:
            if len(conn.rbuf) < 4:
                return None
            length = conn.rbuf[0] | (conn.rbuf[1] << 8) \
                | (conn.rbuf[2] << 16)
            if len(conn.rbuf) < 4 + length:
                return None
            seq = conn.rbuf[3]
            payload = bytes(conn.rbuf[4:4 + length])
            del conn.rbuf[:4 + length]
            if conn.parts or length == MAX_PAYLOAD:
                conn.parts.append(payload)
                if length == MAX_PAYLOAD:
                    continue
                payload = b"".join(conn.parts)
                conn.parts = []
            return payload, seq

    # ---- protocol state machine -----------------------------------------
    def _pump(self, conn: _AioConn) -> None:
        """Process buffered packets until the connection blocks on I/O
        or enters an async statement.  Reentrancy-guarded: a command
        completing synchronously inside the loop below must not start a
        nested pump over the same buffer."""
        if conn.pumping:
            return
        conn.pumping = True
        try:
            while conn.state in ("handshake", "ready") \
                    and len(conn.wbuf) <= WBUF_HWM:
                pkt = self._next_packet(conn)
                if pkt is None:
                    return
                payload, seq = pkt
                if conn.state == "handshake":
                    self._handshake(conn, payload, seq)
                else:
                    self._command(conn, payload, seq)
        finally:
            conn.pumping = False

    def _handshake(self, conn: _AioConn, payload: bytes,
                   seq: int) -> None:
        cc = conn.cc
        cc.io.sequence = (seq + 1) & 0xFF
        if (self.fe.server.ssl_ctx is not None and 4 <= len(payload) <= 32
                and struct.unpack_from("<I", payload, 0)[0]
                & p.CLIENT_SSL):
            self._tls_handoff(conn, payload)
            return
        if cc.finish_handshake(conn.salt, payload):
            conn.state = "ready"
        else:
            conn.state = "closing"  # err packet flushes, then close

    def _tls_handoff(self, conn: _AioConn, payload: bytes) -> None:
        """SSLRequest: hand the connection to a legacy thread for the
        blocking TLS wrap + command loop.  The loop never parks TLS
        sockets — the documented aio-mode tradeoff (TLS connections
        cost a thread in either wire mode)."""
        cc = conn.cc
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        self.conns.pop(cc.conn_id, None)
        conn.state = "closed"  # off the loop; the thread owns it now
        try:
            conn.sock.setblocking(True)
            if conn.wbuf:
                conn.sock.sendall(bytes(conn.wbuf))
                conn.wbuf.clear()
        except OSError:
            self._discard(conn)
            return
        io = PacketIO(conn.sock)
        io.sequence = cc.io.sequence
        cc.io = io
        threading.Thread(target=cc.run,
                         kwargs={"pre": (conn.salt, payload)},
                         daemon=True, name=f"conn-{cc.conn_id}").start()

    def _command(self, conn: _AioConn, payload: bytes, seq: int) -> None:
        if not payload:
            return
        cc = conn.cc
        cc.io.sequence = (seq + 1) & 0xFF
        cmd, body = payload[0], payload[1:]
        if cmd == p.COM_QUIT:
            self._close_conn(conn)
            return
        if cmd == p.COM_QUERY:
            self._start_query(conn, body.decode("utf-8", "replace"))
            return
        try:
            cc.dispatch_command(cmd, body)
        except Exception as e:  # one bad command != dead conn
            log.warning("aio conn-%d command error: %s", cc.conn_id, e)
            cc.io.write_packet(_err_packet_for(e))
        self._after_command(conn)

    def _after_command(self, conn: _AioConn) -> None:
        if conn.state != "closed" and conn.cc.session.killed:
            # plain KILL: drop after the current command's response
            conn.state = "closing"
            self._flush(conn)

    # ---- the async COM_QUERY driver -------------------------------------
    def _start_query(self, conn: _AioConn, sql: str) -> None:
        from ..parser import parse
        cc = conn.cc
        try:
            stmts = parse(sql)
        except Exception as e:
            cc.io.write_packet(p.err_packet(1064, str(e), "42000"))
            self._after_command(conn)
            return
        conn.sql = sql
        conn.stmts = stmts
        conn.idx = 0
        conn.state = "running"
        self._advance(conn)

    def _advance(self, conn: _AioConn) -> None:
        """Drive the multi-statement COM_QUERY forward: pooled
        statements submit async and park the driver until their done
        callback; control statements execute inline (the pool bypass,
        same as a connection thread)."""
        cc = conn.cc
        pool = self.fe.server.pool
        while conn.idx < len(conn.stmts):
            stmt = conn.stmts[conn.idx]
            more = conn.idx + 1 < len(conn.stmts)
            label = conn.sql if len(conn.stmts) == 1 else \
                f"{conn.sql[:200]} [stmt {conn.idx + 1}/{len(conn.stmts)}]"
            if pool.routes_to_pool(stmt):
                try:
                    conn.entry = pool.submit(cc.session, stmt, label,
                                             on_done=self._done_cb(conn))
                except Exception as e:  # 1041 shed / pool shutdown
                    log.debug("query error: %s", e)
                    cc.io.write_packet(_err_packet_for(e))
                    self._finish_command(conn)
                    return
                return  # parked: _on_stmt_done resumes this driver
            try:
                rs = pool.run(cc.session, stmt, label)
            except Exception as e:
                log.debug("query error: %s", e)
                cc.io.write_packet(_err_packet_for(e))
                self._finish_command(conn)
                return
            self._write_result(conn, rs, more)
            conn.idx += 1
        self._finish_command(conn)

    def _done_cb(self, conn: _AioConn):
        return lambda entry: self.post(("done", (conn, entry)))

    def _on_stmt_done(self, conn: _AioConn, entry) -> None:
        if conn.state == "closed" and conn.entry is entry:
            # the deferred teardown leg (_close_conn with an in-flight
            # entry): the worker is done with the session — now it is
            # safe to roll back
            conn.entry = None
            self._finalize_session(conn)
            return
        if conn.state != "running" or conn.entry is not entry:
            return  # connection closed mid-statement: drop the result
        conn.entry = None
        cc = conn.cc
        if entry.error is not None:
            log.debug("query error: %s", entry.error)
            cc.io.write_packet(_err_packet_for(entry.error))
            self._finish_command(conn)  # error aborts remaining stmts
        else:
            self._write_result(conn, entry.result,
                               conn.idx + 1 < len(conn.stmts))
            conn.idx += 1
            self._advance(conn)
        self._flush(conn)

    def _write_result(self, conn: _AioConn, rs, more: bool) -> None:
        cc = conn.cc
        if isinstance(rs, ResultSet):
            cc._write_resultset(rs, more)
        else:
            cc.io.write_packet(p.ok_packet(
                affected=cc.session.last_affected, more_results=more))

    def _finish_command(self, conn: _AioConn) -> None:
        conn.stmts = []
        conn.idx = 0
        conn.entry = None
        if conn.state == "running":
            conn.state = "ready"
        self._after_command(conn)
        self._flush(conn)
        if conn.state == "ready" and conn.rbuf:
            self._pump(conn)  # commands pipelined during execution

    def _on_kill(self, conn_id: int) -> None:
        """Self-pipe kill wake: close a killed idle connection NOW
        (there is no reader thread to notice), cancel a killed queued
        statement without a worker."""
        conn = self.conns.get(conn_id)
        if conn is None or conn.state == "closed":
            return
        sess = conn.cc.session
        if conn.state in ("handshake", "ready") and sess.killed:
            self._close_conn(conn)
        elif conn.state == "running" and conn.entry is not None \
                and (sess.guard.killed or sess.killed):
            self.fe.server.pool.cancel_if_queued(conn.entry,
                                                 QueryKilled())


class AioFrontEnd:
    """The bounded set of event-loop threads multiplexing every
    aio-mode connection (``tidb_aio_loops``; new connections round-robin
    across loops).  Owned by ``server.Server``; started lazily on the
    first aio-mode accept."""

    def __init__(self, server):
        self.server = server
        self._mu = threading.Lock()
        self._loops: List[_Loop] = []
        self._started = False
        self._closed = False
        self._rr = 0

    def start(self) -> None:
        from .pool import read_global_int
        with self._mu:
            if self._started or self._closed:
                return
            n = max(1, read_global_int(self.server.storage,
                                       "tidb_aio_loops", 1))
            self._loops = [_Loop(self, i) for i in range(n)]
            self._started = True
            loops = list(self._loops)
        for lp in loops:
            lp.thread.start()
        interrupt.add_kill_observer(self._kill_observer)
        log.info("aio front end up: %d event loop(s)", len(loops))

    def adopt(self, cc: ClientConn) -> None:
        """Hand one accepted (already conn-registered) connection to an
        event loop.  Called from the accept thread."""
        with self._mu:
            if self._closed or not self._loops:
                lp = None
            else:
                lp = self._loops[self._rr % len(self._loops)]
                self._rr += 1
        if lp is None:
            try:
                cc.sock.close()
            except OSError:
                pass
            self.server.remove_conn(cc.conn_id)
            return
        lp.post(("new", cc))

    def _kill_observer(self, conn_id: int, query_only: bool) -> None:
        """Runs on the KILLER's thread: wake every loop — the one that
        owns the victim acts, the rest no-op on an unknown id."""
        with self._mu:
            loops = list(self._loops)
        for lp in loops:
            lp.post(("kill", conn_id))

    def snapshot(self) -> dict:
        with self._mu:
            loops = list(self._loops)
        return {"loops": len(loops),
                "conns": sum(len(lp.conns) for lp in loops),
                "closed": self._closed}

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
            loops = list(self._loops)
        interrupt.remove_kill_observer(self._kill_observer)
        for lp in loops:
            lp.close()
        for lp in loops:
            lp.thread.join(2)
