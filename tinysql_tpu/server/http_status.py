"""Status HTTP endpoint (reference: server/http_status.go:32-99 — index
page, /status JSON, pprof routes; pprof is Go-specific, the analogue here
is /debug/threads) plus the observability surfaces: Prometheus-text
``/metrics`` (obs/metrics.py), ``/debug/trace`` (the last N query traces
as JSON, chrome://tracing-loadable per entry), and ``/debug/slowlog``
(recent structured slow-query records).
"""
from __future__ import annotations

import json
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


#: (path, one-line description) for every registered debug endpoint —
#: the GET /debug/ index renders this list (ISSUE 20: the surfaces were
#: discoverable only by reading docs)
DEBUG_ENDPOINTS = (
    ("/debug/trace", "last N query traces (JSON; chrome://tracing "
                     "loadable per entry; ?n=)"),
    ("/debug/slowlog", "recent structured slow-query records (JSON)"),
    ("/debug/stmtsummary", "statement summary current window (JSON; "
                           "?incarnation=N replays a prior run)"),
    ("/debug/metrics/summary", "windowed per-metric delta/rate/avg/max "
                               "(JSON)"),
    ("/debug/inspection", "automated inspection findings (JSON; "
                          "?window=, ?incarnation=N)"),
    ("/debug/programs", "compiled-program catalog (JSON)"),
    ("/debug/conprof", "continuous profiler collapsed stacks "
                       "(flamegraph text; ?window=, ?incarnation=N)"),
    ("/debug/heap", "heap profiler collapsed allocation sites "
                    "(flamegraph text; ?window=)"),
    ("/debug/prewarm", "auto-prewarm worker snapshot (JSON)"),
    ("/debug/flight", "flight recorder: arming, stats, incarnation "
                      "catalogue (JSON)"),
    ("/debug/threads", "live python stacks, all threads (text)"),
)


def _prior_incarnation(qs) -> Optional[int]:
    """``?incarnation=N`` → N when N names a PRIOR run; None means
    serve the live surface (absent, junk, or the current id)."""
    from ..obs.flight import current_incarnation
    try:
        n = int(qs.get("incarnation", [""])[0])
    except (ValueError, IndexError):
        return None
    return n if 0 < n < current_incarnation() else None


def _make_handler(server_ref):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send_prior(self, tier: str, incarnation: int,
                        columns) -> None:
            # a prior incarnation's replayed mem-table rows (the live
            # endpoint's dict/text shape only exists for the live
            # stores; dead runs serve rows + column names)
            from ..obs.flight import active_store
            store = active_store()
            rows = store.tier_rows(incarnation, tier) \
                if store is not None else []
            self._send(200, json.dumps(
                {"incarnation": incarnation,
                 "columns": [c[0] for c in columns],
                 "rows": rows}, default=str).encode())

        def _send(self, code: int, body: bytes,
                  ctype: str = "application/json"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            srv = server_ref()
            parsed = urlparse(self.path)
            if parsed.path == "/metrics":
                from ..obs.metrics import render_prometheus
                self._send(200, render_prometheus().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
                return
            if parsed.path == "/debug/trace":
                from ..obs.trace import recent_traces
                qs = parse_qs(parsed.query)
                try:
                    n = int(qs.get("n", ["0"])[0])
                except ValueError:
                    n = 0
                n = n if n > 0 else None  # last-N only; junk = everything
                self._send(200, json.dumps(
                    recent_traces(n), default=str).encode())
                return
            if parsed.path == "/debug/slowlog":
                from ..obs.slowlog import recent
                self._send(200, json.dumps(recent(), default=str).encode())
                return
            if parsed.path == "/debug/stmtsummary":
                from ..obs.stmtsummary import COLUMNS, snapshot
                qs = parse_qs(parsed.query)
                prior = _prior_incarnation(qs)
                if prior is not None:
                    self._send_prior("summary", prior, COLUMNS)
                    return
                self._send(200, json.dumps(snapshot(),
                                           default=str).encode())
                return
            if parsed.path == "/debug/inspection":
                from ..obs import inspect as oinspect
                qs = parse_qs(parsed.query)
                prior = _prior_incarnation(qs)
                if prior is not None:
                    self._send_prior("findings", prior, oinspect.COLUMNS)
                    return
                # absent -> the bounded default window; window=0 -> the
                # whole retained ring
                try:
                    window = float(
                        qs.get("window", [oinspect.DEFAULT_WINDOW_S])[0]
                    ) or None
                except ValueError:
                    window = oinspect.DEFAULT_WINDOW_S
                self._send(200, json.dumps(
                    oinspect.snapshot(window_s=window),
                    default=str).encode())
                return
            if parsed.path == "/debug/metrics/summary":
                from ..obs.tsring import RING
                self._send(200, json.dumps(
                    RING.summary_rows(), default=str).encode())
                return
            if parsed.path == "/debug/conprof":
                # collapsed-stack text (flamegraph.pl / speedscope
                # ingest it directly); ?window=N bounds to the last N
                # seconds of retained windows (absent/0 = everything)
                from ..obs.conprof import COLUMNS, collapsed
                qs = parse_qs(parsed.query)
                prior = _prior_incarnation(qs)
                if prior is not None:
                    self._send_prior("conprof", prior, COLUMNS)
                    return
                try:
                    window = float(qs.get("window", ["0"])[0]) or None
                except ValueError:
                    window = None
                self._send(200, collapsed(window_s=window).encode(),
                           "text/plain; charset=utf-8")
                return
            if parsed.path == "/debug/heap":
                # collapsed allocation-site text (same format as
                # /debug/conprof — conprof.parse_collapsed and
                # flamegraph.pl ingest both; counts are live KB);
                # ?window=N bounds to the last N seconds of windows
                from ..obs.memprof import collapsed as heap_collapsed
                qs = parse_qs(parsed.query)
                try:
                    window = float(qs.get("window", ["0"])[0]) or None
                except ValueError:
                    window = None
                self._send(200,
                           heap_collapsed(window_s=window).encode(),
                           "text/plain; charset=utf-8")
                return
            if parsed.path == "/debug/programs":
                from ..ops.progcache import catalog_snapshot
                self._send(200, json.dumps(catalog_snapshot(),
                                           default=str).encode())
                return
            if parsed.path == "/debug/flight":
                from ..obs.flight import debug_snapshot
                self._send(200, json.dumps(debug_snapshot(),
                                           default=str).encode())
                return
            if parsed.path in ("/debug", "/debug/"):
                rows = "".join(
                    f'<li><a href="{p}">{p}</a> — {desc}</li>'
                    for p, desc in DEBUG_ENDPOINTS)
                self._send(200, ("<h1>debug endpoints</h1><ul>"
                                 f"{rows}</ul>").encode(), "text/html")
                return
            if parsed.path == "/debug/prewarm":
                from ..session.prewarm import stats_snapshot
                worker = getattr(srv, "prewarm", None) if srv else None
                body = worker.snapshot() if worker is not None \
                    else {"stats": stats_snapshot()}
                self._send(200, json.dumps(body, default=str).encode())
                return
            if parsed.path == "/status":
                from ..server.protocol import SERVER_VERSION
                from ..server.admission import stats_snapshot as adm
                from ..ops.batching import stats_snapshot as batch
                pool = getattr(srv, "pool", None) if srv else None
                body = json.dumps({
                    "version": SERVER_VERSION,
                    "connections": len(srv.conns) if srv else 0,
                    "tls_connections": sum(
                        1 for c in list(srv.conns.values())
                        if getattr(c, "tls", False)) if srv else 0,
                    "pool": pool.snapshot() if pool is not None else {},
                    "admission": adm(),
                    "batching": batch(),
                }).encode()
                self._send(200, body)
            elif parsed.path == "/debug/threads":
                out = []
                for tid, frame in sys._current_frames().items():
                    out.append(f"--- thread {tid} ---")
                    out.extend(traceback.format_stack(frame))
                self._send(200, "\n".join(out).encode(),
                           "text/plain; charset=utf-8")
            elif parsed.path == "/":
                self._send(200, b"<h1>tinysql-tpu status</h1>"
                           b'<a href="/status">status</a> '
                           b'<a href="/metrics">metrics</a> '
                           b'<a href="/debug/trace">traces</a> '
                           b'<a href="/debug/slowlog">slowlog</a> '
                           b'<a href="/debug/stmtsummary">stmtsummary</a> '
                           b'<a href="/debug/programs">programs</a> '
                           b'<a href="/debug/conprof">conprof</a> '
                           b'<a href="/debug/heap">heap</a> '
                           b'<a href="/debug/prewarm">prewarm</a> '
                           b'<a href="/debug/inspection">inspection</a> '
                           b'<a href="/debug/metrics/summary">'
                           b'metrics-summary</a> '
                           b'<a href="/debug/flight">flight</a> '
                           b'<a href="/debug/">debug-index</a> '
                           b'<a href="/debug/threads">threads</a>',
                           "text/html")
            else:
                self._send(404, b"{}")
    return Handler


class StatusServer:
    def __init__(self, mysql_server, host: str = "127.0.0.1", port: int = 0):
        import weakref
        ref = weakref.ref(mysql_server) if mysql_server is not None \
            else (lambda: None)
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(ref))
        self.port = self.httpd.server_address[1]

    def start(self) -> int:
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True,
                             name="status-http")
        t.start()
        return self.port

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
