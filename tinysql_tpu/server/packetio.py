"""MySQL packet framing: 3-byte little-endian length + 1-byte sequence id
(reference: server/packetio.go readPacket/writePacket).

Oversized payloads split at 0xFFFFFF per the protocol; sequence ids are
tracked per round-trip.
"""
from __future__ import annotations

import socket
import struct
from typing import Optional

MAX_PAYLOAD = 0xFFFFFF


class PacketIO:
    def __init__(self, conn: socket.socket):
        self.conn = conn
        self.sequence = 0
        self._buf: Optional[bytearray] = None

    def reset_sequence(self) -> None:
        self.sequence = 0

    def begin_buffer(self) -> None:
        """Frame subsequent packets into one buffer; flush() sends them in
        a single syscall (reference: bufio writer in server/packetio.go)."""
        if self._buf is None:
            self._buf = bytearray()

    def flush(self) -> None:
        buf, self._buf = self._buf, None
        if buf:
            self.conn.sendall(bytes(buf))

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            part = self.conn.recv(n - len(buf))
            if not part:
                raise ConnectionError("connection closed")
            buf += part
        return buf

    def read_packet(self) -> bytes:
        payload = b""
        while True:
            header = self._read_exact(4)
            length = header[0] | (header[1] << 8) | (header[2] << 16)
            self.sequence = (header[3] + 1) & 0xFF
            payload += self._read_exact(length) if length else b""
            if length < MAX_PAYLOAD:
                return payload

    def write_packet(self, payload: bytes) -> None:
        out = bytearray()
        pos = 0
        while True:
            part = payload[pos:pos + MAX_PAYLOAD]
            out += struct.pack("<I", len(part))[:3]
            out.append(self.sequence)
            self.sequence = (self.sequence + 1) & 0xFF
            out += part
            pos += len(part)
            if len(part) < MAX_PAYLOAD:
                break
        if self._buf is not None:
            self._buf += out
        else:
            self.conn.sendall(bytes(out))


# ---- lenenc helpers --------------------------------------------------------

def lenenc_int(v: int) -> bytes:
    if v < 251:
        return bytes([v])
    if v < 1 << 16:
        return b"\xfc" + struct.pack("<H", v)
    if v < 1 << 24:
        return b"\xfd" + struct.pack("<I", v)[:3]
    return b"\xfe" + struct.pack("<Q", v)


def lenenc_str(s: bytes) -> bytes:
    return lenenc_int(len(s)) + s


def read_lenenc_int(buf: bytes, pos: int):
    first = buf[pos]
    if first < 251:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(buf[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


def read_nul_str(buf: bytes, pos: int):
    end = buf.index(0, pos)
    return buf[pos:end], end + 1
