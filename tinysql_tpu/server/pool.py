"""Bounded statement-execution pool + same-digest coalescer driver.

Replaces the wire server's unbounded run-on-the-connection-thread model
for heavy statements: SELECT / INSERT / DELETE submitted by connection
threads execute on at most ``tidb_stmt_pool_size`` worker threads, with
a bounded queue in front (``tidb_stmt_pool_queue_depth``) guarded by
``server/admission.py``.  Everything else (SET, SHOW, KILL, BEGIN /
COMMIT, USE, EXPLAIN, DDL, ...) keeps executing directly on the
connection thread — deliberately, so KILL and introspection always work
even when every worker is wedged (the ``admissionDelay`` chaos drill).

Queued statements are first-class citizens: ``processlist`` shows them
with state ``queued`` (session.stmt_state, TIME = wait-so-far), KILL
while queued cancels without ever occupying a worker, and a plain KILL
/ server shutdown wakes the waiting connection thread with a typed
error.

Wait attribution: the pool measures each entry's queue wait (submit →
worker claim) and batch wait (claim → the leg that produced its
result), feeds the pool-side sum to ``server/admission.py``
(``queue_wait_s_sum`` → /metrics + the time-series ring), and deposits
the per-statement measurement on the session (``pending_wait``) right
before invoking it — the statement scope turns it into ``queue_wait``
/ ``batch_wait`` trace spans, ``statements_summary`` columns, and
``slow_query`` fields.  Workers run each statement inside a
``contextvars`` copy of the submitting thread's context, so the span
chain parents across the thread hop (the PR 3 devpipe idiom).

Coalescing: when a worker dequeues a SELECT whose normalized-SQL digest
belongs to a learned batchable family (ops/batching.py — statements
that executed a params-compiled fused dispatch), it pulls every
same-digest statement already waiting (up to ``tidb_batch_max_size``,
topping up within ``tidb_batch_window_ms``) and drives the group
through one batch round: collect (park each member's ParamTable at the
warm program boundary), dispatch (stacked — all ParamTables on a
leading batch axis through ONE vmap-batched program when
``tidb_batch_stack_max`` >= 2 and the layouts agree; back-to-back
through the solo program otherwise), replay (each member consumes its
precomputed output and finishes normally).  Members that never reach a
batchable dispatch complete solo during collect — fallback is
transparent.
"""
from __future__ import annotations

import contextvars
import logging
import threading
import time
import weakref
from collections import deque
from typing import List, Optional

from . import admission
from .. import fail
from ..parser import ast
from ..utils.interrupt import QueryKilled

log = logging.getLogger("tinysql_tpu.pool")

#: live pools (weak — a pool dies with its Server); /metrics sums their
#: queued/running gauges so the queued-vs-running split is scrapeable.
#: Guarded (qlint CC7xx triage): the sampler thread snapshots the set
#: while servers register pools (and GC discards dead ones) on other
#: threads — iterating a WeakSet under concurrent mutation raises
#: RuntimeError out of the /metrics scrape
_POOLS: "weakref.WeakSet" = weakref.WeakSet()
_POOLS_MU = threading.Lock()


def read_global_int(storage, name: str, default: int) -> int:
    """GLOBAL-scope sysvar as an int (DEFAULT_SYSVARS fallback) — THE
    config-read helper for server-side components that have no session
    (the pool, the accept loop's connection cap)."""
    from ..session.session import DEFAULT_SYSVARS
    g = getattr(storage, "_global_vars", {})
    try:
        return int(g.get(name, DEFAULT_SYSVARS.get(name, default)))
    except (TypeError, ValueError):
        return default


def read_global_str(storage, name: str, default: str) -> str:
    """GLOBAL-scope sysvar as a string (the ``tidb_wire_mode`` read in
    the accept loop)."""
    from ..session.session import DEFAULT_SYSVARS
    g = getattr(storage, "_global_vars", {})
    v = g.get(name, DEFAULT_SYSVARS.get(name, default))
    return default if v is None else str(v)


def gauges() -> dict:
    """Aggregate queued/running across every live pool (the /metrics
    feed)."""
    out = {"queued": 0, "running": 0}
    with _POOLS_MU:
        pools = list(_POOLS)
    for p in pools:
        snap = p.snapshot()
        if not snap["closed"]:
            out["queued"] += snap["queued"]
            out["running"] += snap["running"]
    return out

#: statement classes that execute on the pool; the rest run directly on
#: the connection thread (control plane must outlive a wedged pool)
_POOLED_STMTS = (ast.SelectStmt, ast.InsertStmt, ast.DeleteStmt,
                 ast.UpdateStmt)


class PoolClosed(Exception):
    """Typed shutdown error (generic 1105 on the wire)."""
    mysql_code = 1105
    sqlstate = "HY000"

    def __init__(self):
        super().__init__("server is shutting down")


class _Entry:
    __slots__ = ("session", "stmt", "label", "digest", "done", "result",
                 "error", "state", "queued_at", "batchable", "ctx",
                 "queued_mono", "claimed_at", "queue_wait_s", "verdict",
                 "on_done")

    def __init__(self, session, stmt, label: str, digest: str,
                 batchable: bool, on_done=None):
        # completion callback for async submitters (the aio front end):
        # invoked exactly once from complete(), on whatever thread
        # completed the entry (pool worker, canceller, closer).  It must
        # only ENQUEUE — socket writes stay on the event loop.
        self.on_done = on_done
        self.session = session
        self.stmt = stmt
        self.label = label
        self.digest = digest
        self.batchable = batchable
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.state = "queued"
        self.queued_at = time.time()
        # the submitting thread's context, captured NOW: workers run the
        # statement inside a copy of it, so spans recorded during
        # execution parent to whatever span was live at submit time (the
        # PR 3 devpipe cross-thread idiom) instead of silently starting
        # a fresh chain on the worker thread
        self.ctx = contextvars.copy_context()
        # wait attribution (monotonic clock): queue_wait_s is filled at
        # claim time; verdict is "queued" when the entry had to wait
        # behind the pool, "admitted" when a worker was free
        self.queued_mono = time.monotonic()
        self.claimed_at = self.queued_mono
        self.queue_wait_s = 0.0
        self.verdict = "admitted"

    def claim(self) -> None:
        """A worker took this entry off the queue: freeze its measured
        queue wait.  The pool-side accumulator is fed later, at
        execution start (past the kill pre-checks) — an entry killed
        while queued never executes, never ingests its wait into
        statements_summary, and so must not count on the pool side
        either, or the two surfaces drift apart under KILL traffic."""
        self.claimed_at = time.monotonic()
        self.queue_wait_s = max(0.0, self.claimed_at - self.queued_mono)

    def wait_info(self, batch_wait_s: float = 0.0) -> dict:
        return {"queue_wait_s": self.queue_wait_s,
                "batch_wait_s": max(0.0, batch_wait_s),
                "admission_verdict": self.verdict}

    def complete(self, result=None, error: Optional[BaseException] = None):
        self.result = result
        self.error = error
        self.state = "done"
        self.done.set()
        if self.on_done is not None:
            try:
                self.on_done(self)
            except Exception:  # a callback bug must not kill the worker
                log.warning("entry on_done callback failed", exc_info=True)


class StatementPool:
    def __init__(self, storage):
        self.storage = storage
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._queue: deque = deque()
        self._workers: List[threading.Thread] = []
        self._running = 0
        self._closed = False
        with _POOLS_MU:
            _POOLS.add(self)

    # ---- config (GLOBAL sysvars, read live) -----------------------------
    def _gvar(self, name: str, default: int) -> int:
        return read_global_int(self.storage, name, default)

    # ---- submit (connection threads / event loops) ----------------------
    def routes_to_pool(self, stmt) -> bool:
        """Does this statement execute on pool workers?  Control
        statements (and everything while pooling is off) run directly on
        the calling thread — the aio front end uses this to decide
        between async submission and inline execution."""
        return self._gvar("tidb_stmt_pool_size", 4) > 0 \
            and isinstance(stmt, _POOLED_STMTS)

    def run(self, session, stmt, label: str):
        """Execute one statement with admission control; blocks the
        calling connection thread until the pool completes it.  Control
        statements bypass the pool entirely."""
        if not self.routes_to_pool(stmt):
            return session.execute_stmt(stmt, label)
        return self._wait(self.submit(session, stmt, label))

    def submit(self, session, stmt, label: str, on_done=None) -> _Entry:
        """Enqueue one POOLED statement and return its entry without
        waiting (the aio front end's async half; ``run`` is submit +
        ``_wait``).  Admission control runs here — a shed statement
        raises :class:`~.admission.AdmissionRejected` and no entry is
        ever queued.  ``on_done`` fires exactly once at completion, on
        the completing thread."""
        size = self._gvar("tidb_stmt_pool_size", 4)
        digest = ""
        batchable = False
        if isinstance(stmt, ast.SelectStmt) \
                and self._gvar("tidb_batch_max_size", 16) >= 2 \
                and not session.in_txn() \
                and bool(session.get_sysvar("autocommit")):
            from ..ops import batching
            # normalize only once families exist: a cold server (or one
            # whose workload never takes a batchable fused path) skips
            # the per-statement tokenize entirely
            if batching.have_families():
                from ..obs import stmtsummary
                digest, _ = stmtsummary.normalize(
                    getattr(stmt, "src", "") or label)
                batchable = batching.family_batchable(digest)
        entry = _Entry(session, stmt, label, digest, batchable,
                       on_done=on_done)
        with self._cv:
            if self._closed:
                raise PoolClosed()
            admission.check_admit(
                len(self._queue),
                self._gvar("tidb_stmt_pool_queue_depth", 64),
                self._gvar("tidb_admission_mem_limit", 0))
            # a KILL delivered before this statement was submitted aimed
            # at the PREVIOUS statement (MySQL: current-or-nothing)
            session.guard.killed = False
            if self._running >= size or self._queue:
                admission.count_queued()
                entry.verdict = "queued"
            self._queue.append(entry)
            session.stmt_state = "queued"
            session.pending_sql = label
            session.queue_ts = entry.queued_at
            self._ensure_workers(size)
            self._cv.notify()
        return entry

    def cancel_if_queued(self, entry: _Entry,
                         err: BaseException) -> bool:
        """KILL / shutdown path for async submitters: remove a
        still-queued entry and fail it with ``err`` so no worker ever
        touches it (the aio twin of ``_wait``'s poll-cancel).  Returns
        False when a worker already claimed the entry — it then finishes
        through the statement's own interrupt checks."""
        with self._cv:
            if entry.state != "queued":
                return False
            try:
                self._queue.remove(entry)
            except ValueError:
                return False  # a worker grabbed it between checks
        # complete OUTSIDE the pool lock: on_done may hand the result to
        # an event loop (its own lock + wake pipe) — keep the lock order
        # one-way (pool only ever acquires loop-side state lock-free)
        self._fail_entry(entry, err)
        return True

    def _wait(self, entry: _Entry):
        """Poll-wait so KILL / shutdown reach a QUEUED statement without
        a worker ever touching it."""
        sess = entry.session
        while not entry.done.wait(0.05):
            if sess.guard.killed or sess.killed or self._closed:
                with self._cv:
                    if entry.state == "queued":
                        try:
                            self._queue.remove(entry)
                        except ValueError:
                            continue  # a worker grabbed it; keep waiting
                        self._fail_entry(
                            entry, PoolClosed() if self._closed
                            and not sess.guard.killed else QueryKilled())
                # running entries finish through the statement's own
                # interrupt checks — keep waiting for the worker
        if entry.error is not None:
            raise entry.error
        return entry.result

    @staticmethod
    def _clear_queued(session) -> None:
        session.stmt_state = ""
        session.pending_sql = ""

    @classmethod
    def _fail_entry(cls, entry: "_Entry", err: BaseException) -> None:
        """Complete an entry with an error, clearing its session's
        queued processlist state (an abandoned 'queued' row would
        outlive the pool)."""
        cls._clear_queued(entry.session)
        entry.complete(error=err)

    # ---- workers ---------------------------------------------------------
    def _ensure_workers(self, size: int) -> None:
        # caller holds the lock; workers spawn on demand up to the
        # CURRENT pool-size sysvar (growth applies immediately, shrink
        # applies to future spawns)
        self._workers = [t for t in self._workers if t.is_alive()]
        if len(self._workers) < min(size, len(self._queue)
                                    + self._running + 1):
            t = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"stmt-pool-{len(self._workers)}")
            self._workers.append(t)
            t.start()

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                # concurrency is enforced at CLAIM time against the LIVE
                # pool-size sysvar: lowering tidb_stmt_pool_size takes
                # effect immediately (surplus workers idle), not just
                # for future spawns.  Size 0 ("pooling off") stops NEW
                # enqueues in run(), but already-queued entries still
                # drain on one worker — never strand a waiter
                while not self._closed and (
                        not self._queue
                        or self._running >= max(
                            1, self._gvar("tidb_stmt_pool_size", 4))):
                    self._cv.wait(timeout=0.25)
                if self._closed:
                    while self._queue:
                        self._fail_entry(self._queue.popleft(),
                                         PoolClosed())
                    return
                entry = self._queue.popleft()
                entry.claim()
                self._running += 1
            try:
                self._serve(entry)
            finally:
                with self._cv:
                    self._running -= 1
                    self._cv.notify()

    def _serve(self, entry: _Entry) -> None:
        # the chaos wedge: an armed admissionDelay sleeps (or errors)
        # the WORKER with the entry claimed — queue builds behind it,
        # KILL and control statements must keep working
        try:
            fail.inject("admissionDelay")
        except Exception as e:
            self._fail_entry(entry, e)
            return
        group = [entry]
        try:
            if entry.batchable:
                group += self._form_group(entry)
            if len(group) == 1:
                self._run_one(entry)
            else:
                self._run_batch(group)
        except BaseException as e:
            # backstop: NO claimed entry may ever be left incomplete —
            # a waiter with an unset done event would hang its
            # connection thread forever with no error and no KILL path
            for m in group:
                if not m.done.is_set():
                    self._fail_entry(m, e)
            if not isinstance(e, Exception):
                raise  # SystemExit/KeyboardInterrupt still propagate
            log.warning("statement-pool driver error", exc_info=True)

    def _form_group(self, leader: _Entry) -> List[_Entry]:
        """Pull same-digest batchable statements off the queue, topping
        up for at most ``tidb_batch_window_ms``."""
        max_size = self._gvar("tidb_batch_max_size", 16)
        window_s = self._gvar("tidb_batch_window_ms", 2) / 1e3
        deadline = time.monotonic() + window_s
        members: List[_Entry] = []
        while True:
            with self._cv:
                for e in list(self._queue):
                    if len(members) + 1 >= max_size:
                        break
                    if e.batchable and e.digest == leader.digest:
                        self._queue.remove(e)
                        e.claim()
                        e.state = "batched"
                        members.append(e)
                remaining = deadline - time.monotonic()
                if len(members) + 1 >= max_size or remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
        return members

    @staticmethod
    def _exec_entry(entry: _Entry, rnd=None):
        """Run the entry's statement INSIDE the context captured at
        submit time (cross-thread span parenting, the PR 3 devpipe
        idiom): the statement's parse→plan→execute span chain parents
        to whatever span was live on the submitting thread instead of
        starting an orphan chain on the worker.  The batch round (when
        given) is activated inside that copied context — activating it
        on the worker's own context would be invisible there."""
        entry.session.pending_wait = entry.wait_info(
            batch_wait_s=(time.monotonic() - entry.claimed_at)
            if rnd is not None else 0.0)

        def _invoke():
            if rnd is None:
                return entry.session.execute_stmt(entry.stmt, entry.label)
            from ..ops import batching
            tok = batching.activate(rnd)
            try:
                return entry.session.execute_stmt(entry.stmt, entry.label)
            finally:
                batching.deactivate(tok)
        return entry.ctx.run(_invoke)

    def _run_one(self, entry: _Entry) -> None:
        sess = entry.session
        self._clear_queued(sess)
        entry.state = "running"
        if sess.guard.killed or sess.killed:
            entry.complete(error=QueryKilled())
            return
        admission.count_admitted()
        admission.record_queue_wait(entry.queue_wait_s)
        try:
            entry.complete(result=self._exec_entry(entry))
        except BaseException as e:
            entry.complete(error=e)

    def _run_batch(self, group: List[_Entry]) -> None:
        """Drive one coalesced group through collect / dispatch / replay
        (module docstring; ops/batching.py has the protocol contract)."""
        from ..ops import batching
        rnd = batching.BatchRound(
            stack_max=self._gvar("tidb_batch_stack_max", 16))
        pending: List[_Entry] = []
        for e in group:
            sess = e.session
            self._clear_queued(sess)
            e.state = "running"
            if sess.guard.killed or sess.killed:
                e.complete(error=QueryKilled())
                continue
            admission.count_admitted()
            rnd.collecting = True
            try:
                result = self._exec_entry(e, rnd)
            except batching.Parked:
                # wait accounting deferred to the replay leg: a parked
                # member can still be killed before it ever executes,
                # and a killed member must not count on the pool side
                # (the claim() contract)
                pending.append(e)
            except BaseException as ex:
                admission.record_queue_wait(e.queue_wait_s)
                e.complete(error=ex)
            else:
                admission.record_queue_wait(e.queue_wait_s)
                e.complete(result=result)
            finally:
                rnd.collecting = False
        if not pending:
            return
        occ = rnd.dispatch()
        log.debug("batch round: %d member(s) through one program", occ)
        for e in pending:
            # a KILL that landed while this member sat parked (collect
            # of later members, the round dispatch) must abort it here:
            # the replay's own guard.begin() would silently clear the
            # kill flag before any interrupt check could fire
            if e.session.guard.killed or e.session.killed:
                e.complete(error=QueryKilled())
                continue
            admission.record_queue_wait(e.queue_wait_s)
            rnd.replaying = True
            try:
                # the replay leg re-deposits wait info (the parked
                # collect leg consumed the first deposit but is
                # invisible to observability): batch_wait now spans
                # claim -> replay, i.e. the time spent waiting on the
                # round's other members + the shared dispatch
                e.complete(result=self._exec_entry(e, rnd))
            except BaseException as ex:
                e.complete(error=ex)
            finally:
                rnd.replaying = False

    # ---- introspection / lifecycle --------------------------------------
    def snapshot(self) -> dict:
        with self._mu:
            return {"queued": len(self._queue), "running": self._running,
                    "workers": sum(1 for t in self._workers
                                   if t.is_alive()),
                    "closed": self._closed}

    def close(self) -> None:
        with self._cv:
            self._closed = True
            while self._queue:
                self._fail_entry(self._queue.popleft(), PoolClosed())
            self._cv.notify_all()
