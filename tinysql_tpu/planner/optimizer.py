"""Logical optimization rules + logical->physical conversion.

Capability parity with reference planner/core/optimizer.go:44-55 (the
fixed-order rule list) — this module carries predicate pushdown
(rule_predicate_push_down.go), column pruning (rule_column_pruning.go), and
TopN pushdown (rule_topn_push_down.go); further rules (agg pushdown, join
reorder, max/min elimination) land in rules.py as the planner widens.
Physical conversion binds every expression to child schema offsets
(reference: resolve_indices.go).
"""
from __future__ import annotations

from typing import List, Optional, Set, Tuple

from ..expression import (AggFuncDesc, Column, Constant, Expression, Schema,
                          new_function, substitute_column)
from .builder import HANDLE_COL_NAME, PlanError
from .logical import (JOIN_ANTI, JOIN_INNER, JOIN_LEFT, JOIN_SEMI,
                      LogicalAggregation, LogicalDataSource, LogicalJoin,
                      LogicalLimit, LogicalPlan, LogicalProjection,
                      LogicalSelection, LogicalSort, LogicalTableDual,
                      LogicalTopN)
from .physical import (PhysicalHashAgg, PhysicalHashJoin, PhysicalLimit,
                       PhysicalMergeJoin, PhysicalPlan, PhysicalProjection,
                       PhysicalSelection, PhysicalSort, PhysicalTableDual,
                       PhysicalTableReader, PhysicalTableScan, PhysicalTopN)


# ===== predicate pushdown ===================================================

def predicate_pushdown(p: LogicalPlan,
                       conds: List[Expression]) -> Tuple[List[Expression], LogicalPlan]:
    """Push `conds` (from parents) into p; returns (retained, new plan)
    (reference: rule_predicate_push_down.go PredicatePushDown)."""
    if isinstance(p, LogicalSelection):
        child_conds = conds + p.conditions
        retained, child = predicate_pushdown(p.child(0), child_conds)
        if retained:
            return [], LogicalSelection(retained, child)
        return [], child

    if isinstance(p, LogicalDataSource):
        p.pushed_conds.extend(conds)
        p.all_conds = list(p.pushed_conds)
        return [], p

    if isinstance(p, LogicalProjection):
        pushable, retained = [], []
        for c in conds:
            cols = c.collect_columns()
            if all(p.schema.column_index(x) >= 0 for x in cols):
                pushable.append(substitute_column(c, p.schema, p.exprs))
            else:
                retained.append(c)
        r2, child = predicate_pushdown(p.child(0), pushable)
        p.children[0] = (LogicalSelection(r2, child) if r2 else child)
        return retained, p

    if isinstance(p, LogicalJoin):
        from .joinconds import classify_conjuncts
        lsch, rsch = p.children[0].schema, p.children[1].schema
        new_eq, lp, rp, other, retained = classify_conjuncts(
            conds, lsch, rsch, p.tp)
        p.eq_conditions.extend(new_eq)
        p.other_conditions.extend(other)
        if p.tp in (JOIN_INNER, JOIN_SEMI, JOIN_ANTI):
            # semi/anti joins FILTER the left side: a cond on left
            # columns commutes below exactly like through an inner join
            left_push = list(p.left_conditions) + lp
            p.left_conditions = []
        else:
            # Outer join: ON-clause outer-side conditions stay attached to
            # the join — they decide MATCHING, not row survival; a failing
            # outer row must null-extend, not disappear (reference:
            # rule_predicate_push_down.go LeftOuterJoin keeps LeftConditions
            # on the join and the joiner null-extends on miss).  WHERE-side
            # conds (lp) still push below the outer child.
            left_push = lp
        right_push = list(p.right_conditions) + rp
        p.right_conditions = []
        r1, lc = predicate_pushdown(p.children[0], left_push)
        r2, rc = predicate_pushdown(p.children[1], right_push)
        p.children[0] = LogicalSelection(r1, lc) if r1 else lc
        p.children[1] = LogicalSelection(r2, rc) if r2 else rc
        return retained, p

    if isinstance(p, LogicalAggregation):
        gb_uids = {c.unique_id for e in p.group_by
                   for c in ([e] if isinstance(e, Column) else [])}
        push, retained = [], []
        for c in conds:
            cols = c.collect_columns()
            if cols and all(x.unique_id in gb_uids for x in cols):
                push.append(c)
            else:
                retained.append(c)
        r, child = predicate_pushdown(p.child(0), push)
        p.children[0] = LogicalSelection(r, child) if r else child
        return retained, p

    if isinstance(p, (LogicalSort, LogicalTopN)):
        r, child = predicate_pushdown(p.child(0), conds)
        p.children[0] = LogicalSelection(r, child) if r else child
        return [], p

    if isinstance(p, (LogicalLimit, LogicalTableDual)):
        for i, c in enumerate(p.children):
            r, nc = predicate_pushdown(c, [])
            p.children[i] = LogicalSelection(r, nc) if r else nc
        return conds, p

    # default: stop pushing
    for i, c in enumerate(p.children):
        r, nc = predicate_pushdown(c, [])
        p.children[i] = LogicalSelection(r, nc) if r else nc
    return conds, p


# ===== column pruning =======================================================

def _cols_of(exprs) -> Set[int]:
    out: Set[int] = set()
    for e in exprs:
        for c in e.collect_columns():
            out.add(c.unique_id)
    return out


def column_pruning(p: LogicalPlan, needed: Set[int]) -> None:
    """reference: rule_column_pruning.go PruneColumns."""
    if isinstance(p, LogicalProjection):
        keep = [i for i, c in enumerate(p.schema.columns)
                if c.unique_id in needed]
        if not keep:
            keep = [0]
        p.exprs = [p.exprs[i] for i in keep]
        p.schema = Schema([p.schema.columns[i] for i in keep])
        column_pruning(p.child(0), _cols_of(p.exprs))
        return
    if isinstance(p, LogicalSelection):
        column_pruning(p.child(0), needed | _cols_of(p.conditions))
        p.schema = p.child(0).schema
        return
    if isinstance(p, (LogicalSort, LogicalTopN)):
        column_pruning(p.child(0), needed | _cols_of(e for e, _ in p.by))
        p.schema = p.child(0).schema
        return
    if isinstance(p, LogicalLimit):
        column_pruning(p.child(0), needed)
        p.schema = p.child(0).schema
        return
    if isinstance(p, LogicalAggregation):
        keep_idx = [i for i, c in enumerate(p.output_cols)
                    if c.unique_id in needed]
        gb_needed = {c.unique_id for c in getattr(p, "gb_out_cols", [])
                     if c.unique_id in needed}
        if not keep_idx and not gb_needed and p.agg_funcs:
            keep_idx = [0]
        p.agg_funcs = [p.agg_funcs[i] for i in keep_idx]
        p.output_cols = [p.output_cols[i] for i in keep_idx]
        new_schema = [c for c in p.schema.columns
                      if c.unique_id in needed
                      or any(c.unique_id == oc.unique_id for oc in p.output_cols)]
        if new_schema:
            p.schema = Schema(new_schema)
        child_needed = set()
        for d in p.agg_funcs:
            child_needed |= _cols_of(d.args)
        child_needed |= _cols_of(p.group_by)
        column_pruning(p.child(0), child_needed)
        return
    if isinstance(p, LogicalJoin):
        used = set(needed)
        for a, b in p.eq_conditions:
            used |= _cols_of([a, b])
        used |= _cols_of(p.other_conditions)
        used |= _cols_of(p.left_conditions) | _cols_of(p.right_conditions)
        column_pruning(p.children[0], used)
        column_pruning(p.children[1], used)
        if p.tp in (JOIN_SEMI, JOIN_ANTI):
            # semi/anti joins emit LEFT rows only; the right side kept
            # just its equi/other condition columns
            p.schema = Schema(list(p.children[0].schema.columns))
        else:
            p.schema = p.children[0].schema.merge(p.children[1].schema)
        return
    if isinstance(p, LogicalDataSource):
        used = needed | _cols_of(p.pushed_conds)
        cols = [c for c in p.schema.columns if c.unique_id in used]
        if not cols:
            cols = [p.schema.columns[0]]
        p.schema = Schema(cols)
        return
    if isinstance(p, LogicalTableDual):
        p.schema = Schema([c for c in p.schema.columns if c.unique_id in needed])
        return
    for c in p.children:
        column_pruning(c, needed)


# ===== topn pushdown ========================================================

def topn_pushdown(p: LogicalPlan) -> LogicalPlan:
    """Limit(Sort) -> TopN; TopN pushes through Projection
    (reference: rule_topn_push_down.go)."""
    if isinstance(p, LogicalLimit) and isinstance(p.child(0), LogicalSort):
        s = p.child(0)
        t = LogicalTopN(s.by, p.offset, p.count, s.child(0))
        t.schema = s.schema
        return topn_pushdown(t)
    if isinstance(p, LogicalTopN) and isinstance(p.child(0), LogicalProjection):
        proj: LogicalProjection = p.child(0)
        cols = [c for e, _ in p.by for c in e.collect_columns()]
        if all(proj.schema.column_index(c) >= 0 for c in cols):
            new_by = [(substitute_column(e, proj.schema, proj.exprs), d)
                      for e, d in p.by]
            t = LogicalTopN(new_by, p.offset, p.count, proj.child(0))
            t.schema = proj.child(0).schema
            proj.children[0] = topn_pushdown(t)
            return proj
    p.children = [topn_pushdown(c) for c in p.children]
    return p


# ===== logical -> physical ==================================================

def _bind(exprs: List[Expression], schema: Schema) -> List[Expression]:
    return [e.resolve_indices(schema) for e in exprs]


def _merge_join_ok(p: LogicalJoin, left_phys: PhysicalPlan,
                   right_phys: PhysicalPlan) -> bool:
    """Merge join needs key-ordered inputs: decided on the BUILT
    children via the order-property framework — any plan that PROVIDES
    the key order qualifies (clustered-pk table read, covering index
    read, ...), replacing the old ad-hoc pk-reader gate (reference:
    exhaust_physical_plans.go merge-join candidates require matching
    sort properties of the child task)."""
    if p.tp not in (JOIN_INNER, JOIN_LEFT) or len(p.eq_conditions) != 1:
        return False
    a, b = p.eq_conditions[0]
    if not (isinstance(a, Column) and isinstance(b, Column)):
        return False
    from .props import provided_order, satisfies
    return (satisfies(provided_order(left_phys), [(a.unique_id, False)])
            and satisfies(provided_order(right_phys),
                          [(b.unique_id, False)]))


def _unique_on(side: LogicalPlan, key_uids: Set[int], n_keys: int) -> bool:
    """Is the join-key tuple UNIQUE among `side`'s output rows?  True for
    a clustered-pk datasource keyed by its pk, an aggregation whose group
    keys all sit inside the join keys, row-filtering operators over such,
    and inner joins that preserve one side's multiplicity (the OTHER side
    is unique on its own join keys)."""
    if len(key_uids) != n_keys or not key_uids:
        return False  # non-column keys or no equi keys
    if isinstance(side, LogicalAggregation):
        gb = side.group_by
        return (bool(gb) and all(isinstance(e, Column) for e in gb)
                and {e.unique_id for e in gb} <= key_uids)
    if isinstance(side, LogicalDataSource):
        key_names = {sc.name.lower() for sc in side.schema.columns
                     if sc.unique_id in key_uids}
        if len(key_names) != n_keys:
            return False
        pk = side.table_info.get_pk_handle_col()
        if pk is not None and pk.name.lower() in key_names:
            return True
        # a UNIQUE index whose columns are all join keys makes the key
        # tuple unique among MATCHABLE rows (rows with a NULL key never
        # equi-match, so nullable unique duplicates are irrelevant here)
        for idx in side.table_info.public_indices():
            if not idx.unique:
                continue
            if {c.name.lower() for c in idx.columns} <= key_names:
                return True
        return False
    if isinstance(side, (LogicalSelection, LogicalSort, LogicalTopN,
                         LogicalLimit)):
        return _unique_on(side.child(0), key_uids, n_keys)
    if isinstance(side, LogicalProjection):
        # identity columns pass through; expression outputs don't
        ident = {e.unique_id for e in side.exprs if isinstance(e, Column)}
        if not key_uids <= ident:
            return False
        return _unique_on(side.child(0), key_uids, n_keys)
    if isinstance(side, LogicalJoin) and side.tp in (JOIN_SEMI, JOIN_ANTI):
        # a semi/anti join never duplicates left rows: uniqueness of the
        # left child survives
        return _unique_on(side.children[0], key_uids, n_keys)
    if isinstance(side, LogicalJoin) and side.tp == JOIN_INNER \
            and side.eq_conditions:
        lsch, rsch = side.children[0].schema, side.children[1].schema
        lk = {a.unique_id for a, _ in side.eq_conditions
              if isinstance(a, Column)}
        rk = {b.unique_id for _, b in side.eq_conditions
              if isinstance(b, Column)}
        nk = len(side.eq_conditions)
        if all(any(c.unique_id == u for c in rsch.columns)
               for u in key_uids):
            # keys from the right child: unique there AND the left child
            # matches each right row at most once
            return (_unique_on(side.children[1], key_uids, n_keys)
                    and _unique_on(side.children[0], lk, nk))
        if all(any(c.unique_id == u for c in lsch.columns)
               for u in key_uids):
            return (_unique_on(side.children[0], key_uids, n_keys)
                    and _unique_on(side.children[1], rk, nk))
    return False


# ---- physical construction helpers (shared implementation rules) ---------
# Both optimizer frameworks build physical operators through these — the
# System-R tail calls them from to_physical, the cascades implementation
# phase calls them per memo group with its own child winners (reference:
# implementation_rules.go builds the same physical ops both ways).

def phys_selection(p: LogicalSelection, child: PhysicalPlan) -> PhysicalPlan:
    return PhysicalSelection(_bind(p.conditions, child.schema), child)


def phys_projection(p: LogicalProjection, child: PhysicalPlan) -> PhysicalPlan:
    return PhysicalProjection(_bind(p.exprs, child.schema), p.schema, child)


def phys_aggregation(p: LogicalAggregation,
                     child: PhysicalPlan) -> PhysicalPlan:
    gb = _bind(p.group_by, child.schema)
    aggs = []
    for d in p.agg_funcs:
        d2 = d.clone()
        d2.args = _bind(d.args, child.schema)
        aggs.append(d2)
    # map each schema column to ('agg', i) or ('gb', i)
    output_map: List[Tuple[str, int]] = []
    for c in p.schema.columns:
        for i, oc in enumerate(getattr(p, "output_cols", [])):
            if oc.unique_id == c.unique_id:
                output_map.append(("agg", i))
                break
        else:
            for i, gc in enumerate(getattr(p, "gb_out_cols", [])):
                if gc.unique_id == c.unique_id:
                    output_map.append(("gb", i))
                    break
            else:
                raise PlanError(f"agg schema column {c!r} unmapped")
    agg = PhysicalHashAgg(gb, aggs, p.schema, child, [])
    agg.output_map = output_map
    return agg


def phys_join(p: LogicalJoin, left: PhysicalPlan, right: PhysicalPlan,
              cls=PhysicalHashJoin) -> PhysicalPlan:
    # semi/anti joins emit the left child's rows VERBATIM: the physical
    # schema must be the BUILT left child's (join_reorder may have
    # rebuilt that subtree after the logical schema was captured)
    schema = Schema(list(left.schema.columns)) \
        if p.tp in (JOIN_SEMI, JOIN_ANTI) else p.schema
    join = cls(p.tp, left, right, schema)
    join.left_keys = _bind([a for a, _ in p.eq_conditions], left.schema)
    join.right_keys = _bind([b for _, b in p.eq_conditions], right.schema)
    # key-uniqueness per side (reference: schema key info feeding the
    # join executors): unlocks the expansion-free unique-build probe
    join.left_unique = _unique_on(
        p.children[0], {a.unique_id for a, _ in p.eq_conditions
                        if isinstance(a, Column)},
        len(p.eq_conditions))
    join.right_unique = _unique_on(
        p.children[1], {b.unique_id for _, b in p.eq_conditions
                        if isinstance(b, Column)},
        len(p.eq_conditions))
    # other conds see BOTH sides even when the join's output schema is
    # left-only (semi/anti): the executors evaluate them on candidate
    # (probe row, build row) pairs
    join.other_conditions = _bind(p.other_conditions,
                                  left.schema.merge(right.schema))
    # leftover one-side conds (outer joins keep them at the join)
    join.left_conditions = _bind(p.left_conditions, left.schema)
    join.right_conditions = _bind(p.right_conditions, right.schema)
    join.null_aware = getattr(p, "null_aware", False)
    return join


def phys_datasource(p: LogicalDataSource, order_hint=None) -> PhysicalPlan:
    with_handle = any(c.name == HANDLE_COL_NAME for c in p.schema.columns)
    from .access import build_reader
    stats = None
    storage = getattr(p, "storage", None)
    if storage is not None:
        from ..statistics.table_stats import load_stats
        stats = load_stats(storage, p.table_info.id)
    return build_reader(p, stats, with_handle, order_hint)


def to_physical(p: LogicalPlan,
                order_hint=None) -> PhysicalPlan:
    """`order_hint`: the sort property a parent Sort/TopN requires —
    threaded through row-order-preserving operators down to the reader so
    the access-path choice is ORDER-AWARE (reference: findBestTask over a
    required PhysicalProperty; enforcer_rules.go adds the Sort only when
    the child can't provide it)."""
    if isinstance(p, LogicalDataSource):
        return phys_datasource(p, order_hint)
    if isinstance(p, LogicalSelection):
        child = to_physical(p.child(0), order_hint)
        return phys_selection(p, child)
    if isinstance(p, LogicalProjection):
        # projections forward the hint when the ordered columns are
        # identity outputs (their source order survives)
        hint = None
        if order_hint:
            ident = {e.unique_id for e in p.exprs if isinstance(e, Column)}
            if all(uid in ident for uid, _ in order_hint):
                hint = order_hint
        child = to_physical(p.child(0), hint)
        return phys_projection(p, child)
    if isinstance(p, LogicalAggregation):
        return phys_aggregation(p, to_physical(p.child(0)))
    if isinstance(p, LogicalJoin):
        left = to_physical(p.children[0])
        right = to_physical(p.children[1])
        merge_ok = _merge_join_ok(p, left, right)
        if merge_ok:
            from .props import mark_keep_order
            mark_keep_order(left)
            mark_keep_order(right)
        cls = PhysicalMergeJoin if merge_ok else PhysicalHashJoin
        return phys_join(p, left, right, cls)
    if isinstance(p, LogicalSort):
        from .props import (mark_keep_order, provided_order, required_of,
                            satisfies)
        req = required_of(p.by)
        child = to_physical(p.child(0), req)
        if satisfies(provided_order(child), req):
            mark_keep_order(child)
            return child  # Sort eliminated: the reader provides the order
        by = [(e.resolve_indices(child.schema), d) for e, d in p.by]
        return PhysicalSort(by, child)
    if isinstance(p, LogicalTopN):
        from .props import (mark_keep_order, provided_order, required_of,
                            satisfies)
        req = required_of(p.by)
        child = to_physical(p.child(0), req)
        if satisfies(provided_order(child), req):
            # ordered input: TopN degenerates to Limit (the cascades :800
            # course stub's TopN->index rewrite, done via properties)
            mark_keep_order(child)
            return PhysicalLimit(p.offset, p.count, child)
        by = [(e.resolve_indices(child.schema), d) for e, d in p.by]
        return PhysicalTopN(by, p.offset, p.count, child)
    if isinstance(p, LogicalLimit):
        return PhysicalLimit(p.offset, p.count, to_physical(p.child(0)))
    if isinstance(p, LogicalTableDual):
        return PhysicalTableDual(p.schema, p.row_count)
    from .logical import LogicalMemTable
    if isinstance(p, LogicalMemTable):
        from .physical import PhysicalMemTable
        return PhysicalMemTable(p.table, p.schema)
    raise PlanError(f"no physical mapping for {type(p).__name__}")


def _ds_row_count(ds) -> float:
    storage = getattr(ds, "storage", None)
    if storage is None:
        return 0.0
    from ..statistics.table_stats import load_stats
    s = load_stats(storage, ds.table_info.id)
    return float(s.row_count) if s else 0.0


def _propagate_constants_in_plan(p: LogicalPlan) -> None:
    """Constant propagation across equalities in every CNF condition
    list (reference: expression/constant_propagation.go, run as part of
    the logical rewrite list): selections and join residuals get
    `col = const` bindings substituted into sibling conjuncts so later
    rules (pushdown, ranger) see the derived constants."""
    from ..expression import propagate_constants
    for c in p.children:
        _propagate_constants_in_plan(c)
    if isinstance(p, LogicalSelection):
        p.conditions = propagate_constants(p.conditions)
    elif isinstance(p, LogicalJoin) and p.other_conditions:
        p.other_conditions = propagate_constants(p.other_conditions)


def normalize_logical(logical: LogicalPlan,
                      push_predicates: bool = True) -> LogicalPlan:
    """The fixed-order logical rewrite list (reference:
    planner/core/optimizer.go:44-55), shared by BOTH optimizer frameworks
    so their normalization can never drift.  The cascades pipeline skips
    predicate pushdown (its transformation rules own that)."""
    from .rules_extra import (eliminate_aggregation, eliminate_max_min,
                              eliminate_outer_joins, eliminate_projections,
                              join_reorder, push_agg_through_join,
                              push_semi_joins_down)
    root_needed = {c.unique_id for c in logical.schema.columns}
    _propagate_constants_in_plan(logical)
    logical = eliminate_outer_joins(logical, root_needed)
    if push_predicates:
        retained, logical = predicate_pushdown(logical, [])
        if retained:
            logical = LogicalSelection(retained, logical)
    logical = push_agg_through_join(logical)
    column_pruning(logical, root_needed)
    logical = eliminate_aggregation(logical)
    logical = eliminate_max_min(logical)
    logical = eliminate_projections(logical)
    logical = join_reorder(logical, stats_of=_ds_row_count)
    # after reorder: the left-deep inner chain is in place, sink each
    # semi/anti join next to the side its keys come from
    return push_semi_joins_down(logical)


def optimize(logical: LogicalPlan, tpu: bool = True,
             tpu_min_rows: float = 0.0,
             mesh_shards: int = 0,
             verify: bool = False) -> PhysicalPlan:
    """The System-R style pipeline (reference: planner/core/optimizer.go:77
    — the fixed-order rewrite list of optimizer.go:44-55), physical
    conversion, estimate derivation, then the device enforcer (cost+
    capability, incl. the mesh broadcast-vs-shuffle join strategy) +
    coprocessor pushdown.

    `verify=True` (the tidb_qlint_verify sysvar) runs the qlint
    plan-device invariant checker over the placed plan and raises
    analysis.PlanDeviceError instead of handing a mis-placed plan to the
    executor — the runtime arm of `tools/lint.py --plans`."""
    logical = normalize_logical(logical)
    logical = topn_pushdown(logical)
    phys = to_physical(logical)
    from .derive_stats import derive_stats
    phys = derive_stats(phys)
    from .device import place_devices
    phys = place_devices(phys, enabled=tpu, min_rows=tpu_min_rows,
                         mesh_shards=mesh_shards)
    from .cop import push_to_cop
    phys = push_to_cop(phys)
    if verify:
        from ..analysis.plan_device import verify_plan
        verify_plan(phys)
    return phys
