"""Physical plan operators + logical->physical conversion.

Capability parity with reference planner/core/physical_plans.go (367 L) and
the findBestTask machinery (find_best_task.go / task.go) — this module holds
the operator shapes; the cost-based search with the device enforcer lives in
optimizer.py.  Every physical node carries expressions already
resolve_indices-bound to its child schema, so executors evaluate by offset.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..catalog.model import IndexInfo, TableInfo
from ..expression import (AggFuncDesc, Column, Expression, Schema)
from ..mytypes import new_int_type
from .builder import HANDLE_COL_NAME
from .logical import (LogicalAggregation, LogicalDataSource, LogicalJoin,
                      LogicalLimit, LogicalPlan, LogicalProjection,
                      LogicalSelection, LogicalSort, LogicalTableDual,
                      LogicalTopN)


class PhysicalPlan:
    def __init__(self):
        self.children: List[PhysicalPlan] = []
        self.schema = Schema([])
        self.stats_row_count: float = 0.0

    def op_name(self) -> str:
        return type(self).__name__.replace("Physical", "")

    def explain_info(self) -> str:
        return ""

    def __repr__(self):  # pragma: no cover
        return f"{self.op_name()}"


class PhysicalTableScan(PhysicalPlan):
    """Full/ranged scan over a table's record keyspace — runs inside the
    'coprocessor' (storage side) like reference PhysicalTableScan."""

    def __init__(self, table_info: TableInfo, db_name: str, alias: str,
                 schema: Schema, with_handle: bool = False):
        super().__init__()
        self.table_info = table_info
        self.db_name = db_name
        self.alias = alias
        self.schema = schema
        self.with_handle = with_handle
        self.ranges: Optional[list] = None   # handle ranges; None = full
        self.filters: List[Expression] = []  # pushed-down, schema-bound
        # coprocessor-side executor chain (planner/cop.py push_to_cop)
        self.pushed_agg: Optional[dict] = None
        self.pushed_topn: Optional[dict] = None
        self.pushed_limit: Optional[int] = None


class PhysicalIndexScan(PhysicalPlan):
    def __init__(self, table_info: TableInfo, index: IndexInfo, db_name: str,
                 alias: str, schema: Schema, ranges=None):
        super().__init__()
        self.table_info = table_info
        self.index = index
        self.db_name = db_name
        self.alias = alias
        self.schema = schema   # index columns + handle
        self.ranges = ranges
        self.filters: List[Expression] = []
        self.desc = False
        # covering reads: per-schema-column source ("idx", i) | ("handle",)
        self.output_sources: List[tuple] = []


class PhysicalTableReader(PhysicalPlan):
    """Host-side reader driving coprocessor scans (reference:
    PhysicalTableReader)."""

    def __init__(self, scan: PhysicalTableScan):
        super().__init__()
        self.scan = scan
        self.schema = scan.schema


class PhysicalIndexReader(PhysicalPlan):
    def __init__(self, scan: PhysicalIndexScan):
        super().__init__()
        self.scan = scan
        self.schema = scan.schema


class PhysicalIndexLookUpReader(PhysicalPlan):
    """Double read: index keys -> handles -> table rows (reference:
    IndexLookUpExecutor 2-stage pipeline, distsql.go:237)."""

    def __init__(self, index_scan: PhysicalIndexScan,
                 table_scan: PhysicalTableScan):
        super().__init__()
        self.index_scan = index_scan
        self.table_scan = table_scan
        self.schema = table_scan.schema


class PhysicalMemTable(PhysicalPlan):
    def __init__(self, table: str, schema: Schema):
        super().__init__()
        self.table = table
        self.schema = schema


class PhysicalSelection(PhysicalPlan):
    def __init__(self, conditions: List[Expression], child: PhysicalPlan):
        super().__init__()
        self.conditions = conditions
        self.children = [child]
        self.schema = child.schema


class PhysicalProjection(PhysicalPlan):
    def __init__(self, exprs: List[Expression], schema: Schema,
                 child: PhysicalPlan):
        super().__init__()
        self.exprs = exprs
        self.schema = schema
        self.children = [child]


class PhysicalHashAgg(PhysicalPlan):
    def __init__(self, group_by: List[Expression], aggs: List[AggFuncDesc],
                 schema: Schema, child: PhysicalPlan,
                 gb_output_offsets: List[int]):
        super().__init__()
        self.group_by = group_by
        self.aggs = aggs
        self.schema = schema
        self.children = [child]
        # offsets in `schema` where each group-by value lands (after aggs)
        self.gb_output_offsets = gb_output_offsets
        self.use_tpu = False


class PhysicalStreamAgg(PhysicalHashAgg):
    """Sorted-input aggregation (reference: StreamAggExec)."""


class PhysicalHashJoin(PhysicalPlan):
    def __init__(self, tp: str, left: PhysicalPlan, right: PhysicalPlan,
                 schema: Schema):
        super().__init__()
        self.tp = tp
        self.children = [left, right]
        self.schema = schema
        self.left_keys: List[Expression] = []
        self.right_keys: List[Expression] = []
        self.other_conditions: List[Expression] = []
        self.build_side = 1  # 1 = right is build side
        self.use_tpu = False
        # NOT IN three-valued semantics on anti joins (decorrelate.py)
        self.null_aware = False


class PhysicalMergeJoin(PhysicalHashJoin):
    """Sorted-input merge join (reference: MergeJoinExec)."""


class PhysicalSort(PhysicalPlan):
    def __init__(self, by: List[Tuple[Expression, bool]], child: PhysicalPlan):
        super().__init__()
        self.by = by
        self.children = [child]
        self.schema = child.schema
        self.use_tpu = False


class PhysicalTopN(PhysicalPlan):
    def __init__(self, by: List[Tuple[Expression, bool]], offset: int,
                 count: int, child: PhysicalPlan):
        super().__init__()
        self.by = by
        self.offset = offset
        self.count = count
        self.children = [child]
        self.schema = child.schema
        self.use_tpu = False


class PhysicalLimit(PhysicalPlan):
    def __init__(self, offset: int, count: int, child: PhysicalPlan):
        super().__init__()
        self.offset = offset
        self.count = count
        self.children = [child]
        self.schema = child.schema


class PhysicalTableDual(PhysicalPlan):
    def __init__(self, schema: Schema, row_count: int = 1):
        super().__init__()
        self.schema = schema
        self.row_count = row_count
