"""EXPLAIN text rendering (reference: planner/core/explain.go + stringer)."""
from __future__ import annotations

from typing import List

from .physical import (PhysicalHashAgg, PhysicalHashJoin,
                       PhysicalIndexLookUpReader, PhysicalIndexReader,
                       PhysicalLimit, PhysicalPlan, PhysicalProjection,
                       PhysicalSelection, PhysicalSort, PhysicalTableDual,
                       PhysicalTableReader, PhysicalTopN)


def _ranges_str(ranges) -> str:
    if ranges is None:
        return "full"
    return f"{len(ranges)} range" + ("s" if len(ranges) != 1 else "")


def _info(p: PhysicalPlan) -> str:
    if isinstance(p, PhysicalTableReader):
        s = p.scan
        filt = f", filters:{len(s.filters)}" if s.filters else ""
        push = ""
        if s.pushed_agg is not None:
            push = f", cop_agg:{len(s.pushed_agg['aggs'])}"
        elif s.pushed_topn is not None:
            push = f", cop_topn:{s.pushed_topn['n']}"
        elif s.pushed_limit is not None:
            push = f", cop_limit:{s.pushed_limit}"
        ko = "true" if getattr(s, "keep_order", False) else "false"
        return (f"table:{s.alias}, ranges:{_ranges_str(s.ranges)}, "
                f"keep order:{ko}{filt}{push}")
    if isinstance(p, PhysicalIndexReader):
        s = p.scan
        filt = f", filters:{len(s.filters)}" if s.filters else ""
        ko = ", keep order:true" if getattr(s, "keep_order", False) else ""
        return (f"table:{s.alias}, index:{s.index.name}, covering, "
                f"ranges:{_ranges_str(s.ranges)}{ko}{filt}")
    if isinstance(p, PhysicalIndexLookUpReader):
        s = p.index_scan
        filt = (f", filters:{len(p.table_scan.filters)}"
                if p.table_scan.filters else "")
        return (f"table:{s.alias}, index:{s.index.name}, "
                f"ranges:{_ranges_str(s.ranges)}{filt}")
    if isinstance(p, PhysicalSelection):
        return ", ".join(c.key() for c in p.conditions)
    if isinstance(p, PhysicalProjection):
        return ", ".join(e.key() for e in p.exprs)
    if isinstance(p, PhysicalHashAgg):
        gb = ",".join(e.key() for e in p.group_by) or "-"
        aggs = ",".join(f"{d.name}({','.join(a.key() for a in d.args)})"
                        for d in p.aggs)
        return f"group by:{gb}, funcs:{aggs}"
    if isinstance(p, PhysicalHashJoin):
        keys = ",".join(f"{l.key()}={r.key()}" for l, r in
                        zip(p.left_keys, p.right_keys)) or "CARTESIAN"
        mesh = getattr(p, "mesh_strategy", None)
        mesh = f", mesh:{mesh}" if mesh else ""
        return f"{p.tp} join, equal:[{keys}]{mesh}"
    if isinstance(p, (PhysicalSort, PhysicalTopN)):
        by = ",".join(f"{e.key()}{' desc' if d else ''}" for e, d in p.by)
        extra = (f", offset:{p.offset}, count:{p.count}"
                 if isinstance(p, PhysicalTopN) else "")
        return by + extra
    if isinstance(p, PhysicalLimit):
        return f"offset:{p.offset}, count:{p.count}"
    if isinstance(p, PhysicalTableDual):
        return f"rows:{p.row_count}"
    return ""


def _task(p: PhysicalPlan) -> str:
    if isinstance(p, PhysicalTableReader):
        return "root"
    if getattr(p, "use_tpu", False):
        return "tpu"
    return "root"


def _est_rows(p: PhysicalPlan) -> str:
    """Row estimate column (reference explain format: id, estRows, task,
    operator info); blank ONLY when the node carries no estimate at all —
    a genuine 0-row estimate renders 0.00 like the reference."""
    r = getattr(p, "stats_row_count", None)
    if r is None or (r == 0.0 and not getattr(p, "has_estimate", False)):
        # nodes never costed leave stats_row_count at the 0.0 default;
        # costed nodes mark has_estimate so real zeros still render
        return ""
    return f"{r:.2f}"


def explain_text(p: PhysicalPlan, depth: int = 0,
                 out: List[list] = None) -> List[list]:
    if out is None:
        out = []
    name = p.op_name()
    if getattr(p, "use_tpu", False):
        name += "(TPU)"
    out.append(["  " * depth + name, _est_rows(p), _task(p), _info(p)])
    children = list(p.children)
    if isinstance(p, PhysicalTableReader):
        out.append(["  " * (depth + 1) + "TableScan",
                    _est_rows(p.scan) or _est_rows(p), "cop",
                    f"table:{p.scan.alias}"])
    for c in children:
        explain_text(c, depth + 1, out)
    return out
