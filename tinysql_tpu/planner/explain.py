"""EXPLAIN / EXPLAIN ANALYZE text rendering (reference:
planner/core/explain.go + stringer; common_plans.go Explain with
RuntimeStats for the ANALYZE columns)."""
from __future__ import annotations

import hashlib
import re
from typing import List, Optional

from .physical import (PhysicalHashAgg, PhysicalHashJoin,
                       PhysicalIndexLookUpReader, PhysicalIndexReader,
                       PhysicalLimit, PhysicalPlan, PhysicalProjection,
                       PhysicalSelection, PhysicalSort, PhysicalTableDual,
                       PhysicalTableReader, PhysicalTopN)


def _ranges_str(ranges) -> str:
    if ranges is None:
        return "full"
    return f"{len(ranges)} range" + ("s" if len(ranges) != 1 else "")


def _info(p: PhysicalPlan) -> str:
    if isinstance(p, PhysicalTableReader):
        s = p.scan
        filt = f", filters:{len(s.filters)}" if s.filters else ""
        push = ""
        if s.pushed_agg is not None:
            push = f", cop_agg:{len(s.pushed_agg['aggs'])}"
        elif s.pushed_topn is not None:
            push = f", cop_topn:{s.pushed_topn['n']}"
        elif s.pushed_limit is not None:
            push = f", cop_limit:{s.pushed_limit}"
        ko = "true" if getattr(s, "keep_order", False) else "false"
        return (f"table:{s.alias}, ranges:{_ranges_str(s.ranges)}, "
                f"keep order:{ko}{filt}{push}")
    if isinstance(p, PhysicalIndexReader):
        s = p.scan
        filt = f", filters:{len(s.filters)}" if s.filters else ""
        ko = ", keep order:true" if getattr(s, "keep_order", False) else ""
        return (f"table:{s.alias}, index:{s.index.name}, covering, "
                f"ranges:{_ranges_str(s.ranges)}{ko}{filt}")
    if isinstance(p, PhysicalIndexLookUpReader):
        s = p.index_scan
        filt = (f", filters:{len(p.table_scan.filters)}"
                if p.table_scan.filters else "")
        return (f"table:{s.alias}, index:{s.index.name}, "
                f"ranges:{_ranges_str(s.ranges)}{filt}")
    if isinstance(p, PhysicalSelection):
        return ", ".join(c.key() for c in p.conditions)
    if isinstance(p, PhysicalProjection):
        return ", ".join(e.key() for e in p.exprs)
    if isinstance(p, PhysicalHashAgg):
        gb = ",".join(e.key() for e in p.group_by) or "-"
        aggs = ",".join(f"{d.name}({','.join(a.key() for a in d.args)})"
                        for d in p.aggs)
        return f"group by:{gb}, funcs:{aggs}"
    if isinstance(p, PhysicalHashJoin):
        keys = ",".join(f"{l.key()}={r.key()}" for l, r in
                        zip(p.left_keys, p.right_keys)) or "CARTESIAN"
        mesh = getattr(p, "mesh_strategy", None)
        mesh = f", mesh:{mesh}" if mesh else ""
        na = ", null-aware" if getattr(p, "null_aware", False) else ""
        return f"{p.tp} join, equal:[{keys}]{mesh}{na}"
    if isinstance(p, (PhysicalSort, PhysicalTopN)):
        by = ",".join(f"{e.key()}{' desc' if d else ''}" for e, d in p.by)
        extra = (f", offset:{p.offset}, count:{p.count}"
                 if isinstance(p, PhysicalTopN) else "")
        return by + extra
    if isinstance(p, PhysicalLimit):
        return f"offset:{p.offset}, count:{p.count}"
    if isinstance(p, PhysicalTableDual):
        return f"rows:{p.row_count}"
    return ""


def _task(p: PhysicalPlan) -> str:
    if isinstance(p, PhysicalTableReader):
        return "root"
    if getattr(p, "use_tpu", False):
        return "tpu"
    return "root"


def _est_rows(p: PhysicalPlan) -> str:
    """Row estimate column (reference explain format: id, estRows, task,
    operator info); blank ONLY when the node carries no estimate at all —
    a genuine 0-row estimate renders 0.00 like the reference."""
    r = getattr(p, "stats_row_count", None)
    if r is None or (r == 0.0 and not getattr(p, "has_estimate", False)):
        # nodes never costed leave stats_row_count at the 0.0 default;
        # costed nodes mark has_estimate so real zeros still render
        return ""
    return f"{r:.2f}"


def explain_text(p: PhysicalPlan, depth: int = 0,
                 out: List[list] = None) -> List[list]:
    if out is None:
        out = []
    name = p.op_name()
    if getattr(p, "use_tpu", False):
        name += "(TPU)"
    out.append(["  " * depth + name, _est_rows(p), _task(p), _info(p)])
    children = list(p.children)
    if isinstance(p, PhysicalTableReader):
        out.append(["  " * (depth + 1) + "TableScan",
                    _est_rows(p.scan) or _est_rows(p), "cop",
                    f"table:{p.scan.alias}"])
    for c in children:
        explain_text(c, depth + 1, out)
    return out


_COL_ID_RE = re.compile(r"col#(\d+)")


def plan_digest(p: PhysicalPlan) -> str:
    """Stable digest of the plan SHAPE (operator tree + operator info,
    estimates excluded so stats drift keeps the digest) — the join key
    across the slow log, the feedback file, and
    ``information_schema.statements_summary`` (reference: plan digest in
    the slow log).

    Column references render as ``col#<unique_id>`` from a PROCESS-GLOBAL
    allocator, so re-planning the identical statement produces fresh ids;
    they are canonicalized to first-seen order here — without this, no
    two executions ever shared a digest and every digest join was
    silently empty."""
    parts: List[str] = []

    def walk(n, depth):
        parts.append(f"{depth}:{n.op_name()}"
                     f":{int(bool(getattr(n, 'use_tpu', False)))}"
                     f":{_info(n)}")
        for c in n.children:
            walk(c, depth + 1)

    walk(p, 0)
    text = "|".join(parts)
    seen: dict = {}

    def canon(m):
        uid = m.group(1)
        if uid not in seen:
            seen[uid] = len(seen)
        return f"col#{seen[uid]}"

    text = _COL_ID_RE.sub(canon, text)
    return hashlib.sha1(text.encode()).hexdigest()[:16]


# ---- EXPLAIN ANALYZE -----------------------------------------------------

EXPLAIN_ANALYZE_COLUMNS = ("id", "estRows", "actRows", "task",
                           "execution info", "device info",
                           "operator info")


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            if unit == "B":
                # integer byte counts render bare; the occupancy-weighted
                # fractional shares a stacked member carries (its 1/B
                # slice of the round's h2d/d2h bytes) keep two decimals —
                # int() truncation rendered a 170.67B share as 170B and
                # broke the shares-sum-to-round-total readback
                return (f"{int(n)}B" if n.is_integer()
                        else f"{n:.2f}B")
            return f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}GB"


def _exec_info(st) -> str:
    return (f"time:{st.wall_s * 1e3:.1f}ms, open:{st.open_s * 1e3:.1f}ms, "
            f"loops:{st.loops}")


def _fmt_count(v) -> str:
    """Counter cell: integers render bare; the occupancy-weighted
    FRACTIONAL shares a stacked batch member carries (its 1/B slice of
    the round's one dispatch — ops/batching.py) keep two decimals
    instead of truncating to a misleading 0."""
    f = float(v)
    return str(int(f)) if f.is_integer() else f"{f:.2f}"


def _device_info(st) -> str:
    """Device-economics cell: program dispatches, packed D2H transfers/
    bytes, program-cache hits/misses, and the pipeline stage/dispatch/
    drain/overlap accounting — only the families that actually fired."""
    d = st.device
    parts = []
    if d.get("dispatches"):
        parts.append(f"dispatches:{_fmt_count(d['dispatches'])}")
    if d.get("device_s"):
        # MEASURED device busy time (sampling profiler,
        # tidb_device_profile_rate) — distinct from the host wall in
        # execution info, which on a real device times the async submit
        parts.append(f"device:{d['device_s'] * 1e3:.1f}ms"
                     f"/{_fmt_count(d.get('profiled_dispatches', 0))}smp")
    if d.get("compile_s"):
        parts.append(f"compile:{d['compile_s'] * 1e3:.1f}ms")
    if d.get("d2h_transfers"):
        parts.append(f"d2h:{_fmt_count(d['d2h_transfers'])}/"
                     f"{_fmt_bytes(d.get('d2h_bytes', 0))}")
    if d.get("h2d_transfers"):
        parts.append(f"h2d:{_fmt_count(d['h2d_transfers'])}/"
                     f"{_fmt_bytes(d.get('h2d_bytes', 0))}")
    hits, misses = d.get("progcache_hits", 0), d.get("progcache_misses", 0)
    if hits or misses:
        parts.append(f"cache:{int(hits)}h/{int(misses)}m")
    if d.get("pipe_blocks"):
        from ..ops.kernels import pipe_overlap_frac
        overlap = pipe_overlap_frac(d)
        parts.append(f"pipe:{int(d['pipe_blocks'])}blk"
                     f"/stage:{d.get('pipe_stage_s', 0.0) * 1e3:.1f}ms"
                     f"/drain:{d.get('pipe_drain_s', 0.0) * 1e3:.1f}ms"
                     f"/overlap:{overlap:.2f}")
    if d.get("spill_bytes"):
        sp = (f"spill:{int(d.get('spill_partitions', 0))}p"
              f"/{_fmt_bytes(d['spill_bytes'])}"
              f"/reload:{_fmt_bytes(d.get('spill_reload_bytes', 0))}")
        if d.get("spill_repartitions"):
            sp += f"/repart:{int(d['spill_repartitions'])}"
        parts.append(sp)
    return ", ".join(parts)


def explain_analyze_text(p: PhysicalPlan, qobs, depth: int = 0,
                         out: Optional[List[list]] = None) -> List[list]:
    """The four EXPLAIN columns plus actRows / execution info / device
    info from the per-operator RuntimeStats collected while the
    statement ran (``qobs`` = the statement's obs scope; operators the
    executor tree never built — e.g. inside a fused devpipe program —
    render with blank analyze cells)."""
    if out is None:
        out = []
    name = p.op_name()
    if getattr(p, "use_tpu", False):
        name += "(TPU)"
    st = qobs.op_stats_for(p) if qobs is not None else None
    act = str(st.act_rows) if st is not None else ""
    einfo = _exec_info(st) if st is not None else ""
    dinfo = _device_info(st) if st is not None else ""
    out.append(["  " * depth + name, _est_rows(p), act, _task(p),
                einfo, dinfo, _info(p)])
    if isinstance(p, PhysicalTableReader):
        out.append(["  " * (depth + 1) + "TableScan",
                    _est_rows(p.scan) or _est_rows(p), "", "cop", "", "",
                    f"table:{p.scan.alias}"])
    for c in p.children:
        explain_analyze_text(c, qobs, depth + 1, out)
    return out
