"""Access-path selection: table scan vs index scan vs index lookup.

Capability parity with reference planner/core/find_best_task.go (the
DataSource task enumeration + skyline pruning stub :214 implemented for
real) and planner/util/path.go AccessPath.  Ranges come from ranger.py;
row-count estimates from statistics/table_stats.py (histograms + CMSketch
when ANALYZE ran, heuristic defaults otherwise).

Cost model (reference task.go GetCost, reduced): scanning N rows costs N;
a covering index scan costs 0.9N (narrower rows); an index lookup pays a
double-read penalty per matched row (task.go finishCopTask's network/seek
factor analogue).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..catalog.model import IndexInfo
from ..expression import Column, Expression
from .logical import LogicalDataSource
from .physical import (PhysicalIndexLookUpReader, PhysicalIndexReader,
                       PhysicalIndexScan, PhysicalPlan, PhysicalTableReader,
                       PhysicalTableScan)
from . import ranger

LOOKUP_FACTOR = 4.0     # random point-read penalty per matched row
COVER_FACTOR = 0.9      # narrower index rows scan cheaper
PSEUDO_ROWS = 10000.0   # row estimate when no stats exist


@dataclass
class AccessPath:
    """reference: planner/util/path.go"""
    index: Optional[IndexInfo]          # None = table (handle) path
    ranges: list
    access_conds: List[Expression]
    remaining: List[Expression]
    covering: bool
    est_rows: float
    cost: float = 0.0
    index_cols: List[Column] = field(default_factory=list)


def _schema_col(ds: LogicalDataSource, name: str) -> Optional[Column]:
    for c in ds.schema.columns:
        if c.name == name:
            return c
    return None


def _sort_cost(n: float) -> float:
    """Cost of materializing + sorting n rows, in scan-row units
    (reference: task.go sort GetCost rows*log(rows)*cpuFactor)."""
    import math
    n = max(n, 1.0)
    return n * math.log2(max(n, 2.0)) * 0.05


def choose_path(ds: LogicalDataSource, stats,
                order_names=None) -> AccessPath:
    """Enumerate paths, skyline-prune, pick min cost.  `order_names`
    (ascending column-name prefix required by a parent Sort/TopN) makes
    this ORDER-AWARE (reference: findBestTask enumerating under a
    required PhysicalProperty): an order-providing path wins when its
    cost beats the cheapest path PLUS the Sort enforcer it avoids."""
    conds = list(ds.pushed_conds)
    # live commit-time count deltas make row_count real even without
    # ANALYZE (stats_meta analogue); only a table we know NOTHING about
    # falls back to the pseudo default
    known = stats is not None and (stats.row_count > 0 or stats.columns
                                   or stats.modify_count > 0)
    total = float(max(stats.row_count, 1)) if known else PSEUDO_ROWS

    paths: List[AccessPath] = []
    order_paths: List[AccessPath] = []

    # ---- table path (clustered int pk -> handle ranges) ----------------
    pk = ds.table_info.get_pk_handle_col()
    pk_col = _schema_col(ds, pk.name) if pk is not None else None
    if pk_col is not None:
        hranges, access, remaining = ranger.build_handle_ranges(conds, pk_col)
    else:
        hranges, access, remaining = None, [], conds
    sel = _sel(stats, access, _handle_heuristic(hranges, total))
    paths.append(AccessPath(None, hranges, access, remaining, True,
                            total * (sel if access else 1.0)))

    # ---- index paths ----------------------------------------------------
    for idx in ds.possible_indices:
        icols = []
        for ic in idx.columns:
            if ic.length >= 0:
                break  # prefix-length column: truncated values can't seek
            c = _schema_col(ds, ic.name)
            if c is None:
                break  # index column pruned out of scope
            icols.append(c)
        if not icols:
            continue
        ranges, access, remaining = ranger.detach_conditions(conds, icols)
        covering = _covers(ds, idx, pk)
        idx_names = _order_idx_names(idx)
        order_ok = (order_names is not None and covering
                    and idx_names[:len(order_names)] == order_names)
        if not access and not order_ok:
            continue  # no seek advantage and no order to provide
        est = total * _sel(stats, access, _heuristic_sel(ranges, icols)
                           if access else 1.0)
        if not access:
            # order-only FULL scan: the whole keyspace INCLUDING the null
            # section (a comparison-derived MIN bound would skip NULLs,
            # but ORDER BY must emit them — first, like the key codec
            # sorts them); exempt from skyline (kept for its ORDER)
            ranges = [ranger.Range(low=(), high=())]
            order_paths.append(AccessPath(idx, ranges, access, remaining,
                                          covering, est, index_cols=icols))
        else:
            paths.append(AccessPath(idx, ranges, access, remaining,
                                    covering, est, index_cols=icols))

    paths = _skyline_prune(paths) + order_paths

    # a nonempty table never estimates below one row (reference pseudo
    # stats floor, planner/core/stats.go: pseudo estimates are fractions
    # of pseudoRowCount, never zero) — EQ_DEFAULT x a tiny row count would
    # otherwise render estRows 0.00 and feed the cost model garbage
    if total >= 1.0:
        for p in paths:
            p.est_rows = min(total, max(p.est_rows, 1.0))

    for p in paths:
        if p.index is None:
            p.cost = p.est_rows if p.access_conds else total
        elif p.covering:
            p.cost = p.est_rows * COVER_FACTOR
        else:
            p.cost = p.est_rows * (1.0 + LOOKUP_FACTOR)
    best = min(paths, key=lambda p: p.cost)
    if order_names is not None:
        sat = [p for p in paths if _path_provides(p, pk, order_names)]
        if sat:
            best_sat = min(sat, key=lambda p: p.cost)
            out_rows = best.est_rows * _residual_sel(stats, best.remaining)
            if best_sat.cost <= best.cost + _sort_cost(out_rows):
                return best_sat
    return best


def _order_idx_names(idx: IndexInfo):
    """Index columns usable for ORDER, stopping at the FIRST
    prefix-length column — a truncated key column breaks the emitted
    order for everything after it (shared by order_ok, _path_provides,
    and build_reader's order_col_uids so they can never disagree)."""
    out = []
    for ic in idx.columns:
        if ic.length >= 0:
            break
        out.append(ic.name)
    return out


def _path_provides(p: AccessPath, pk, order_names) -> bool:
    """Does this path emit `order_names` (ascending prefix)?"""
    if p.index is None:
        return pk is not None and order_names == [pk.name]
    if not p.covering:
        return False  # double-read does not preserve index order here
    return _order_idx_names(p.index)[:len(order_names)] == order_names


def _sel(stats, access_conds: List[Expression], fallback: float) -> float:
    if not access_conds:
        return 1.0
    if stats is not None and not stats.pseudo:
        return stats.selectivity(access_conds)
    return fallback


def _residual_sel(stats, remaining: List[Expression]) -> float:
    """Selectivity of the NON-access filters applied inside the scan: the
    reader's OUTPUT estimate is access-rows x this (reference: the cop
    Selection's own stats row)."""
    if not remaining:
        return 1.0
    if stats is not None and not stats.pseudo:
        return stats.selectivity(remaining)
    from ..statistics.table_stats import DEFAULT_SELECTIVITY
    return DEFAULT_SELECTIVITY ** len(remaining)  # selectionFactor/conjunct


def _handle_heuristic(hranges, total: float) -> float:
    """No stats: a pk point range is ~1 row; narrow ranges scale by width,
    unbounded ranges fall back to the range default 30%."""
    if not hranges:
        return 1.0
    rows = 0.0
    for lo, hi in hranges:
        width = hi - lo + 1
        rows += width if width < total else total * 0.3
    return min(1.0, rows / max(total, 1.0))


def _heuristic_sel(ranges: List[ranger.Range], icols) -> float:
    """No stats: each eq column ~10%, a range column ~30% (reference
    pseudo-stats fractions)."""
    if not ranges:
        return 0.0
    r = ranges[0]
    n_eq = len(r.low) - (0 if r.is_point() else 1)
    s = (0.1 ** max(n_eq, 0))
    if not r.is_point():
        s *= 0.3
    return min(1.0, s * max(len(ranges), 1) ** 0.5)


def _covers(ds: LogicalDataSource, idx: IndexInfo, pk) -> bool:
    """Index covers the query iff every needed schema column is an index
    column (full-length prefix) or the clustered pk handle."""
    idx_names = {ic.name for ic in idx.columns if ic.length < 0}
    for c in ds.schema.columns:
        if c.name in idx_names:
            continue
        if pk is not None and c.name == pk.name:
            continue  # handle rides along in the index entry
        return False
    return True


def _skyline_prune(paths: List[AccessPath]) -> List[AccessPath]:
    """reference find_best_task.go:214 compareCandidates: drop a path whose
    access-condition set is a subset of another's, which is not covering
    while the other is, and which matches no more ranges."""
    keep: List[AccessPath] = []
    for a in paths:
        dominated = False
        a_set = {e.key() for e in a.access_conds}
        for b in paths:
            if a is b:
                continue
            b_set = {e.key() for e in b.access_conds}
            if (a_set < b_set and b.covering >= a.covering) or \
               (a_set == b_set and not a.covering and b.covering):
                dominated = True
                break
        if not dominated:
            keep.append(a)
    return keep or paths



def _out_rows(path_rows: float, resid: float) -> float:
    """Reader output estimate: access rows x residual selectivity, floored
    at one row whenever the access estimate itself says rows exist."""
    v = path_rows * resid
    return max(v, 1.0) if path_rows >= 1.0 else v


# ===== physical construction ===============================================

def build_reader(ds: LogicalDataSource, stats, with_handle: bool,
                 order_hint=None) -> PhysicalPlan:
    """`order_hint`: [(unique_id, desc)] required above this reader —
    mapped to ascending column names for the order-aware path choice.
    The built scans always carry their PROVIDED order metadata
    (order_col_uid / order_col_uids) for props.provided_order."""
    from .optimizer import _bind  # late: avoid import cycle
    order_names = None
    if order_hint:
        by_uid = {c.unique_id: c.name for c in ds.schema.columns}
        if all(not desc and uid in by_uid for uid, desc in order_hint):
            order_names = [by_uid[uid] for uid, _ in order_hint]
    path = choose_path(ds, stats, order_names)
    pk = ds.table_info.get_pk_handle_col()
    pk_uid = None
    if pk is not None:
        sc = next((c for c in ds.schema.columns if c.name == pk.name), None)
        pk_uid = sc.unique_id if sc is not None else None
    if path.index is None:
        scan = PhysicalTableScan(ds.table_info, ds.db_name, ds.alias,
                                 ds.schema, with_handle)
        scan.ranges = path.ranges  # None = full scan
        scan.filters = _bind(path.remaining, ds.schema)
        scan.stats_row_count = path.est_rows
        scan.has_estimate = True
        scan.order_col_uid = pk_uid  # handle-ordered scan
        reader = PhysicalTableReader(scan)
        reader.stats_row_count = _out_rows(
            path.est_rows, _residual_sel(stats, path.remaining))
        reader.has_estimate = True
        return reader

    iscan = PhysicalIndexScan(ds.table_info, path.index, ds.db_name,
                              ds.alias, ds.schema, path.ranges)
    iscan.stats_row_count = path.est_rows
    iscan.has_estimate = True
    # index scans emit index-column order (the kv iteration is ordered);
    # record the uid prefix that maps onto in-scope schema columns
    uids = []
    by_name = {c.name: c.unique_id for c in ds.schema.columns}
    for name in _order_idx_names(path.index):
        if name not in by_name:
            break
        uids.append(by_name[name])
    iscan.order_col_uids = uids
    if path.covering:
        # output plan: ds.schema columns sourced from index values / handle
        pk = ds.table_info.get_pk_handle_col()
        sources = []
        idx_pos = {ic.name: i for i, ic in enumerate(path.index.columns)}
        for c in ds.schema.columns:
            if pk is not None and c.name == pk.name:
                sources.append(("handle",))
            else:
                sources.append(("idx", idx_pos[c.name]))
        iscan.output_sources = sources
        iscan.filters = _bind(path.remaining, ds.schema)
        reader = PhysicalIndexReader(iscan)
        reader.stats_row_count = _out_rows(
            path.est_rows, _residual_sel(stats, path.remaining))
        reader.has_estimate = True
        return reader

    tscan = PhysicalTableScan(ds.table_info, ds.db_name, ds.alias,
                              ds.schema, with_handle)
    tscan.filters = _bind(path.remaining, ds.schema)
    tscan.stats_row_count = _out_rows(
        path.est_rows, _residual_sel(stats, path.remaining))
    tscan.has_estimate = True
    reader = PhysicalIndexLookUpReader(iscan, tscan)
    reader.stats_row_count = tscan.stats_row_count
    reader.has_estimate = True
    return reader
