"""Shared join-conjunct classification (reference: the condition split in
rule_predicate_push_down.go).  Used by both optimizer frameworks so eq
extraction and side routing cannot drift between them.
"""
from __future__ import annotations

from typing import List, Tuple

from ..expression import Expression
from .logical import JOIN_INNER


def classify_conjuncts(conds: List[Expression], lsch, rsch, tp: str):
    """Split CNF `conds` for a join with child schemas (lsch, rsch).

    Returns (new_eq, left_push, right_push, other, retained):
    - new_eq: (left expr, right expr) equi-pairs extracted from `=` conds
    - left_push / right_push: one-side conditions safe to push below
    - other: cross-side non-equi conditions evaluated at the join
    - retained: conditions that must stay ABOVE the join (outer joins)
    """
    new_eq: List[Tuple[Expression, Expression]] = []
    left_push: List[Expression] = []
    right_push: List[Expression] = []
    other: List[Expression] = []
    retained: List[Expression] = []
    for c in conds:
        cols = c.collect_columns()
        on_left = all(lsch.contains(x) for x in cols)
        on_right = all(rsch.contains(x) for x in cols)
        if tp == JOIN_INNER:
            if getattr(c, "name", "") == "=":
                a, b = c.children()
                ac, bc = a.collect_columns(), b.collect_columns()
                if (ac and bc and all(lsch.contains(x) for x in ac)
                        and all(rsch.contains(x) for x in bc)):
                    new_eq.append((a, b))
                    continue
                if (ac and bc and all(rsch.contains(x) for x in ac)
                        and all(lsch.contains(x) for x in bc)):
                    new_eq.append((b, a))
                    continue
            if on_left:
                left_push.append(c)
            elif on_right:
                right_push.append(c)
            else:
                other.append(c)
        else:  # left outer join: only left-side conds push below
            if on_left:
                left_push.append(c)
            else:
                retained.append(c)
    return new_eq, left_push, right_push, other, retained
