"""AST -> logical plan: name resolution + expression rewriting + select
construction.

Capability parity with reference planner/core/logical_plan_builder.go
(buildSelect/buildJoin/buildAggregation/buildProjection/buildSort…, 1,680 L),
expression_rewriter.go (AST expr -> expression.Expression with column
binding), preprocess.go (validation).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..catalog.model import TableInfo
from ..expression import (AGG_FIRST_ROW, AggFuncDesc, Column, Constant,
                          Expression, Schema, fold_constants, new_function,
                          split_cnf)
from ..mytypes import (Datum, new_int_type, new_real_type, new_string_type,
                       FieldType)
from ..parser import ast
from .logical import (JOIN_INNER, JOIN_LEFT, LogicalAggregation,
                      LogicalDataSource, LogicalJoin, LogicalLimit,
                      LogicalPlan, LogicalProjection, LogicalSelection,
                      LogicalSort, LogicalTableDual, LogicalTopN)

HANDLE_COL_NAME = "_tidb_rowid"  # hidden handle column (reference: model.ExtraHandleID)


class PlanError(Exception):
    pass


class UnknownColumn(PlanError):
    def __init__(self, name):
        super().__init__(f"Unknown column '{name}'")


class AmbiguousColumn(PlanError):
    def __init__(self, name):
        super().__init__(f"Column '{name}' in field list is ambiguous")


def _lit_ft(v: Datum) -> FieldType:
    if v is None:
        return new_int_type()
    if isinstance(v, bool) or isinstance(v, int):
        # decimal literals above the signed range are unsigned in MySQL
        return new_int_type(unsigned=v > (1 << 63) - 1)
    if isinstance(v, float):
        return new_real_type()
    return new_string_type()


_BINOP_MAP = {"+": "+", "-": "-", "*": "*", "/": "/", "div": "div",
              "%": "%", "and": "and", "or": "or", "xor": "xor",
              "=": "=", "!=": "!=", "<": "<", "<=": "<=", ">": ">",
              ">=": ">=", "<=>": "<=>"}


class ExprRewriter:
    """AST expression -> typed Expression bound to an input schema
    (reference: expression_rewriter.go)."""

    def __init__(self, schema: Schema, builder: "PlanBuilder",
                 agg_mapper: Optional[Dict[int, Column]] = None,
                 alias_schema: Optional[Schema] = None,
                 outer_schema: Optional[Schema] = None):
        self.schema = schema
        self.builder = builder
        self.agg_mapper = agg_mapper or {}
        # secondary resolution scope (select aliases, for HAVING/ORDER BY)
        self.alias_schema = alias_schema
        # correlated-subquery resolution scope: columns of the OUTER query
        # visible inside an EXISTS subquery (planner/decorrelate.py pulls
        # the conjuncts referencing them up to the semi join)
        self.outer_schema = outer_schema

    def rewrite(self, e: ast.ExprNode) -> Expression:
        if isinstance(e, ast.Literal):
            return Constant(e.value, _lit_ft(e.value))
        if isinstance(e, ast.ParenExpr):
            return self.rewrite(e.expr)
        if isinstance(e, ast.ColumnRef):
            return self.resolve_column(e)
        if isinstance(e, ast.UnaryOp):
            if e.op == "-":
                return new_function("unaryminus", [self.rewrite(e.operand)])
            if e.op == "not":
                return new_function("not", [self.rewrite(e.operand)])
            raise PlanError(f"unsupported unary op {e.op}")
        if isinstance(e, ast.BinaryOp):
            op = _BINOP_MAP.get(e.op)
            if op is None:
                raise PlanError(f"unsupported operator {e.op}")
            return new_function(op, [self.rewrite(e.left), self.rewrite(e.right)])
        if isinstance(e, ast.IsNullExpr):
            f = new_function("isnull", [self.rewrite(e.expr)])
            return new_function("not", [f]) if e.negated else f
        if isinstance(e, ast.IsTruthExpr):
            f = new_function("istrue" if e.truth else "isfalse",
                             [self.rewrite(e.expr)])
            return new_function("not", [f]) if e.negated else f
        if isinstance(e, ast.LikeExpr):
            f = new_function("like", [self.rewrite(e.expr),
                                      self.rewrite(e.pattern),
                                      Constant(e.escape, new_string_type())])
            return new_function("not", [f]) if e.negated else f
        if isinstance(e, ast.InExpr):
            f = new_function("in", [self.rewrite(e.expr)]
                             + [self.rewrite(x) for x in e.items])
            return new_function("not", [f]) if e.negated else f
        if isinstance(e, ast.BetweenExpr):
            x = self.rewrite(e.expr)
            lo = new_function(">=", [x, self.rewrite(e.lo)])
            hi = new_function("<=", [x, self.rewrite(e.hi)])
            f = new_function("and", [lo, hi])
            return new_function("not", [f]) if e.negated else f
        if isinstance(e, ast.FuncCall):
            return new_function(e.name, [self.rewrite(a) for a in e.args])
        if isinstance(e, ast.AggFunc):
            col = self.agg_mapper.get(id(e))
            if col is None:
                raise PlanError(f"invalid use of aggregate {e.name}()")
            return col
        if isinstance(e, ast.CaseExpr):
            args: List[Expression] = []
            for cond, res in e.when_clauses:
                c = self.rewrite(cond)
                if e.operand is not None:
                    c = new_function("=", [self.rewrite(e.operand), c])
                args += [c, self.rewrite(res)]
            if e.else_clause is not None:
                args.append(self.rewrite(e.else_clause))
            return new_function("case", args)
        if isinstance(e, ast.VariableExpr):
            v = self.builder.get_variable(e)
            return Constant(v, _lit_ft(v))
        if isinstance(e, ast.SubqueryExpr):
            # scalar subquery: evaluated eagerly at plan time (reference:
            # expression_rewriter.go handleScalarSubquery — uncorrelated
            # scalar subqueries fold to constants during optimization);
            # PR 6 literal parameterization keeps the folded constant out
            # of program-cache keys.  Note an IN-list landing here (not a
            # decorrelated top-level WHERE conjunct) gets SCALAR
            # semantics: >1 subquery row is a loud 1242 error.
            v = self.builder.eval_scalar_subquery(e)
            return Constant(v, _lit_ft(v))
        if isinstance(e, ast.ExistsExpr):
            # EXISTS outside a decorrelatable WHERE conjunct: eager
            # boolean evaluation (uncorrelated only — a correlated column
            # fails name resolution inside)
            v = self.builder.eval_exists_subquery(e)
            f = Constant(v, _lit_ft(v))
            return new_function("not", [f]) if e.negated else f
        if isinstance(e, ast.RowExpr):
            raise PlanError("row expressions are only valid in IN lists")
        if isinstance(e, ast.DefaultExpr):
            raise PlanError("DEFAULT is only valid in VALUES lists")
        raise PlanError(f"unsupported expression {type(e).__name__}")

    def resolve_column(self, ref: ast.ColumnRef) -> Column:
        hits = _find_in_schema(self.schema, ref)
        if not hits and self.alias_schema is not None:
            hits = _find_in_schema(self.alias_schema, ref)
        if not hits and self.outer_schema is not None:
            # correlated reference into the enclosing query's scope
            hits = _find_in_schema(self.outer_schema, ref)
        if not hits:
            raise UnknownColumn(str(ref))
        if len(hits) > 1:
            raise AmbiguousColumn(str(ref))
        return hits[0]


def _find_in_schema(schema: Schema, ref: ast.ColumnRef) -> List[Column]:
    name = ref.name.lower()
    table = ref.table.lower()
    db = ref.db.lower()
    out = []
    for c in schema.columns:
        if c.name.lower() != name:
            continue
        if table and (c.table or "").lower() != table:
            continue
        if db and (c.db or "").lower() != db:
            continue
        out.append(c)
    # duplicate unique_ids (same col seen via merge) count once
    seen = set()
    uniq = []
    for c in out:
        if c.unique_id not in seen:
            seen.add(c.unique_id)
            uniq.append(c)
    return uniq


class PlanBuilder:
    """reference: planner/core/planbuilder.go PlanBuilder (the SELECT slice;
    non-query statements build executor-level plans in executor/builder)."""

    def __init__(self, ctx):
        # ctx: session context with .infoschema(), .current_db, .get_sysvar,
        # .get_uservar
        self.ctx = ctx

    # ---- variables ------------------------------------------------------
    def get_variable(self, e: ast.VariableExpr) -> Datum:
        if e.is_system:
            return self.ctx.get_sysvar(e.name, e.scope)
        return self.ctx.get_uservar(e.name)

    # ---- subquery evaluation (planner/decorrelate.py's eager arm) -------
    def eval_scalar_subquery(self, e: ast.SubqueryExpr) -> Datum:
        """Execute an uncorrelated scalar subquery NOW; 0 rows -> NULL,
        >1 rows -> error 1242 (MySQL semantics)."""
        rows = self._run_subquery(e.select)
        if len(rows) > 1:
            raise PlanError("Subquery returns more than 1 row")
        if not rows:
            return None
        if len(rows[0]) != 1:
            raise PlanError("Operand should contain 1 column(s)")
        v = rows[0][0]
        return v.item() if hasattr(v, "item") else v

    def eval_exists_subquery(self, e: ast.ExistsExpr) -> int:
        import copy
        stmt = copy.copy(e.select)
        if stmt.limit is None:
            stmt.limit = (0, 1)  # EXISTS needs one row at most
        return 1 if self._run_subquery(stmt) else 0

    def _run_subquery(self, stmt: ast.SelectStmt) -> list:
        runner = getattr(self.ctx, "_run_select_plan", None)
        if runner is None:
            raise PlanError("subqueries need an executing session context")
        return runner(stmt, self.ctx.get_txn())

    # ---- entry -----------------------------------------------------------
    def build_select(self, stmt: ast.SelectStmt) -> LogicalPlan:
        if stmt.from_ is not None:
            p = self.build_table_refs(stmt.from_)
        else:
            p = LogicalTableDual()
        if stmt.where is not None:
            # subquery-bearing conjuncts first: IN/EXISTS decorrelate
            # into semi/anti joins over p (planner/decorrelate.py)
            from .decorrelate import apply_where_subqueries
            p, residual = apply_where_subqueries(self, p, stmt.where)
            conds = []
            rw = ExprRewriter(p.schema, self)
            for conj in residual:
                conds.extend(fold_constants(c)
                             for c in split_cnf(rw.rewrite(conj)))
            if conds:
                p = LogicalSelection(conds, p)

        # ---- wildcard expansion -------------------------------------
        fields = self._expand_wildcards(stmt.fields, p.schema)

        # ---- aggregate analysis -------------------------------------
        agg_nodes: List[ast.AggFunc] = []
        for f in fields:
            if f.expr is not None:
                agg_nodes += [x for x in ast.walk_expr(f.expr)
                              if isinstance(x, ast.AggFunc)]
        having_aggs = [x for x in ast.walk_expr(stmt.having)
                       if isinstance(x, ast.AggFunc)] if stmt.having else []
        order_aggs = []
        for e, _ in stmt.order_by:
            order_aggs += [x for x in ast.walk_expr(e)
                           if isinstance(x, ast.AggFunc)]
        all_aggs = agg_nodes + having_aggs + order_aggs
        need_agg = bool(all_aggs) or bool(stmt.group_by)

        agg_mapper: Dict[int, Column] = {}
        gb_cols: Dict[str, Column] = {}
        if need_agg:
            p, agg_mapper, gb_cols = self._build_aggregation(
                p, stmt.group_by, all_aggs, fields)

        # ---- having --------------------------------------------------
        if stmt.having is not None:
            rw = ExprRewriter(p.schema, self, agg_mapper,
                              alias_schema=self._alias_schema(fields, p, agg_mapper))
            conds = split_cnf(rw.rewrite(stmt.having))
            p = LogicalSelection(conds, p)

        # ---- projection ---------------------------------------------
        rw = ExprRewriter(p.schema, self, agg_mapper)
        proj_exprs: List[Expression] = []
        out_cols: List[Column] = []
        for f in fields:
            e = rw.rewrite(f.expr)
            proj_exprs.append(e)
            name = f.as_name or (f.expr.name if isinstance(f.expr, ast.ColumnRef)
                                 else (f.text or "expr"))
            if isinstance(e, Column) and not f.as_name:
                out_cols.append(e.renamed(name=name, table=e.table))
            else:
                out_cols.append(Column(e.ret_type, name=name))
        proj_schema = Schema(out_cols)
        p = LogicalProjection(proj_exprs, proj_schema, p)

        # ---- distinct -----------------------------------------------
        if stmt.distinct:
            p = self._build_distinct(p)

        # ---- order by -----------------------------------------------
        visible = len(proj_schema)
        if stmt.order_by:
            p, extra = self._build_sort(p, stmt.order_by, fields, agg_mapper,
                                        gb_cols)
        # ---- limit --------------------------------------------------
        if stmt.limit is not None:
            off, cnt = stmt.limit
            p = LogicalLimit(off, cnt, p)
        # trim hidden order-by columns
        if len(p.schema) > visible:
            keep = p.schema.columns[:visible]
            p = LogicalProjection(list(keep), Schema(list(keep)), p)
        return p

    def _expand_wildcards(self, fields: List[ast.SelectField],
                          schema: Schema) -> List[ast.SelectField]:
        """Expand * and t.* into explicit column fields (reference:
        logical_plan_builder.go unfoldWildStar)."""
        out: List[ast.SelectField] = []
        for f in fields:
            if not f.is_wildcard:
                out.append(f)
                continue
            want = f.wildcard_table.lower()
            matched = False
            for c in schema.columns:
                if c.name == HANDLE_COL_NAME:
                    continue
                if want and (c.table or "").lower() != want:
                    continue
                matched = True
                out.append(ast.SelectField(
                    ast.ColumnRef(c.name, table=c.table or ""),
                    as_name=c.name))
            if not matched:
                raise UnknownColumn(f"{f.wildcard_table or ''}.*")
        return out

    # ---- FROM ------------------------------------------------------------
    def build_table_refs(self, j: ast.Join) -> LogicalPlan:
        if j.right is None:
            return self._build_table_source(j.left)
        left = (self.build_table_refs(j.left) if isinstance(j.left, ast.Join)
                else self._build_table_source(j.left))
        right = (self.build_table_refs(j.right) if isinstance(j.right, ast.Join)
                 else self._build_table_source(j.right))
        tp = j.tp
        if tp == "right":
            left, right = right, left
            tp = JOIN_LEFT
        elif tp == "cross":
            tp = JOIN_INNER
        join = LogicalJoin(tp, left, right)
        conds: List[Expression] = []
        if j.on is not None:
            rw = ExprRewriter(join.schema, self)
            conds = split_cnf(rw.rewrite(j.on))
        for name in j.using:
            lref = _find_in_schema(left.schema, ast.ColumnRef(name))
            rref = _find_in_schema(right.schema, ast.ColumnRef(name))
            if not lref or not rref:
                raise UnknownColumn(name)
            conds.append(new_function("=", [lref[0], rref[0]]))
        self._classify_join_conds(join, conds)
        return join

    def _classify_join_conds(self, join: LogicalJoin,
                             conds: List[Expression]) -> None:
        """Split ON conjuncts into equi-keys / one-side filters / other
        (reference: LogicalJoin.attachOnConds + extractOnCondition)."""
        lsch, rsch = join.children[0].schema, join.children[1].schema
        for c in conds:
            cols = c.collect_columns()
            from_left = any(lsch.contains(x) for x in cols)
            from_right = any(rsch.contains(x) for x in cols)
            if (getattr(c, "name", "") == "=" and from_left and from_right):
                a, b = c.children()
                acols, bcols = a.collect_columns(), b.collect_columns()
                a_left = acols and all(lsch.contains(x) for x in acols)
                b_right = bcols and all(rsch.contains(x) for x in bcols)
                a_right = acols and all(rsch.contains(x) for x in acols)
                b_left = bcols and all(lsch.contains(x) for x in bcols)
                if a_left and b_right:
                    join.eq_conditions.append((a, b))
                    continue
                if a_right and b_left:
                    join.eq_conditions.append((b, a))
                    continue
            if from_left and not from_right:
                join.left_conditions.append(c)
            elif from_right and not from_left:
                join.right_conditions.append(c)
            else:
                join.other_conditions.append(c)

    def _build_table_source(self, src) -> LogicalPlan:
        if isinstance(src, ast.Join):
            return self.build_table_refs(src)
        assert isinstance(src, ast.TableSource)
        if isinstance(src.source, ast.SelectStmt):
            sub = self.build_select(src.source)
            # re-qualify output columns under the derived-table alias
            cols = [c.renamed(table=src.as_name) for c in sub.schema.columns]
            sub = LogicalProjection(list(sub.schema.columns), Schema(cols), sub)
            return sub
        tn: ast.TableName = src.source
        db = tn.db or self.ctx.current_db
        if not db:
            raise PlanError("No database selected")
        from ..catalog.memtables import is_memtable, memtable_columns
        if is_memtable(db, tn.name):
            from .logical import LogicalMemTable
            alias = src.as_name or tn.name
            cols = [Column(ft, name=name, table=alias, db=db)
                    for name, ft in memtable_columns(tn.name)]
            return LogicalMemTable(db, tn.name.lower(), cols)
        tbl: TableInfo = self.ctx.infoschema().table_by_name(db, tn.name)
        alias = src.as_name or tn.name
        cols = []
        for c in tbl.public_columns():
            col = Column(c.ft, name=c.name, table=alias, db=db)
            col.stats_col_id = c.id  # feeds histogram/CMS selectivity
            cols.append(col)
        ds = LogicalDataSource(db, tbl, alias, cols)
        ds.storage = self.ctx.storage  # stats lookup at physical time
        return ds

    # ---- aggregation ------------------------------------------------------
    def _build_aggregation(self, p: LogicalPlan, group_by: List[ast.ExprNode],
                           agg_nodes: List[ast.AggFunc],
                           fields: List[ast.SelectField]):
        rw = ExprRewriter(p.schema, self)
        # group-by items; `GROUP BY 1` = field ordinal; bare alias resolves
        # against select fields (MySQL extension)
        gb_exprs: List[Expression] = []
        gb_ast: List[ast.ExprNode] = []
        for g in group_by:
            if isinstance(g, ast.Literal) and isinstance(g.value, int):
                idx = g.value - 1
                if not (0 <= idx < len(fields)) or fields[idx].expr is None:
                    raise PlanError(f"Unknown column '{g.value}' in group statement")
                g = fields[idx].expr
            elif isinstance(g, ast.ColumnRef) and not g.table:
                try:
                    rw.resolve_column(g)
                except UnknownColumn:
                    for f in fields:
                        if f.as_name and f.as_name.lower() == g.name.lower():
                            g = f.expr
                            break
            gb_ast.append(g)
            gb_exprs.append(fold_constants(rw.rewrite(g)))

        # dedupe agg funcs by structural key
        descs: List[AggFuncDesc] = []
        desc_cols: List[Column] = []
        agg_mapper: Dict[int, Column] = {}
        by_key: Dict[str, Column] = {}
        for node in agg_nodes:
            args = [rw.rewrite(a) for a in node.args]
            desc = AggFuncDesc(node.name, args, distinct=node.distinct)
            key = f"{node.name}|{node.distinct}|" + ",".join(a.key() for a in args)
            col = by_key.get(key)
            if col is None:
                col = Column(desc.ret_type, name=f"{node.name}#{len(descs)}")
                by_key[key] = col
                descs.append(desc)
                desc_cols.append(col)
            agg_mapper[id(node)] = col

        # group-by outputs (referencable in SELECT/HAVING/ORDER BY)
        gb_cols: Dict[str, Column] = {}
        gb_out_cols: List[Column] = []
        for g_ast, g_expr in zip(gb_ast, gb_exprs):
            if isinstance(g_expr, Column):
                out = g_expr
            else:
                out = Column(g_expr.ret_type, name=g_expr.key())
            gb_cols[g_expr.key()] = out
            gb_out_cols.append(out)

        # non-aggregated select columns become first_row aggs (MySQL's
        # non-ONLY_FULL_GROUP_BY behavior; reference adds FirstRow descs)
        gb_keys = {e.key() for e in gb_exprs}
        for f in fields:
            if f.expr is None:
                continue
            for node in ast.walk_expr(f.expr):
                if isinstance(node, ast.AggFunc):
                    break
            else:
                e = rw.rewrite(f.expr)
                for c in e.collect_columns():
                    if c.key() in gb_keys:
                        continue
                    if any(c.unique_id == gc.unique_id for gc in gb_out_cols):
                        continue
                    if any(c.unique_id == dc.unique_id for dc in desc_cols):
                        continue
                    # first_row passthrough keeps the same column identity
                    descs.append(AggFuncDesc(AGG_FIRST_ROW, [c]))
                    desc_cols.append(c)

        schema = Schema(desc_cols + [c for c in gb_out_cols
                                     if not any(c.unique_id == d.unique_id
                                                for d in desc_cols)])
        agg = LogicalAggregation(gb_exprs, descs, schema, p)
        # stash output binding: executor emits desc outputs then gb outputs
        agg.output_cols = desc_cols
        agg.gb_out_cols = gb_out_cols
        return agg, agg_mapper, gb_cols

    def _build_distinct(self, p: LogicalProjection) -> LogicalPlan:
        """SELECT DISTINCT -> group by all output columns (reference:
        buildDistinct)."""
        gb = list(p.schema.columns)
        descs = [AggFuncDesc(AGG_FIRST_ROW, [c]) for c in gb]
        agg = LogicalAggregation(list(gb), descs, Schema(list(gb)), p)
        agg.output_cols = list(gb)
        agg.gb_out_cols = list(gb)
        return agg

    def _alias_schema(self, fields, p, agg_mapper) -> Schema:
        cols = []
        for f in fields:
            if f.as_name and f.expr is not None:
                try:
                    rw = ExprRewriter(p.schema, self, agg_mapper)
                    e = rw.rewrite(f.expr)
                except PlanError:
                    continue
                if isinstance(e, Column):
                    cols.append(e.renamed(name=f.as_name, table=""))
        return Schema(cols)

    # ---- order by ---------------------------------------------------------
    def _build_sort(self, p: LogicalPlan,
                    order_by: List[Tuple[ast.ExprNode, bool]],
                    fields: List[ast.SelectField],
                    agg_mapper: Dict[int, Column],
                    gb_cols: Dict[str, Column]):
        """ORDER BY resolves against select aliases first, then the
        projection input; expressions not in the projection get appended as
        hidden columns (trimmed by the caller)."""
        proj: LogicalProjection = p if isinstance(p, LogicalProjection) else None
        items: List[Tuple[Expression, bool]] = []
        extra = 0
        for e_ast, desc in order_by:
            e = self._resolve_order_item(e_ast, p, fields, agg_mapper)
            if e is None:
                # not available in current output: compute beneath, append
                if proj is None:
                    raise UnknownColumn(str(e_ast))
                rw = ExprRewriter(proj.child(0).schema, self, agg_mapper)
                inner = rw.rewrite(e_ast)
                hidden = Column(inner.ret_type, name=f"_order_{extra}")
                proj.exprs.append(inner)
                proj.schema = Schema(proj.schema.columns + [hidden])
                p.schema = proj.schema
                e = hidden
                extra += 1
            items.append((e, desc))
        return LogicalSort(items, p), extra

    def _resolve_order_item(self, e_ast, p, fields, agg_mapper):
        # ordinal
        if isinstance(e_ast, ast.Literal) and isinstance(e_ast.value, int):
            idx = e_ast.value - 1
            if 0 <= idx < len(p.schema.columns):
                return p.schema.columns[idx]
            raise PlanError(f"Unknown column '{e_ast.value}' in order clause")
        try:
            rw = ExprRewriter(p.schema, self, agg_mapper)
            return rw.rewrite(e_ast)
        except PlanError:
            return None
