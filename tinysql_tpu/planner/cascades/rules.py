"""Cascades transformation rules (reference:
planner/cascades/transformation_rules.go — the Transformation interface
with pattern + Match + OnTransform; the rule set mirrors the course's:
PushSelDownTableScan/Join/Projection/Aggregation, MergeAdjacentSelection,
PushTopNDownProjection, PushLimitDownProjection.

The reference's :497 stub (PushAggDownGather — partial aggregation through
the storage-gather boundary) and :800 stub (TopN onto index source) are
realized in this engine at the shared physical tail: planner/cop.py
push_to_cop splits aggregates into cop PARTIAL1 + root FINAL and pre-cuts
TopN per region, and planner/access.py compiles TopN-compatible index
ranges — both run on cascades output exactly as on the System-R path.
"""
from __future__ import annotations

import copy
from typing import List

from ...expression import Column, Expression
from ..logical import (JOIN_INNER, LogicalAggregation, LogicalDataSource,
                       LogicalJoin, LogicalLimit, LogicalPlan,
                       LogicalProjection, LogicalSelection, LogicalSort,
                       LogicalTopN)
from ..optimizer import substitute_column
from .memo import ANY, Group, GroupExpr, Memo, Pattern


def _mk_sel(conds, schema):
    s = LogicalSelection.__new__(LogicalSelection)
    LogicalPlan.__init__(s)
    s.conditions = conds
    s.schema = schema
    return s


def _mk_proj(exprs, schema):
    pr = LogicalProjection.__new__(LogicalProjection)
    LogicalPlan.__init__(pr)
    pr.exprs = exprs
    pr.schema = schema
    return pr


def _mk_topn(by, offset, count, schema):
    t = LogicalTopN.__new__(LogicalTopN)
    LogicalPlan.__init__(t)
    t.by = by
    t.offset = offset
    t.count = count
    t.schema = schema
    return t


def _mk_limit(offset, count, schema):
    t = LogicalLimit.__new__(LogicalLimit)
    LogicalPlan.__init__(t)
    t.offset = offset
    t.count = count
    t.schema = schema
    return t


class Transformation:
    pattern: Pattern = None

    def on_transform(self, memo: Memo, group: Group, binding) -> bool:
        """Insert equivalent expression(s) into `group`; returns True if
        the memo changed."""
        raise NotImplementedError


def _clone_ds(ds: LogicalDataSource) -> LogicalDataSource:
    c = LogicalDataSource(ds.db_name, ds.table_info, ds.alias,
                          list(ds.schema.columns))
    c.schema = ds.schema
    c.pushed_conds = list(ds.pushed_conds)
    c.all_conds = list(ds.all_conds)
    c.possible_indices = list(ds.possible_indices)
    if hasattr(ds, "storage"):
        c.storage = ds.storage
    return c


class PushSelDownDataSource(Transformation):
    """Selection(DataSource) => DataSource with merged pushed conds
    (reference: PushSelDownTableScan/TiKVSingleGather)."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalDataSource)])

    def on_transform(self, memo, group, binding):
        sel_ge, ds_ge = binding[0], binding[1][0]
        ds = _clone_ds(ds_ge.op)
        ds.pushed_conds.extend(sel_ge.op.conditions)
        ds.all_conds = list(ds.pushed_conds)
        return memo.insert_equivalent(group, ds, [])


class MergeAdjacentSelection(Transformation):
    """Selection(Selection(x)) => Selection(x) with merged CNF."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalSelection)])

    def on_transform(self, memo, group, binding):
        outer, inner = binding[0], binding[1][0]
        merged = _mk_sel(
            list(outer.op.conditions) + list(inner.op.conditions),
            group.schema)
        return memo.insert_equivalent(group, merged, list(inner.children))


class PushSelDownProjection(Transformation):
    """Selection(Projection(x)) => Projection(Selection(x)) for conditions
    expressible over the projection input."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalProjection)])

    def on_transform(self, memo, group, binding):
        sel_ge, proj_ge = binding[0], binding[1][0]
        proj = proj_ge.op
        pushable, retained = [], []
        for c in sel_ge.op.conditions:
            cols = c.collect_columns()
            if all(proj.schema.column_index(x) >= 0 for x in cols):
                pushable.append(substitute_column(c, proj.schema, proj.exprs))
            else:
                retained.append(c)
        if not pushable:
            return False
        child_group = proj_ge.children[0]
        new_sel = _mk_sel(pushable, child_group.schema)
        sel_group = Group(child_group.schema)
        sel_group.insert(GroupExpr(new_sel, [child_group]))
        new_proj = _mk_proj(list(proj.exprs), proj.schema)
        if retained:
            inner_proj_group = Group(proj.schema)
            inner_proj_group.insert(GroupExpr(new_proj, [sel_group]))
            top = _mk_sel(retained, group.schema)
            return memo.insert_equivalent(group, top, [inner_proj_group])
        return memo.insert_equivalent(group, new_proj, [sel_group])


class PushSelDownJoin(Transformation):
    """Selection(Join(l, r)) => Join' with side conditions pushed into new
    child selections (reference: PushSelDownJoin)."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalJoin)])

    def on_transform(self, memo, group, binding):
        from ..joinconds import classify_conjuncts
        sel_ge, join_ge = binding[0], binding[1][0]
        join: LogicalJoin = join_ge.op
        lgroup, rgroup = join_ge.children
        lsch, rsch = lgroup.schema, rgroup.schema
        new_eq, lp, rp, other, retained = classify_conjuncts(
            sel_ge.op.conditions, lsch, rsch, join.tp)
        new_join = copy.copy(join)
        new_join.eq_conditions = list(join.eq_conditions) + new_eq
        new_join.other_conditions = list(join.other_conditions) + other
        # inner join: the join's own one-side ON conditions push down WITH
        # the selection's.  Outer join: ON-clause outer-side conditions
        # must STAY on the join (they decide matching; a failing outer row
        # null-extends instead of being filtered) — only WHERE-side conds
        # (lp) push below the outer child.
        from ..logical import JOIN_INNER as _INNER
        if join.tp == _INNER:
            left_push = list(join.left_conditions) + lp
            new_join.left_conditions = []
        else:
            left_push = lp
            new_join.left_conditions = list(join.left_conditions)
        right_push = list(join.right_conditions) + rp
        new_join.right_conditions = []
        if not (left_push or right_push or new_eq):
            return False

        def wrap(child_group, conds):
            if not conds:
                return child_group
            s = _mk_sel(conds, child_group.schema)
            g = Group(child_group.schema)
            g.insert(GroupExpr(s, [child_group]))
            return g
        children = [wrap(lgroup, left_push), wrap(rgroup, right_push)]
        if retained:
            jg = Group(group.schema)
            jg.insert(GroupExpr(new_join, children))
            top = _mk_sel(retained, group.schema)
            return memo.insert_equivalent(group, top, [jg])
        return memo.insert_equivalent(group, new_join, children)


class PushSelDownAggregation(Transformation):
    """Selection(Agg(x)) => Agg(Selection(x)) for conditions over plain
    group-by columns (reference: PushSelDownAggregation)."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalAggregation)])

    def on_transform(self, memo, group, binding):
        sel_ge, agg_ge = binding[0], binding[1][0]
        agg: LogicalAggregation = agg_ge.op
        gb_uids = {c.unique_id for e in agg.group_by
                   for c in ([e] if isinstance(e, Column) else [])}
        push, retained = [], []
        for c in sel_ge.op.conditions:
            cols = c.collect_columns()
            if cols and all(x.unique_id in gb_uids for x in cols):
                push.append(c)
            else:
                retained.append(c)
        if not push:
            return False
        child_group = agg_ge.children[0]
        s = _mk_sel(push, child_group.schema)
        sg = Group(child_group.schema)
        sg.insert(GroupExpr(s, [child_group]))
        new_agg = copy.copy(agg)
        if retained:
            ag = Group(agg.schema)
            ag.insert(GroupExpr(new_agg, [sg]))
            top = _mk_sel(retained, group.schema)
            return memo.insert_equivalent(group, top, [ag])
        return memo.insert_equivalent(group, new_agg, [sg])


class PushTopNDownProjection(Transformation):
    """TopN(Projection(x)) => Projection(TopN(x)) when sort keys resolve
    below the projection (reference: PushTopNDownProjection)."""
    pattern = Pattern(LogicalTopN, [Pattern(LogicalProjection)])

    def on_transform(self, memo, group, binding):
        topn_ge, proj_ge = binding[0], binding[1][0]
        topn: LogicalTopN = topn_ge.op
        proj = proj_ge.op
        try:
            by = [(substitute_column(e, proj.schema, proj.exprs), d)
                  for e, d in topn.by]
        except Exception:
            return False
        child_group = proj_ge.children[0]
        inner = _mk_topn(by, topn.offset, topn.count, child_group.schema)
        tg = Group(child_group.schema)
        tg.insert(GroupExpr(inner, [child_group]))
        new_proj = _mk_proj(list(proj.exprs), proj.schema)
        return memo.insert_equivalent(group, new_proj, [tg])


class MergeLimitSortToTopN(Transformation):
    """Limit(Sort(x)) => TopN(x) (the System-R topn_pushdown analogue;
    makes per-region TopN pre-cut reachable from cascades plans)."""
    pattern = Pattern(LogicalLimit, [Pattern(LogicalSort)])

    def on_transform(self, memo, group, binding):
        lim_ge, sort_ge = binding[0], binding[1][0]
        lim: LogicalLimit = lim_ge.op
        topn = _mk_topn(list(sort_ge.op.by), lim.offset, lim.count,
                        group.schema)
        return memo.insert_equivalent(group, topn, list(sort_ge.children))


class PushLimitDownProjection(Transformation):
    """Limit(Projection(x)) => Projection(Limit(x))."""
    pattern = Pattern(LogicalLimit, [Pattern(LogicalProjection)])

    def on_transform(self, memo, group, binding):
        lim_ge, proj_ge = binding[0], binding[1][0]
        lim: LogicalLimit = lim_ge.op
        proj = proj_ge.op
        child_group = proj_ge.children[0]
        inner = _mk_limit(lim.offset, lim.count, child_group.schema)
        lg = Group(child_group.schema)
        lg.insert(GroupExpr(inner, [child_group]))
        new_proj = _mk_proj(list(proj.exprs), proj.schema)
        return memo.insert_equivalent(group, new_proj, [lg])


class PushSelDownSort(Transformation):
    """Selection(Sort(x)) => Sort(Selection(x)) — filtering before the
    sort is never worse (reference: PushSelDownSort
    transformation_rules.go:388)."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalSort)])

    def on_transform(self, memo, group, binding):
        sel_ge, sort_ge = binding[0], binding[1][0]
        child_group = sort_ge.children[0]
        s = _mk_sel(list(sel_ge.op.conditions), child_group.schema)
        sg = Group(child_group.schema)
        sg.insert(GroupExpr(s, [child_group]))
        new_sort = copy.copy(sort_ge.op)
        return memo.insert_equivalent(group, new_sort, [sg])


class EliminateProjection(Transformation):
    """Projection that is a 1:1 column passthrough of its child's schema
    merges the child group's expressions into its own (reference:
    EliminateProjection transformation_rules.go:623)."""
    # no child pattern: one binding per projection expression (the child
    # group is rescanned wholesale anyway)
    pattern = Pattern(LogicalProjection)

    def on_transform(self, memo, group, binding):
        proj_ge = binding[0]
        proj: LogicalProjection = proj_ge.op
        if not proj_ge.children:
            return False
        child_group = proj_ge.children[0]
        csch = child_group.schema.columns
        if len(proj.exprs) != len(csch):
            return False
        for e, oc, c in zip(proj.exprs, proj.schema.columns, csch):
            if not isinstance(e, Column) or e.unique_id != c.unique_id:
                return False
            if oc.unique_id != c.unique_id:
                return False  # renaming projection: parents reference
                # the NEW unique id — eliminating it would orphan them
        changed = False
        for cge in list(child_group.exprs):
            changed |= memo.insert_equivalent(group, cge.op,
                                              list(cge.children))
        return changed


class MergeAdjacentProjection(Transformation):
    """Projection(Projection(x)) => one Projection with the outer exprs
    substituted through the inner (reference: MergeAdjacentProjection
    transformation_rules.go:663)."""
    pattern = Pattern(LogicalProjection, [Pattern(LogicalProjection)])

    def on_transform(self, memo, group, binding):
        outer_ge, inner_ge = binding[0], binding[1][0]
        outer, inner = outer_ge.op, inner_ge.op
        # explicit resolvability check: substitute_column passes unknown
        # columns through unchanged, which would silently emit a merged
        # node referencing columns the new child does not produce
        for e in outer.exprs:
            if any(inner.schema.column_index(c) < 0
                   for c in e.collect_columns()):
                return False
        exprs = [substitute_column(e, inner.schema, inner.exprs)
                 for e in outer.exprs]
        merged = _mk_proj(exprs, outer.schema)
        return memo.insert_equivalent(group, merged,
                                      list(inner_ge.children))


class MergeAggregationProjection(Transformation):
    """Aggregation(Projection(x)) => Aggregation'(x) with group-by and
    argument expressions substituted through the projection (reference:
    MergeAggregationProjection transformation_rules.go:778 — a course
    stub there; realized per its header contract)."""
    pattern = Pattern(LogicalAggregation, [Pattern(LogicalProjection)])

    def on_transform(self, memo, group, binding):
        agg_ge, proj_ge = binding[0], binding[1][0]
        agg: LogicalAggregation = agg_ge.op
        proj = proj_ge.op
        for e in list(agg.group_by) + [a for d in agg.agg_funcs
                                       for a in d.args]:
            if any(proj.schema.column_index(c) < 0
                   for c in e.collect_columns()):
                return False
        gb = [substitute_column(e, proj.schema, proj.exprs)
              for e in agg.group_by]
        funcs = []
        for d in agg.agg_funcs:
            d2 = d.clone()
            d2.args = [substitute_column(a, proj.schema, proj.exprs)
                       for a in d.args]
            funcs.append(d2)
        new_agg = copy.copy(agg)
        new_agg.group_by = gb
        new_agg.agg_funcs = funcs
        return memo.insert_equivalent(group, new_agg,
                                      list(proj_ge.children))


class PushTopNDownOuterJoin(Transformation):
    """TopN(LeftJoin(l, r)) with every sort key from the OUTER side =>
    also TopN the left child (limit offset+count, offset 0): the join
    preserves every outer row, so the global top-(o+c) is within the
    outer top-(o+c) (the System-R topn_pushdown's join arm, reachable
    from cascades plans; reference TiDB PushTopNDownOuterJoin)."""
    pattern = Pattern(LogicalTopN, [Pattern(LogicalJoin)])

    def on_transform(self, memo, group, binding):
        from ..logical import JOIN_LEFT
        topn_ge, join_ge = binding[0], binding[1][0]
        topn: LogicalTopN = topn_ge.op
        join: LogicalJoin = join_ge.op
        if join.tp != JOIN_LEFT:
            return False
        lgroup, rgroup = join_ge.children
        lsch = lgroup.schema
        for e, _ in topn.by:
            cols = e.collect_columns()
            if not cols or not all(lsch.column_index(c) >= 0
                                   for c in cols):
                return False
        inner = _mk_topn(list(topn.by), 0, topn.offset + topn.count, lsch)
        lg = Group(lsch)
        lg.insert(GroupExpr(inner, [lgroup]))
        new_join = copy.copy(join)
        jg = Group(group.schema)
        jg.insert(GroupExpr(new_join, [lg, rgroup]))
        top = _mk_topn(list(topn.by), topn.offset, topn.count,
                       group.schema)
        return memo.insert_equivalent(group, top, [jg])


DEFAULT_RULES = [
    MergeLimitSortToTopN(),
    MergeAdjacentSelection(),
    PushSelDownDataSource(),
    PushSelDownProjection(),
    PushSelDownSort(),
    PushSelDownJoin(),
    PushSelDownAggregation(),
    PushTopNDownProjection(),
    PushTopNDownOuterJoin(),
    PushLimitDownProjection(),
    EliminateProjection(),
    MergeAdjacentProjection(),
    MergeAggregationProjection(),
]
