"""Cascades transformation rules (reference:
planner/cascades/transformation_rules.go — the Transformation interface
with pattern + Match + OnTransform; the rule set mirrors the course's:
PushSelDownTableScan/Join/Projection/Aggregation, MergeAdjacentSelection,
PushTopNDownProjection, PushLimitDownProjection.

The reference's :497 stub (PushAggDownGather — partial aggregation through
the storage-gather boundary) and :800 stub (TopN onto index source) are
realized in this engine at the shared physical tail: planner/cop.py
push_to_cop splits aggregates into cop PARTIAL1 + root FINAL and pre-cuts
TopN per region, and planner/access.py compiles TopN-compatible index
ranges — both run on cascades output exactly as on the System-R path.
"""
from __future__ import annotations

import copy
from typing import List

from ...expression import Column, Expression
from ..logical import (JOIN_INNER, LogicalAggregation, LogicalDataSource,
                       LogicalJoin, LogicalLimit, LogicalPlan,
                       LogicalProjection, LogicalSelection, LogicalSort,
                       LogicalTopN)
from ..optimizer import substitute_column
from .memo import ANY, Group, GroupExpr, Memo, Pattern


def _mk_sel(conds, schema):
    s = LogicalSelection.__new__(LogicalSelection)
    LogicalPlan.__init__(s)
    s.conditions = conds
    s.schema = schema
    return s


def _mk_proj(exprs, schema):
    pr = LogicalProjection.__new__(LogicalProjection)
    LogicalPlan.__init__(pr)
    pr.exprs = exprs
    pr.schema = schema
    return pr


def _mk_topn(by, offset, count, schema):
    t = LogicalTopN.__new__(LogicalTopN)
    LogicalPlan.__init__(t)
    t.by = by
    t.offset = offset
    t.count = count
    t.schema = schema
    return t


def _mk_limit(offset, count, schema):
    t = LogicalLimit.__new__(LogicalLimit)
    LogicalPlan.__init__(t)
    t.offset = offset
    t.count = count
    t.schema = schema
    return t


class Transformation:
    pattern: Pattern = None

    def on_transform(self, memo: Memo, group: Group, binding) -> bool:
        """Insert equivalent expression(s) into `group`; returns True if
        the memo changed."""
        raise NotImplementedError


def _clone_ds(ds: LogicalDataSource) -> LogicalDataSource:
    c = LogicalDataSource(ds.db_name, ds.table_info, ds.alias,
                          list(ds.schema.columns))
    c.schema = ds.schema
    c.pushed_conds = list(ds.pushed_conds)
    c.all_conds = list(ds.all_conds)
    c.possible_indices = list(ds.possible_indices)
    if hasattr(ds, "storage"):
        c.storage = ds.storage
    return c


class PushSelDownDataSource(Transformation):
    """Selection(DataSource) => DataSource with merged pushed conds
    (reference: PushSelDownTableScan/TiKVSingleGather)."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalDataSource)])

    def on_transform(self, memo, group, binding):
        sel_ge, ds_ge = binding[0], binding[1][0]
        ds = _clone_ds(ds_ge.op)
        ds.pushed_conds.extend(sel_ge.op.conditions)
        ds.all_conds = list(ds.pushed_conds)
        return memo.insert_equivalent(group, ds, [])


class MergeAdjacentSelection(Transformation):
    """Selection(Selection(x)) => Selection(x) with merged CNF."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalSelection)])

    def on_transform(self, memo, group, binding):
        outer, inner = binding[0], binding[1][0]
        merged = _mk_sel(
            list(outer.op.conditions) + list(inner.op.conditions),
            group.schema)
        return memo.insert_equivalent(group, merged, list(inner.children))


class PushSelDownProjection(Transformation):
    """Selection(Projection(x)) => Projection(Selection(x)) for conditions
    expressible over the projection input."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalProjection)])

    def on_transform(self, memo, group, binding):
        sel_ge, proj_ge = binding[0], binding[1][0]
        proj = proj_ge.op
        pushable, retained = [], []
        for c in sel_ge.op.conditions:
            cols = c.collect_columns()
            if all(proj.schema.column_index(x) >= 0 for x in cols):
                pushable.append(substitute_column(c, proj.schema, proj.exprs))
            else:
                retained.append(c)
        if not pushable:
            return False
        child_group = proj_ge.children[0]
        new_sel = _mk_sel(pushable, child_group.schema)
        sel_group = Group(child_group.schema)
        sel_group.insert(GroupExpr(new_sel, [child_group]))
        new_proj = _mk_proj(list(proj.exprs), proj.schema)
        if retained:
            inner_proj_group = Group(proj.schema)
            inner_proj_group.insert(GroupExpr(new_proj, [sel_group]))
            top = _mk_sel(retained, group.schema)
            return memo.insert_equivalent(group, top, [inner_proj_group])
        return memo.insert_equivalent(group, new_proj, [sel_group])


class PushSelDownJoin(Transformation):
    """Selection(Join(l, r)) => Join' with side conditions pushed into new
    child selections (reference: PushSelDownJoin)."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalJoin)])

    def on_transform(self, memo, group, binding):
        from ..joinconds import classify_conjuncts
        sel_ge, join_ge = binding[0], binding[1][0]
        join: LogicalJoin = join_ge.op
        lgroup, rgroup = join_ge.children
        lsch, rsch = lgroup.schema, rgroup.schema
        new_eq, lp, rp, other, retained = classify_conjuncts(
            sel_ge.op.conditions, lsch, rsch, join.tp)
        new_join = copy.copy(join)
        new_join.eq_conditions = list(join.eq_conditions) + new_eq
        new_join.other_conditions = list(join.other_conditions) + other
        # inner join: the join's own one-side ON conditions push down WITH
        # the selection's.  Outer join: ON-clause outer-side conditions
        # must STAY on the join (they decide matching; a failing outer row
        # null-extends instead of being filtered) — only WHERE-side conds
        # (lp) push below the outer child.
        from ..logical import JOIN_INNER as _INNER
        if join.tp == _INNER:
            left_push = list(join.left_conditions) + lp
            new_join.left_conditions = []
        else:
            left_push = lp
            new_join.left_conditions = list(join.left_conditions)
        right_push = list(join.right_conditions) + rp
        new_join.right_conditions = []
        if not (left_push or right_push or new_eq):
            return False

        def wrap(child_group, conds):
            if not conds:
                return child_group
            s = _mk_sel(conds, child_group.schema)
            g = Group(child_group.schema)
            g.insert(GroupExpr(s, [child_group]))
            return g
        children = [wrap(lgroup, left_push), wrap(rgroup, right_push)]
        if retained:
            jg = Group(group.schema)
            jg.insert(GroupExpr(new_join, children))
            top = _mk_sel(retained, group.schema)
            return memo.insert_equivalent(group, top, [jg])
        return memo.insert_equivalent(group, new_join, children)


class PushSelDownAggregation(Transformation):
    """Selection(Agg(x)) => Agg(Selection(x)) for conditions over plain
    group-by columns (reference: PushSelDownAggregation)."""
    pattern = Pattern(LogicalSelection, [Pattern(LogicalAggregation)])

    def on_transform(self, memo, group, binding):
        sel_ge, agg_ge = binding[0], binding[1][0]
        agg: LogicalAggregation = agg_ge.op
        gb_uids = {c.unique_id for e in agg.group_by
                   for c in ([e] if isinstance(e, Column) else [])}
        push, retained = [], []
        for c in sel_ge.op.conditions:
            cols = c.collect_columns()
            if cols and all(x.unique_id in gb_uids for x in cols):
                push.append(c)
            else:
                retained.append(c)
        if not push:
            return False
        child_group = agg_ge.children[0]
        s = _mk_sel(push, child_group.schema)
        sg = Group(child_group.schema)
        sg.insert(GroupExpr(s, [child_group]))
        new_agg = copy.copy(agg)
        if retained:
            ag = Group(agg.schema)
            ag.insert(GroupExpr(new_agg, [sg]))
            top = _mk_sel(retained, group.schema)
            return memo.insert_equivalent(group, top, [ag])
        return memo.insert_equivalent(group, new_agg, [sg])


class PushTopNDownProjection(Transformation):
    """TopN(Projection(x)) => Projection(TopN(x)) when sort keys resolve
    below the projection (reference: PushTopNDownProjection)."""
    pattern = Pattern(LogicalTopN, [Pattern(LogicalProjection)])

    def on_transform(self, memo, group, binding):
        topn_ge, proj_ge = binding[0], binding[1][0]
        topn: LogicalTopN = topn_ge.op
        proj = proj_ge.op
        try:
            by = [(substitute_column(e, proj.schema, proj.exprs), d)
                  for e, d in topn.by]
        except Exception:
            return False
        child_group = proj_ge.children[0]
        inner = _mk_topn(by, topn.offset, topn.count, child_group.schema)
        tg = Group(child_group.schema)
        tg.insert(GroupExpr(inner, [child_group]))
        new_proj = _mk_proj(list(proj.exprs), proj.schema)
        return memo.insert_equivalent(group, new_proj, [tg])


class MergeLimitSortToTopN(Transformation):
    """Limit(Sort(x)) => TopN(x) (the System-R topn_pushdown analogue;
    makes per-region TopN pre-cut reachable from cascades plans)."""
    pattern = Pattern(LogicalLimit, [Pattern(LogicalSort)])

    def on_transform(self, memo, group, binding):
        lim_ge, sort_ge = binding[0], binding[1][0]
        lim: LogicalLimit = lim_ge.op
        topn = _mk_topn(list(sort_ge.op.by), lim.offset, lim.count,
                        group.schema)
        return memo.insert_equivalent(group, topn, list(sort_ge.children))


class PushLimitDownProjection(Transformation):
    """Limit(Projection(x)) => Projection(Limit(x))."""
    pattern = Pattern(LogicalLimit, [Pattern(LogicalProjection)])

    def on_transform(self, memo, group, binding):
        lim_ge, proj_ge = binding[0], binding[1][0]
        lim: LogicalLimit = lim_ge.op
        proj = proj_ge.op
        child_group = proj_ge.children[0]
        inner = _mk_limit(lim.offset, lim.count, child_group.schema)
        lg = Group(child_group.schema)
        lg.insert(GroupExpr(inner, [child_group]))
        new_proj = _mk_proj(list(proj.exprs), proj.schema)
        return memo.insert_equivalent(group, new_proj, [lg])


DEFAULT_RULES = [
    MergeLimitSortToTopN(),
    MergeAdjacentSelection(),
    PushSelDownDataSource(),
    PushSelDownProjection(),
    PushSelDownJoin(),
    PushSelDownAggregation(),
    PushTopNDownProjection(),
    PushLimitDownProjection(),
]
