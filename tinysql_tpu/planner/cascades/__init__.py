"""Cascades optimizer framework (reference: planner/cascades + memo +
implementation, SURVEY §2.3): memo-based exploration with pattern-matched
transformation rules, then cost-driven winner extraction sharing the
System-R physical tail.  Enabled per-session with
SET @@tidb_enable_cascades_planner = 1."""
from .optimize import find_best_plan

__all__ = ["find_best_plan"]
