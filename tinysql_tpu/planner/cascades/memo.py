"""Memo structure for the cascades search (reference: planner/memo —
group.go Group, group_expr.go GroupExpr, pattern.go Operand/Pattern,
expr_iter.go ExprIter).

A Group holds logically-equivalent expressions; a GroupExpr is one logical
operator whose children are Groups.  Fingerprints dedup expressions within
a group; the whole memo deduplicates subtrees by fingerprint so repeated
exploration converges.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..logical import (LogicalAggregation, LogicalDataSource, LogicalJoin,
                       LogicalLimit, LogicalPlan, LogicalProjection,
                       LogicalSelection, LogicalSort, LogicalTableDual,
                       LogicalTopN)

ANY = object()  # wildcard operand (reference: pattern.OperandAny)


class GroupExpr:
    __slots__ = ("op", "children", "explored")

    def __init__(self, op: LogicalPlan, children: List["Group"]):
        self.op = op          # logical node; its .children are NOT used
        self.children = children
        self.explored = False

    def fingerprint(self) -> str:
        return op_key(self.op) + "|" + ",".join(
            str(id(g)) for g in self.children)


class Group:
    __slots__ = ("exprs", "_fps", "schema", "explored", "best", "impl")

    def __init__(self, schema):
        self.exprs: List[GroupExpr] = []
        self._fps = set()
        self.schema = schema
        self.explored = False
        # implementation winner: (cost, est_rows, logical tree)
        self.best: Optional[Tuple[float, float, LogicalPlan]] = None
        # PHYSICAL winners per required order property:
        # {prop tuple: (cost, est_rows, PhysicalPlan)} (implementation.py)
        self.impl: Dict[tuple, tuple] = {}

    def insert(self, ge: GroupExpr) -> bool:
        fp = ge.fingerprint()
        if fp in self._fps:
            return False
        self._fps.add(fp)
        self.exprs.append(ge)
        self.explored = False
        return True


def op_key(p: LogicalPlan) -> str:
    """Operator identity WITHOUT children (parameters only)."""
    if isinstance(p, LogicalDataSource):
        conds = ",".join(sorted(c.key() for c in p.pushed_conds))
        return f"DS({p.table_info.id}|{p.alias}|{conds})"
    if isinstance(p, LogicalSelection):
        return "Sel(" + ",".join(sorted(c.key() for c in p.conditions)) + ")"
    if isinstance(p, LogicalProjection):
        return "Proj(" + ",".join(e.key() for e in p.exprs) + ")"
    if isinstance(p, LogicalAggregation):
        gb = ",".join(e.key() for e in p.group_by)
        ag = ",".join(f"{d.name}({','.join(a.key() for a in d.args)})"
                      for d in p.agg_funcs)
        return f"Agg({gb}|{ag})"
    if isinstance(p, LogicalJoin):
        eq = ",".join(f"{a.key()}={b.key()}" for a, b in p.eq_conditions)
        oth = ",".join(c.key() for c in p.other_conditions)
        lc = ",".join(c.key() for c in p.left_conditions)
        rc = ",".join(c.key() for c in p.right_conditions)
        return f"Join({p.tp}|{eq}|{oth}|{lc}|{rc})"
    if isinstance(p, LogicalSort):
        return "Sort(" + ",".join(
            f"{e.key()}{'-' if d else '+'}" for e, d in p.by) + ")"
    if isinstance(p, LogicalTopN):
        by = ",".join(f"{e.key()}{'-' if d else '+'}" for e, d in p.by)
        return f"TopN({by}|{p.offset},{p.count})"
    if isinstance(p, LogicalLimit):
        return f"Limit({p.offset},{p.count})"
    if isinstance(p, LogicalTableDual):
        return f"Dual({p.row_count})"
    return type(p).__name__


class Memo:
    def __init__(self):
        self._groups: Dict[str, Group] = {}  # subtree fingerprint -> group

    def build(self, p: LogicalPlan) -> Group:
        """Convert a logical tree into the memo (reference:
        memo.Convert2Group)."""
        child_groups = [self.build(c) for c in p.children]
        ge = GroupExpr(p, child_groups)
        fp = ge.fingerprint()
        g = self._groups.get(fp)
        if g is None:
            g = Group(p.schema)
            g.insert(ge)
            self._groups[fp] = g
        return g

    def insert_equivalent(self, group: Group, p: LogicalPlan,
                          children: List[Group]) -> bool:
        """Add an equivalent expression produced by a transformation rule."""
        return group.insert(GroupExpr(p, children))


# ---- pattern matching ------------------------------------------------------

class Pattern:
    """Two-level operand pattern (reference: pattern.Pattern).  `op_type`
    is a Logical* class or ANY; children match against the child groups'
    expressions."""

    def __init__(self, op_type, children: Optional[List["Pattern"]] = None):
        self.op_type = op_type
        self.children = children or []

    def match_expr(self, ge: GroupExpr):
        """Yield bindings: a tuple (ge, child_bindings...) where each child
        binding is a GroupExpr from the corresponding child group matching
        the child pattern (reference: ExprIter)."""
        if self.op_type is not ANY and not isinstance(ge.op, self.op_type):
            return
        if not self.children:
            yield (ge,)
            return
        if len(self.children) != len(ge.children):
            return

        def rec(i, acc):
            if i == len(self.children):
                yield tuple(acc)
                return
            for cge in ge.children[i].exprs:
                for sub in self.children[i].match_expr(cge):
                    yield from rec(i + 1, acc + [sub])
        for binding in rec(0, []):
            yield (ge,) + binding
