"""Cascades search driver (reference: planner/cascades/optimize.go —
exploration phase :131 to rule fixpoint, then implementation phase :245
picking cost winners per group).

Implementation winners are computed bottom-up over the memo with the same
cost shapes as the System-R task model (scan rows via the access-path
chooser, per-operator factors); the winning logical tree is then extracted
and converted through the shared physical tail (to_physical ->
place_devices -> push_to_cop), so device placement and coprocessor
pushdown behave identically across both optimizer frameworks.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

from ..logical import (LogicalAggregation, LogicalDataSource, LogicalJoin,
                       LogicalLimit, LogicalPlan, LogicalProjection,
                       LogicalSelection, LogicalSort, LogicalTableDual,
                       LogicalTopN)
from .memo import Group, GroupExpr, Memo
from .rules import DEFAULT_RULES

MAX_EXPLORE_ROUNDS = 16


def explore(memo: Memo, group: Group) -> None:
    """Apply transformation rules to fixpoint (reference: onPhaseExploration
    optimize.go:131-190)."""
    for _ in range(MAX_EXPLORE_ROUNDS):
        if group.explored:
            return
        group.explored = True
        for ge in list(group.exprs):
            for child in ge.children:
                explore(memo, child)
            if ge.explored:
                continue
            ge.explored = True
            for rule in DEFAULT_RULES:
                for binding in rule.pattern.match_expr(ge):
                    if rule.on_transform(memo, group, binding):
                        group.explored = False
        if group.explored:
            return


# ---- implementation phase: cost winners per group -------------------------

def _ds_cost(ds: LogicalDataSource) -> Tuple[float, float]:
    """(cost, est_rows) for the best access path of a data source."""
    from ..access import choose_path
    stats = None
    storage = getattr(ds, "storage", None)
    if storage is not None:
        from ...statistics.table_stats import load_stats
        stats = load_stats(storage, ds.table_info.id)
    path = choose_path(ds, stats)
    return max(path.cost, 1.0), max(path.est_rows, 1.0)


def implement(group: Group) -> Tuple[float, float, LogicalPlan]:
    """Pick the min-cost expression in the group; returns
    (cost, est_rows, extracted logical tree) — memoized on the group
    (reference: implGroup optimize.go:245-300)."""
    if group.best is not None:
        return group.best
    best = None
    for ge in group.exprs:
        child_results = [implement(c) for c in ge.children]
        cost, rows = _expr_cost(ge, child_results)
        if best is None or cost < best[0]:
            tree = _shallow_copy(ge.op)
            tree.children = [r[2] for r in child_results]
            best = (cost, rows, tree)
    assert best is not None, "empty group"
    group.best = best
    return best


def _shallow_copy(op: LogicalPlan) -> LogicalPlan:
    import copy
    c = copy.copy(op)
    c.children = []
    return c


def _expr_cost(ge: GroupExpr, childs) -> Tuple[float, float]:
    op = ge.op
    ccost = sum(c[0] for c in childs)
    crows = childs[0][1] if childs else 1.0
    if isinstance(op, LogicalDataSource):
        return _ds_cost(op)
    if isinstance(op, LogicalSelection):
        return ccost + crows * 0.2, max(crows * 0.5, 1.0)
    if isinstance(op, LogicalProjection):
        return ccost + crows * 0.1, crows
    if isinstance(op, LogicalAggregation):
        out = max(math.sqrt(crows), 1.0) if op.group_by else 1.0
        return ccost + crows, out
    if isinstance(op, LogicalJoin):
        lrows, rrows = childs[0][1], childs[1][1]
        out = max(lrows, rrows) if op.eq_conditions else lrows * rrows
        return ccost + lrows + 2.0 * rrows + out * 0.1, max(out, 1.0)
    if isinstance(op, LogicalSort):
        return ccost + crows * max(math.log2(max(crows, 2.0)), 1.0), crows
    if isinstance(op, LogicalTopN):
        n = float(op.offset + op.count)
        return ccost + crows, min(crows, n)
    if isinstance(op, LogicalLimit):
        return ccost, min(crows, float(op.offset + op.count))
    if isinstance(op, LogicalTableDual):
        return 1.0, float(op.row_count)
    return ccost + crows, crows


def find_best_plan(logical: LogicalPlan, tpu: bool = True,
                   tpu_min_rows: float = 0.0, mesh_shards: int = 0):
    """Full cascades pipeline: pre-normalization -> memo -> explore ->
    implement -> shared physical tail (reference: Optimize/FindBestPlan
    optimize.go:105; the pre-passes mirror the System-R rewrites whose
    effects the transformation rule set does not replicate)."""
    from ..optimizer import normalize_logical, to_physical
    from ..derive_stats import derive_stats
    from ..device import place_devices
    from ..cop import push_to_cop
    logical = normalize_logical(logical, push_predicates=False)
    memo = Memo()
    root = memo.build(logical)
    explore(memo, root)
    try:
        # cascades' OWN implementation phase: physical candidates +
        # enforcers with per-group cost winners (implementation.py) — the
        # framework can pick different physical operators than System-R
        from .implementation import NoImplementationRule, implement_group
        phys = implement_group(root, ())[2]
    except NoImplementationRule:
        # operator shapes outside the implementation rules (mem-tables,
        # exotic ops): logical winner + the shared physical tail.
        # Genuine bugs in the implementation phase propagate — a silent
        # System-R downgrade would mask them.
        _, _, tree = implement(root)
        phys = to_physical(tree)
    phys = derive_stats(phys)
    phys = place_devices(phys, enabled=tpu, min_rows=tpu_min_rows,
                         mesh_shards=mesh_shards)
    return push_to_cop(phys)
