"""Cascades implementation phase: PHYSICAL enumeration with per-(group,
required-order-property) cost winners and order enforcers.

Reference: planner/cascades/implementation_rules.go:1-431 (one
ImplementationRule per logical operand producing physical candidates),
enforcer_rules.go (OrderEnforcer adds a Sort when a group cannot provide
the required property natively), optimize.go:245 implGroup (memoized
per-group winners under a required property).

This phase makes cascades' physical choices INDEPENDENT of the System-R
tail: a join group carries both a hash and a merge candidate (the merge
one requiring key order from its children, possibly via enforcers), a
Sort group can be absorbed by an order-providing child, and the winner is
the min-cost candidate — so cascades can legitimately pick a different
physical plan than System-R's rule-based tail (e.g. hash join where the
merge gate would fire but keep-order scans cost more than the hash
build).  Physical nodes are built through the SAME construction helpers
as the System-R tail (optimizer.phys_*), so operator semantics can never
drift between frameworks.
"""
from __future__ import annotations

import math
from typing import Iterator, Optional, Tuple

from ...expression import Column
from ..logical import (LogicalAggregation, LogicalDataSource, LogicalJoin,
                       LogicalLimit, LogicalPlan, LogicalProjection,
                       LogicalSelection, LogicalSort, LogicalTableDual,
                       LogicalTopN)
from ..physical import (PhysicalHashJoin, PhysicalIndexLookUpReader,
                        PhysicalIndexReader, PhysicalLimit,
                        PhysicalMergeJoin, PhysicalPlan, PhysicalSort,
                        PhysicalTableDual, PhysicalTableReader, PhysicalTopN)
from ..props import mark_keep_order, provided_order, required_of, satisfies
from .memo import Group, GroupExpr

# ---- cost factors (the task.go GetCost shapes, flattened) -----------------
# Scans: a keep-order scan walks regions sequentially (the scatter-gather
# concurrency of an unordered scan is lost — reference: copTask keepOrder
# costing), so providing order from storage is priced above a plain scan.
SCAN = 1.0
KEEP_ORDER_SCAN = 1.4
INDEX_SCAN = 1.1
LOOKUP = 3.0            # IndexLookUp double-read per row
HASH_BUILD = 2.0        # build-side per-row cost (hash table construction)
SORT_UNIT = 1.0         # per row x log2(rows) for an enforced sort
SEL_F = 0.2
PROJ_F = 0.1
JOIN_OUT_F = 0.1

Impl = Tuple[float, float, PhysicalPlan]  # (cost, est_rows, plan)


class NoImplementationRule(NotImplementedError):
    """Raised when a memo group's operator has no implementation rule —
    the ONLY signal on which find_best_plan may fall back to the shared
    System-R tail (a bare NotImplementedError from deeper code must
    propagate, not silently downgrade the framework)."""


def implement_group(group: Group, prop: tuple = ()) -> Impl:
    """Min-cost physical implementation of `group` whose output satisfies
    the required order `prop` ([(unique_id, desc)] tuple) — natively or
    through a Sort enforcer; memoized per (group, prop)."""
    key = tuple(prop)
    hit = group.impl.get(key)
    if hit is not None:
        return hit
    best: Optional[Impl] = None
    for ge in group.exprs:
        for cand in _implementations(ge, key):
            if best is None or cand[0] < best[0]:
                best = cand
    if key:
        # enforcer alternative (enforcer_rules.go): implement unordered,
        # sort on top — also the fallback when nothing provides the
        # order natively
        base = implement_group(group, ())
        enforced = _enforce_order(base, key, group)
        if enforced is not None and (best is None
                                     or enforced[0] < best[0]):
            best = enforced
    if best is None:
        # operator shape outside the implementation rules: the caller
        # (find_best_plan) falls back to the logical winner + shared tail
        raise NoImplementationRule(
            f"no implementation rule for {type(group.exprs[0].op).__name__}"
            if group.exprs else "empty group")
    group.impl[key] = best
    return best


def _enforce_order(base: Impl, prop: tuple, group: Group) -> Optional[Impl]:
    cost, rows, plan = base
    by = []
    for uid, desc in prop:
        idx = next((i for i, c in enumerate(plan.schema.columns)
                    if c.unique_id == uid), None)
        if idx is None:
            return None
        by.append((plan.schema.columns[idx].clone_with_index(idx),
                   bool(desc)))
    sort_cost = SORT_UNIT * rows * max(math.log2(max(rows, 2.0)), 1.0)
    return (cost + sort_cost, rows, PhysicalSort(by, plan))


def _reader_cost(plan: PhysicalPlan, rows: float, ordered: bool) -> float:
    if isinstance(plan, PhysicalIndexLookUpReader):
        return rows * LOOKUP
    if isinstance(plan, PhysicalIndexReader):
        return rows * INDEX_SCAN
    return rows * (KEEP_ORDER_SCAN if ordered else SCAN)


def _implementations(ge: GroupExpr, prop: tuple) -> Iterator[Impl]:
    """Physical candidates of one group expression whose output satisfies
    `prop` NATIVELY (the enforcer alternative is handled by the
    caller)."""
    from ..optimizer import (phys_aggregation, phys_datasource, phys_join,
                             phys_projection, phys_selection)
    op = ge.op
    want = list(prop)

    if isinstance(op, LogicalDataSource):
        plan = phys_datasource(op, order_hint=want or None)
        rows = max(getattr(plan, "stats_row_count", 1.0), 1.0)
        provided = provided_order(plan)
        if not prop:
            yield (_reader_cost(plan, rows, False), rows, plan)
        elif satisfies(provided, want):
            mark_keep_order(plan)
            yield (_reader_cost(plan, rows, True), rows, plan)
        return

    if isinstance(op, LogicalSelection):
        # row filters pass order through: push the requirement down
        ccost, crows, child = implement_group(ge.children[0], prop)
        rows = max(crows * 0.5, 1.0)
        yield (ccost + crows * SEL_F, rows, phys_selection(op, child))
        return

    if isinstance(op, LogicalProjection):
        ident = {e.unique_id for e in op.exprs if isinstance(e, Column)}
        if prop and not all(uid in ident for uid, _ in prop):
            return  # computed outputs: order cannot pass through
        ccost, crows, child = implement_group(ge.children[0], prop)
        yield (ccost + crows * PROJ_F, crows, phys_projection(op, child))
        return

    if isinstance(op, LogicalAggregation):
        if prop:
            return  # hash agg provides no order; enforcer covers it
        ccost, crows, child = implement_group(ge.children[0], ())
        out = max(math.sqrt(crows), 1.0) if op.group_by else 1.0
        yield (ccost + crows, out, phys_aggregation(op, child))
        return

    if isinstance(op, LogicalJoin):
        # hash join: unordered children, no provided order
        if not prop:
            lc, lr, lplan = implement_group(ge.children[0], ())
            rc, rr, rplan = implement_group(ge.children[1], ())
            out = max(lr, rr) if op.eq_conditions else lr * rr
            cost = lc + rc + lr + HASH_BUILD * rr + out * JOIN_OUT_F
            yield (cost, max(out, 1.0),
                   phys_join(op, lplan, rplan, PhysicalHashJoin))
        # merge join: key-ordered children (native or enforced inside),
        # emits left-key ascending order
        mk = _merge_keys(op)
        if mk is not None:
            (la, ra) = mk
            if satisfies([(la, False)], want) or not prop:
                lc, lr, lplan = implement_group(ge.children[0],
                                                ((la, False),))
                rc, rr, rplan = implement_group(ge.children[1],
                                                ((ra, False),))
                out = max(lr, rr)
                cost = lc + rc + lr + rr + out * JOIN_OUT_F
                yield (cost, max(out, 1.0),
                       phys_join(op, lplan, rplan, PhysicalMergeJoin))
        return

    if isinstance(op, LogicalSort):
        req = required_of(op.by)
        if req is not None and satisfies(req, want):
            # absorb the sort into an order-providing child (or an
            # enforcer inside it — cost decides); output IS the order
            yield implement_group(ge.children[0], tuple(req))
        elif not prop:
            ccost, crows, child = implement_group(ge.children[0], ())
            by = [(e.resolve_indices(child.schema), d) for e, d in op.by]
            sc = SORT_UNIT * crows * max(math.log2(max(crows, 2.0)), 1.0)
            yield (ccost + sc, crows, PhysicalSort(by, child))
        return

    if isinstance(op, LogicalTopN):
        n = float(op.offset + op.count)
        req = required_of(op.by)
        if req is not None and (satisfies(req, want) or not prop):
            # ordered child: TopN degenerates to Limit (cascades :800
            # TopN->index shape, via the property framework)
            ccost, crows, child = implement_group(ge.children[0],
                                                  tuple(req))
            yield (ccost + min(crows, n), min(crows, n),
                   PhysicalLimit(op.offset, op.count, child))
        if not prop:
            ccost, crows, child = implement_group(ge.children[0], ())
            by = [(e.resolve_indices(child.schema), d) for e, d in op.by]
            yield (ccost + crows, min(crows, n),
                   PhysicalTopN(by, op.offset, op.count, child))
        return

    if isinstance(op, LogicalLimit):
        # ONLY the empty property (reference ImplLimit): pushing a
        # required order BELOW a limit would change which rows survive
        # it — an ORDER BY above a LIMIT must sort the limit's output
        # (the enforcer), never reorder its input
        if prop:
            return
        ccost, crows, child = implement_group(ge.children[0], ())
        n = float(op.offset + op.count)
        yield (ccost, min(crows, n),
               PhysicalLimit(op.offset, op.count, child))
        return

    if isinstance(op, LogicalTableDual):
        if not prop:
            yield (1.0, float(op.row_count),
                   PhysicalTableDual(op.schema, op.row_count))
        return


def _merge_keys(op: LogicalJoin):
    """(left_uid, right_uid) when a merge join is admissible: single
    plain-column equi key, inner/left join (MergeJoinExec's surface)."""
    if op.tp not in ("inner", "left") or len(op.eq_conditions) != 1:
        return None
    a, b = op.eq_conditions[0]
    if not (isinstance(a, Column) and isinstance(b, Column)):
        return None
    return a.unique_id, b.unique_id
