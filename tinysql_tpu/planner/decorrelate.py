"""Subquery decorrelation: expression subqueries -> join operators.

Capability parity with reference planner/core/expression_rewriter.go's
subquery handling (PatternInExpr / ExistsSubqueryExpr -> LogicalJoin semi
variants) plus the decorrelation slice of rule_decorrelate.go, reduced to
the shapes this grammar produces:

- ``expr IN (SELECT c FROM ...)`` as a top-level WHERE conjunct becomes a
  SEMI join on ``expr = c``; ``NOT IN`` becomes a NULL-AWARE ANTI join
  (three-valued logic: any NULL build key kills every probe row, a NULL
  probe key passes only an empty build side).
- ``[NOT] EXISTS (SELECT ...)`` becomes a SEMI/ANTI join.  Correlated
  equality conjuncts in the subquery's WHERE (``inner.x = outer.y``) are
  pulled up as the join's equi-keys; other correlated conjuncts become
  join ``other_conditions``; fully-local conjuncts stay inside the
  subquery.  An uncorrelated EXISTS degenerates to a cartesian semi join
  (the executor only checks build-side emptiness).
- A scalar subquery anywhere in an expression is evaluated EAGERLY at
  plan time and folded to a Constant — the reference evaluates
  uncorrelated scalar subqueries during optimization the same way, and
  the PR 6 literal parameterization erases the folded constant from
  program cache keys, so a changed subquery result is still a compiled
  program HIT.

The pass runs INSIDE PlanBuilder.build_select, before the residual WHERE
becomes a LogicalSelection, so everything downstream (pushdown, pruning,
reorder, the device enforcer) sees plain logical join nodes.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..expression import Column, Expression, fold_constants, split_cnf
from ..parser import ast
from .logical import (JOIN_ANTI, JOIN_SEMI, LogicalJoin, LogicalPlan,
                      LogicalSelection, LogicalTableDual)


def split_and_conjuncts(e: ast.ExprNode) -> List[ast.ExprNode]:
    """Top-level AND split of a WHERE tree (parens transparent)."""
    if isinstance(e, ast.ParenExpr):
        return split_and_conjuncts(e.expr)
    if isinstance(e, ast.BinaryOp) and e.op == "and":
        return split_and_conjuncts(e.left) + split_and_conjuncts(e.right)
    return [e]


def _unwrap_not(e: ast.ExprNode) -> Tuple[ast.ExprNode, bool]:
    """Strip ParenExpr and count NOT wrappers -> (inner, negated)."""
    neg = False
    while True:
        if isinstance(e, ast.ParenExpr):
            e = e.expr
        elif isinstance(e, ast.UnaryOp) and e.op == "not":
            e = e.operand
            neg = not neg
        else:
            return e, neg


def _subquery_conjunct(e: ast.ExprNode):
    """(kind, node, negated) when `e` is a decorrelatable conjunct —
    kind 'in' (InExpr over a SubqueryExpr) or 'exists' — else None."""
    inner, neg = _unwrap_not(e)
    if isinstance(inner, ast.InExpr) and len(inner.items) == 1 \
            and isinstance(inner.items[0], ast.SubqueryExpr):
        return "in", inner, neg ^ inner.negated
    if isinstance(inner, ast.ExistsExpr):
        return "exists", inner, neg ^ inner.negated
    return None


def apply_where_subqueries(builder, p: LogicalPlan,
                           where: ast.ExprNode
                           ) -> Tuple[LogicalPlan, List[ast.ExprNode]]:
    """Rewrite every subquery-bearing top-level conjunct of `where` into
    a semi/anti join over `p`; returns (new plan, residual AST
    conjuncts).  Scalar subqueries inside residual conjuncts are handled
    later by the expression rewriter (eager evaluation)."""
    residual: List[ast.ExprNode] = []
    for conj in split_and_conjuncts(where):
        got = _subquery_conjunct(conj)
        if got is None:
            residual.append(conj)
            continue
        kind, node, negated = got
        if kind == "in":
            p = build_in_join(builder, p, node, negated)
        else:
            p = build_exists_join(builder, p, node, negated)
    return p, residual


def build_in_join(builder, p: LogicalPlan, ie: ast.InExpr,
                  negated: bool) -> LogicalJoin:
    """``expr [NOT] IN (SELECT c ...)`` -> semi / null-aware anti join.
    The subquery builds as a normal SELECT (aggregation, HAVING, its own
    subqueries all compose); it must produce exactly one column."""
    from .builder import ExprRewriter, PlanError
    sub = builder.build_select(ie.items[0].select)
    if len(sub.schema.columns) != 1:
        raise PlanError("Operand should contain 1 column(s)")
    rw = ExprRewriter(p.schema, builder)
    outer = fold_constants(rw.rewrite(ie.expr))
    join = LogicalJoin(JOIN_ANTI if negated else JOIN_SEMI, p, sub)
    join.eq_conditions.append((outer, sub.schema.columns[0]))
    # NOT IN is null-aware; NOT EXISTS (below) is not — a NULL correlated
    # key simply never matches there
    join.null_aware = negated
    return join


def build_exists_join(builder, p: LogicalPlan, ex: ast.ExistsExpr,
                      negated: bool) -> LogicalJoin:
    """``[NOT] EXISTS (SELECT ...)`` -> semi / anti join, decorrelating
    equality conjuncts that reference the outer scope."""
    from .builder import ExprRewriter, PlanError
    stmt = ex.select
    tp = JOIN_ANTI if negated else JOIN_SEMI
    if stmt.limit is not None and stmt.limit[1] == 0:
        # LIMIT 0: the subquery is empty by construction
        return LogicalJoin(tp, p, LogicalTableDual(row_count=0))
    if stmt.group_by or stmt.having or stmt.distinct or _has_aggs(stmt):
        # aggregate-shaped EXISTS: build the full subquery plan and use
        # it as an (uncorrelated) cartesian build side.  A correlated
        # column inside would fail name resolution — loudly.
        sub = builder.build_select(stmt)
        return LogicalJoin(tp, p, sub)
    if stmt.from_ is None:
        # EXISTS (SELECT <exprs>): one constant row, always non-empty
        return LogicalJoin(tp, p, LogicalTableDual(row_count=1))
    sub_p = builder.build_table_refs(stmt.from_)
    corr: List[Expression] = []
    if stmt.where is not None:
        rw = ExprRewriter(sub_p.schema, builder, outer_schema=p.schema)
        local: List[Expression] = []
        for c in split_cnf(rw.rewrite(stmt.where)):
            cols = c.collect_columns()
            if all(sub_p.schema.contains(x) for x in cols):
                local.append(fold_constants(c))
            else:
                corr.append(c)
        if local:
            sub_p = LogicalSelection(local, sub_p)
    join = LogicalJoin(tp, p, sub_p)
    for c in corr:
        pair = _eq_pair(c, p.schema, sub_p.schema)
        if pair is not None:
            join.eq_conditions.append(pair)
        else:
            join.other_conditions.append(c)
    return join


def _has_aggs(stmt: ast.SelectStmt) -> bool:
    for f in stmt.fields:
        if f.expr is not None and ast.has_agg(f.expr):
            return True
    return False


def _eq_pair(c: Expression, outer_schema,
             inner_schema) -> Optional[Tuple[Expression, Expression]]:
    """``inner_expr = outer_expr`` (either order) -> (outer, inner) pair
    for the semi join's equi-keys; None when the conjunct is not such an
    equality (it stays an other_condition)."""
    if getattr(c, "name", "") != "=" or len(c.children()) != 2:
        return None
    a, b = c.children()
    ac, bc = a.collect_columns(), b.collect_columns()
    if not ac or not bc:
        return None
    a_outer = all(outer_schema.contains(x) for x in ac)
    b_outer = all(outer_schema.contains(x) for x in bc)
    a_inner = all(inner_schema.contains(x) for x in ac)
    b_inner = all(inner_schema.contains(x) for x in bc)
    if a_outer and b_inner:
        return a, b
    if b_outer and a_inner:
        return b, a
    return None
