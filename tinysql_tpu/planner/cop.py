"""Coprocessor pushdown pass (reference: planner/core/task.go — the
copTask/rootTask boundary.  finishCopTask :273 decides what crosses from
storage-side execution to root; here the pass runs bottom-up over the
built physical tree, after the device enforcer).

- HashAgg over a TableReader (not TPU-placed): split into PARTIAL1 in the
  coprocessor + FINAL at root (reference: attach2Task for aggregation;
  the aggregation/descriptor.go Split schema).
- TopN / Limit over a TableReader: copy into the cop request as a
  per-region pre-cut; the root operator still merges (task.go:392-452).
"""
from __future__ import annotations

from typing import List

from ..distsql.exprpb import _ft_to_pb, can_push, expr_to_pb
from ..expression import Column, Schema
from ..expression.aggregation import (AGG_AVG, AGG_COUNT, AGG_FIRST_ROW,
                                      AGG_MAX, AGG_MIN, AGG_SUM, AggMode)
from .physical import (PhysicalHashAgg, PhysicalLimit, PhysicalPlan,
                       PhysicalProjection, PhysicalTableReader, PhysicalTopN)

_PUSHABLE_AGGS = {AGG_COUNT, AGG_SUM, AGG_AVG, AGG_MAX, AGG_MIN,
                  AGG_FIRST_ROW}


def push_to_cop(p: PhysicalPlan) -> PhysicalPlan:
    p.children = [push_to_cop(c) for c in p.children]
    if isinstance(p, PhysicalHashAgg) and not getattr(p, "use_tpu", False):
        child = p.children[0] if p.children else None
        if (isinstance(child, PhysicalTableReader)
                and child.scan.pushed_agg is None):
            _try_push_agg(p, child)
    elif isinstance(p, PhysicalTopN):
        child = p.children[0] if p.children else None
        if (isinstance(child, PhysicalTableReader)
                and child.scan.pushed_agg is None
                and child.scan.pushed_topn is None
                and all(can_push(e) for e in child.scan.filters)
                and all(can_push(e) for e, _ in p.by)):
            child.scan.pushed_topn = {
                "by": [(expr_to_pb(e), d) for e, d in p.by],
                "n": p.offset + p.count,
            }
    elif isinstance(p, PhysicalLimit):
        # limit is expression-free: it pre-cuts through any row-preserving
        # 1:1 operator chain (projections) down to the reader
        child = p.children[0] if p.children else None
        while isinstance(child, PhysicalProjection):
            child = child.children[0]
        if (isinstance(child, PhysicalTableReader)
                and child.scan.pushed_agg is None
                and child.scan.pushed_topn is None
                and all(can_push(e) for e in child.scan.filters)):
            child.scan.pushed_limit = p.offset + p.count
    return p


def _try_push_agg(agg: PhysicalHashAgg, reader: PhysicalTableReader) -> bool:
    if not all(can_push(e) for e in reader.scan.filters):
        return False  # unfiltered partials would aggregate wrong rows
    if not all(can_push(e) for e in agg.group_by):
        return False
    for d in agg.aggs:
        if d.name not in _PUSHABLE_AGGS or d.distinct:
            return False
        if not all(can_push(a) for a in d.args):
            return False

    # partial output layout: [gb cols..., per-desc partial slots...]
    n_gb = len(agg.group_by)
    partial_pbs: List[dict] = []
    final_descs = []
    out_cols: List[Column] = [
        Column(e.ret_type, index=i) for i, e in enumerate(agg.group_by)]
    base = n_gb
    for d in agg.aggs:
        pr_types = d.partial_result_types()
        ordinals = list(range(base, base + len(pr_types)))
        partials, final = d.split(ordinals)
        for pd in partials:
            partial_pbs.append({
                "name": pd.name,
                "args": [expr_to_pb(a) for a in pd.args],
                "distinct": pd.distinct,
                "ret": _ft_to_pb(pd.ret_type),
            })
        for ft, o in zip(pr_types, ordinals):
            out_cols.append(Column(ft, index=o))
        final_descs.append(final)
        base += len(pr_types)

    reader.scan.pushed_agg = {
        "group_by": [expr_to_pb(e) for e in agg.group_by],
        "aggs": partial_pbs,
    }
    # the reader now emits partial rows
    reader.schema = Schema(list(out_cols))
    reader.stats_row_count = max(agg.stats_row_count, 1.0)

    # rewire the root agg to FINAL mode over the partial rows
    agg.group_by = [Column(e.ret_type, index=i)
                    for i, e in enumerate(agg.group_by)]
    agg.aggs = final_descs
    return True
