"""Order properties for the physical search (reference:
planner/property/physical_property.go:31 — required sort order threaded
through findBestTask — and cascades/enforcer_rules.go: a Sort enforcer is
added only when the child cannot PROVIDE the required order).

Reduced shape: a property is a list of (column unique_id, desc) pairs.
Readers provide ascending clustered-pk / index-column order (the scan
layer iterates the ordered keyspace; region scatter-gather preserves
range order); Sort/TopN provide their by-order; row-filtering operators
pass their child's order through.  `satisfies` = required is a prefix of
provided.  Consumers: Sort elimination + TopN->Limit in to_physical,
the merge-join child gate, and the order-aware access-path choice.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..expression import Column, Expression
from .physical import (PhysicalIndexReader, PhysicalLimit, PhysicalMergeJoin,
                       PhysicalPlan, PhysicalProjection, PhysicalSelection,
                       PhysicalSort, PhysicalTableReader, PhysicalTopN)

OrderProp = List[Tuple[int, bool]]  # (column unique_id, desc)


def required_of(by: List[Tuple[Expression, bool]]) -> Optional[OrderProp]:
    """Sort items -> property; None when any key is a non-Column
    expression (computed keys are never provided by storage order)."""
    out: OrderProp = []
    for e, desc in by:
        if not isinstance(e, Column):
            return None
        out.append((e.unique_id, bool(desc)))
    return out


def provided_order(p: PhysicalPlan) -> OrderProp:
    """The order `p` emits (empty = none guaranteed)."""
    if isinstance(p, PhysicalTableReader):
        uid = getattr(p.scan, "order_col_uid", None)
        return [(uid, False)] if uid is not None else []
    if isinstance(p, PhysicalIndexReader):
        uids = getattr(p.scan, "order_col_uids", None) or []
        return [(u, False) for u in uids]
    if isinstance(p, (PhysicalSort, PhysicalTopN)):
        req = required_of(p.by)
        return req or []
    if isinstance(p, PhysicalMergeJoin):
        # emits left-side key order ascending (sorted-stream merge)
        lk = p.left_keys
        if len(lk) == 1 and isinstance(lk[0], Column):
            return [(lk[0].unique_id, False)]
        return []
    if isinstance(p, (PhysicalSelection, PhysicalLimit)):
        return provided_order(p.children[0])
    if isinstance(p, PhysicalProjection):
        child = provided_order(p.children[0])
        # identity output columns keep their source order
        ident = {e.unique_id for e in p.exprs if isinstance(e, Column)}
        out = []
        for uid, desc in child:
            if uid not in ident:
                break  # order beyond a dropped column is meaningless
            out.append((uid, desc))
        return out
    return []


def mark_keep_order(p: PhysicalPlan) -> None:
    """Record that a consumer RELIES on this subtree's emitted order
    (EXPLAIN shows keep order:true on the reader, reference explain
    format); walks through row-order-preserving operators."""
    while isinstance(p, (PhysicalSelection, PhysicalProjection,
                         PhysicalLimit)):
        p = p.children[0]
    scan = getattr(p, "scan", None)
    if scan is not None:
        scan.keep_order = True


def satisfies(provided: OrderProp, required: Optional[OrderProp]) -> bool:
    if required is None:
        return False
    if not required:
        return True
    return provided[:len(required)] == required
