"""Device enforcer: decide which physical operators run on TPU.

The north-star planner capability (BASELINE.json): a device dimension on
physical plans — the analogue of the reference's copTask/rootTask split
(planner/core/task.go:42,364) where the question is "where does this subtree
execute".  TPU operators are admitted when their hot loop is expressible as
device kernels:

- HashAgg: agg args numeric (device segment-reduce); group keys numeric OR
  plain string Columns (order-preserving dictionary codes built host-side).
- HashJoin: numeric equi-keys — one pair (sort+searchsorted kernel) or
  several plain signed-int columns (devpipe composite lanes).
- Sort/TopN: keys numeric or plain string Columns (dictionary codes).
- Projection/Selection: every expression lowers through ops/exprjit.

Everything else falls back to the CPU tier (numpy-vectorized volcano
executors) — mirroring the reference's own Vectorized()==false fallback
(projection.go:92-93).
"""
from __future__ import annotations

from typing import Optional

from ..expression import Column, Expression
from ..expression.aggregation import (AGG_AVG, AGG_COUNT, AGG_FIRST_ROW,
                                      AGG_MAX, AGG_MIN, AGG_SUM)
from ..mytypes import EvalType
from ..ops.exprjit import is_jittable
from .physical import (PhysicalHashAgg, PhysicalHashJoin,
                       PhysicalMergeJoin, PhysicalPlan, PhysicalProjection,
                       PhysicalSelection, PhysicalSort, PhysicalTopN)

_TPU_AGGS = {AGG_COUNT, AGG_SUM, AGG_AVG, AGG_MAX, AGG_MIN, AGG_FIRST_ROW}


def _key_ok(e: Expression) -> bool:
    """Group/sort key: device-jittable numeric, or a bare string column
    (dictionary-encoded host-side with order-preserving codes)."""
    if is_jittable(e):
        return True
    return isinstance(e, Column) and e.eval_type is EvalType.STRING


def _agg_ok(d) -> bool:
    if d.name not in _TPU_AGGS or d.distinct:
        return False
    if d.name == AGG_FIRST_ROW:
        return isinstance(d.args[0], Column)  # gathered host-side by row id
    if d.name == AGG_COUNT:
        from ..expression import Constant
        a = d.args[0]
        return isinstance(a, (Column, Constant)) or is_jittable(a)
    return all(is_jittable(a) for a in d.args)


def _input_rows(p: PhysicalPlan) -> float:
    """Estimated input size of the operator's hot loop (derive_stats ran
    before placement, so children carry estimates)."""
    if not p.children:
        return 0.0
    return max(c.stats_row_count for c in p.children)


def tpu_admissibility(p: PhysicalPlan) -> Optional[str]:
    """CAPABILITY check alone: None when `p`'s hot loop is expressible as
    device kernels, else the reason it is not.  The ONE definition shared
    by the enforcer (place_devices) and the plan-device invariant checker
    (analysis/plan_device.py) — placement and verification can never
    drift apart.  Cost gating (min_rows) is deliberately not part of
    admissibility: cost only shrinks the TPU set, never makes an
    inadmissible operator legal."""
    if isinstance(p, PhysicalMergeJoin):
        return "MergeJoin is a sorted-stream operator: CPU tier only"
    if isinstance(p, PhysicalHashAgg):
        for e in p.group_by:
            if not _key_ok(e):
                return (f"group key {e.key()!r} is neither device-jittable"
                        " nor a plain string column")
        for d in p.aggs:
            if not _agg_ok(d):
                return (f"aggregate {d.name}({', '.join(a.key() for a in d.args)})"
                        f"{' distinct' if d.distinct else ''} has no"
                        " device kernel")
        return None
    if isinstance(p, PhysicalHashJoin):
        def _uns(e):
            return (e.eval_type is EvalType.INT
                    and getattr(e.ret_type, "is_unsigned", False))
        if p.tp in ("semi", "anti"):
            # device membership test (sort + searchsorted): numeric keys
            # (multi-key rides the composite factorization lane), no
            # per-pair residual evaluation
            if p.other_conditions:
                return ("semi/anti residual conditions need per-pair"
                        " evaluation: CPU tier only")
            if not p.left_keys:
                return "cartesian semi/anti join has no device kernel"
            if len(p.left_keys) == 1:
                lk, rk = p.left_keys[0], p.right_keys[0]
                if not (is_jittable(lk) and is_jittable(rk)):
                    return "join keys not device-jittable"
                if _uns(lk) != _uns(rk):
                    return ("mixed-signedness int keys need per-pair"
                            " compare semantics the membership kernel"
                            " lacks")
                return None
            for k in list(p.left_keys) + list(p.right_keys):
                if not (isinstance(k, Column)
                        and k.eval_type is EvalType.INT
                        and not _uns(k)):
                    return ("multi-key semi/anti join needs plain"
                            " signed-int columns (composite lane)")
            return None
        if p.tp not in ("inner", "left"):
            return f"{p.tp} join has no device kernel"
        if not p.left_keys:
            return "cartesian join has no device kernel"
        if len(p.left_keys) == 1:
            lk, rk = p.left_keys[0], p.right_keys[0]
            if not (is_jittable(lk) and is_jittable(rk)):
                return "join keys not device-jittable"
            if _uns(lk) != _uns(rk):
                return ("mixed-signedness int keys need per-pair compare"
                        " semantics the sort+searchsorted kernel lacks")
            return None
        for k in list(p.left_keys) + list(p.right_keys):
            if not (isinstance(k, Column)
                    and k.eval_type is EvalType.INT
                    and not _uns(k)):
                return ("multi-key join needs plain signed-int columns"
                        " (devpipe composite lanes)")
        return None
    if isinstance(p, (PhysicalSort, PhysicalTopN)):
        for e, _ in p.by:
            if not _key_ok(e):
                return (f"sort key {e.key()!r} is neither device-jittable"
                        " nor a plain string column")
        return None
    if isinstance(p, PhysicalProjection):
        for e in p.exprs:
            if not is_jittable(e):
                return f"projection expr {e.key()!r} not device-jittable"
        return None
    if isinstance(p, PhysicalSelection):
        for c in p.conditions:
            if not is_jittable(c):
                return f"filter condition {c.key()!r} not device-jittable"
        return None
    return f"{p.op_name()} has no device lowering"


def mesh_admissible(p: PhysicalPlan) -> Optional[str]:
    """CAPABILITY gate for the sharded operator tier (ops/shardops.py +
    kernels.fused_segment_aggregate_sharded): None when a TPU-admitted
    operator also has a partition-parallel kernel family, else the
    reason it runs single-device under a live mesh.  Checked on top of
    tpu_admissibility — sharding never admits an operator the device
    tier rejected."""
    if isinstance(p, PhysicalHashAgg):
        return None  # partial->final merge covers scalar and grouped
    if isinstance(p, PhysicalHashJoin):
        if len(p.left_keys) != 1:
            return ("multi-key joins ride the devpipe composite lane"
                    " unsharded")
        return None
    if isinstance(p, (PhysicalSort, PhysicalTopN)):
        if len(p.by) != 1:
            return ("multi-key order has no single total-order score"
                    " lane to merge ranks over")
        return None
    return f"{p.op_name()} has no sharded kernel family"


def _mesh_join_strategy(p: PhysicalHashJoin, n_shards: int) -> None:
    """estRows-driven broadcast-vs-shuffle cost compare for mesh joins
    (reference GetCost pattern, planner/core/task.go:146; VERDICT r4
    next-4): broadcasting replicates the build side to every shard
    (bytes x n_shards over ICI), shuffling moves each row of BOTH sides
    exactly once (all_to_all).  ANALYZE stats feed the row estimates
    through derive_stats; tidb_broadcast_build_max_rows remains a manual
    override at execution time.

    The build side mirrors the EXECUTOR's choice (devpipe _JoinNode
    compile / tpu_executors probe_side): left only when left-unique inner
    and not right-unique; right otherwise."""
    build_side = (0 if (getattr(p, "left_unique", False)
                        and p.tp == "inner"
                        and not getattr(p, "right_unique", False)
                        and len(p.left_keys) == 1)
                  else 1)
    build = p.children[build_side]
    probe = p.children[1 - build_side]
    rb = max(getattr(build, "stats_row_count", 0.0), 1.0)
    rp = max(getattr(probe, "stats_row_count", 0.0), 1.0)
    wb = 8.0 * max(len(build.schema.columns), 1)
    wp = 8.0 * max(len(probe.schema.columns), 1)
    broadcast_bytes = rb * wb * n_shards
    shuffle_bytes = rb * wb + rp * wp
    p.mesh_cost = {"broadcast_bytes": broadcast_bytes,
                   "shuffle_bytes": shuffle_bytes}
    # a build side estimated above the per-device broadcast budget never
    # broadcasts regardless of relative cost — replicating it to every
    # shard is the memory blow-up the budget exists to prevent (and the
    # executor re-checks against the ACTUAL runtime row count).  One
    # definition of the budget: the sysvar default.
    from ..session.session import DEFAULT_SYSVARS
    over_budget = rb > float(
        DEFAULT_SYSVARS["tidb_broadcast_build_max_rows"])
    p.mesh_strategy = ("shuffle" if over_budget
                       or shuffle_bytes < broadcast_bytes
                       else "broadcast")


def place_devices(p: PhysicalPlan, enabled: bool = True,
                  min_rows: float = 0.0,
                  mesh_shards: int = 0) -> PhysicalPlan:
    """Decide placement per operator: CAPABILITY (kernel expressible) AND
    COST (estimated input rows >= min_rows — an XLA compile is never worth
    it for a handful of rows; reference task.go prices the cop/root
    boundary the same way, tidb_tpu_min_rows carries the threshold).
    With a live mesh (mesh_shards >= 2) joins additionally get a
    broadcast-vs-shuffle strategy from the cost model."""
    for c in p.children:
        place_devices(c, enabled, min_rows, mesh_shards)
    if not enabled:
        return p
    big = _input_rows(p) >= min_rows
    if isinstance(p, (PhysicalHashAgg, PhysicalHashJoin, PhysicalSort,
                      PhysicalTopN, PhysicalProjection,
                      PhysicalSelection)):
        p.use_tpu = big and tpu_admissibility(p) is None
        if (isinstance(p, PhysicalHashJoin) and p.use_tpu
                and mesh_shards >= 2):
            _mesh_join_strategy(p, mesh_shards)
        # estRows-driven shard count for the sharded operator tier: a
        # power-of-two <= device count through dist.shard_bucket (the
        # sanctioned mesh-shape launder), annotated only when an actual
        # estimate exists — 1 means "degenerate, stay single-device",
        # absent means "no planner opinion, the executor's runtime row
        # gate decides alone"
        if p.use_tpu and mesh_shards >= 2 and mesh_admissible(p) is None:
            est = _input_rows(p)
            if est > 0:
                from ..parallel import dist
                p.mesh_shards = dist.shard_bucket(est, mesh_shards)
    return p
