"""Bucket-estimate hook for AOT prewarming (tools/warm.py).

Every device kernel pads its inputs to a power-of-two bucket
(ops/kernels.bucket), so the set of buckets a plan will touch is
derivable BEFORE execution from the planner's cardinality estimates:
each physical node's ``stats_row_count`` (ANALYZE stats through
derive_stats — the reference's task.go GetCost inputs) maps to the
bucket its kernels will compile for, plus the next bucket up as
headroom for stats drift (inserts growing a table past the boundary
must not pay a cold compile on the first query that sees them).

Estimates drift; measurements don't.  ``merge_feedback`` folds a
per-query RuntimeStats feedback file (obs/feedback.py JSONL, written
when ``TINYSQL_STATS_FEEDBACK`` is set; consumed by ``tools/warm.py
--from-stats``) into the prewarm set, so buckets that OBSERVED operator
cardinalities hit — but the estimates missed — also compile ahead of
time.
"""
from __future__ import annotations

import json
from typing import Optional, Set


def buckets_for_rows(rows: int) -> Set[int]:
    """THE bucket-plus-growth-headroom policy, shared by the estimate
    path (below), the feedback writer (obs/feedback.py) and the feedback
    reader (merge_feedback): the bucket ``rows`` pads to, plus the next
    bucket up so drift past the boundary never pays a cold compile."""
    if rows <= 0:
        return set()
    from ..ops.kernels import bucket
    nb = bucket(rows)
    return {nb, nb * 2}


def bucket_estimates(plan, session_vars=None) -> Set[int]:
    """Power-of-two buckets a placed physical plan is expected to hit,
    from per-node cardinality estimates (plus one growth bucket each).
    When ``session_vars`` carries a block budget (tidb_device_block_rows)
    the block bucket joins the set — block-wise streaming pads every
    block to it."""
    from ..ops.kernels import bucket
    out: Set[int] = set()

    def walk(p) -> None:
        est = int(max(getattr(p, "stats_row_count", 0.0) or 0.0, 0))
        out.update(buckets_for_rows(est))
        scan = getattr(p, "scan", None)
        if scan is not None:  # TableReader wraps its scan out-of-tree
            walk(scan)
        for c in getattr(p, "children", []):
            walk(c)

    walk(plan)
    budget = _block_budget(session_vars)
    if budget > 0:
        out.add(bucket(budget))
    return out


def merge_feedback(path: str, into: Optional[Set[int]] = None) -> Set[int]:
    """Union the buckets recorded in a RuntimeStats feedback JSONL file
    (obs/feedback.py records: ``{"plan_digest", "buckets", "operators"}``
    — records also carrying only ``operators``/``act_rows`` are
    re-bucketed here) into ``into``.  Unreadable files or lines are
    skipped: feedback is advisory, never load-bearing."""
    out: Set[int] = into if into is not None else set()
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return out
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        bl = rec.get("buckets", [])
        for b in (bl if isinstance(bl, list) else []):
            try:
                out.add(int(b))
            except (TypeError, ValueError):
                continue
        ops = rec.get("operators", [])
        for op in (ops if isinstance(ops, list) else []):
            try:
                rows = int(op.get("act_rows", 0) or 0)
            except (TypeError, ValueError, AttributeError):
                continue
            out.update(buckets_for_rows(rows))
    return out


def _block_budget(session_vars) -> int:
    if not session_vars:
        return 0
    try:
        return int(session_vars.get("tidb_device_block_rows", 0) or 0)
    except Exception:
        return 0
