"""Bucket-estimate hook for AOT prewarming (tools/warm.py).

Every device kernel pads its inputs to a power-of-two bucket
(ops/kernels.bucket), so the set of buckets a plan will touch is
derivable BEFORE execution from the planner's cardinality estimates:
each physical node's ``stats_row_count`` (ANALYZE stats through
derive_stats — the reference's task.go GetCost inputs) maps to the
bucket its kernels will compile for, plus the next bucket up as
headroom for stats drift (inserts growing a table past the boundary
must not pay a cold compile on the first query that sees them).
"""
from __future__ import annotations

from typing import Optional, Set


def bucket_estimates(plan, session_vars=None) -> Set[int]:
    """Power-of-two buckets a placed physical plan is expected to hit,
    from per-node cardinality estimates (plus one growth bucket each).
    When ``session_vars`` carries a block budget (tidb_device_block_rows)
    the block bucket joins the set — block-wise streaming pads every
    block to it."""
    from ..ops.kernels import bucket
    out: Set[int] = set()

    def walk(p) -> None:
        est = int(max(getattr(p, "stats_row_count", 0.0) or 0.0, 0))
        if est > 0:
            nb = bucket(est)
            out.add(nb)
            out.add(nb * 2)  # stats-drift headroom
        scan = getattr(p, "scan", None)
        if scan is not None:  # TableReader wraps its scan out-of-tree
            walk(scan)
        for c in getattr(p, "children", []):
            walk(c)

    walk(plan)
    budget = _block_budget(session_vars)
    if budget > 0:
        out.add(bucket(budget))
    return out


def _block_budget(session_vars) -> int:
    if not session_vars:
        return 0
    try:
        return int(session_vars.get("tidb_device_block_rows", 0) or 0)
    except Exception:
        return 0
