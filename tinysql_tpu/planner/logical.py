"""Logical plan operators.

Capability parity with reference planner/core/logical_plans.go:601
(DataSource, Selection, Projection, Aggregation, Join, Sort, TopN, Limit,
TableDual) with schemas of expression Columns.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..catalog.model import IndexInfo, TableInfo
from ..expression import AggFuncDesc, Column, Expression, Schema


class LogicalPlan:
    children: List["LogicalPlan"]
    schema: Schema

    def __init__(self):
        self.children = []
        self.schema = Schema([])

    def child(self, i: int = 0) -> "LogicalPlan":
        return self.children[i]

    def set_child(self, i: int, p: "LogicalPlan") -> None:
        self.children[i] = p

    def op_name(self) -> str:
        return type(self).__name__.replace("Logical", "")

    def __repr__(self):  # pragma: no cover
        return f"{self.op_name()}({', '.join(map(repr, self.children))})"


class LogicalDataSource(LogicalPlan):
    """reference: logical_plans.go DataSource."""

    def __init__(self, db_name: str, table_info: TableInfo, alias: str,
                 columns: List[Column]):
        super().__init__()
        self.db_name = db_name
        self.table_info = table_info
        self.alias = alias or table_info.name
        self.schema = Schema(columns)
        # filters pushed down to the scan (reference: pushedDownConds)
        self.pushed_conds: List[Expression] = []
        self.all_conds: List[Expression] = []
        # chosen access path is decided at physical time (index or table)
        self.possible_indices: List[IndexInfo] = list(table_info.public_indices())


class LogicalSelection(LogicalPlan):
    def __init__(self, conditions: List[Expression], child: LogicalPlan):
        super().__init__()
        self.conditions = conditions
        self.children = [child]
        self.schema = child.schema


class LogicalProjection(LogicalPlan):
    def __init__(self, exprs: List[Expression], schema: Schema,
                 child: LogicalPlan):
        super().__init__()
        self.exprs = exprs
        self.schema = schema
        self.children = [child]


class LogicalAggregation(LogicalPlan):
    def __init__(self, group_by: List[Expression],
                 agg_funcs: List[AggFuncDesc], schema: Schema,
                 child: LogicalPlan):
        super().__init__()
        self.group_by = group_by
        self.agg_funcs = agg_funcs
        self.schema = schema
        self.children = [child]


JOIN_INNER = "inner"
JOIN_LEFT = "left"
JOIN_RIGHT = "right"
JOIN_SEMI = "semi"
JOIN_ANTI = "anti"


class LogicalJoin(LogicalPlan):
    """reference: logical_plans.go LogicalJoin."""

    def __init__(self, tp: str, left: LogicalPlan, right: LogicalPlan):
        super().__init__()
        self.tp = tp
        self.children = [left, right]
        # semi/anti joins are FILTERS on the left side: they emit left
        # rows only (reference: LogicalJoin.SemiJoin schema = left)
        if tp in (JOIN_SEMI, JOIN_ANTI):
            self.schema = Schema(list(left.schema.columns))
        else:
            self.schema = left.schema.merge(right.schema)
        # CNF split of the ON/WHERE conditions by side
        self.eq_conditions: List[Tuple[Expression, Expression]] = []  # (lcol expr, rcol expr)
        self.left_conditions: List[Expression] = []
        self.right_conditions: List[Expression] = []
        self.other_conditions: List[Expression] = []
        # NOT IN anti joins carry three-valued NULL semantics: any NULL
        # build key kills every probe row, a NULL probe key only passes
        # an EMPTY build side (reference: null-aware anti join)
        self.null_aware = False


class LogicalSort(LogicalPlan):
    def __init__(self, by: List[Tuple[Expression, bool]], child: LogicalPlan):
        super().__init__()
        self.by = by  # (expr, desc)
        self.children = [child]
        self.schema = child.schema


class LogicalTopN(LogicalPlan):
    def __init__(self, by: List[Tuple[Expression, bool]], offset: int,
                 count: int, child: LogicalPlan):
        super().__init__()
        self.by = by
        self.offset = offset
        self.count = count
        self.children = [child]
        self.schema = child.schema


class LogicalLimit(LogicalPlan):
    def __init__(self, offset: int, count: int, child: LogicalPlan):
        super().__init__()
        self.offset = offset
        self.count = count
        self.children = [child]
        self.schema = child.schema


class LogicalMemTable(LogicalPlan):
    """Virtual INFORMATION_SCHEMA source (reference: infoschema mem-tables
    + planner MemTable plan)."""

    def __init__(self, db_name: str, table: str, columns: List[Column]):
        super().__init__()
        self.db_name = db_name
        self.table = table
        self.schema = Schema(columns)


class LogicalTableDual(LogicalPlan):
    """One-row (or zero-row) constant source (reference: TableDual)."""

    def __init__(self, schema: Optional[Schema] = None, row_count: int = 1):
        super().__init__()
        self.schema = schema or Schema([])
        self.row_count = row_count
