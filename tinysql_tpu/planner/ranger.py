"""Filter -> key-range compilation (reference: util/ranger — points.go,
ranger.go:34-359 BuildTableRange/BuildIndexRange, detacher.go
DetachCondAndBuildRangeForIndex).

Given the CNF filter list on a data source and an ordered column prefix
(an index's columns, or the integer primary key), split the conditions into
*access conditions* (compiled into ranges the storage scan seeks directly)
and *remaining filters* (re-checked per row), and emit the ranges.

Supported shapes per column: `=`, IN (point sets), `<' `<=` `>` `>=`
(intervals), IS NULL (the null point — nulls sort first in the key codec).
Equality prefixes extend to the next index column; the first range column
terminates the prefix (reference detacher semantics).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..expression import Column, Constant, Expression, ScalarFunction
from ..mytypes import Datum, EvalType

# a bound value of None with incl=True means the NULL point; MIN/MAX are
# open bounds (full column range)
MIN = object()
MAX = object()


@dataclass
class Range:
    """Half-open-configurable range over an index column prefix.  `low` and
    `high` are datum tuples (shorter than the index width = prefix range)."""
    low: tuple
    high: tuple
    low_incl: bool = True
    high_incl: bool = True

    def is_point(self) -> bool:
        return (self.low == self.high and self.low_incl and self.high_incl
                and MIN not in self.low and MAX not in self.high)


FULL_RANGE = Range((MIN,), (MAX,), False, False)


def _flip(op: str) -> str:
    return {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}[op]


def _cond_on(e: Expression, col: Column):
    """Classify `e` as an access condition on `col`: returns
    (kind, payload) — ('eq', v) | ('in', [v...]) | ('lt'/'le'/'gt'/'ge', v)
    | ('isnull', None) — or None if not usable."""
    if not isinstance(e, ScalarFunction):
        return None
    name = e.name
    if name == "isnull" and isinstance(e.args[0], Column) \
            and e.args[0].unique_id == col.unique_id:
        return ("isnull", None)
    if name == "in":
        tgt = e.args[0]
        if (isinstance(tgt, Column) and tgt.unique_id == col.unique_id
                and all(isinstance(a, Constant) and a.value is not None
                        for a in e.args[1:])):
            vals = [_coerce(a.value, col) for a in e.args[1:]]
            if any(v is None for v in vals):
                return None  # un-coercible item: keep the whole IN a filter
            return ("in", vals)
        return None
    if name not in ("=", "<", "<=", ">", ">="):
        return None
    a, b = e.args
    if isinstance(a, Column) and isinstance(b, Constant):
        c, v, op = a, b, name
    elif isinstance(b, Column) and isinstance(a, Constant):
        c, v, op = b, a, _flip(name)
    else:
        return None
    if c.unique_id != col.unique_id or v.value is None:
        return None
    val = _coerce(v.value, col)
    if val is None:
        return None
    return {"=": ("eq", val), "<": ("lt", val), "<=": ("le", val),
            ">": ("gt", val), ">=": ("ge", val)}[op]


def _coerce(v: Datum, col: Column) -> Optional[Datum]:
    """Constant -> the column's key-codec family; None if incomparable
    (e.g. string constant against an int column stays a filter)."""
    et = col.eval_type
    if et is EvalType.INT:
        if isinstance(v, bool):
            return int(v)
        if isinstance(v, int):
            return v
        if isinstance(v, float) and float(v).is_integer():
            return int(v)
        return None
    if et is EvalType.REAL:
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        return None
    return str(v) if isinstance(v, str) else None


def detach_conditions(conds: List[Expression], index_cols: List[Column]
                      ) -> Tuple[List[Range], List[Expression],
                                 List[Expression]]:
    """Split CNF `conds` over the column prefix `index_cols`.

    Returns (ranges, access_conds, remaining_conds).  Empty access_conds
    means the index gives no seek advantage (full range)."""
    remaining = list(conds)
    access: List[Expression] = []
    prefixes: List[tuple] = [()]
    final: Optional[List[Range]] = None

    for col in index_cols:
        # gather every usable condition on this column
        eq_points: Optional[List[Datum]] = None
        lo, lo_incl, hi, hi_incl = MIN, False, MAX, False
        used: List[Expression] = []
        for e in list(remaining):
            kind = _cond_on(e, col)
            if kind is None:
                continue
            k, v = kind
            if k == "eq":
                pts = [v]
            elif k == "in":
                pts = sorted(set(v), key=lambda x: (x is None, x))
            elif k == "isnull":
                pts = [None]
            else:
                pts = None
            if pts is not None:
                eq_points = (pts if eq_points is None
                             else [p for p in eq_points if p in pts])
                used.append(e)
                continue
            # interval bound (intersect)
            if k in ("gt", "ge"):
                if lo is MIN or v > lo or (v == lo and k == "gt"):
                    lo, lo_incl = v, (k == "ge")
            else:
                if hi is MAX or v < hi or (v == hi and k == "lt"):
                    hi, hi_incl = v, (k == "le")
            used.append(e)
        if eq_points is not None:
            # equality point(s), filtered by any interval bounds gathered on
            # the same column (a = 5 AND a > 7 -> empty)
            def _in_bounds(v):
                if v is None:  # NULL point never satisfies an interval
                    return lo is MIN and hi is MAX
                if lo is not MIN and (v < lo or (v == lo and not lo_incl)):
                    return False
                if hi is not MAX and (v > hi or (v == hi and not hi_incl)):
                    return False
                return True
            eq_points = [v for v in eq_points if _in_bounds(v)]
            access.extend(used)
            for e in used:
                remaining.remove(e)
            prefixes = [p + (v,) for p in prefixes for v in eq_points]
            if not prefixes:  # contradictory IN/=: empty result
                return [], access, remaining
            continue
        if lo is not MIN or hi is not MAX:
            # range column terminates the prefix
            access.extend(used)
            for e in used:
                remaining.remove(e)
            final = [Range(p + (lo,), p + (hi,), lo_incl, hi_incl)
                     for p in prefixes]
        break

    if final is None:
        if prefixes == [()]:
            return [FULL_RANGE], [], remaining
        final = [Range(p, p, True, True) for p in prefixes]
    return final, access, remaining


# ===== handle (int primary key) ranges ======================================

def build_handle_ranges(conds: List[Expression], pk_col: Column
                        ) -> Tuple[Optional[List[Tuple[int, int]]],
                                   List[Expression], List[Expression]]:
    """Integer [lo, hi] (inclusive) handle ranges for the clustered PK.
    Returns (ranges|None, access_conds, remaining).  None = full scan."""
    ranges, access, remaining = detach_conditions(conds, [pk_col])
    if not access:
        return None, [], conds
    out: List[Tuple[int, int]] = []
    for r in ranges:
        lo = r.low[0] if r.low else MIN
        hi = r.high[0] if r.high else MAX
        if lo is None or hi is None:  # IS NULL on a NOT NULL pk: empty
            continue
        ilo = -(1 << 63) if lo is MIN else int(lo) + (0 if r.low_incl else 1)
        ihi = (1 << 63) - 1 if hi is MAX else int(hi) - (0 if r.high_incl else 1)
        if ilo <= ihi:
            out.append((ilo, ihi))
    return out, access, remaining
