"""Additional logical rewrite rules (reference: planner/core's fixed-order
rule list, optimizer.go:44-55): projection elimination
(rule_eliminate_projection.go), max/min elimination
(rule_max_min_eliminate.go), aggregation elimination
(rule_aggregation_elimination.go), outer-join elimination
(rule_join_elimination.go), greedy join reorder (rule_join_reorder.go).
"""
from __future__ import annotations

from typing import List, Optional, Set

from ..expression import (AggFuncDesc, Column, Constant, Expression,
                          Schema, new_function)
from ..expression.aggregation import (AGG_AVG, AGG_COUNT, AGG_FIRST_ROW,
                                      AGG_MAX, AGG_MIN, AGG_SUM)
from ..mytypes import new_int_type
from .logical import (JOIN_INNER, JOIN_LEFT, LogicalAggregation,
                      LogicalDataSource, LogicalJoin, LogicalPlan,
                      LogicalProjection, LogicalSelection, LogicalSort,
                      LogicalTopN)


# ===== projection elimination ==============================================

def eliminate_projections(p: LogicalPlan) -> LogicalPlan:
    """Drop identity projections: exprs are exactly the child's schema
    columns, in order, same names exposed (reference:
    rule_eliminate_projection.go canProjectionBeEliminatedLoose)."""
    p.children = [eliminate_projections(c) for c in p.children]
    if isinstance(p, LogicalProjection) and p.children:
        child = p.child(0)
        if (len(p.exprs) == len(child.schema.columns)
                and all(isinstance(e, Column)
                        and e.unique_id == c.unique_id
                        for e, c in zip(p.exprs, child.schema.columns))
                and len(p.schema.columns) == len(child.schema.columns)
                and all(a.unique_id == b.unique_id for a, b in
                        zip(p.schema.columns, child.schema.columns))):
            return child
    return p


# ===== max/min elimination =================================================

def eliminate_max_min(p: LogicalPlan) -> LogicalPlan:
    """A lone MAX(col)/MIN(col) with no GROUP BY only needs one row:
    insert NOT NULL filter + TopN(1) below the aggregation (reference:
    rule_max_min_eliminate.go)."""
    p.children = [eliminate_max_min(c) for c in p.children]
    if not isinstance(p, LogicalAggregation) or p.group_by:
        return p
    if len(p.agg_funcs) != 1:
        return p
    d = p.agg_funcs[0]
    if d.name not in (AGG_MAX, AGG_MIN) or d.distinct:
        return p
    arg = d.args[0]
    if not isinstance(arg, Column):
        return p
    child = p.child(0)
    # NULLs never win max/min; filtering them keeps TopN(1) correct for
    # MIN (NULL sorts first ascending)
    not_null = new_function("not", [new_function("isnull", [arg])])
    sel = LogicalSelection([not_null], child)
    topn = LogicalTopN([(arg, d.name == AGG_MAX)], 0, 1, sel)
    topn.schema = child.schema
    p.children = [topn]
    return p


# ===== aggregation elimination =============================================

def eliminate_aggregation(p: LogicalPlan) -> LogicalPlan:
    """GROUP BY over a unique key produces one row per group: rewrite the
    aggregation into a projection of per-row equivalents (reference:
    rule_aggregation_elimination.go)."""
    p.children = [eliminate_aggregation(c) for c in p.children]
    if not isinstance(p, LogicalAggregation) or not p.group_by:
        return p
    child = p.child(0)
    gb_uids = {e.unique_id for e in p.group_by if isinstance(e, Column)}
    if len(gb_uids) != len(p.group_by):
        return p  # non-column group keys
    if not _covers_unique_key(child, gb_uids):
        return p
    exprs: List[Expression] = []
    out_cols: List[Column] = []
    for c in p.schema.columns:
        src = _agg_output_source(p, c)
        if src is None:
            return p
        per_row = _per_row_equivalent(src)
        if per_row is None:
            return p
        exprs.append(per_row)
        out_cols.append(c)
    proj = LogicalProjection(exprs, Schema(out_cols), child)
    return proj


def unique_key_sets(p: LogicalPlan) -> List[Set[int]]:
    """Derive the unique keys (as column unique_id sets) of a logical
    subtree — the reference's Schema.Keys maintained by buildKeyInfo
    (rule_build_key_info.go).  Propagation through joins is what lets
    aggregation elimination fire above an agg-pushdown join: a join whose
    build side is unique on ALL its equi-key columns never duplicates the
    probe side, so probe-side keys stay unique."""
    if isinstance(p, LogicalDataSource):
        keys: List[Set[int]] = []
        pk = p.table_info.get_pk_handle_col()
        for c in p.schema.columns:
            if pk is not None and c.name == pk.name:
                keys.append({c.unique_id})
        for idx in p.table_info.public_indices():
            if idx.unique and len(idx.columns) == 1:
                name = idx.columns[0].name
                for c in p.schema.columns:
                    # a NULLABLE unique index admits multiple NULL rows
                    # (catalog/table.py encodes NULL entries non-uniquely),
                    # and GROUP BY groups NULLs together — only a NOT NULL
                    # column is a true key (reference buildKeyInfo does the
                    # same check)
                    if c.name == name and c.ret_type.not_null:
                        keys.append({c.unique_id})
        return keys
    if isinstance(p, (LogicalSelection, LogicalSort, LogicalTopN)):
        return unique_key_sets(p.child(0))
    if isinstance(p, LogicalProjection):
        out_of = {}
        for e, oc in zip(p.exprs, p.schema.columns):
            if isinstance(e, Column):
                out_of.setdefault(e.unique_id, oc.unique_id)
        keys = []
        for k in unique_key_sets(p.child(0)):
            if all(u in out_of for u in k):
                keys.append({out_of[u] for u in k})
        return keys
    if isinstance(p, LogicalAggregation):
        gb_outs = getattr(p, "gb_out_cols", [])
        if p.group_by and len(gb_outs) == len(p.group_by):
            return [{c.unique_id for c in gb_outs}]
        return []
    if isinstance(p, LogicalJoin) and p.tp in ("semi", "anti"):
        # semi/anti joins never duplicate (or extend) left rows
        return unique_key_sets(p.child(0))
    if isinstance(p, LogicalJoin) and p.tp in (JOIN_INNER, JOIN_LEFT):
        lkeys = unique_key_sets(p.child(0))
        rkeys = unique_key_sets(p.child(1))
        l_eq = {a.unique_id for a, _ in p.eq_conditions
                if isinstance(a, Column)}
        r_eq = {b.unique_id for _, b in p.eq_conditions
                if isinstance(b, Column)}
        r_unique = bool(p.eq_conditions) and any(k <= r_eq for k in rkeys)
        l_unique = bool(p.eq_conditions) and any(k <= l_eq for k in lkeys)
        out: List[Set[int]] = []
        if r_unique:
            out += lkeys  # every probe row matches at most one build row
        if l_unique and p.tp == JOIN_INNER:
            out += rkeys
        return out
    return []


def _covers_unique_key(child: LogicalPlan, gb_uids: Set[int]) -> bool:
    """Does some unique key of `child` sit inside the group-by columns?"""
    return any(k and k <= gb_uids for k in unique_key_sets(child))


def _agg_output_source(agg: LogicalAggregation, col: Column):
    for out_c, d in zip(agg.output_cols, agg.agg_funcs):
        if out_c.unique_id == col.unique_id:
            return d
    for out_c, e in zip(getattr(agg, "gb_out_cols", []), agg.group_by):
        if out_c.unique_id == col.unique_id:
            return e
    return None


def _per_row_equivalent(src) -> Optional[Expression]:
    """One-row-group equivalents (reference: rewriteExpr in
    rule_aggregation_elimination.go).  FINAL-mode descriptors consume
    partial STATES (one state per row once groups are unique): the merge
    of a single partial is the partial itself — except AVG, whose state is
    a (sum, count) column pair."""
    from ..expression.aggregation import AggMode
    if isinstance(src, Expression):
        return src  # group-by column passes through
    d: AggFuncDesc = src
    arg = d.args[0]
    if d.mode is AggMode.FINAL:
        if d.name == AGG_AVG:
            # sum/count; x/0 is NULL, matching AVG of an all-NULL group
            return new_function("/", [d.args[0], d.args[1]])
        e = arg  # COUNT merges by SUM of one partial count = itself, etc.
        if (d.ret_type.eval_type is not e.ret_type.eval_type
                and d.ret_type.eval_type.name == "REAL"):
            e = new_function("cast_real", [e])
        return e
    if d.name in (AGG_MAX, AGG_MIN, AGG_FIRST_ROW, AGG_SUM, AGG_AVG):
        if d.distinct and d.name in (AGG_SUM, AGG_AVG):
            pass  # distinct over one row is the row itself
        e = arg
        if d.ret_type.eval_type is not e.ret_type.eval_type:
            e = new_function("cast_real", [e]) \
                if d.ret_type.eval_type.name == "REAL" else e
        return e
    if d.name == AGG_COUNT:
        if isinstance(arg, Constant) and arg.value is not None:
            return Constant(1, new_int_type())  # COUNT(*)
        isn = new_function("isnull", [arg])
        return new_function("if", [isn, Constant(0, new_int_type()),
                                   Constant(1, new_int_type())])
    return None


# ===== outer join elimination ==============================================

def eliminate_outer_joins(p: LogicalPlan, needed: Set[int]) -> LogicalPlan:
    """LEFT JOIN whose right side contributes no needed columns and whose
    join keys hit a unique key on the right (no row duplication) reduces
    to its left child (reference: rule_join_elimination.go)."""
    if isinstance(p, LogicalJoin) and p.tp == JOIN_LEFT:
        right = p.children[1]
        right_uids = {c.unique_id for c in right.schema.columns}
        if not (needed & right_uids) and _right_keys_unique(p):
            return eliminate_outer_joins(p.children[0], needed)
    for i, c in enumerate(p.children):
        child_needed = _needed_below(p, needed)
        p.children[i] = eliminate_outer_joins(c, child_needed)
    return p


def _right_keys_unique(join: LogicalJoin) -> bool:
    right = join.children[1]
    if not isinstance(right, LogicalDataSource) or join.other_conditions:
        return False
    pk = right.table_info.get_pk_handle_col()
    pk_uid = None
    for c in right.schema.columns:
        if pk is not None and c.name == pk.name:
            pk_uid = c.unique_id
    r_keys = {b.unique_id for _, b in join.eq_conditions
              if isinstance(b, Column)}
    if pk_uid is not None and pk_uid in r_keys:
        return True
    # single-column unique index fully matched
    for idx in right.table_info.public_indices():
        if idx.unique and len(idx.columns) == 1:
            name = idx.columns[0].name
            for c in right.schema.columns:
                if c.name == name and c.unique_id in r_keys:
                    return True
    return False


def _needed_below(p: LogicalPlan, needed: Set[int]) -> Set[int]:
    out = set(needed)
    if isinstance(p, LogicalProjection):
        out = set()
        for e in p.exprs:
            out |= {c.unique_id for c in e.collect_columns()}
    elif isinstance(p, LogicalSelection):
        for e in p.conditions:
            out |= {c.unique_id for c in e.collect_columns()}
    elif isinstance(p, LogicalAggregation):
        out = set()
        for d in p.agg_funcs:
            for a in d.args:
                out |= {c.unique_id for c in a.collect_columns()}
        for e in p.group_by:
            out |= {c.unique_id for c in e.collect_columns()}
    elif isinstance(p, LogicalJoin):
        for a, b in p.eq_conditions:
            out |= {c.unique_id for c in a.collect_columns()}
            out |= {c.unique_id for c in b.collect_columns()}
        for e in (p.other_conditions + p.left_conditions
                  + p.right_conditions):
            out |= {c.unique_id for c in e.collect_columns()}
    elif isinstance(p, (LogicalSort, LogicalTopN)):
        for e, _ in p.by:
            out |= {c.unique_id for c in e.collect_columns()}
    return out


# ===== greedy join reorder =================================================

def join_reorder(p: LogicalPlan, stats_of=None) -> LogicalPlan:
    """Flatten chains of inner equi-joins and rebuild left-deep, smallest
    estimated source first, preferring connected (equi-cond) pairs
    (reference: rule_join_reorder.go greedy solver)."""
    p.children = [join_reorder(c, stats_of) for c in p.children]
    if isinstance(p, LogicalJoin) and p.tp in ("semi", "anti"):
        # the reordered left subtree may expose its columns in a new
        # order; a semi/anti join mirrors the left child exactly
        p.schema = Schema(list(p.children[0].schema.columns))
    if not (isinstance(p, LogicalJoin) and p.tp == JOIN_INNER):
        return p
    nodes: List[LogicalPlan] = []
    eqs: List[tuple] = []
    others: List[Expression] = []

    def flatten(j: LogicalPlan):
        if (isinstance(j, LogicalJoin) and j.tp == JOIN_INNER
                and not j.left_conditions and not j.right_conditions):
            flatten(j.children[0])
            flatten(j.children[1])
            eqs.extend(j.eq_conditions)
            others.extend(j.other_conditions)
        else:
            nodes.append(j)
    flatten(p)
    if len(nodes) <= 2:
        return p

    def est(n: LogicalPlan) -> float:
        if isinstance(n, LogicalDataSource) and stats_of is not None:
            s = stats_of(n)
            if s:
                return float(s)
        return 1e4

    if len(nodes) <= DP_REORDER_LIMIT:
        tree = _dp_best_tree(nodes, eqs, est)
        cur, _, pending_eqs = _build_join_tree(tree, nodes, list(eqs))
        return _finish_reorder(cur, pending_eqs, others)

    remaining = sorted(nodes, key=est)
    cur = remaining.pop(0)
    cur_uids = {c.unique_id for c in cur.schema.columns}
    pending_eqs = list(eqs)
    while remaining:
        # prefer a node connected to the current tree by an equi cond
        pick = None
        for cand in remaining:
            cand_uids = {c.unique_id for c in cand.schema.columns}
            for a, b in pending_eqs:
                au = {c.unique_id for c in a.collect_columns()}
                bu = {c.unique_id for c in b.collect_columns()}
                if ((au <= cur_uids and bu <= cand_uids)
                        or (bu <= cur_uids and au <= cand_uids)):
                    pick = cand
                    break
            if pick is not None:
                break
        if pick is None:
            pick = remaining[0]
        remaining.remove(pick)
        j = LogicalJoin(JOIN_INNER, cur, pick)
        pick_uids = {c.unique_id for c in pick.schema.columns}
        pending_eqs = _attach_eqs(j, cur_uids, pick_uids, pending_eqs)
        cur = j
        cur_uids = cur_uids | pick_uids
    return _finish_reorder(cur, pending_eqs, others)


def _attach_eqs(j: LogicalJoin, luids: Set[int], ruids: Set[int],
                pending_eqs: List[tuple]) -> List[tuple]:
    """Attach every pending equi condition whose two sides are now both
    in scope, oriented left-side-first; returns the still-pending rest
    (shared by the greedy and DP assemblies)."""
    new_uids = luids | ruids
    still = []
    for a, b in pending_eqs:
        au = {c.unique_id for c in a.collect_columns()}
        bu = {c.unique_id for c in b.collect_columns()}
        if au <= new_uids and bu <= new_uids:
            if au <= luids:
                j.eq_conditions.append((a, b))
            else:
                j.eq_conditions.append((b, a))
        else:
            still.append((a, b))
    return still


def _finish_reorder(cur: LogicalPlan, pending_eqs: List[tuple],
                    others: List[Expression]) -> LogicalPlan:
    if others:
        assert isinstance(cur, LogicalJoin)
        cur.other_conditions.extend(others)
    # any unplaced equi conds (degenerate) become other conditions
    for a, b in pending_eqs:
        if isinstance(cur, LogicalJoin):
            cur.other_conditions.append(new_function("=", [a, b]))
    return cur


# ===== semi/anti join sink =================================================

def push_semi_joins_down(p: LogicalPlan) -> LogicalPlan:
    """Sink a semi/anti join below the inner-join chain under its left
    child, next to the side its equi-keys actually come from (reference:
    TiDB plans the decorrelated semi join against the correlated table,
    not the whole FROM product).  A semi/anti join is a row FILTER on
    its left input, so it commutes with inner joins (and the outer side
    of a LEFT join) exactly like a selection — sinking it prunes the
    chain EARLY instead of filtering the full join product (Q5: the
    region membership lands on nation's 25 rows, not on the 5-way join
    output)."""
    p.children = [push_semi_joins_down(c) for c in p.children]
    if isinstance(p, LogicalJoin) and p.tp in ("semi", "anti"):
        return _sink_semi(p)
    return p


def _sink_semi(semi: LogicalJoin) -> LogicalPlan:
    left = semi.children[0]
    if not (isinstance(left, LogicalJoin)
            and left.tp in (JOIN_INNER, JOIN_LEFT)):
        return semi
    need = set()
    for a, _ in semi.eq_conditions:
        need |= {c.unique_id for c in a.collect_columns()}
    for c in semi.other_conditions:
        need |= {x.unique_id for x in c.collect_columns()
                 if left.schema.contains(x)}
    if not need:
        return semi  # cartesian membership: no side to sink toward
    for side in (0, 1):
        if side == 1 and left.tp != JOIN_INNER:
            continue  # never below the inner side of a LEFT join
        child_uids = {c.unique_id
                      for c in left.children[side].schema.columns}
        if need <= child_uids:
            semi.children[0] = left.children[side]
            semi.schema = Schema(list(left.children[side].schema.columns))
            left.children[side] = _sink_semi(semi)
            return left
    return semi


# ===== aggregation pushdown through join ===================================

def push_agg_through_join(p: LogicalPlan) -> LogicalPlan:
    """Decompose an aggregation over an inner join into a PARTIAL
    aggregation below one join side + the original aggregation in FINAL
    mode above (reference: rule_aggregation_push_down.go:181
    tryToPushDownAgg; the cascades course rule
    transformation_rules.go:497 is the same shape).

    Validity: the partial side's group keys always include that side's
    equi-join keys, so every row of one partial group carries the SAME
    join key and duplicates identically across matches — partial states
    recombine exactly as the raw rows would have (sum of sums, count of
    counts via FINAL mode, min of mins...).  Requirements enforced:

    - inner join, no residual cross-side conditions (those filter
      per-PAIR and would have to run before pre-aggregation), no side
      conditions left on the push side
    - every agg arg reads ONE side only; count(*)/const-arg descs ride
      with whichever side the rest picked
    - push-side group-by items and join keys are bare Columns
    - no DISTINCT (partial states don't compose)
    """
    p.children = [push_agg_through_join(c) for c in p.children]
    if not isinstance(p, LogicalAggregation) or not p.children:
        return p
    j = p.child(0)
    if not isinstance(j, LogicalJoin) or j.tp != JOIN_INNER:
        return p
    if j.other_conditions or not j.eq_conditions:
        return p
    if any(d.distinct for d in p.agg_funcs) or not p.agg_funcs:
        return p
    lsch, rsch = j.children[0].schema, j.children[1].schema
    sides = []
    for d in p.agg_funcs:
        cols = [c for a in d.args for c in a.collect_columns()]
        if not cols:
            sides.append(None)
        elif all(lsch.contains(c) for c in cols):
            sides.append(0)
        elif all(rsch.contains(c) for c in cols):
            sides.append(1)
        else:
            return p
    picked = {s for s in sides if s is not None}
    if len(picked) != 1:
        return p
    side = picked.pop()
    if (j.left_conditions if side == 0 else j.right_conditions):
        return p
    side_schema = lsch if side == 0 else rsch
    keys = [(a if side == 0 else b) for a, b in j.eq_conditions]
    if not all(isinstance(k, Column) for k in keys):
        return p
    # partial group keys: push-side group-by columns + push-side join keys
    part_keys: List[Column] = []
    for e in p.group_by:
        cols = e.collect_columns()
        if any(side_schema.contains(c) for c in cols):
            if not isinstance(e, Column):
                return p
            part_keys.append(e)
    for k in keys:
        if not any(k.unique_id == c.unique_id for c in part_keys):
            part_keys.append(k)

    partial_descs: List[AggFuncDesc] = []
    partial_cols: List[Column] = []
    final_descs: List[AggFuncDesc] = []
    for d in p.agg_funcs:
        prt = d.partial_result_types()
        partials, final = d.split(list(range(len(prt))))
        fresh = [Column(ft, name=f"partial_{d.name}#{len(partial_cols) + i}")
                 for i, ft in enumerate(prt)]
        final.args = list(fresh)  # rebind by unique id, not dummy ordinal
        partial_descs.extend(partials)
        partial_cols.extend(fresh)
        final_descs.append(final)

    part_schema = Schema(partial_cols + part_keys)
    partial = LogicalAggregation(list(part_keys), partial_descs,
                                 part_schema, j.children[side])
    partial.output_cols = partial_cols
    partial.gb_out_cols = list(part_keys)  # pass-through identity
    j.children[side] = partial
    j.schema = j.children[0].schema.merge(j.children[1].schema)
    p.agg_funcs = final_descs
    return p


# ===== DP join reorder =====================================================

DP_REORDER_LIMIT = 8  # exhaustive DP up to this many join nodes


def _dp_best_tree(nodes, eqs, est):
    """Exact join-order search over connected subsets (reference:
    rule_join_reorder_dp.go — DP over bitmasks; TiDB bounds it with
    tidb_opt_join_reorder_threshold, greedy beyond).  Returns a nested
    (left_tree, right_tree) tuple of node indices; bushy shapes allowed.

    Cost model (matches derive_stats): an equi-connected join yields
    max(|L|,|R|) rows, a cartesian product |L|*|R|; plan cost = sum of
    intermediate result sizes.  Cartesian cost dominance makes the DP
    prefer any connected order before a product, which is the practical
    win over the greedy's local choice."""
    n = len(nodes)
    uids = [frozenset(c.unique_id for c in nd.schema.columns)
            for nd in nodes]
    edge_sides = []
    for a, b in eqs:
        au = frozenset(c.unique_id for c in a.collect_columns())
        bu = frozenset(c.unique_id for c in b.collect_columns())
        edge_sides.append((au, bu))

    def mask_uids(mask):
        out = set()
        for i in range(n):
            if mask & (1 << i):
                out |= uids[i]
        return out

    mu = {1 << i: set(uids[i]) for i in range(n)}

    def connected(lmask, rmask):
        lu, ru = mu[lmask], mu[rmask]
        for au, bu in edge_sides:
            if (au <= lu and bu <= ru) or (bu <= lu and au <= ru):
                return True
        return False

    # best[mask] = (cost, rows, tree)
    best = {1 << i: (0.0, max(est(nodes[i]), 1.0), i) for i in range(n)}
    full = (1 << n) - 1
    for mask in range(3, full + 1):
        if mask & (mask - 1) == 0:  # single node
            continue
        if mask not in mu:
            mu[mask] = mask_uids(mask)
        cand = None
        sub = (mask - 1) & mask
        while sub > 0:
            other = mask ^ sub
            if sub < other:  # canonical split once
                l, r = sub, other
                if l in best and r in best:
                    cl, rl, tl = best[l]
                    cr, rr, tr = best[r]
                    rows = (max(rl, rr) if connected(l, r)
                            else rl * rr)
                    cost = cl + cr + rows
                    if cand is None or cost < cand[0]:
                        cand = (cost, rows, (tl, tr))
            sub = (sub - 1) & mask
        if cand is not None:
            best[mask] = cand
    return best[full][2]


def _build_join_tree(tree, nodes, pending_eqs):
    """Materialize the DP tree into LogicalJoins, attaching each equi
    condition at the first join where both sides are in scope (oriented
    left-first, like the greedy assembly)."""
    if isinstance(tree, int):
        nd = nodes[tree]
        return nd, {c.unique_id for c in nd.schema.columns}, pending_eqs
    lplan, luids, pending_eqs = _build_join_tree(tree[0], nodes,
                                                 pending_eqs)
    rplan, ruids, pending_eqs = _build_join_tree(tree[1], nodes,
                                                 pending_eqs)
    j = LogicalJoin(JOIN_INNER, lplan, rplan)
    still = _attach_eqs(j, luids, ruids, pending_eqs)
    return j, luids | ruids, still
