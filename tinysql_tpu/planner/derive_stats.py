"""Row-estimate derivation over the physical tree.

Capability parity with reference planner/core/stats.go DeriveStats +
explain.go's estRows column: every physical operator carries a row
estimate, derived bottom-up from the access-path estimates the readers
already carry.  These estimates feed (a) the four-column EXPLAIN output
and (b) the device enforcer's row gate (a tiny input never pays an XLA
compile — reference task.go's whole point is that placement is COST
based, not capability based).

Heuristics when histograms can't answer (reference pseudo-stats factors):
selection keeps 0.8 per conjunct (selectionFactor), a group-by column
keeps 0.8 of input NDV-wise, an equi-join yields max(|L|,|R|) rows.
"""
from __future__ import annotations

from .physical import (PhysicalHashAgg, PhysicalHashJoin,
                       PhysicalIndexLookUpReader, PhysicalIndexReader,
                       PhysicalLimit, PhysicalMemTable, PhysicalMergeJoin,
                       PhysicalPlan, PhysicalProjection, PhysicalSelection,
                       PhysicalSort, PhysicalTableDual, PhysicalTableReader,
                       PhysicalTopN)

# single source for the reference's selectionFactor tuning constant
from ..statistics.table_stats import DEFAULT_SELECTIVITY as SELECTION_FACTOR

GROUP_NDV_FACTOR = 0.8   # pseudo NDV of one group-by column
MEMTABLE_ROWS = 100.0    # virtual INFORMATION_SCHEMA tables are tiny


def _set(p: PhysicalPlan, rows: float) -> None:
    p.stats_row_count = max(float(rows), 0.0)
    p.has_estimate = True


def derive_stats(p: PhysicalPlan) -> PhysicalPlan:
    """Bottom-up estimate fill.  Readers keep their access-path estimates
    (set in access.py with residual-filter selectivity applied)."""
    for c in p.children:
        derive_stats(c)
    if isinstance(p, (PhysicalTableReader, PhysicalIndexReader,
                      PhysicalIndexLookUpReader)):
        return p  # already estimated from the chosen access path
    if isinstance(p, PhysicalTableDual):
        _set(p, p.row_count)
        return p
    if isinstance(p, PhysicalMemTable):
        _set(p, MEMTABLE_ROWS)
        return p
    child = p.children[0].stats_row_count if p.children else 0.0
    if isinstance(p, PhysicalSelection):
        _set(p, child * (SELECTION_FACTOR ** max(len(p.conditions), 1)))
    elif isinstance(p, PhysicalProjection):
        _set(p, child)
    elif isinstance(p, PhysicalHashAgg):
        if not p.group_by:
            _set(p, 1.0)
        else:
            # pseudo NDV product, capped by input size
            _set(p, min(child, max(1.0,
                                   child * (GROUP_NDV_FACTOR
                                            ** len(p.group_by)))))
    elif isinstance(p, (PhysicalHashJoin, PhysicalMergeJoin)):
        left = p.children[0].stats_row_count
        right = p.children[1].stats_row_count
        if p.tp in ("semi", "anti"):
            # semi/anti joins filter the left side: output <= left rows
            # (reference stats.go semi-join selectionFactor)
            frac = SELECTION_FACTOR if p.tp == "semi" \
                else 1.0 - SELECTION_FACTOR
            _set(p, left * frac)
            return p
        if getattr(p, "left_keys", None):
            rows = max(left, right)
        else:
            rows = left * right  # cross join
        n_other = len(getattr(p, "other_conditions", []) or [])
        rows *= SELECTION_FACTOR ** n_other
        if p.tp == "left":
            rows = max(rows, left)  # every outer row survives
        _set(p, rows)
    elif isinstance(p, PhysicalSort):
        _set(p, child)
    elif isinstance(p, (PhysicalTopN, PhysicalLimit)):
        _set(p, min(child, float(p.count)))
    else:
        _set(p, child)
    return p
