"""Field types and eval types.

Mirrors the *capability* of the reference's types/field_type.go +
types/eval_type.go: the engine supports exactly three eval families —
int (signed/unsigned int64), real (float64), string — as documented in
SURVEY §0.2 and enforced by reference util/chunk/column.go:64-76.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field

# MySQL-ish type codes (subset actually reachable in the reference grammar).
TYPE_NULL = 0x06
TYPE_LONG = 0x03        # INT
TYPE_LONGLONG = 0x08    # BIGINT
TYPE_FLOAT = 0x04
TYPE_DOUBLE = 0x05
TYPE_VARCHAR = 0x0F
TYPE_STRING = 0xFE      # CHAR

_INT_TYPES = {TYPE_LONG, TYPE_LONGLONG}
_REAL_TYPES = {TYPE_FLOAT, TYPE_DOUBLE}
_STRING_TYPES = {TYPE_VARCHAR, TYPE_STRING}

# Column flags (subset of parser/mysql/type.go flags used by the engine).
FLAG_NOT_NULL = 1
FLAG_PRI_KEY = 2
FLAG_UNIQUE_KEY = 4
FLAG_UNSIGNED = 32
FLAG_AUTO_INCREMENT = 512


class EvalType(enum.Enum):
    """The three vectorized evaluation families (reference: types/eval_type.go)."""
    INT = "int"
    REAL = "real"
    STRING = "string"

    @property
    def fixed_width(self) -> bool:
        return self is not EvalType.STRING


@dataclass
class FieldType:
    tp: int = TYPE_LONGLONG
    flag: int = 0
    flen: int = -1
    decimal: int = -1
    charset: str = "utf8mb4"
    collate: str = "utf8mb4_bin"

    @property
    def eval_type(self) -> EvalType:
        if self.tp in _INT_TYPES:
            return EvalType.INT
        if self.tp in _REAL_TYPES:
            return EvalType.REAL
        if self.tp in _STRING_TYPES or self.tp == TYPE_NULL:
            return EvalType.STRING if self.tp != TYPE_NULL else EvalType.INT
        raise ValueError(f"unsupported field type {self.tp}")

    @property
    def is_unsigned(self) -> bool:
        return bool(self.flag & FLAG_UNSIGNED)

    @property
    def not_null(self) -> bool:
        return bool(self.flag & FLAG_NOT_NULL)

    def clone(self) -> "FieldType":
        return FieldType(self.tp, self.flag, self.flen, self.decimal,
                         self.charset, self.collate)

    def type_name(self) -> str:
        return {
            TYPE_LONG: "int", TYPE_LONGLONG: "bigint",
            TYPE_FLOAT: "float", TYPE_DOUBLE: "double",
            TYPE_VARCHAR: "varchar", TYPE_STRING: "char",
            TYPE_NULL: "null",
        }[self.tp]

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        u = " unsigned" if self.is_unsigned else ""
        return f"FieldType({self.type_name()}{u})"


def new_int_type(unsigned: bool = False, not_null: bool = False) -> FieldType:
    flag = (FLAG_UNSIGNED if unsigned else 0) | (FLAG_NOT_NULL if not_null else 0)
    return FieldType(TYPE_LONGLONG, flag=flag, flen=20)


def new_real_type(not_null: bool = False) -> FieldType:
    return FieldType(TYPE_DOUBLE, flag=(FLAG_NOT_NULL if not_null else 0), flen=22)


def new_string_type(flen: int = -1, not_null: bool = False) -> FieldType:
    return FieldType(TYPE_VARCHAR, flag=(FLAG_NOT_NULL if not_null else 0), flen=flen)


def agg_field_type(fts: list[FieldType]) -> FieldType:
    """Merge field types (reference: types/field_type.go AggFieldType semantics,
    reduced to the 3-family lattice: string > real > int)."""
    best = EvalType.INT
    unsigned = True
    for ft in fts:
        et = ft.eval_type
        if et is EvalType.STRING:
            best = EvalType.STRING
        elif et is EvalType.REAL and best is EvalType.INT:
            best = EvalType.REAL
        unsigned = unsigned and ft.is_unsigned
    if best is EvalType.STRING:
        return new_string_type()
    if best is EvalType.REAL:
        return new_real_type()
    return new_int_type(unsigned=unsigned)
