"""Typed values for the engine.

Capability parity with the reference's three eval families
(reference: util/chunk/column.go:64-76 — ETInt / ETReal / ETString;
types/eval_type.go): int64, float64, string.  No DECIMAL/TIME exists in the
reference (SURVEY §2.9), so none here.
"""
from .field_type import (
    EvalType,
    FieldType,
    TYPE_LONG,
    TYPE_LONGLONG,
    TYPE_FLOAT,
    TYPE_DOUBLE,
    TYPE_VARCHAR,
    TYPE_STRING,
    TYPE_NULL,
    FLAG_NOT_NULL,
    FLAG_PRI_KEY,
    FLAG_UNIQUE_KEY,
    FLAG_UNSIGNED,
    FLAG_AUTO_INCREMENT,
    new_int_type,
    new_real_type,
    new_string_type,
    agg_field_type,
)
from .datum import (
    Datum,
    datum_compare,
    coerce_for_compare,
    cast_datum,
    sort_key,
    format_real,
    to_int,
    to_uint,
    to_real,
    to_string,
    to_bool,
    wrap_i64,
)

__all__ = [
    "EvalType", "FieldType",
    "TYPE_LONG", "TYPE_LONGLONG", "TYPE_FLOAT", "TYPE_DOUBLE",
    "TYPE_VARCHAR", "TYPE_STRING", "TYPE_NULL",
    "FLAG_NOT_NULL", "FLAG_PRI_KEY", "FLAG_UNIQUE_KEY", "FLAG_UNSIGNED",
    "FLAG_AUTO_INCREMENT",
    "new_int_type", "new_real_type", "new_string_type", "agg_field_type",
    "Datum", "datum_compare", "coerce_for_compare", "cast_datum", "sort_key",
    "format_real", "to_int", "to_uint", "to_real", "to_string", "to_bool",
    "wrap_i64",
]
