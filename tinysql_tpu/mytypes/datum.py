"""Scalar values ("datums") and MySQL-compatible coercion/comparison.

Capability parity with reference types/datum.go + types/compare.go +
types/convert.go, reduced to the int/real/string families the reference
supports (SURVEY §2.9).  Host-side scalar path only; the vectorized/TPU path
lives in chunk/ and ops/.
"""
from __future__ import annotations

from typing import Any, Optional

from .field_type import EvalType, FieldType

# A Datum is simply: None (NULL), int, float, or str.
Datum = Optional[object]

_U64_MASK = (1 << 64) - 1
_I64_MAX = (1 << 63) - 1
_I64_MIN = -(1 << 63)


def wrap_i64(v: int) -> int:
    """Wrap python int into signed-int64 two's-complement range (Go overflow
    semantics differ — reference types/overflow.go errors; we clamp errors at
    the conversion layer and wrap in arithmetic like the columnar path does)."""
    v &= _U64_MASK
    return v - (1 << 64) if v > _I64_MAX else v


def to_int(v: Datum, truncate_ok: bool = True) -> Optional[int]:
    """Convert datum to int64 (reference: types/convert.go ToInt64)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, int):
        return wrap_i64(v)
    if isinstance(v, float):
        # MySQL rounds half away from zero when casting real->int.
        r = int(v + 0.5) if v >= 0 else -int(-v + 0.5)
        return max(_I64_MIN, min(_I64_MAX, r))
    if isinstance(v, (str, bytes)):
        s = v.decode() if isinstance(v, bytes) else v
        s = s.strip()
        # MySQL parses the leading numeric prefix.
        num = _leading_number(s)
        if num is None:
            if not truncate_ok:
                raise ValueError(f"cannot convert {s!r} to int")
            return 0
        # integer-shaped strings must not round-trip through float (loses
        # precision above 2^53)
        if num.lstrip("+-").isdigit():
            return max(_I64_MIN, min(_I64_MAX, int(num)))
        return to_int(float(num))
    raise TypeError(f"bad datum {v!r}")


def to_uint(v: Datum, truncate_ok: bool = True) -> Optional[int]:
    """Convert datum to uint64 range [0, 2^64) (reference: types/convert.go
    ToUint64)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, int):
        if not 0 <= v < (1 << 64):
            raise ValueError(f"constant {v} overflows unsigned bigint")
        return v
    if isinstance(v, float):
        r = int(v + 0.5) if v >= 0 else -int(-v + 0.5)
        if not 0 <= r < (1 << 64):
            raise ValueError(f"constant {v} overflows unsigned bigint")
        return r
    if isinstance(v, (str, bytes)):
        s = (v.decode() if isinstance(v, bytes) else v).strip()
        num = _leading_number(s)
        if num is None:
            if not truncate_ok:
                raise ValueError(f"cannot convert {s!r} to uint")
            return 0
        if num.lstrip("+-").isdigit():
            return to_uint(int(num))
        return to_uint(float(num))
    raise TypeError(f"bad datum {v!r}")


def to_real(v: Datum) -> Optional[float]:
    if v is None:
        return None
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, (str, bytes)):
        s = v.decode() if isinstance(v, bytes) else v
        num = _leading_number(s.strip())
        return float(num) if num is not None else 0.0
    raise TypeError(f"bad datum {v!r}")


def to_string(v: Datum) -> Optional[str]:
    if v is None:
        return None
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, float):
        return format_real(v)
    return str(v)


def format_real(f: float) -> str:
    """MySQL-style float formatting: no trailing .0 for integral values."""
    if f != f or f in (float("inf"), float("-inf")):
        return str(f)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def to_bool(v: Datum) -> Optional[int]:
    """SQL truthiness: nonzero numeric prefix = true (reference:
    expression/expression.go:205 VecEvalBool semantics)."""
    if v is None:
        return None
    return 1 if to_real(v) != 0.0 else 0


def _leading_number(s: str) -> Optional[str]:
    i, n = 0, len(s)
    if i < n and s[i] in "+-":
        i += 1
    start_digits = i
    while i < n and s[i].isdigit():
        i += 1
    if i < n and s[i] == ".":
        i += 1
        while i < n and s[i].isdigit():
            i += 1
    if i < n and s[i] in "eE":
        j = i + 1
        if j < n and s[j] in "+-":
            j += 1
        if j < n and s[j].isdigit():
            i = j
            while i < n and s[i].isdigit():
                i += 1
    text = s[:i]
    if text in ("", "+", "-") or i == start_digits == len(text):
        return None
    try:
        float(text)
        return text
    except ValueError:
        return None


def coerce_for_compare(a: Datum, b: Datum) -> tuple:
    """Coerce two datums to a comparable pair per MySQL comparison rules
    (reference: types/compare.go CompareDatum): NULL handled by caller;
    numeric vs string compares numerically; string vs string binary collate."""
    if isinstance(a, str) and isinstance(b, str):
        return a, b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        # python compares int/float pairs exactly — do NOT lift big ints
        # to float64 (2^63+3 and 2^63+9 both round to the same double)
        return a, b
    if isinstance(a, (int, float)) or isinstance(b, (int, float)):
        return to_real(a), to_real(b)
    return to_string(a), to_string(b)


def datum_compare(a: Datum, b: Datum) -> Optional[int]:
    """3-valued compare: returns -1/0/1, or None if either side is NULL."""
    if a is None or b is None:
        return None
    x, y = coerce_for_compare(a, b)
    if x < y:
        return -1
    if x > y:
        return 1
    return 0


def sort_key(v: Datum):
    """Total-order key for host sorts: NULL first (MySQL ORDER BY semantics)."""
    if v is None:
        return (0, 0)
    if isinstance(v, (int, float)):
        return (1, float(v))
    return (2, v)


def cast_datum(v: Datum, ft: FieldType) -> Datum:
    """Cast a datum to a column's field type on the write path
    (reference: table/column.go CastValue)."""
    if v is None:
        return None
    et = ft.eval_type
    if et is EvalType.INT:
        return to_uint(v) if ft.is_unsigned else to_int(v)
    if et is EvalType.REAL:
        return to_real(v)
    s = to_string(v)
    if ft.flen >= 0 and s is not None and len(s) > ft.flen:
        raise ValueError(f"data too long (len {len(s)} > {ft.flen})")
    return s
