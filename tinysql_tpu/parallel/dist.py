"""Multi-chip distributed query primitives: SPMD over a jax Mesh.

The TPU-native replacement for the reference's distributed communication
backend (SURVEY §2.6/§2.11): KV regions -> mesh shards; coprocessor
scatter-gather (P2) -> data-parallel shard_map; region-sharded operators
(P4/P5) -> the ops/shardops.py sharded tier.  Collectives ride the mesh
axis (ICI on real hardware, host rings on the CPU test mesh); no
NCCL/MPI analogue exists or is needed — XLA inserts the collectives.

What actually ships on this layer today:

- **partial->final aggregation** (P5; PAPERS.md "Global Hash Tables
  Strike Back!", "Partial Partial Aggregates"): each shard reduces its
  row slice to a fixed-shape partial state — segment tables for GROUP
  BY (kernels.fused_segment_aggregate_sharded), scalar accumulator
  lanes for global aggregates (shardops.fused_scalar_aggregate_sharded)
  — merged ONCE over the mesh axis with psum/pmin/pmax.  No shuffle:
  the partial state, not the rows, crosses the interconnect.
- **broadcast join** (P4, small build side): probe rows shard, the
  sorted build side replicates via all_gather, every shard probes
  locally (devpipe's default mesh join; make_broadcast_join_counts is
  the seed demo).
- **shuffle join** (P4, large build side): both sides re-partition BY
  KEY HASH over the mesh with all_to_all (hash_dest_np/_traced +
  exchange_lanes + local_unique_join below, driven by devpipe's
  joinshuf programs), so each shard holds only its hash partition of
  the build table.
- **partitioned build/probe join + semijoin, sharded sort/top-k**
  (ops/shardops.py): the host scatters rows into per-shard blocks with
  THE PR 9 SPILL PARTITIONER (ops/spill.py hash_partition — shard =
  spill partition, one partitioner drives device placement and the
  spill ladder), shards work locally, exact merges (searchsorted rank
  counting, top-k tournaments) happen on-device.

Policy lives here too: session_mesh/sized_mesh gate on
tidb_mesh_parallel and cache Mesh objects; shard_bucket is the
estRows->shard-count launder the planner annotates plans with;
shardable is the per-dispatch row-bucket gate.  The 1-device outcome of
any gate means "run the single-device kernel" — Tier-1 on CPU is byte
identical because every sharded family degenerates to its unsharded
twin below the thresholds.  Every shard_map in the tree is constructed
through shard_map_fn/shard_map_unchecked (qlint DF805 enforces this).
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import numpy as np

from ..ops import kernels


def shard_map_fn():
    """(shard_map, PartitionSpec) with the jax-version fallback in ONE
    place — every mesh kernel imports through here."""
    from jax.sharding import PartitionSpec
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map, PartitionSpec


def shard_map_unchecked(fn, mesh, in_specs, out_specs):
    """shard_map for kernels whose outputs are replicated by construction
    (all_gather + pure compute): the static replication checker cannot
    prove it, so disable it — kwarg name varies by jax version."""
    shard_map, _ = shard_map_fn()
    for kw in ("check_vma", "check_rep"):
        try:
            return shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **{kw: False})
        except TypeError:
            continue
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def make_mesh(n_devices: Optional[int] = None):
    """1-D device mesh over axis 'shard' (DP/region axis)."""
    jax = kernels.jax()
    devs = jax.devices()
    n = n_devices or len(devs)
    from jax.sharding import Mesh
    return Mesh(np.array(devs[:n]), ("shard",))


_SESSION_MESH = None


def session_mesh(session_vars):
    """The query-execution mesh when the session asks for multi-chip
    (tidb_mesh_parallel) and >=2 devices exist; cached per device set.
    Shared by every mesh-parallel tier (fused aggregate, devpipe join)."""
    if not bool(session_vars.get("tidb_mesh_parallel", 0)):
        return None
    devs = kernels.jax().devices()
    if len(devs) < 2:
        return None
    global _SESSION_MESH
    if _SESSION_MESH is None or _SESSION_MESH.devices.size != len(devs):
        _SESSION_MESH = make_mesh(len(devs))
    return _SESSION_MESH


_SIZED_MESHES: dict = {}


def sized_mesh(n_shards: int):
    """A cached k-device submesh (first k devices) for plans whose
    estRows-driven shard count is below the full device set; k < 2
    degenerates to None = run the single-device kernel."""
    if n_shards < 2:
        return None
    devs = kernels.jax().devices()
    k = min(int(n_shards), len(devs))
    if k < 2:
        return None
    m = _SIZED_MESHES.get(k)
    if m is None or m.devices.size != k:
        m = _SIZED_MESHES[k] = make_mesh(k)
    return m


def mesh_shards(mesh) -> int:
    """Shard count of a mesh — THE sanctioned launder from mesh shape to
    progcache-key literal (qlint DF807: mesh-shape scalars must not mint
    program keys except through here / shard_bucket)."""
    return 0 if mesh is None else int(mesh.devices.size)


#: a shard must expect at least this many rows before fan-out pays for
#: the partition scatter + collectives (estRows-driven; the per-dispatch
#: row-bucket gate `shardable` still applies at runtime)
MIN_SHARD_ROWS = 256


def shard_bucket(est_rows: float, n_devices: int) -> int:
    """estRows -> power-of-two shard count <= n_devices: the planner's
    mesh admissibility output and the OTHER sanctioned mesh-shape
    launder.  1 means 'stay single-device' (the degenerate mesh)."""
    n = 1
    est = max(float(est_rows or 0), 0.0)
    while n * 2 <= n_devices and est >= MIN_SHARD_ROWS * (n * 2):
        n *= 2
    return n


def shardable(nb: int, mesh) -> bool:
    """Row-bucket gate for sharding over `mesh`: divisible and big enough
    to amortize the collectives."""
    if mesh is None:
        return False
    n = int(mesh.devices.size)
    return nb % n == 0 and nb >= 16 * n


# =========================================================================
# distributed partial/final aggregation (SURVEY §2.11 P5)
# =========================================================================

def make_sharded_group_sum(mesh, n_buckets: int):
    """Per-shard segment-sum into a fixed bucket table + psum merge: the
    reference's partial workers -> shuffle -> final workers pipeline
    (aggregate.go:55-93) collapsed into one SPMD program.

    Inputs (host-side global shapes): bucket ids int32 [n_shards, rows],
    values f64 [n_shards, rows], valid mask [n_shards, rows].
    Output: per-bucket (sum, count) replicated on every shard.
    """
    jax = kernels.jax()
    jnp = kernels.jnp()
    shard_map, P = shard_map_fn()

    @partial(shard_map, mesh=mesh,
             in_specs=(P("shard", None), P("shard", None), P("shard", None)),
             out_specs=(P(), P()))
    def step(bucket_ids, vals, valid):
        # each shard sees [1, rows]
        b = bucket_ids[0]
        v = jnp.where(valid[0], vals[0], 0.0)
        c = valid[0].astype(jnp.int64)
        partial_sum = jax.ops.segment_sum(v, b, num_segments=n_buckets)
        partial_cnt = jax.ops.segment_sum(c, b, num_segments=n_buckets)
        # ICI all-reduce of partial states (the reduce-scatter schema)
        total = jax.lax.psum(partial_sum, "shard")
        cnt = jax.lax.psum(partial_cnt, "shard")
        return total, cnt

    return kernels.counted_jit(step)


# =========================================================================
# distributed broadcast join (SURVEY §2.11 P4)
# =========================================================================

def make_broadcast_join_counts(mesh):
    """Probe side sharded over the mesh; build side broadcast (all_gather)
    to every shard; each shard counts its local matches; psum gives the
    global match count.  The 'partition build side' variant (hash
    re-sharding via all_to_all) lands with the distributed executor."""
    jax = kernels.jax()
    jnp = kernels.jnp()
    shard_map, P = shard_map_fn()

    @partial(shard_map, mesh=mesh,
             in_specs=(P("shard", None), P("shard", None), P(None)),
             out_specs=(P("shard", None), P()))
    def step(lkeys, lvalid, rkeys_sorted):
        lk = lkeys[0]
        lv = lvalid[0]
        lo = jnp.searchsorted(rkeys_sorted, lk, side="left")
        hi = jnp.searchsorted(rkeys_sorted, lk, side="right")
        counts = jnp.where(lv, hi - lo, 0)
        total = jax.lax.psum(jnp.sum(counts), "shard")
        return counts[None, :], total

    return kernels.counted_jit(step)


# =========================================================================
# hash-partitioned (shuffle) join primitives (SURVEY §2.11 P4 north star:
# "partition build-side tables")
# =========================================================================
# Both sides re-partition BY KEY HASH over the mesh axis with all_to_all
# (ICI on hardware), so every shard holds only its hash partition of the
# build side — build tables larger than one chip's HBM budget become
# servable.  Static shapes: the host computes EXACT per-(source, dest)
# bucket capacities from the raw key lanes (partitioning is value-only,
# pre-filter; filters ride the validity lane through the exchange), so
# the scatter never drops rows.  Padding rows spread round-robin to keep
# the capacity bound tight.

# golden-ratio multiplier (two's-complement int64 of 0x9E3779B97F4A7C15)
HASH_GOLDEN = np.int64(0x9E3779B97F4A7C15 - (1 << 64))


def hash_dest_np(keys: np.ndarray, n_shards: int,
                 n_rows: Optional[int] = None) -> np.ndarray:
    """Destination shard per row — MUST stay bit-identical to
    hash_dest_traced (the host capacity bound relies on it)."""
    with np.errstate(over="ignore"):
        h = keys.astype(np.int64, copy=False) * HASH_GOLDEN
    d = (h >> 33) & (n_shards - 1)
    if n_rows is not None:
        idx = np.arange(len(keys), dtype=np.int64)
        d = np.where(idx < n_rows, d, idx % n_shards)
    return d


def hash_dest_traced(jn, keys, n_shards: int, global_idx, n_rows):
    """Traced twin of hash_dest_np (int64 wrap-around multiply)."""
    h = keys * HASH_GOLDEN
    d = (h >> 33) & (n_shards - 1)
    return jn.where(global_idx < n_rows, d, global_idx % n_shards)


def shuffle_cap(keys_padded: np.ndarray, n_shards: int, n_rows: int) -> int:
    """Power-of-two capacity per (source shard, dest shard) send bucket:
    the exact max block histogram of the destinations."""
    dest = hash_dest_np(keys_padded, n_shards, n_rows)
    per = len(keys_padded) // n_shards
    mx = 1
    for i in range(n_shards):
        c = np.bincount(dest[i * per:(i + 1) * per], minlength=n_shards)
        mx = max(mx, int(c.max()))
    return kernels.bucket(mx)


def exchange_lanes(jn, lanes, dest_local, cap: int, n_shards: int,
                   axis: str = "shard"):
    """Traced, per shard: scatter each lane into an [n, cap] send buffer
    by (dest, rank-within-dest), all_to_all over the mesh axis, return
    flattened [n*cap] received lanes.  lanes = [(array [m], fill)]."""
    from jax import lax
    m = dest_local.shape[0]
    order = jn.argsort(dest_local, stable=True)
    ds = dest_local[order]
    rank = jn.arange(m) - jn.searchsorted(ds, ds, side="left")
    outs = []
    for arr, fill in lanes:
        buf = jn.full((n_shards, cap), fill, dtype=arr.dtype)
        buf = buf.at[ds, rank].set(arr[order], mode="drop")
        r = lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                           tiled=True)
        outs.append(r.reshape(n_shards * cap))
    return outs


def local_unique_join(jn, bk, blive, pk, BN: int):
    """Traced, per shard: sort the received build partition by
    (key, liveness) and probe with searchsorted.  Returns (hit, brow):
    hit[i] = probe key i has a LIVE build row; brow[i] = its position in
    the received build lanes.  Lexicographic sort puts the live row first
    among equal keys, so a dead row never shadows a live one."""
    from jax import lax
    kmask = jn.where(blive, bk, jn.iinfo(jn.int64).max)
    inv = (~blive).astype(jn.int32)
    sk, sinv, sperm = lax.sort(
        (kmask, inv, jn.arange(BN, dtype=jn.int64)), num_keys=2)
    lo = jn.searchsorted(sk, pk, side="left")
    loc = jn.clip(lo, 0, BN - 1)
    hit = (lo < BN) & (sk[loc] == pk) & (sinv[loc] == 0)
    return hit, sperm[loc]


# =========================================================================
# full distributed step (the dryrun/"training step" entry)
# =========================================================================

def distributed_query_step(mesh, n_buckets: int = 64):
    """One fused SPMD 'query step': filter + partial aggregate + psum +
    broadcast-join counts — the whole distributed pipeline the engine's
    multi-chip executor drives, jitted over the mesh."""
    jax = kernels.jax()
    jnp = kernels.jnp()
    agg = make_sharded_group_sum(mesh, n_buckets)
    join = make_broadcast_join_counts(mesh)

    def step(bucket_ids, vals, valid, lkeys, lvalid, rkeys_sorted):
        sums, cnts = agg(bucket_ids, vals, valid)
        counts, total = join(lkeys, lvalid, rkeys_sorted)
        return sums, cnts, counts, total

    return step


def shard_rows(arr: np.ndarray, n_shards: int, fill=0) -> np.ndarray:
    """Host helper: pad + reshape a 1-D array to [n_shards, rows]."""
    n = len(arr)
    per = (n + n_shards - 1) // n_shards
    out = np.full(n_shards * per, fill, dtype=arr.dtype)
    out[:n] = arr
    return out.reshape(n_shards, per)
