"""TINYSQL_RACE_STRESS — the dynamic half of qlint's CC7xx concurrency
pass (tools/race_stress.py is the CLI; tests/conftest.py arms this when
the env var is set).

Static analysis yields PLAUSIBLE findings; this module converts them
into CONFIRMED (or measured-benign) ones by making races overwhelmingly
more likely to fire and by instrumenting the lock catalogue:

- :func:`install` shrinks ``sys.setswitchinterval`` (default 20 us vs
  CPython's 5 ms — thread preemption every few bytecodes) and patches
  ``threading.Lock``/``RLock`` so every lock constructed AFTERWARD is an
  :class:`InstrumentedLock`: per-allocation-site acquire / contention /
  wait / hold accounting, a per-thread held-stack, and a dynamic
  lock-order edge set (the runtime twin of static CC702).
- :func:`audit_known` wraps the catalogued shared module dicts
  (kernels.STATS, progcache registries, admission/fail/prewarm/tsring
  state) in an :class:`AuditDict` that records an UNGUARDED-WRITE report
  whenever a mutation arrives without the owning instrumented lock held
  by the writing thread — the dynamic twin of static CC701.
- :func:`report` / :func:`write_report` publish the whole picture (top
  contended locks, max hold times, dynamic lock-order cycles, unguarded
  writes) — the race-stress CI job uploads it as an artifact.

Counter updates are deliberately lock-free (approximate under extreme
contention): the instrumentation must not serialize the very schedules
it exists to provoke.  Release-by-another-thread (Condition waiter
hand-offs) is tolerated: the holder slot clears, the held-stack entry is
discarded only from the releasing thread's own stack.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

_STATE = {"installed": False, "switch_interval": 0.0}
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

#: allocation site -> aggregate stats (site = "file:line" of the first
#: frame outside threading/queue/this module)
_SITES: Dict[str, dict] = {}
_SITES_MU = _REAL_LOCK()

#: dynamic lock-order edges between allocation sites
_ORDER_EDGES: set = set()

#: unguarded-write reports from AuditDict
_UNGUARDED: List[dict] = []

#: labels successfully wrapped by audit_known
_AUDITED: List[str] = []

_TLS = threading.local()


def _held_stack() -> list:
    st = getattr(_TLS, "held", None)
    if st is None:
        st = _TLS.held = []
    return st


def _alloc_site() -> str:
    skip = (os.sep + "threading.py", os.sep + "queue.py", "racestress.py",
            os.sep + "logging" + os.sep)
    for frame in traceback.extract_stack()[-12:][::-1]:
        fn = frame.filename
        if not any(s in fn for s in skip):
            parts = fn.split(os.sep)
            return "/".join(parts[-3:]) + f":{frame.lineno}"
    return "<unknown>"


def _site_stats(site: str) -> dict:
    st = _SITES.get(site)
    if st is None:
        with _SITES_MU:
            st = _SITES.setdefault(site, {
                "acquires": 0, "contended": 0, "wait_s": 0.0,
                "hold_s": 0.0, "hold_max_s": 0.0})
    return st


class InstrumentedLock:
    """Wrapper around a real lock with site-aggregated accounting.
    Quacks enough like ``threading.Lock`` for ``Condition`` (explicit
    ``_is_owned`` so plain-Lock conditions work; RLock extras delegate
    to the inner lock)."""

    __slots__ = ("_inner", "_stats", "_site", "_holder", "_t0")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site
        self._stats = _site_stats(site)
        self._holder = None
        self._t0 = 0.0

    # ---- the lock protocol ----------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        st = self._stats
        got = self._inner.acquire(False)
        if not got:
            st["contended"] += 1
            if not blocking:
                return False
            t0 = time.perf_counter()
            got = self._inner.acquire(True, timeout)
            st["wait_s"] += time.perf_counter() - t0
            if not got:
                return False
        st["acquires"] += 1
        me = threading.get_ident()
        if self._holder != me:  # first (non-reentrant) level
            self._holder = me
            self._t0 = time.perf_counter()
            held = _held_stack()
            for h in held:
                # edges are SITE-keyed: skip same-site pairs — two
                # DIFFERENT instances born at one `self._mu = Lock()`
                # line nested once would otherwise read as a self-cycle
                if h is not self and h._site != self._site:
                    _ORDER_EDGES.add((h._site, self._site))
            held.append(self)
        return True

    def release(self):
        me = threading.get_ident()
        if self._holder == me:
            st = self._stats
            dt = time.perf_counter() - self._t0
            st["hold_s"] += dt
            if dt > st["hold_max_s"]:
                st["hold_max_s"] = dt
            self._holder = None
        held = getattr(_TLS, "held", None)
        if held is not None and self in held:
            held.remove(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # Condition-protocol hook (plain locks): "owned" == this thread took
    # it through the wrapper and has not released it
    def _is_owned(self):
        return self._holder == threading.get_ident()

    def held_by_current(self) -> bool:
        return self._holder == threading.get_ident()

    def __getattr__(self, name):  # RLock _release_save/_acquire_restore
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<InstrumentedLock {self._site} {self._inner!r}>"


def _make_lock():
    return InstrumentedLock(_REAL_LOCK(), _alloc_site())


def _make_rlock():
    return InstrumentedLock(_REAL_RLOCK(), _alloc_site())


def install(switch_interval: Optional[float] = None) -> None:
    """Arm the stress mode (idempotent): shrink the bytecode switch
    interval and patch the lock constructors.  Locks created BEFORE the
    call stay raw — arm before importing tinysql_tpu modules."""
    if _STATE["installed"]:
        return
    if switch_interval is None:
        switch_interval = float(os.environ.get(
            "TINYSQL_RACE_STRESS_SWITCH", "2e-5"))
    sys.setswitchinterval(switch_interval)
    _STATE["switch_interval"] = switch_interval
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    _STATE["installed"] = True


class AuditDict(dict):
    """dict whose mutations must arrive with the owning instrumented
    lock held by the writing thread; violations are recorded (never
    raised — the suite must finish so the report is complete)."""

    __slots__ = ("_guard", "_label")

    def __init__(self, src, guard, label: str):
        super().__init__(src)
        self._guard = guard
        self._label = label

    def _check(self):
        g = self._guard
        if g is not None and not g.held_by_current():
            frames = [f"{'/'.join(f.filename.split(os.sep)[-3:])}"
                      f":{f.lineno}"
                      for f in traceback.extract_stack()[-6:-2]]
            _UNGUARDED.append({
                "state": self._label,
                "thread": threading.current_thread().name,
                "stack": frames})

    def __setitem__(self, k, v):
        self._check()
        dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        self._check()
        dict.__delitem__(self, k)

    def update(self, *a, **kw):
        self._check()
        dict.update(self, *a, **kw)

    def pop(self, *a):
        self._check()
        return dict.pop(self, *a)

    def popitem(self):
        self._check()
        return dict.popitem(self)

    def clear(self):
        self._check()
        dict.clear(self)

    def setdefault(self, k, d=None):
        self._check()
        return dict.setdefault(self, k, d)


#: the audited-state catalogue: (module, dict attr, guard lock attr).
#: Exactly the guard relationships qlint CC701 infers statically.
AUDIT_CATALOG = [
    ("tinysql_tpu.ops.kernels", "STATS", "_STATS_MU"),
    ("tinysql_tpu.ops.progcache", "STATS", "_mu"),
    ("tinysql_tpu.ops.progcache", "_REG", "_mu"),
    ("tinysql_tpu.ops.progcache", "_CATALOG", "_mu"),
    ("tinysql_tpu.server.admission", "STATS", "_mu"),
    ("tinysql_tpu.server.admission", "CONN_STATS", "_mu"),
    ("tinysql_tpu.session.prewarm", "PREWARM_STATS", "_STATS_MU"),
    ("tinysql_tpu.obs.tsring", "_SOURCES", "_src_mu"),
    ("tinysql_tpu.fail", "_ACTIVE", "_mu"),
    ("tinysql_tpu.fail", "_HITS", "_mu"),
]


def audit_known() -> List[str]:
    """Wrap every catalogued shared dict whose guard lock came out of
    the instrumented constructors.  Returns the labels wrapped."""
    import importlib
    wrapped = []
    for modname, dname, lname in AUDIT_CATALOG:
        try:
            mod = importlib.import_module(modname)
        except Exception:
            continue
        d = getattr(mod, dname, None)
        g = getattr(mod, lname, None)
        if not isinstance(d, dict) or isinstance(d, AuditDict) \
                or not isinstance(g, InstrumentedLock):
            continue
        label = f"{modname}.{dname}"
        setattr(mod, dname, AuditDict(d, g, label))
        wrapped.append(label)
    _AUDITED.extend(wrapped)
    return wrapped


def _order_cycles() -> List[List[str]]:
    """Cycles in the dynamically observed lock-order graph."""
    edges: Dict[str, set] = {}
    for a, b in _ORDER_EDGES:
        edges.setdefault(a, set()).add(b)
    cycles: List[List[str]] = []
    seen_keys = set()
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(u: str) -> None:
        color[u] = 1
        stack.append(u)
        for v in sorted(edges.get(u, ())):
            if color.get(v, 0) == 0:
                dfs(v)
            elif color.get(v) == 1:
                cyc = stack[stack.index(v):] + [v]
                key = frozenset(cyc)
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(cyc)
        stack.pop()
        color[u] = 2

    for u in sorted(edges):
        if color.get(u, 0) == 0:
            dfs(u)
    return cycles


def report() -> dict:
    """The full stress report (JSON-able)."""
    with _SITES_MU:
        sites = {k: dict(v) for k, v in _SITES.items()}
    locks = [dict(site=site, **st) for site, st in sites.items()]
    locks.sort(key=lambda r: (-r["contended"], -r["hold_max_s"]))
    for r in locks:
        for k in ("wait_s", "hold_s", "hold_max_s"):
            r[k] = round(r[k], 6)
    return {
        "installed": _STATE["installed"],
        "switch_interval": _STATE["switch_interval"],
        "locks_instrumented": len(locks),
        "locks": locks,
        "lock_order_edges": len(_ORDER_EDGES),
        "lock_order_cycles": _order_cycles(),
        "audited_state": list(_AUDITED),
        "unguarded_writes": list(_UNGUARDED[:200]),
        "unguarded_write_count": len(_UNGUARDED),
    }


def write_report(path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=2, sort_keys=True)
