"""TINYSQL_XFER_AUDIT — the dynamic half of qlint's DF8xx device-dataflow
pass (tools/transfer_audit.py is the CLI; tests/conftest.py arms this
when the env var is set), built in the racestress mold.

The static pass proves no SOURCE LINE performs an uncounted transfer;
this module proves no RUNTIME transfer escapes the counters, closing the
gap the AST cannot see (dynamic dispatch, jax-internal fallbacks, code
the batch didn't include):

- :func:`install` interposes jax's transfer entry points —
  ``jax.device_put`` / ``jax.device_get``, the implicit-upload
  ``jax.numpy.asarray`` / ``jax.numpy.array`` (host operand, outside a
  trace), and ``ArrayImpl.__array__`` (every ``np.asarray(dev)``
  download lands there) — recording one EVENT per observed transfer
  with a stack-derived attribution:

  * **sanctioned** — a ``kernels.h2d`` / ``h2d_pad`` / ``d2h`` /
    ``d2h_many`` frame is on the stack: the transfer is counted.
  * **engine** — a ``tinysql_tpu/`` frame is on the stack but no
    sanctioned wrapper: an UNCOUNTED transfer (the DF801/DF802 runtime
    twin).  Any such event is a divergence.
  * **harness** — only test/driver frames: tests poking device arrays
    directly; tallied, excluded from divergence.

- A lazily-attached shadow of ``kernels.stats_add`` accumulates every
  ``h2d_transfers`` / ``d2h_transfers`` increment (reset-proof, unlike
  reading STATS at the end).  Conservation: the sanctioned event count
  must equal the counter increments EXACTLY — each wrapper performs one
  real transfer per bump.
- :func:`report` / :func:`write_report` publish events, the uncounted
  list (with stack signatures), the counter shadow, and the divergence
  verdict — the transfer-audit CI job uploads it as an artifact.

Arm BEFORE importing tinysql_tpu (conftest does) so the kernels module
resolves ``jnp().asarray`` to the interposed functions at call time.
Recording is deliberately cheap-but-locked: transfer frequency is
orders below lock frequency, so a mutex here cannot serialize anything
the race-stress mode cares about.
"""
from __future__ import annotations

import json
import os
import threading
import traceback
from typing import Dict, List, Optional

_STATE = {"installed": False, "attached": False}
_MU = threading.Lock()
_TLS = threading.local()

#: observed transfer events (bounded detail; totals are exact)
_EVENTS: List[dict] = []
_EVENT_DETAIL_CAP = 400
#: kind -> {"sanctioned": n, "engine": n, "harness": n}
_TOTALS: Dict[str, Dict[str, int]] = {
    "h2d": {"sanctioned": 0, "engine": 0, "harness": 0},
    "d2h": {"sanctioned": 0, "engine": 0, "harness": 0},
}
#: shadow of every stats_add increment on the transfer counters
_COUNTED: Dict[str, float] = {"h2d_transfers": 0, "d2h_transfers": 0,
                              "h2d_bytes": 0, "d2h_bytes": 0}

#: the counted-wrapper frames that sanction an observed transfer
_SANCTIONED_FNS = {"h2d", "h2d_pad", "d2h", "d2h_many"}
_KERNELS_FILE = os.sep + os.path.join("ops", "kernels.py")
_PKG_DIR = os.sep + "tinysql_tpu" + os.sep
_SELF_FILE = "xferaudit.py"


def _depth() -> int:
    return getattr(_TLS, "depth", 0)


class _reenter:
    """Nested interposed calls (asarray -> device_put) record once."""

    def __enter__(self):
        _TLS.depth = _depth() + 1
        return self

    def __exit__(self, *exc):
        _TLS.depth = _depth() - 1
        return False


def _classify() -> tuple:
    """(attribution, site) from the current stack: sanctioned / engine /
    harness, plus the innermost attributable frame."""
    site = "<unknown>"
    engine = False
    frames = traceback.extract_stack()
    for f in frames[::-1]:
        fn = f.filename
        if _SELF_FILE in fn:
            continue
        if fn.endswith(_KERNELS_FILE) and f.name in _SANCTIONED_FNS:
            parts = fn.split(os.sep)
            return "sanctioned", "/".join(parts[-3:]) + f":{f.lineno}"
        if _PKG_DIR in fn and not engine:
            engine = True
            parts = fn.split(os.sep)
            site = "/".join(parts[-3:]) + f":{f.lineno}"
    if engine:
        return "engine", site
    for f in frames[::-1]:
        fn = f.filename
        if _SELF_FILE in fn or os.sep + "jax" in fn \
                or os.sep + "numpy" in fn:
            continue
        parts = fn.split(os.sep)
        site = "/".join(parts[-3:]) + f":{f.lineno}"
        break
    return "harness", site


def _record(kind: str, nbytes: int) -> None:
    _ensure_attached()
    attr, site = _classify()
    with _MU:
        _TOTALS[kind][attr] += 1
        if len(_EVENTS) < _EVENT_DETAIL_CAP or attr == "engine":
            ev = {"kind": kind, "attr": attr, "site": site,
                  "bytes": int(nbytes)}
            if attr == "engine":
                ev["stack"] = [
                    "/".join(f.filename.split(os.sep)[-3:]) + f":{f.lineno}"
                    for f in traceback.extract_stack()[-10:-3]]
            _EVENTS.append(ev)


def _nbytes(x) -> int:
    try:
        return int(getattr(x, "nbytes", 0))
    except Exception:
        return 0


def _is_device_value(x) -> bool:
    import jax
    return isinstance(x, jax.Array)


def install() -> None:
    """Interpose the jax transfer entry points (idempotent).  Safe to
    call before any tinysql_tpu import — only jax is touched here."""
    if _STATE["installed"]:
        return
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax._src.array import ArrayImpl

    real_asarray = jnp.asarray
    real_array = jnp.array
    real_device_put = jax.device_put
    real_device_get = jax.device_get
    real_dunder_array = ArrayImpl.__array__
    real_np_asarray = np.asarray
    real_np_array = np.array

    def _traced() -> bool:
        try:
            return not jax.core.trace_state_clean()
        except Exception:
            return False

    def _upload_wrapper(real):
        def wrapped(a, *args, **kwargs):
            if _depth() == 0 and not _traced() and not _is_device_value(a):
                _record("h2d", _nbytes(a))
            with _reenter():
                return real(a, *args, **kwargs)
        wrapped.__name__ = real.__name__
        return wrapped

    def device_put(x, *args, **kwargs):
        if _depth() == 0 and not _traced():
            _record("h2d", _nbytes(x))
        with _reenter():
            return real_device_put(x, *args, **kwargs)

    def device_get(x, *args, **kwargs):
        if _depth() == 0:
            _record("d2h", 0)  # bytes land on the host side afterward
        with _reenter():
            return real_device_get(x, *args, **kwargs)

    def dunder_array(self, *args, **kwargs):
        if _depth() == 0 and not _traced():
            _record("d2h", _nbytes(self))
        with _reenter():
            return real_dunder_array(self, *args, **kwargs)

    def _download_wrapper(real):
        # on CPU jax, numpy converts ArrayImpl via the C buffer protocol
        # — __array__ never fires — so np.asarray(dev) downloads must be
        # caught at the numpy MODULE attribute (python call sites only;
        # C-internal conversions like np.ascontiguousarray(dev) stay
        # invisible, which is why kernels.d2h is the sanctioned spelling)
        def wrapped(a, *args, **kwargs):
            if _depth() == 0 and isinstance(a, jax.Array) \
                    and not isinstance(a, jax.core.Tracer):
                _record("d2h", _nbytes(a))
            with _reenter():
                return real(a, *args, **kwargs)
        wrapped.__name__ = real.__name__
        return wrapped

    jnp.asarray = _upload_wrapper(real_asarray)
    jnp.array = _upload_wrapper(real_array)
    jax.device_put = device_put
    jax.device_get = device_get
    ArrayImpl.__array__ = dunder_array
    np.asarray = _download_wrapper(real_np_asarray)
    np.array = _download_wrapper(real_np_array)
    _STATE["installed"] = True


def _ensure_attached() -> None:
    """Shadow kernels.stats_add once the module exists (it is imported
    AFTER install() arms — conftest order), so every transfer-counter
    increment is mirrored reset-proof."""
    if _STATE["attached"]:
        return
    import sys
    kernels = sys.modules.get("tinysql_tpu.ops.kernels")
    if kernels is None:
        return
    with _MU:
        if _STATE["attached"]:
            return
        real_stats_add = kernels.stats_add

        def stats_add(key, n=1):
            if key in _COUNTED:
                with _MU:
                    _COUNTED[key] += n
            return real_stats_add(key, n)

        kernels.stats_add = stats_add
        _STATE["attached"] = True


def report() -> dict:
    """The full audit (JSON-able) with the divergence verdict:

    - any ENGINE-attributed event is an uncounted transfer -> diverged;
    - sanctioned event counts must equal the counter-increment shadow
      (one real transfer per bump) -> any mismatch diverged.
    """
    with _MU:
        totals = {k: dict(v) for k, v in _TOTALS.items()}
        counted = dict(_COUNTED)
        uncounted = [e for e in _EVENTS if e["attr"] == "engine"]
        events = list(_EVENTS[:_EVENT_DETAIL_CAP])
    reasons: List[str] = []
    if totals["h2d"]["engine"] or totals["d2h"]["engine"]:
        reasons.append(
            f"uncounted engine transfers: "
            f"h2d={totals['h2d']['engine']} d2h={totals['d2h']['engine']}")
    for kind, key in (("h2d", "h2d_transfers"), ("d2h", "d2h_transfers")):
        if totals[kind]["sanctioned"] != int(counted[key]):
            reasons.append(
                f"{key} counter ({int(counted[key])}) != observed "
                f"sanctioned {kind} events ({totals[kind]['sanctioned']})")
    return {
        "installed": _STATE["installed"],
        "attached": _STATE["attached"],
        "observed": totals,
        "counted": {k: int(v) for k, v in counted.items()},
        "uncounted_transfers": uncounted[:200],
        "uncounted_count": len(uncounted),
        "events_detail": events,
        "divergence": bool(reasons),
        "divergence_reasons": reasons,
    }


def write_report(path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=2, sort_keys=True)
