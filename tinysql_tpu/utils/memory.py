"""Per-query memory quota (reference: util/memory.Tracker +
``tidb_mem_quota_query`` with the CANCEL OOM action).

A statement whose session sets ``tidb_mem_quota_query > 0`` runs with a
:class:`MemTracker` installed in a contextvar; the chunk layer
(chunk/column.py) charges every column-buffer allocation —
``Column.__init__`` capacity, ``_grow`` deltas, ``from_numpy``
materializations — against it.  Blowing the quota raises
:class:`MemQuotaExceeded` (MySQL error 8175), aborting the statement
through the session's normal error path instead of letting a hash build
or sort materialization OOM the process.

Accounting model: CUMULATIVE bytes allocated into chunk columns over
the statement (buffers are not released back on operator close).  That
is stricter than a live-set tracker for long streaming plans — the
documented trade for a dependency-free implementation; zero-copy views
(``Column.wrap_raw`` over replica arrays) are never charged.
"""
from __future__ import annotations

import contextvars
import threading
from typing import Optional

#: process-total statements aborted by quota (exported to /metrics)
_aborts_mu = threading.Lock()
_ABORTS = 0


class MemQuotaExceeded(Exception):
    """TiDB error 8175 (ErrMemoryExceedForQuery)."""
    mysql_code = 8175
    sqlstate = "HY000"

    def __init__(self, consumed: int, quota: int):
        super().__init__(
            "Out Of Memory Quota! query tried to allocate "
            f"{consumed} bytes with tidb_mem_quota_query = {quota}")
        self.consumed = consumed
        self.quota = quota


class MemTracker:
    """Byte accumulator with a hard quota.  ``consume`` is called from
    the statement thread and any pipeline producer threads (context is
    copied across).  With a quota armed it locks (the abort decision
    must see a consistent total); with quota 0 — the always-installed
    tracker feeding ``processlist.mem_bytes`` — it is a bare ``+=``:
    display-only accounting tolerates the rare torn update under
    producer threads, and the hot allocation path stays lock-free."""

    __slots__ = ("quota", "consumed", "_aborted", "_mu")

    def __init__(self, quota: int):
        self.quota = int(quota)
        self.consumed = 0
        self._aborted = False
        self._mu = threading.Lock()

    def consume(self, n: int) -> None:
        global _ABORTS
        if n <= 0:
            return
        if self.quota <= 0:
            self.consumed += n
            return
        with self._mu:
            self.consumed += n
            over = 0 < self.quota < self.consumed
            consumed = self.consumed
            # the statement-abort counter counts STATEMENTS: the first
            # over-quota consume trips it; re-raises while the doomed
            # statement unwinds (producer thread, cleanup allocs) don't
            first = over and not self._aborted
            if over:
                self._aborted = True
        if over:
            if first:
                with _aborts_mu:
                    _ABORTS += 1
            raise MemQuotaExceeded(consumed, self.quota)


_TRACKER: contextvars.ContextVar = contextvars.ContextVar(
    "tinysql_mem_tracker", default=None)


def activate(tracker: MemTracker):
    return _TRACKER.set(tracker)


def deactivate(token) -> None:
    _TRACKER.reset(token)


def current() -> Optional[MemTracker]:
    return _TRACKER.get()


def consume(n: int) -> None:
    """The allocation hook: charges the active statement's tracker —
    one contextvar read plus a lock-free ``+=`` without a quota (the
    session installs a quota-0 tracker for every statement so
    ``processlist`` can report live bytes), the locked quota path
    otherwise; a bare contextvar read outside any statement."""
    t = _TRACKER.get()
    if t is not None:
        t.consume(n)


def aborts_total() -> int:
    with _aborts_mu:
        return _ABORTS
