"""Per-query memory quota (reference: util/memory.Tracker +
``tidb_mem_quota_query`` with the CANCEL OOM action) — now with LIVE-SET
accounting and a soft spill watermark.

A statement always runs with a :class:`MemTracker` installed in a
contextvar; the chunk layer (chunk/column.py) charges every column-buffer
allocation — ``Column.__init__`` capacity, ``_grow`` deltas,
``from_numpy`` materializations — against it and RELEASES the charge when
the buffer is freed (``Column.__del__`` / ``free``), so ``consumed`` is
the statement's live working set, not a monotonic allocation total.
``peak`` keeps the high-water mark for ``statements_summary``.

Two thresholds, one graceful-degradation ladder:

- ``spill_watermark`` (``tidb_mem_quota_spill_ratio`` × the quota): a SOFT
  line.  Crossing it flips ``spill_requested()`` true and fires any
  registered pressure callbacks — spill-capable operators (ops/spill.py:
  hybrid hash join, hash agg, sort/topn) switch into partitioned spill
  mode instead of dying, turning the quota into a working-set bound.
- ``quota`` (``tidb_mem_quota_query``): the HARD line.  Before raising,
  ``consume`` gives registered pressure callbacks one chance to evict
  (spill partitions release through :meth:`release`); only if the total
  is still over does :class:`MemQuotaExceeded` (MySQL error 8175) abort
  the statement — the true last resort after recursive-repartition
  exhaustion in the spill layer.

Zero-copy views (``Column.wrap_raw`` over replica arrays) are never
charged.  The spill layer's own partition buffers charge through
``consume_soft`` (track + watermark, never raise): the layer whose job
is REDUCING pressure must not be killed by its own bookkeeping.

:func:`soft_scope` extends the same exemption to a spill-mode operator's
INPUT materialization: a cold scan (no replica to serve zero-copy views)
must accumulate the child's chunks into one charged buffer before the
partitioner can take over and release it — killing the statement inside
that transient would defeat the spill it was about to perform.  Charges
made inside the scope route through ``consume_soft`` (tracked, visible
in ``peak``/processlist, watermark still fires); the very next hard
``consume`` outside the scope re-enforces the quota against the full
live set.  The scope rides a contextvar, so pipeline producer threads
(spawned under a copied context) inherit it.
"""
from __future__ import annotations

import contextvars
import threading
from typing import Callable, List, Optional

#: process-total statements aborted by quota (exported to /metrics)
_aborts_mu = threading.Lock()
_ABORTS = 0

#: depth of the active soft-ingest scope (see :func:`soft_scope`)
_SOFT_SCOPE: contextvars.ContextVar = contextvars.ContextVar(
    "tinysql_mem_soft_scope", default=0)


class MemQuotaExceeded(Exception):
    """TiDB error 8175 (ErrMemoryExceedForQuery)."""
    mysql_code = 8175
    sqlstate = "HY000"

    def __init__(self, consumed: int, quota: int, detail: str = ""):
        super().__init__(
            "Out Of Memory Quota! query tried to allocate "
            f"{consumed} bytes with tidb_mem_quota_query = {quota}"
            + (f" ({detail})" if detail else ""))
        self.consumed = consumed
        self.quota = quota


class MemTracker:
    """Live-byte accumulator with a hard quota and a soft spill
    watermark.  ``consume``/``release`` are called from the statement
    thread and any pipeline producer threads (context is copied across).
    With a quota armed it locks (the abort decision must see a consistent
    total); with quota 0 — the always-installed tracker feeding
    ``processlist.mem_bytes`` — it is a bare ``+=``: display-only
    accounting tolerates the rare torn update under producer threads, and
    the hot allocation path stays lock-free."""

    __slots__ = ("quota", "consumed", "peak", "spill_watermark",
                 "spill_engaged", "_spill_live", "_aborted", "_spilling",
                 "_in_evict", "_cbs", "_mu")

    def __init__(self, quota: int, spill_watermark: int = 0):
        self.quota = int(quota)
        self.consumed = 0
        self.peak = 0
        #: soft line (bytes); 0 = no watermark (spill only when forced)
        self.spill_watermark = int(spill_watermark)
        #: sticky: a spill ROUTE ran for this statement (SpillContext
        #: marks it at route entry).  From then on the hard abort defers
        #: to the spill layer's ladder (typed 8175 at
        #: recursive-repartition exhaustion) — the statement chose
        #: graceful degradation, so transient over-quota staging
        #: (ingest, key extraction, output assembly over a still-live
        #: materialized input) must not kill it.  A context that opens
        #: and closes WITHOUT running a route (sort/topn single-run,
        #: agg falling back to sort-based grouping) does NOT engage:
        #: hard enforcement resumes at its close.
        self.spill_engaged = False
        #: live SpillContext count: the abort also defers while one is
        #: open (its staging is in flight even before the route runs)
        self._spill_live = 0
        self._aborted = False
        self._spilling = False     # watermark crossed at least once
        self._in_evict = False     # re-entrancy guard for callbacks
        self._cbs: List[Callable[[], None]] = []
        self._mu = threading.Lock()

    # ---- pressure callbacks (ops/spill.py registers) --------------------
    def on_pressure(self, cb: Callable[[], None]) -> None:
        """Register a spill callback: invoked (outside the lock) when the
        soft watermark is crossed and again as a last chance before a
        hard-quota abort.  Callbacks must be idempotent and must only
        FREE memory (via :meth:`release`), never allocate unboundedly."""
        with self._mu:
            if cb not in self._cbs:
                self._cbs.append(cb)

    def remove_pressure(self, cb) -> None:
        with self._mu:
            try:
                self._cbs.remove(cb)
            except ValueError:
                pass

    # ---- spill engagement (ops/spill.SpillContext drives) ---------------
    def spill_enter(self) -> None:
        """A SpillContext opened: defer the hard abort while it lives."""
        with self._mu:
            self._spill_live += 1

    def spill_exit(self) -> None:
        with self._mu:
            if self._spill_live > 0:
                self._spill_live -= 1

    def spill_engage(self) -> None:
        """A spill route actually ran: the deferral becomes sticky (the
        route's output assembly outlives its context)."""
        self.spill_engaged = True

    def spill_requested(self) -> bool:
        """True once live bytes crossed the soft watermark — operators
        poll this at block boundaries to flip into spill mode."""
        if self._spilling:
            return True
        return (self.spill_watermark > 0
                and self.consumed >= self.spill_watermark)

    def headroom(self) -> int:
        """Bytes left below the soft watermark (0 when none / no
        watermark armed) — the spill layer's resident-partition budget."""
        if self.spill_watermark <= 0:
            return 0
        return max(self.spill_watermark - self.consumed, 0)

    # ---- accounting ------------------------------------------------------
    def consume(self, n: int) -> None:
        global _ABORTS
        if n <= 0:
            return
        if _SOFT_SCOPE.get():
            # spill-mode ingest transient (see soft_scope): tracked, never
            # aborts — the partitioner releases it right after
            self.consume_soft(n)
            return
        if self.quota <= 0:
            self.consumed += n
            if self.consumed > self.peak:
                self.peak = self.consumed
            return
        with self._mu:
            self.consumed += n
            if self.consumed > self.peak:
                self.peak = self.consumed
            over = self.quota < self.consumed
            cross = (not self._spilling and self.spill_watermark > 0
                     and self.consumed >= self.spill_watermark)
            if cross:
                self._spilling = True
            cbs = list(self._cbs) if (over or cross) else ()
        # callbacks run OUTSIDE the lock: they release() through us
        if cbs and not self._in_evict:
            self._in_evict = True
            try:
                for cb in cbs:
                    try:
                        cb()
                    except MemQuotaExceeded:
                        raise
                    except Exception:
                        pass  # a broken spiller must not mask the abort
            finally:
                self._in_evict = False
        if not over:
            return
        if self._in_evict:
            # an eviction callback's own transient allocations must not
            # abort the statement mid-spill; the post-evict re-check in
            # the frame that triggered eviction still enforces the quota
            return
        with self._mu:
            still_over = self.quota < self.consumed
            consumed = self.consumed
            engaged = self.spill_engaged or self._spill_live > 0
        if still_over and engaged:
            # this statement engaged memory-adaptive execution (a spill
            # context is live, or a spill route already ran) and the
            # evictors had their chance: what remains over quota is
            # staging the spill layer owns — ingest accumulation,
            # whole-input key extraction, output assembly over a
            # still-live materialized input.  The abort defers to that
            # layer's ladder (recursive repartition -> typed 8175 at
            # exhaustion); statements that never engage keep the
            # immediate hard kill below.
            return
        with self._mu:
            still_over = self.quota < self.consumed
            consumed = self.consumed
            # the statement-abort counter counts STATEMENTS: the first
            # over-quota consume trips it; re-raises while the doomed
            # statement unwinds (producer thread, cleanup allocs) don't
            first = still_over and not self._aborted
            if still_over:
                self._aborted = True
        if still_over:
            if first:
                with _aborts_mu:
                    _ABORTS += 1
            raise MemQuotaExceeded(consumed, self.quota)

    def consume_soft(self, n: int) -> None:
        """Track ``n`` bytes without ever raising: the spill layer's own
        partition residency.  Watermark state still updates so
        ``spill_requested`` / ``headroom`` see the true live set, and
        crossing the watermark fires the pressure callbacks once (so a
        spill layer whose own residency is the pressure evicts itself)."""
        if n <= 0:
            return
        if self.quota <= 0:
            self.consumed += n
            if self.consumed > self.peak:
                self.peak = self.consumed
            return
        with self._mu:
            self.consumed += n
            if self.consumed > self.peak:
                self.peak = self.consumed
            cross = (not self._spilling and self.spill_watermark > 0
                     and self.consumed >= self.spill_watermark)
            if cross:
                self._spilling = True
            cbs = list(self._cbs) if cross else ()
        if cbs and not self._in_evict:
            self._in_evict = True
            try:
                for cb in cbs:
                    try:
                        cb()
                    except Exception:
                        pass
            finally:
                self._in_evict = False

    def release(self, n: int) -> None:
        """Return ``n`` bytes to the budget (buffer freed / partition
        spilled out).  Floored at 0: over-release from mismatched pairing
        must not wrap the live set negative."""
        if n <= 0:
            return
        if self.quota <= 0:
            c = self.consumed - n
            self.consumed = c if c > 0 else 0
            return
        with self._mu:
            c = self.consumed - n
            self.consumed = c if c > 0 else 0


_TRACKER: contextvars.ContextVar = contextvars.ContextVar(
    "tinysql_mem_tracker", default=None)


def activate(tracker: MemTracker):
    return _TRACKER.set(tracker)


def deactivate(token) -> None:
    _TRACKER.reset(token)


def current() -> Optional[MemTracker]:
    return _TRACKER.get()


def consume(n: int) -> None:
    """The allocation hook: charges the active statement's tracker —
    one contextvar read plus a lock-free ``+=`` without a quota (the
    session installs a quota-0 tracker for every statement so
    ``processlist`` can report live bytes), the locked quota path
    otherwise; a bare contextvar read outside any statement."""
    t = _TRACKER.get()
    if t is not None:
        t.consume(n)


def consume_tracked(n: int) -> Optional[MemTracker]:
    """Charge ``n`` bytes and return the tracker that was charged (None
    outside any statement) — the chunk layer pairs the release against
    the SAME tracker at buffer free, so a column outliving its statement
    can never corrupt a later statement's books."""
    t = _TRACKER.get()
    if t is not None and n > 0:
        t.consume(n)
    return t


class soft_scope:
    """``with memory.soft_scope():`` — charges inside route through
    :meth:`MemTracker.consume_soft` (tracked + watermark, never 8175).
    Used by spill-mode operators around the input-materialization copies
    (_drain_chunk accumulator growth, the materialization ``compact()``)
    that the partitioner immediately consumes and releases; everything
    else in the subtree keeps hard enforcement.  Nestable; thread-safe
    via contextvar (producer threads under copied contexts inherit)."""

    __slots__ = ("_tok",)

    def __enter__(self):
        self._tok = _SOFT_SCOPE.set(_SOFT_SCOPE.get() + 1)
        return self

    def __exit__(self, *exc):
        _SOFT_SCOPE.reset(self._tok)
        return False


def aborts_total() -> int:
    with _aborts_mu:
        return _ABORTS
