"""Back-compat shim over the ``tinysql_tpu.fail`` registry.

The original failpoint library grew into a full package (``fail/`` —
catalogue, env/sysvar arming, action verbs, hit counters); existing
call sites and tests keep this module's surface.  New code should import
``tinysql_tpu.fail`` directly.
"""
from __future__ import annotations

import contextlib
from typing import Any, Optional

from .. import fail

disable_point = fail.disarm
disable_all = fail.disarm_all
inject = fail.inject
eval = fail.eval_point       # noqa: A001 - mirrors failpoint.Eval


@contextlib.contextmanager
def enable(name: str, value: Any = True,
           exc: Optional[Exception] = None, times: int = -1):
    """The OLD positional signature — (name, value, exc, times) — which
    ``fail.armed`` no longer matches (it grew sleep/panic between exc
    and times); aliasing it would silently rebind a positional ``times``
    as a sleep duration."""
    with fail.armed(name, value=value, exc=exc, times=times):
        yield


def enable_point(name: str, value: Any = True,
                 exc: Optional[Exception] = None, times: int = -1) -> None:
    fail.arm(name, value=value, exc=exc, times=times)


def enable_times(name: str, value: Any = True,
                 exc: Optional[Exception] = None, times: int = 1) -> None:
    fail.arm(name, value=value, exc=exc, times=times)
