"""Failpoint injection library (reference: pingcap/failpoint — 20 inject
sites across the reference, SURVEY §5.3).

Usage at an inject site:
    failpoint.inject("commitFailed")          # raises if enabled w/ error
    if failpoint.eval("rpcHang"):             # truthy value if enabled
        ...
Tests:
    with failpoint.enable("commitFailed", exc=IOError("boom")): ...
    failpoint.enable_times("x", exc=..., times=2)  # fire twice then off
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Optional

_mu = threading.Lock()
_points: Dict[str, dict] = {}


def enable_point(name: str, value: Any = True, exc: Optional[Exception] = None,
                 times: int = -1) -> None:
    with _mu:
        _points[name] = {"value": value, "exc": exc, "times": times}


def disable_point(name: str) -> None:
    with _mu:
        _points.pop(name, None)


def disable_all() -> None:
    with _mu:
        _points.clear()


@contextlib.contextmanager
def enable(name: str, value: Any = True, exc: Optional[Exception] = None,
           times: int = -1):
    enable_point(name, value, exc, times)
    try:
        yield
    finally:
        disable_point(name)


def _consume(name: str) -> Optional[dict]:
    with _mu:
        p = _points.get(name)
        if p is None:
            return None
        if p["times"] == 0:
            return None
        if p["times"] > 0:
            p["times"] -= 1
        return p


def eval(name: str) -> Any:  # noqa: A001 - mirrors failpoint.Eval
    p = _consume(name)
    if p is None:
        return None
    if p["exc"] is not None:
        raise p["exc"]
    return p["value"]


def inject(name: str) -> None:
    eval(name)
