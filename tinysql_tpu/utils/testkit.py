"""TestKit: the SQL-level integration-test fixture.

Capability parity with reference util/testkit/testkit.go:23-60 —
MustExec / MustQuery().Check(rows) against an in-process session on mock
storage; the dominant test pattern across the reference suite.
"""
from __future__ import annotations

from typing import List, Optional

from ..mytypes import to_string
from ..session.session import Session, new_session


def rows(*lines: str) -> List[List[str]]:
    """reference: testkit.Rows — each line is space-separated fields."""
    return [line.split() for line in lines]


class QueryResult:
    def __init__(self, columns, data):
        self.columns = columns
        self.data = data

    def check(self, expected: List[List[str]]) -> None:
        got = self.sorted_str() if False else self.as_str()
        if got != expected:
            raise AssertionError(
                f"query result mismatch:\n got: {got}\nwant: {expected}")

    def check_sorted(self, expected: List[List[str]]) -> None:
        got = sorted(self.as_str())
        if got != sorted(expected):
            raise AssertionError(
                f"query result mismatch (sorted):\n got: {got}\nwant: {expected}")

    def as_str(self) -> List[List[str]]:
        return [[("<nil>" if v is None else to_string(v)) for v in row]
                for row in self.data]

    def sorted_str(self):
        return sorted(self.as_str())


class TestKit:
    __test__ = False  # not a pytest class

    def __init__(self, storage=None, db: str = ""):
        self.session: Session = new_session(storage, db)

    def must_exec(self, sql: str) -> None:
        self.session.execute(sql)

    def must_query(self, sql: str) -> QueryResult:
        rs = self.session.query(sql)
        return QueryResult(rs.columns, rs.rows)

    def exec_err(self, sql: str) -> Exception:
        try:
            self.session.execute(sql)
        except Exception as e:
            return e
        raise AssertionError(f"expected error for {sql!r}")
