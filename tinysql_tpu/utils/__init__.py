"""Cross-cutting utilities (reference: util/*)."""
from . import failpoint

__all__ = ["failpoint"]
