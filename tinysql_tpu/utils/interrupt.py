"""Statement interruption: per-session kill flag + execution deadline.

The volcano interruption design (reference: executor/executor.go
``handleNoDelay``/killed-flag checks inside Next loops, plus
expensivequery.go's max_execution_time enforcement): every session owns
one :class:`StatementGuard`; the session arms it per statement (reset
kill flag, compute the ``max_execution_time`` deadline) and installs it
in a contextvar so every block boundary — ``Executor.drain``, the
all-consuming agg/join/sort loops, the BlockPipeline producer (context
is copied across the thread), the distsql worker pool, and
``Backoffer.backoff`` — can call :func:`check` without plumbing.

``KILL [QUERY] <conn_id>`` resolves through the process-global session
registry here: every Session gets a unique ``conn_id`` at construction
(the MySQL thread id the server hands out in its handshake), and
:func:`kill` flips the target's guard from ANY thread.  A plain ``KILL``
additionally marks the session dead so its server connection closes
after the current command.

Error surface (MySQL codes): kill -> 1317 ER_QUERY_INTERRUPTED,
deadline -> 3024 ER_QUERY_TIMEOUT.
"""
from __future__ import annotations

import contextvars
import itertools
import threading
import time
import weakref
from typing import Dict, Optional


class QueryKilled(Exception):
    """ER_QUERY_INTERRUPTED."""
    mysql_code = 1317
    sqlstate = "70100"

    def __init__(self, msg: str = "Query execution was interrupted"):
        super().__init__(msg)


class QueryTimeout(Exception):
    """ER_QUERY_TIMEOUT."""
    mysql_code = 3024
    sqlstate = "HY000"

    def __init__(self, msg: str = "Query execution was interrupted, "
                                  "maximum statement execution time "
                                  "exceeded"):
        super().__init__(msg)


class StatementGuard:
    """Kill flag + deadline for ONE session's current statement.  The
    flag is a plain bool written from other threads (GIL-atomic); the
    deadline is a monotonic timestamp or None."""

    __slots__ = ("conn_id", "killed", "deadline")

    def __init__(self, conn_id: int = 0):
        self.conn_id = conn_id
        self.killed = False
        self.deadline: Optional[float] = None

    def begin(self, deadline: Optional[float] = None) -> None:
        """Arm for a fresh statement.  A kill that raced in BETWEEN
        statements is dropped, matching MySQL (KILL QUERY affects the
        statement executing at the time, or nothing)."""
        self.killed = False
        self.deadline = deadline

    def kill(self) -> None:
        self.killed = True

    def check(self) -> None:
        if self.killed:
            raise QueryKilled()
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise QueryTimeout()


_GUARD: contextvars.ContextVar = contextvars.ContextVar(
    "tinysql_stmt_guard", default=None)


def activate(guard: StatementGuard):
    return _GUARD.set(guard)


def deactivate(token) -> None:
    _GUARD.reset(token)


def current() -> Optional[StatementGuard]:
    return _GUARD.get()


def check() -> None:
    """THE block-boundary hook: raises QueryKilled / QueryTimeout when
    the current statement was killed or ran past its deadline; no-op
    outside a guarded statement."""
    g = _GUARD.get()
    if g is not None:
        g.check()


# ---- session registry (KILL target resolution) ----------------------------

_reg_mu = threading.Lock()
_next_conn_id = itertools.count(1)
#: conn_id -> weakref to the owning Session
_SESSIONS: Dict[int, "weakref.ref"] = {}


def register_session(session) -> int:
    """Assign a process-unique connection id and index the session for
    KILL resolution.  Dead entries are swept opportunistically."""
    cid = next(_next_conn_id)
    ref = weakref.ref(session, lambda _r, cid=cid: _drop(cid))
    with _reg_mu:
        _SESSIONS[cid] = ref
    return cid


def _drop(cid: int) -> None:
    with _reg_mu:
        _SESSIONS.pop(cid, None)


def lookup(conn_id: int):
    with _reg_mu:
        ref = _SESSIONS.get(conn_id)
    return ref() if ref is not None else None


def sessions():
    """Snapshot of live registered sessions as ``(conn_id, session)``
    pairs (the ``information_schema.processlist`` feed).  Dead weakrefs
    are skipped; the strong refs live only as long as the caller's
    iteration."""
    with _reg_mu:
        refs = list(_SESSIONS.items())
    out = []
    for cid, ref in refs:
        sess = ref()
        if sess is not None:
            out.append((cid, sess))
    return out


def executing_threads() -> Dict[int, object]:
    """``thread ident -> session`` for sessions whose statement is
    currently EXECUTING on that thread (``session.stmt_thread_ident``,
    stamped when the statement is armed) — the continuous profiler's
    attribution feed (obs/conprof.py): a stack sample landing on one of
    these threads is on-thread time of that session's live statement.
    Queued statements (no worker yet) and helper threads a statement
    spawns (devpipe producer, distsql workers) are deliberately absent.
    """
    out: Dict[int, object] = {}
    for _cid, sess in sessions():
        if not getattr(sess, "stmt_running", False):
            continue
        tid = getattr(sess, "stmt_thread_ident", 0)
        if tid:
            out[tid] = sess
    return out


#: kill observers (the aio front end's wake hook): a parked idle
#: connection has NO blocked reader thread to notice ``session.killed``,
#: so the event loop registers a callback here and :func:`kill` invokes
#: it AFTER the flags flip — the loop's self-pipe then closes the victim
#: within one tick.  Callbacks must only enqueue/wake, never block.
_KILL_OBSERVERS: list = []
_obs_mu = threading.Lock()


def add_kill_observer(fn) -> None:
    """Register ``fn(conn_id, query_only)`` to run after every kill."""
    with _obs_mu:
        if fn not in _KILL_OBSERVERS:
            _KILL_OBSERVERS.append(fn)


def remove_kill_observer(fn) -> None:
    with _obs_mu:
        try:
            _KILL_OBSERVERS.remove(fn)
        except ValueError:
            pass


def kill(conn_id: int, query_only: bool = True) -> bool:
    """KILL [QUERY] <conn_id>.  Returns False when the id is unknown.
    ``query_only=False`` (plain KILL) also marks the session killed so
    its server connection drops after the current command."""
    sess = lookup(conn_id)
    if sess is None:
        return False
    guard = getattr(sess, "guard", None)
    if guard is not None:
        guard.kill()
    if not query_only:
        sess.killed = True
    with _obs_mu:
        observers = list(_KILL_OBSERVERS)
    for fn in observers:
        try:
            fn(conn_id, query_only)
        except Exception:  # a wake-hook bug must not fail the KILL
            pass
    return True
