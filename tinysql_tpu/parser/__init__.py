"""SQL front end (reference: parser/)."""
from .lexer import ParseError, tokenize
from .parser import Parser, parse, parse_one
from . import astnodes as ast

__all__ = ["ParseError", "tokenize", "Parser", "parse", "parse_one", "ast"]
