"""AST node types.

Capability parity with reference parser/ast/: dml.go (SelectStmt, Join,
TableSource, InsertStmt, DeleteStmt, ShowStmt…), ddl.go (Create/Drop/Alter),
expressions.go (BinaryOperationExpr, PatternInExpr, BetweenExpr,
PatternLikeExpr, IsNullExpr, CaseExpr, AggregateFuncExpr…), misc.go
(Set/Use/Begin/Commit/Rollback/Explain/Admin).  Dataclasses instead of the
Go visitor — tree walks are plain-Python recursion in the planner.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..mytypes import FieldType


class Node:
    pass


class ExprNode(Node):
    pass


class StmtNode(Node):
    #: this statement's own source slice within the parsed batch
    #: (Parser.parse fills it in) — the observability layer normalizes
    #: and samples THIS, never the display label a batch decorates
    src: str = ""


# ---------------- expressions ----------------------------------------------

@dataclass
class Literal(ExprNode):
    value: object  # None | int | float | str | bool


@dataclass
class DefaultExpr(ExprNode):
    """DEFAULT in a VALUES list."""


@dataclass
class ColumnRef(ExprNode):
    name: str
    table: str = ""
    db: str = ""

    def __str__(self) -> str:
        parts = [p for p in (self.db, self.table, self.name) if p]
        return ".".join(parts)


@dataclass
class UnaryOp(ExprNode):
    op: str          # '-', '+', 'not', '~'
    operand: ExprNode


@dataclass
class BinaryOp(ExprNode):
    op: str          # '+','-','*','/','div','%','and','or','xor',
                     # '=','<','>','<=','>=','!=','<=>'
    left: ExprNode
    right: ExprNode


@dataclass
class IsNullExpr(ExprNode):
    expr: ExprNode
    negated: bool = False


@dataclass
class IsTruthExpr(ExprNode):
    expr: ExprNode
    truth: bool      # IS TRUE / IS FALSE
    negated: bool = False


@dataclass
class LikeExpr(ExprNode):
    expr: ExprNode
    pattern: ExprNode
    negated: bool = False
    escape: str = "\\"


@dataclass
class InExpr(ExprNode):
    expr: ExprNode
    items: List[ExprNode] = field(default_factory=list)
    negated: bool = False


@dataclass
class BetweenExpr(ExprNode):
    expr: ExprNode
    lo: ExprNode
    hi: ExprNode
    negated: bool = False


@dataclass
class FuncCall(ExprNode):
    name: str                 # lowercase
    args: List[ExprNode] = field(default_factory=list)


@dataclass
class AggFunc(ExprNode):
    name: str                 # count/sum/avg/max/min/first_row (lowercase)
    args: List[ExprNode] = field(default_factory=list)
    distinct: bool = False


@dataclass
class CaseExpr(ExprNode):
    operand: Optional[ExprNode]
    when_clauses: List[Tuple[ExprNode, ExprNode]] = field(default_factory=list)
    else_clause: Optional[ExprNode] = None


@dataclass
class RowExpr(ExprNode):
    items: List[ExprNode] = field(default_factory=list)


@dataclass
class VariableExpr(ExprNode):
    name: str
    is_system: bool = False
    scope: str = ""           # '', 'global', 'session'


@dataclass
class ParenExpr(ExprNode):
    expr: ExprNode


@dataclass
class SubqueryExpr(ExprNode):
    """(SELECT ...) used as an expression: a scalar subquery in a
    comparison, or the list side of IN (reference: ast/expressions.go
    SubqueryExpr).  The inner statement is NOT walked by walk_expr —
    its aggregates/columns belong to the subquery's own scope."""
    select: "SelectStmt" = None


@dataclass
class ExistsExpr(ExprNode):
    """[NOT] EXISTS (SELECT ...) (reference: ast/expressions.go
    ExistsSubqueryExpr).  Decorrelates into a semi/anti join when it is
    a top-level WHERE conjunct; evaluates eagerly (uncorrelated only)
    elsewhere."""
    select: "SelectStmt" = None
    negated: bool = False


# ---------------- table refs -----------------------------------------------

@dataclass
class TableName(Node):
    name: str
    db: str = ""


@dataclass
class TableSource(Node):
    source: Node              # TableName | SelectStmt | Join
    as_name: str = ""


@dataclass
class Join(Node):
    """reference: ast/dml.go Join; the course's JoinTable production."""
    left: Node                # TableSource | Join
    right: Optional[Node]
    tp: str = "cross"         # cross | inner | left | right
    on: Optional[ExprNode] = None
    using: List[str] = field(default_factory=list)


# ---------------- DML -------------------------------------------------------

@dataclass
class SelectField(Node):
    expr: Optional[ExprNode]      # None for wildcard
    as_name: str = ""
    wildcard_table: str = ""      # for t.* ; '' means plain *
    is_wildcard: bool = False
    text: str = ""


@dataclass
class SelectStmt(StmtNode):
    fields: List[SelectField] = field(default_factory=list)
    from_: Optional[Join] = None
    where: Optional[ExprNode] = None
    group_by: List[ExprNode] = field(default_factory=list)
    having: Optional[ExprNode] = None
    order_by: List[Tuple[ExprNode, bool]] = field(default_factory=list)  # (expr, desc)
    limit: Optional[Tuple[int, int]] = None     # (offset, count)
    distinct: bool = False


@dataclass
class Assignment(Node):
    column: ColumnRef
    expr: ExprNode


@dataclass
class InsertStmt(StmtNode):
    table: TableName = None
    columns: List[str] = field(default_factory=list)
    lists: List[List[ExprNode]] = field(default_factory=list)
    select: Optional[SelectStmt] = None
    is_replace: bool = False


@dataclass
class DeleteStmt(StmtNode):
    table: TableSource = None
    where: Optional[ExprNode] = None


@dataclass
class UpdateStmt(StmtNode):
    """UPDATE t SET c = expr [, ...] [WHERE ...] (reference: ast/dml.go
    UpdateStmt, single-table form — a genuine extension past the
    reference's reduced surface, ROADMAP item 5)."""
    table: TableSource = None
    assignments: List[Assignment] = field(default_factory=list)
    where: Optional[ExprNode] = None


# ---------------- DDL -------------------------------------------------------

@dataclass
class ColumnOption(Node):
    tp: str                   # not_null/null/primary/unique/auto_increment/default
    value: object = None


@dataclass
class ColumnDef(Node):
    name: str
    ft: FieldType
    options: List[ColumnOption] = field(default_factory=list)


@dataclass
class Constraint(Node):
    tp: str                   # primary | unique | index
    name: str = ""
    columns: List[Tuple[str, int]] = field(default_factory=list)  # (col, prefix_len)


@dataclass
class CreateDatabaseStmt(StmtNode):
    name: str
    if_not_exists: bool = False


@dataclass
class DropDatabaseStmt(StmtNode):
    name: str
    if_exists: bool = False


@dataclass
class CreateTableStmt(StmtNode):
    table: TableName
    cols: List[ColumnDef] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    if_not_exists: bool = False


@dataclass
class DropTableStmt(StmtNode):
    tables: List[TableName] = field(default_factory=list)
    if_exists: bool = False


@dataclass
class TruncateTableStmt(StmtNode):
    table: TableName = None


@dataclass
class CreateIndexStmt(StmtNode):
    index_name: str = ""
    table: TableName = None
    columns: List[Tuple[str, int]] = field(default_factory=list)
    unique: bool = False


@dataclass
class DropIndexStmt(StmtNode):
    index_name: str = ""
    table: TableName = None
    if_exists: bool = False


@dataclass
class AlterTableSpec(Node):
    tp: str                   # add_column | drop_column | add_index | drop_index | add_constraint
    column: Optional[ColumnDef] = None
    constraint: Optional[Constraint] = None
    name: str = ""


@dataclass
class AlterTableStmt(StmtNode):
    table: TableName = None
    specs: List[AlterTableSpec] = field(default_factory=list)


# ---------------- simple / admin -------------------------------------------

@dataclass
class ShowStmt(StmtNode):
    tp: str                   # databases|tables|columns|create_table|indexes|variables
    db: str = ""
    table: Optional[TableName] = None
    pattern: Optional[str] = None
    where: Optional[ExprNode] = None
    full: bool = False
    global_scope: bool = False


@dataclass
class SetStmt(StmtNode):
    # (scope, name, value) ; scope in '', 'global', 'session', 'user'
    assignments: List[Tuple[str, str, ExprNode]] = field(default_factory=list)


@dataclass
class UseStmt(StmtNode):
    db: str = ""


@dataclass
class BeginStmt(StmtNode):
    pass


@dataclass
class CommitStmt(StmtNode):
    pass


@dataclass
class RollbackStmt(StmtNode):
    pass


@dataclass
class ExplainStmt(StmtNode):
    stmt: StmtNode = None
    analyze: bool = False
    # EXPLAIN FOR CONNECTION <id>: render the target session's last
    # plan via the interrupt registry (stmt is None in that form)
    for_conn: Optional[int] = None


@dataclass
class TraceStmt(StmtNode):
    # TRACE [FORMAT = 'row'] <stmt>: execute the statement and return
    # its recorded span tree as rows (obs/trace.py trace_rows) — span,
    # parent, start offset, duration, thread role
    stmt: StmtNode = None
    format: str = "row"


@dataclass
class AnalyzeTableStmt(StmtNode):
    tables: List[TableName] = field(default_factory=list)


@dataclass
class AdminStmt(StmtNode):
    tp: str                   # show_ddl | show_ddl_jobs | check_table
    tables: List[TableName] = field(default_factory=list)


@dataclass
class KillStmt(StmtNode):
    # KILL [QUERY|CONNECTION] <conn_id>: QUERY aborts the target's
    # running statement; plain/CONNECTION also drops the connection
    conn_id: int = 0
    query_only: bool = False


@dataclass
class EmptyStmt(StmtNode):
    pass


# ---------------- tree walking ----------------------------------------------

def walk_expr(e: ExprNode):
    """Yield every expression node in the subtree (pre-order)."""
    if e is None:
        return
    yield e
    for child in expr_children(e):
        yield from walk_expr(child)


def expr_children(e: ExprNode) -> List[ExprNode]:
    if isinstance(e, UnaryOp):
        return [e.operand]
    if isinstance(e, BinaryOp):
        return [e.left, e.right]
    if isinstance(e, (IsNullExpr, IsTruthExpr)):
        return [e.expr]
    if isinstance(e, LikeExpr):
        return [e.expr, e.pattern]
    if isinstance(e, InExpr):
        return [e.expr] + e.items
    if isinstance(e, BetweenExpr):
        return [e.expr, e.lo, e.hi]
    if isinstance(e, (FuncCall, AggFunc)):
        return list(e.args)
    if isinstance(e, CaseExpr):
        out = [e.operand] if e.operand else []
        for c, r in e.when_clauses:
            out += [c, r]
        if e.else_clause:
            out.append(e.else_clause)
        return out
    if isinstance(e, RowExpr):
        return list(e.items)
    if isinstance(e, ParenExpr):
        return [e.expr]
    return []


def has_agg(e: ExprNode) -> bool:
    return any(isinstance(x, AggFunc) for x in walk_expr(e))
