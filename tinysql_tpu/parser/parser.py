"""SQL parser: recursive descent with Pratt expression climbing.

Capability parity with reference parser/parser.y (5,299-line goyacc LALR
grammar, tinysql statement subset — parser.y:4521-4543) including the JOIN
productions the course has students add (courses/proj2).  Hand-rolled
instead of generated: the grammar subset is small enough that a Pratt parser
is clearer and plenty fast (the reference itself keeps the lexer hand-written,
lexer.go).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..mytypes import (FieldType, TYPE_DOUBLE, TYPE_FLOAT, TYPE_LONG,
                       TYPE_LONGLONG, TYPE_STRING, TYPE_VARCHAR,
                       FLAG_AUTO_INCREMENT, FLAG_NOT_NULL, FLAG_PRI_KEY,
                       FLAG_UNIQUE_KEY, FLAG_UNSIGNED)
from .astnodes import *  # noqa: F401,F403
from .lexer import (ParseError, T_EOF, T_FLOAT, T_IDENT, T_INT, T_OP,
                    T_QIDENT, T_STRING, T_SYSVAR, T_USERVAR, Token, tokenize)

AGG_FUNCS = {"count", "sum", "avg", "max", "min"}

_CMP_OPS = {"=", "<", ">", "<=", ">=", "!=", "<>", "<=>"}


class Parser:
    """reference: parser/yy_parser.go Parser (entry: Parse)."""

    def __init__(self):
        self.toks: List[Token] = []
        self.i = 0
        self.sql = ""

    # ==== token helpers =====================================================
    def _cur(self) -> Token:
        return self.toks[self.i]

    def _peek(self, k: int = 1) -> Token:
        j = min(self.i + k, len(self.toks) - 1)
        return self.toks[j]

    def _advance(self) -> Token:
        t = self.toks[self.i]
        if t.kind != T_EOF:
            self.i += 1
        return t

    def _at_kw(self, *kws: str) -> bool:
        t = self._cur()
        return t.kind == T_IDENT and t.value.lower() in kws

    def _accept_kw(self, *kws: str) -> Optional[str]:
        if self._at_kw(*kws):
            return self._advance().value.lower()
        return None

    def _expect_kw(self, kw: str) -> None:
        if not self._accept_kw(kw):
            raise ParseError(f"expected {kw.upper()}, got {self._cur().text!r}",
                             self._cur().pos)

    def _at_op(self, *ops: str) -> bool:
        t = self._cur()
        return t.kind == T_OP and t.value in ops

    def _accept_op(self, *ops: str) -> Optional[str]:
        if self._at_op(*ops):
            return self._advance().value
        return None

    def _expect_op(self, op: str) -> None:
        if not self._accept_op(op):
            raise ParseError(f"expected {op!r}, got {self._cur().text!r}",
                             self._cur().pos)

    def _ident(self) -> str:
        t = self._cur()
        if t.kind in (T_IDENT, T_QIDENT):
            self._advance()
            return t.value
        raise ParseError(f"expected identifier, got {t.text!r}", t.pos)

    # ==== entry =============================================================
    def parse(self, sql: str) -> List[StmtNode]:
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0
        stmts: List[StmtNode] = []
        while self._cur().kind != T_EOF:
            if self._accept_op(";"):
                continue
            start = self._cur().pos
            stmt = self._statement()
            end = self._cur().pos if self._cur().kind != T_EOF \
                else len(sql)
            # the statement's OWN source slice: digest normalization and
            # sampling must see it, not a batch-decorated display label
            stmt.src = sql[start:end].strip().rstrip(";").rstrip()
            stmts.append(stmt)
            if self._cur().kind != T_EOF:
                self._expect_op(";")
        return stmts

    def parse_one(self, sql: str) -> StmtNode:
        stmts = self.parse(sql)
        if len(stmts) != 1:
            raise ParseError(f"expected exactly one statement, got {len(stmts)}")
        return stmts[0]

    # ==== statements ========================================================
    def _statement(self) -> StmtNode:
        t = self._cur()
        if t.kind != T_IDENT and not (t.kind == T_OP and t.value == "("):
            raise ParseError(f"unexpected {t.text!r}", t.pos)
        kw = t.value.lower() if t.kind == T_IDENT else "("
        if kw in ("select", "("):
            return self._select_stmt()
        if kw == "insert":
            return self._insert_stmt(replace=False)
        if kw == "replace":
            return self._insert_stmt(replace=True)
        if kw == "delete":
            return self._delete_stmt()
        if kw == "update":
            return self._update_stmt()
        if kw == "create":
            return self._create_stmt()
        if kw == "drop":
            return self._drop_stmt()
        if kw == "alter":
            return self._alter_stmt()
        if kw == "truncate":
            self._advance()
            self._accept_kw("table")
            return TruncateTableStmt(self._table_name())
        if kw == "show":
            return self._show_stmt()
        if kw == "set":
            return self._set_stmt()
        if kw == "use":
            self._advance()
            return UseStmt(self._ident())
        if kw in ("begin", "start"):
            self._advance()
            if kw == "start":
                self._expect_kw("transaction")
            return BeginStmt()
        if kw == "commit":
            self._advance()
            return CommitStmt()
        if kw == "rollback":
            self._advance()
            return RollbackStmt()
        if kw in ("explain", "desc", "describe"):
            return self._explain_stmt()
        if kw == "trace":
            return self._trace_stmt()
        if kw == "analyze":
            self._advance()
            self._expect_kw("table")
            tables = [self._table_name()]
            while self._accept_op(","):
                tables.append(self._table_name())
            return AnalyzeTableStmt(tables)
        if kw == "admin":
            return self._admin_stmt()
        if kw == "kill":
            return self._kill_stmt()
        raise ParseError(f"unsupported statement {t.text!r}", t.pos)

    def _kill_stmt(self) -> KillStmt:
        """KILL [TIDB] [QUERY | CONNECTION] <conn_id> (reference:
        executor/simple.go executeKill + server kill dispatch)."""
        self._advance()
        self._accept_kw("tidb")
        query_only = self._accept_kw("query") is not None
        if not query_only:
            self._accept_kw("connection")
        return KillStmt(conn_id=self._uint_literal(), query_only=query_only)

    # ---- SELECT ------------------------------------------------------------
    def _select_stmt(self) -> SelectStmt:
        if self._at_op("("):
            # parenthesized select at statement level
            self._expect_op("(")
            s = self._select_stmt()
            self._expect_op(")")
            return s
        self._expect_kw("select")
        stmt = SelectStmt()
        if self._accept_kw("distinct"):
            stmt.distinct = True
        else:
            self._accept_kw("all")
        stmt.fields = self._select_fields()
        if self._accept_kw("from"):
            stmt.from_ = self._table_refs()
        if self._accept_kw("where"):
            stmt.where = self._expr()
        if self._at_kw("group"):
            self._advance()
            self._expect_kw("by")
            stmt.group_by.append(self._expr())
            while self._accept_op(","):
                stmt.group_by.append(self._expr())
        if self._accept_kw("having"):
            stmt.having = self._expr()
        if self._at_kw("order"):
            self._advance()
            self._expect_kw("by")
            stmt.order_by = self._order_items()
        if self._accept_kw("limit"):
            stmt.limit = self._limit_clause()
        return stmt

    def _select_fields(self) -> List[SelectField]:
        fields = []
        while True:
            start = self._cur().pos
            if self._at_op("*"):
                self._advance()
                fields.append(SelectField(None, is_wildcard=True))
            elif (self._cur().kind in (T_IDENT, T_QIDENT)
                  and self._peek().kind == T_OP and self._peek().value == "."
                  and self._peek(2).kind == T_OP and self._peek(2).value == "*"):
                tbl = self._ident()
                self._advance()  # .
                self._advance()  # *
                fields.append(SelectField(None, is_wildcard=True,
                                          wildcard_table=tbl))
            else:
                e = self._expr()
                as_name = ""
                if self._accept_kw("as"):
                    as_name = self._ident_or_string()
                elif (self._cur().kind in (T_IDENT, T_QIDENT)
                      and not self._at_kw(*_CLAUSE_KWS)):
                    as_name = self._ident()
                end = self._cur().pos
                fields.append(SelectField(e, as_name=as_name,
                                          text=self.sql[start:end].strip()))
            if not self._accept_op(","):
                return fields

    def _ident_or_string(self) -> str:
        t = self._cur()
        if t.kind == T_STRING:
            self._advance()
            return t.value
        return self._ident()

    def _order_items(self) -> List[Tuple[ExprNode, bool]]:
        out = []
        while True:
            e = self._expr()
            desc = False
            if self._accept_kw("desc"):
                desc = True
            else:
                self._accept_kw("asc")
            out.append((e, desc))
            if not self._accept_op(","):
                return out

    def _limit_clause(self) -> Tuple[int, int]:
        a = self._uint_literal()
        if self._accept_op(","):
            return a, self._uint_literal()
        if self._accept_kw("offset"):
            return self._uint_literal(), a
        return 0, a

    def _uint_literal(self) -> int:
        t = self._cur()
        if t.kind != T_INT or t.value < 0:
            raise ParseError(f"expected unsigned integer, got {t.text!r}", t.pos)
        self._advance()
        return t.value

    # ---- table refs (the course's JoinTable production) --------------------
    def _table_refs(self) -> Join:
        left = self._join_side()
        while True:
            if self._accept_op(","):
                right = self._join_side()
                left = Join(left, right, tp="cross")
                continue
            tp = None
            if self._at_kw("join", "inner", "cross"):
                w = self._advance().value.lower()
                if w in ("inner", "cross"):
                    self._expect_kw("join")
                tp = "inner" if w != "cross" else "cross"
            elif self._at_kw("left", "right"):
                w = self._advance().value.lower()
                self._accept_kw("outer")
                self._expect_kw("join")
                tp = w
            else:
                return left if isinstance(left, Join) else Join(left, None)
            right = self._join_side()
            j = Join(left, right, tp=tp)
            if self._accept_kw("on"):
                j.on = self._expr()
            elif self._accept_kw("using"):
                self._expect_op("(")
                j.using.append(self._ident())
                while self._accept_op(","):
                    j.using.append(self._ident())
                self._expect_op(")")
            elif tp in ("left", "right"):
                raise ParseError("outer join requires ON or USING",
                                 self._cur().pos)
            left = j

    def _join_side(self):
        if self._at_op("("):
            if (self._peek().kind == T_IDENT
                    and self._peek().value.lower() == "select"):
                self._advance()
                sub = self._select_stmt()
                self._expect_op(")")
                self._accept_kw("as")
                name = self._ident()
                return TableSource(sub, as_name=name)
            self._advance()
            inner = self._table_refs()
            self._expect_op(")")
            return inner
        tn = self._table_name()
        as_name = ""
        if self._accept_kw("as"):
            as_name = self._ident()
        elif (self._cur().kind in (T_IDENT, T_QIDENT)
              and not self._at_kw(*_TABLE_CLAUSE_KWS)):
            as_name = self._ident()
        return TableSource(tn, as_name=as_name)

    def _table_name(self) -> TableName:
        a = self._ident()
        if self._accept_op("."):
            return TableName(self._ident(), db=a)
        return TableName(a)

    # ---- INSERT / DELETE ---------------------------------------------------
    def _insert_stmt(self, replace: bool) -> InsertStmt:
        self._advance()  # insert | replace
        self._accept_kw("into")
        stmt = InsertStmt(is_replace=replace)
        stmt.table = self._table_name()
        if self._at_op("(") :
            # could be column list or values-select paren; column list only
            # if followed by idents then ')'
            save = self.i
            self._advance()
            try:
                cols = [self._ident()]
                while self._accept_op(","):
                    cols.append(self._ident())
                self._expect_op(")")
                stmt.columns = cols
            except ParseError:
                self.i = save
        if self._accept_kw("values", "value"):
            while True:
                self._expect_op("(")
                row: List[ExprNode] = []
                if not self._at_op(")"):
                    row.append(self._insert_value())
                    while self._accept_op(","):
                        row.append(self._insert_value())
                self._expect_op(")")
                stmt.lists.append(row)
                if not self._accept_op(","):
                    break
        elif self._at_kw("select") or self._at_op("("):
            stmt.select = self._select_stmt()
        else:
            raise ParseError("expected VALUES or SELECT", self._cur().pos)
        return stmt

    def _insert_value(self) -> ExprNode:
        if self._accept_kw("default"):
            return DefaultExpr()
        return self._expr()

    def _update_stmt(self) -> UpdateStmt:
        """UPDATE t [AS a] SET col = expr [, ...] [WHERE ...]."""
        self._advance()  # update
        tn = self._table_name()
        as_name = ""
        if self._accept_kw("as"):
            as_name = self._ident()
        elif (self._cur().kind in (T_IDENT, T_QIDENT)
              and not self._at_kw("set")):
            as_name = self._ident()
        self._expect_kw("set")
        stmt = UpdateStmt(TableSource(tn, as_name))
        while True:
            col = self._column_ref_only()
            if not self._accept_op("="):
                self._expect_op(":=")
            stmt.assignments.append(Assignment(col, self._expr()))
            if not self._accept_op(","):
                break
        if self._accept_kw("where"):
            stmt.where = self._expr()
        return stmt

    def _column_ref_only(self) -> ColumnRef:
        a = self._ident()
        if self._accept_op("."):
            b = self._ident()
            if self._accept_op("."):
                return ColumnRef(self._ident(), table=b, db=a)
            return ColumnRef(b, table=a)
        return ColumnRef(a)

    def _delete_stmt(self) -> DeleteStmt:
        self._advance()
        self._expect_kw("from")
        tn = self._table_name()
        as_name = ""
        if self._accept_kw("as"):
            as_name = self._ident()
        elif self._cur().kind in (T_IDENT, T_QIDENT) and not self._at_kw("where"):
            as_name = self._ident()
        stmt = DeleteStmt(TableSource(tn, as_name))
        if self._accept_kw("where"):
            stmt.where = self._expr()
        return stmt

    # ---- DDL ---------------------------------------------------------------
    def _create_stmt(self) -> StmtNode:
        self._advance()  # create
        if self._accept_kw("database", "schema"):
            ine = self._if_not_exists()
            return CreateDatabaseStmt(self._ident(), ine)
        if self._accept_kw("table"):
            return self._create_table()
        unique = bool(self._accept_kw("unique"))
        if self._accept_kw("index"):
            name = self._ident()
            self._expect_kw("on")
            tn = self._table_name()
            cols = self._index_col_list()
            return CreateIndexStmt(name, tn, cols, unique)
        raise ParseError("unsupported CREATE", self._cur().pos)

    def _if_not_exists(self) -> bool:
        if self._accept_kw("if"):
            self._expect_kw("not")
            self._expect_kw("exists")
            return True
        return False

    def _if_exists(self) -> bool:
        if self._accept_kw("if"):
            self._expect_kw("exists")
            return True
        return False

    def _create_table(self) -> CreateTableStmt:
        ine = self._if_not_exists()
        tn = self._table_name()
        stmt = CreateTableStmt(tn, if_not_exists=ine)
        self._expect_op("(")
        while True:
            if self._at_kw("primary"):
                self._advance()
                self._expect_kw("key")
                stmt.constraints.append(
                    Constraint("primary", columns=self._index_col_list()))
            elif self._at_kw("unique"):
                self._advance()
                self._accept_kw("key", "index")
                name = ""
                if self._cur().kind in (T_IDENT, T_QIDENT) and not self._at_op("("):
                    name = self._ident()
                stmt.constraints.append(
                    Constraint("unique", name, self._index_col_list()))
            elif self._at_kw("index", "key"):
                self._advance()
                name = ""
                if self._cur().kind in (T_IDENT, T_QIDENT) and not self._at_op("("):
                    name = self._ident()
                stmt.constraints.append(
                    Constraint("index", name, self._index_col_list()))
            else:
                stmt.cols.append(self._column_def())
            if not self._accept_op(","):
                break
        self._expect_op(")")
        # swallow table options (ENGINE=, CHARSET=, ...) permissively
        while self._cur().kind == T_IDENT and not self._at_op(";"):
            self._advance()
            self._accept_op("=")
            if self._cur().kind in (T_IDENT, T_QIDENT, T_INT, T_STRING):
                self._advance()
        return stmt

    def _column_def(self) -> ColumnDef:
        name = self._ident()
        ft = self._field_type()
        col = ColumnDef(name, ft)
        while True:
            if self._accept_kw("not"):
                self._expect_kw("null")
                col.options.append(ColumnOption("not_null"))
            elif self._accept_kw("null"):
                col.options.append(ColumnOption("null"))
            elif self._at_kw("primary"):
                self._advance()
                self._expect_kw("key")
                col.options.append(ColumnOption("primary"))
            elif self._accept_kw("unique"):
                self._accept_kw("key")
                col.options.append(ColumnOption("unique"))
            elif self._accept_kw("auto_increment"):
                col.options.append(ColumnOption("auto_increment"))
            elif self._accept_kw("default"):
                v = self._signed_literal()
                col.options.append(ColumnOption("default", v))
            elif self._accept_kw("comment"):
                self._advance()  # string
            else:
                return col

    def _signed_literal(self):
        neg = False
        if self._accept_op("-"):
            neg = True
        t = self._cur()
        if t.kind in (T_INT, T_FLOAT, T_STRING):
            self._advance()
            return -t.value if neg and t.kind != T_STRING else t.value
        if self._accept_kw("null"):
            return None
        if self._accept_kw("true"):
            return 1
        if self._accept_kw("false"):
            return 0
        raise ParseError(f"expected literal, got {t.text!r}", t.pos)

    def _field_type(self) -> FieldType:
        w = self._ident().lower()
        flen = -1
        if self._accept_op("("):
            flen = self._uint_literal()
            self._accept_op(",") and self._uint_literal()  # ignore decimals
            self._expect_op(")")
        ft: FieldType
        if w in ("int", "integer", "mediumint"):
            ft = FieldType(TYPE_LONG, flen=11)
        elif w in ("bigint",):
            ft = FieldType(TYPE_LONGLONG, flen=20)
        elif w in ("smallint", "tinyint", "bool", "boolean"):
            ft = FieldType(TYPE_LONG, flen=6)
        elif w in ("float", "real"):
            ft = FieldType(TYPE_FLOAT, flen=12)
        elif w in ("double", "decimal", "numeric"):
            # no DECIMAL family in the engine (reference has none either,
            # SURVEY §2.9); map to double like tinysql's tests do
            if w == "double":
                self._accept_kw("precision")
            ft = FieldType(TYPE_DOUBLE, flen=22)
        elif w in ("varchar", "text", "longtext", "mediumtext"):
            ft = FieldType(TYPE_VARCHAR, flen=flen)
        elif w in ("char",):
            ft = FieldType(TYPE_STRING, flen=flen if flen >= 0 else 1)
        else:
            raise ParseError(f"unsupported column type {w!r}", self._cur().pos)
        if flen >= 0 and w not in ("varchar", "char", "text"):
            ft.flen = flen
        if self._accept_kw("unsigned"):
            ft.flag |= FLAG_UNSIGNED
        self._accept_kw("signed")
        if self._accept_kw("zerofill"):
            pass
        # charset/collate noise
        if self._accept_kw("character"):
            self._expect_kw("set")
            self._ident()
        if self._accept_kw("charset"):
            self._ident()
        if self._accept_kw("collate"):
            self._ident()
        return ft

    def _index_col_list(self) -> List[Tuple[str, int]]:
        self._expect_op("(")
        cols = [self._index_col()]
        while self._accept_op(","):
            cols.append(self._index_col())
        self._expect_op(")")
        return cols

    def _index_col(self) -> Tuple[str, int]:
        name = self._ident()
        ln = -1
        if self._accept_op("("):
            ln = self._uint_literal()
            self._expect_op(")")
        return name, ln

    def _drop_stmt(self) -> StmtNode:
        self._advance()  # drop
        if self._accept_kw("database", "schema"):
            ie = self._if_exists()
            return DropDatabaseStmt(self._ident(), ie)
        if self._accept_kw("table"):
            ie = self._if_exists()
            tables = [self._table_name()]
            while self._accept_op(","):
                tables.append(self._table_name())
            return DropTableStmt(tables, ie)
        if self._accept_kw("index"):
            name = self._ident()
            self._expect_kw("on")
            return DropIndexStmt(name, self._table_name())
        raise ParseError("unsupported DROP", self._cur().pos)

    def _alter_stmt(self) -> AlterTableStmt:
        self._advance()
        self._expect_kw("table")
        stmt = AlterTableStmt(self._table_name())
        while True:
            if self._accept_kw("add"):
                if self._accept_kw("index", "key"):
                    name = ""
                    if self._cur().kind in (T_IDENT, T_QIDENT) and not self._at_op("("):
                        name = self._ident()
                    stmt.specs.append(AlterTableSpec(
                        "add_index",
                        constraint=Constraint("index", name, self._index_col_list())))
                elif self._accept_kw("unique"):
                    self._accept_kw("key", "index")
                    name = ""
                    if self._cur().kind in (T_IDENT, T_QIDENT) and not self._at_op("("):
                        name = self._ident()
                    stmt.specs.append(AlterTableSpec(
                        "add_index",
                        constraint=Constraint("unique", name, self._index_col_list())))
                else:
                    self._accept_kw("column")
                    stmt.specs.append(AlterTableSpec(
                        "add_column", column=self._column_def()))
            elif self._accept_kw("drop"):
                if self._accept_kw("index", "key"):
                    stmt.specs.append(AlterTableSpec("drop_index",
                                                     name=self._ident()))
                else:
                    self._accept_kw("column")
                    stmt.specs.append(AlterTableSpec("drop_column",
                                                     name=self._ident()))
            else:
                raise ParseError("unsupported ALTER TABLE action",
                                 self._cur().pos)
            if not self._accept_op(","):
                return stmt

    # ---- SHOW / SET / EXPLAIN / ADMIN --------------------------------------
    def _show_stmt(self) -> ShowStmt:
        self._advance()
        full = bool(self._accept_kw("full"))
        glob = bool(self._accept_kw("global"))
        self._accept_kw("session")
        if self._accept_kw("databases", "schemas"):
            stmt = ShowStmt("databases")
        elif self._accept_kw("tables"):
            stmt = ShowStmt("tables")
            if self._accept_kw("from", "in"):
                stmt.db = self._ident()
        elif self._accept_kw("columns", "fields"):
            self._expect_kw("from")
            stmt = ShowStmt("columns", table=self._table_name())
            if self._accept_kw("from", "in"):
                stmt.db = self._ident()
        elif self._accept_kw("create"):
            if self._accept_kw("database", "schema"):
                stmt = ShowStmt("create_database")
                stmt.db = self._ident()
            else:
                self._expect_kw("table")
                stmt = ShowStmt("create_table", table=self._table_name())
        elif self._accept_kw("index", "indexes", "keys"):
            self._expect_kw("from")
            stmt = ShowStmt("indexes", table=self._table_name())
        elif self._accept_kw("variables"):
            stmt = ShowStmt("variables", global_scope=glob)
        elif self._accept_kw("processlist"):
            stmt = ShowStmt("processlist")
        elif self._accept_kw("warnings"):
            stmt = ShowStmt("warnings")
        elif self._accept_kw("errors"):
            stmt = ShowStmt("errors")
        else:
            raise ParseError("unsupported SHOW", self._cur().pos)
        stmt.full = full
        # LIKE/WHERE tails only on the list-producing kinds (MySQL
        # rejects e.g. SHOW WARNINGS LIKE ...)
        if stmt.tp in ("databases", "tables", "columns", "indexes",
                       "variables"):
            if self._accept_kw("like"):
                t = self._cur()
                if t.kind != T_STRING:
                    raise ParseError("expected pattern string", t.pos)
                self._advance()
                stmt.pattern = t.value
            elif self._accept_kw("where"):
                stmt.where = self._expr()
        return stmt

    def _set_stmt(self) -> SetStmt:
        self._advance()
        stmt = SetStmt()
        while True:
            scope = ""
            t = self._cur()
            if t.kind == T_SYSVAR:
                self._advance()
                name = t.value
                if name.startswith("global."):
                    scope, name = "global", name[7:]
                elif name.startswith("session."):
                    scope, name = "session", name[8:]
                else:
                    scope = "session"
            elif t.kind == T_USERVAR:
                self._advance()
                scope, name = "user", t.value
            elif self._accept_kw("global"):
                scope, name = "global", self._ident().lower()
            elif self._accept_kw("session"):
                scope, name = "session", self._ident().lower()
            elif self._accept_kw("names"):
                # SET NAMES utf8: accept & ignore (charset fixed)
                self._ident_or_string()
                if not self._accept_op(","):
                    return stmt
                continue
            else:
                scope, name = "session", self._ident().lower()
            if not self._accept_op("="):
                self._expect_op(":=")
            value = self._expr()
            stmt.assignments.append((scope, name, value))
            if not self._accept_op(","):
                return stmt

    def _explain_stmt(self) -> StmtNode:
        kw = self._advance().value.lower()
        if kw in ("desc", "describe") and self._cur().kind in (T_IDENT, T_QIDENT) \
                and not self._at_kw("select", "insert", "delete", "replace", "analyze"):
            # DESC t == SHOW COLUMNS FROM t
            return ShowStmt("columns", table=self._table_name())
        analyze = bool(self._accept_kw("analyze"))
        if not analyze and self._accept_kw("for"):
            # EXPLAIN FOR CONNECTION <id> (reference: common_plans.go
            # ExplainFor — the plan of whatever the target conn ran last)
            self._expect_kw("connection")
            return ExplainStmt(None, for_conn=self._uint_literal())
        return ExplainStmt(self._statement(), analyze=analyze)

    def _trace_stmt(self) -> TraceStmt:
        """TRACE [FORMAT = 'row'] <statement> (reference: TiDB's
        executor/trace.go — execute the statement and return its span
        tree as rows).  Only the 'row' format is supported."""
        self._advance()
        fmt = "row"
        if self._accept_kw("format"):
            self._expect_op("=")
            t = self._cur()
            if t.kind != T_STRING:
                raise ParseError("TRACE FORMAT expects a string literal",
                                 t.pos)
            fmt = str(t.value).lower()
            self._advance()
            if fmt != "row":
                raise ParseError(f"unsupported TRACE format {fmt!r}",
                                 t.pos)
        return TraceStmt(self._statement(), format=fmt)

    def _admin_stmt(self) -> AdminStmt:
        self._advance()
        if self._accept_kw("show"):
            self._expect_kw("ddl")
            if self._accept_kw("jobs"):
                return AdminStmt("show_ddl_jobs")
            return AdminStmt("show_ddl")
        if self._accept_kw("check"):
            self._expect_kw("table")
            tables = [self._table_name()]
            while self._accept_op(","):
                tables.append(self._table_name())
            return AdminStmt("check_table", tables)
        raise ParseError("unsupported ADMIN", self._cur().pos)

    # ==== expressions (Pratt) ==============================================
    def _expr(self) -> ExprNode:
        return self._or_expr()

    def _or_expr(self) -> ExprNode:
        left = self._xor_expr()
        while self._at_kw("or") or self._at_op("||"):
            self._advance()
            left = BinaryOp("or", left, self._xor_expr())
        return left

    def _xor_expr(self) -> ExprNode:
        left = self._and_expr()
        while self._at_kw("xor"):
            self._advance()
            left = BinaryOp("xor", left, self._and_expr())
        return left

    def _and_expr(self) -> ExprNode:
        left = self._not_expr()
        while self._at_kw("and") or self._at_op("&&"):
            self._advance()
            left = BinaryOp("and", left, self._not_expr())
        return left

    def _not_expr(self) -> ExprNode:
        if self._accept_kw("not"):
            return UnaryOp("not", self._not_expr())
        return self._predicate()

    def _predicate(self) -> ExprNode:
        left = self._additive()
        while True:
            if self._at_op(*_CMP_OPS):
                op = self._advance().value
                if op == "<>":
                    op = "!="
                left = BinaryOp(op, left, self._additive())
                continue
            if self._at_kw("is"):
                self._advance()
                neg = bool(self._accept_kw("not"))
                if self._accept_kw("null"):
                    left = IsNullExpr(left, neg)
                elif self._accept_kw("true"):
                    left = IsTruthExpr(left, True, neg)
                elif self._accept_kw("false"):
                    left = IsTruthExpr(left, False, neg)
                else:
                    raise ParseError("expected NULL/TRUE/FALSE after IS",
                                     self._cur().pos)
                continue
            neg = False
            save = self.i
            if self._accept_kw("not"):
                neg = True
            if self._accept_kw("like"):
                left = LikeExpr(left, self._additive(), neg)
                if self._accept_kw("escape"):
                    t = self._cur()
                    if t.kind != T_STRING:
                        raise ParseError("expected escape string", t.pos)
                    self._advance()
                    left.escape = t.value or "\\"
                continue
            if self._accept_kw("in"):
                self._expect_op("(")
                if self._at_kw("select"):
                    # IN (subquery): the single item is a SubqueryExpr —
                    # the planner decorrelates it into a semi/anti join
                    sub = self._select_stmt()
                    self._expect_op(")")
                    left = InExpr(left, [SubqueryExpr(sub)], neg)
                    continue
                items = [self._expr()]
                while self._accept_op(","):
                    items.append(self._expr())
                self._expect_op(")")
                left = InExpr(left, items, neg)
                continue
            if self._accept_kw("between"):
                lo = self._additive()
                self._expect_kw("and")
                hi = self._additive()
                left = BetweenExpr(left, lo, hi, neg)
                continue
            if neg:
                self.i = save
            return left

    def _additive(self) -> ExprNode:
        left = self._multiplicative()
        while self._at_op("+", "-"):
            op = self._advance().value
            left = BinaryOp(op, left, self._multiplicative())
        return left

    def _multiplicative(self) -> ExprNode:
        left = self._unary()
        while True:
            if self._at_op("*", "/", "%"):
                op = self._advance().value
                left = BinaryOp(op, left, self._unary())
            elif self._at_kw("div"):
                self._advance()
                left = BinaryOp("div", left, self._unary())
            elif self._at_kw("mod"):
                self._advance()
                left = BinaryOp("%", left, self._unary())
            else:
                return left

    def _unary(self) -> ExprNode:
        if self._at_op("-", "+", "!", "~"):
            op = self._advance().value
            operand = self._unary()
            if op == "+":
                return operand
            if op == "!":
                return UnaryOp("not", operand)
            # constant-fold negative literals so -9223372036854775808 parses
            if op == "-" and isinstance(operand, Literal) \
                    and isinstance(operand.value, (int, float)) \
                    and not isinstance(operand.value, bool):
                return Literal(-operand.value)
            return UnaryOp(op, operand)
        return self._primary()

    def _primary(self) -> ExprNode:
        t = self._cur()
        if t.kind == T_INT or t.kind == T_FLOAT or t.kind == T_STRING:
            self._advance()
            return Literal(t.value)
        if t.kind == T_SYSVAR:
            self._advance()
            name, scope = t.value, ""
            if name.startswith("global."):
                scope, name = "global", name[7:]
            elif name.startswith("session."):
                scope, name = "session", name[8:]
            return VariableExpr(name, is_system=True, scope=scope)
        if t.kind == T_USERVAR:
            self._advance()
            return VariableExpr(t.value, is_system=False)
        if self._at_op("("):
            if (self._peek().kind == T_IDENT
                    and self._peek().value.lower() == "select"):
                # scalar subquery: (SELECT ...) as an expression operand
                self._advance()
                sub = self._select_stmt()
                self._expect_op(")")
                return SubqueryExpr(sub)
            self._advance()
            e = self._expr()
            if self._at_op(","):
                items = [e]
                while self._accept_op(","):
                    items.append(self._expr())
                self._expect_op(")")
                return RowExpr(items)
            self._expect_op(")")
            return ParenExpr(e)
        if t.kind in (T_IDENT, T_QIDENT):
            word = t.value.lower() if t.kind == T_IDENT else None
            if word in RESERVED_NON_EXPR:
                # LEFT( / RIGHT( / REPLACE( are function CALLS despite the
                # words being reserved for joins/statements (MySQL allows
                # them when directly followed by a parenthesis)
                nxt = self._peek(1)
                if word == "exists" and nxt.kind == T_OP \
                        and nxt.value == "(":
                    # EXISTS (SELECT ...); NOT EXISTS arrives via the
                    # generic NOT operator and is normalized by the
                    # planner's decorrelation pass
                    self._advance()  # exists
                    self._expect_op("(")
                    if not self._at_kw("select"):
                        raise ParseError("expected SELECT after EXISTS (",
                                         self._cur().pos)
                    sub = self._select_stmt()
                    self._expect_op(")")
                    return ExistsExpr(sub)
                if word in ("left", "right", "replace") \
                        and nxt.kind == T_OP and nxt.value == "(":
                    return self._func_call()
                raise ParseError(f"unexpected keyword {t.text!r} in expression",
                                 t.pos)
            if word == "null":
                self._advance()
                return Literal(None)
            if word == "true":
                self._advance()
                return Literal(1)
            if word == "false":
                self._advance()
                return Literal(0)
            if word == "case":
                return self._case_expr()
            if word == "row" and self._peek().kind == T_OP and self._peek().value == "(":
                self._advance()
                self._expect_op("(")
                items = [self._expr()]
                while self._accept_op(","):
                    items.append(self._expr())
                self._expect_op(")")
                return RowExpr(items)
            # function call?
            if self._peek().kind == T_OP and self._peek().value == "(" \
                    and t.kind == T_IDENT:
                return self._func_call()
            # column ref: a | t.a | db.t.a
            a = self._ident()
            if self._accept_op("."):
                b = self._ident()
                if self._accept_op("."):
                    return ColumnRef(self._ident(), table=b, db=a)
                return ColumnRef(b, table=a)
            return ColumnRef(a)
        raise ParseError(f"unexpected token {t.text!r} in expression", t.pos)

    def _func_call(self) -> ExprNode:
        name = self._ident().lower()
        self._expect_op("(")
        if name in AGG_FUNCS:
            distinct = bool(self._accept_kw("distinct"))
            if name == "count" and self._at_op("*"):
                self._advance()
                self._expect_op(")")
                return AggFunc("count", [Literal(1)], distinct=False)
            args = [self._expr()]
            while self._accept_op(","):
                args.append(self._expr())
            self._expect_op(")")
            return AggFunc(name, args, distinct)
        args = []
        if not self._at_op(")"):
            args.append(self._expr())
            while self._accept_op(","):
                args.append(self._expr())
        self._expect_op(")")
        return FuncCall(name, args)

    def _case_expr(self) -> CaseExpr:
        self._advance()  # case
        operand = None
        if not self._at_kw("when"):
            operand = self._expr()
        cases = []
        while self._accept_kw("when"):
            cond = self._expr()
            self._expect_kw("then")
            cases.append((cond, self._expr()))
        els = None
        if self._accept_kw("else"):
            els = self._expr()
        self._expect_kw("end")
        if not cases:
            raise ParseError("CASE requires at least one WHEN", self._cur().pos)
        return CaseExpr(operand, cases, els)


# MySQL reserved words that can never appear bare as a column reference
# (reference: parser/misc.go tokenMap reserved section, trimmed to this
# grammar's keyword set)
RESERVED_NON_EXPR = frozenset("""
    select from where group having order limit insert update delete replace
    create drop alter table index join inner left right cross on using and
    or xor not like in between is when then else as by asc desc distinct
    values set into union for default primary unique references exists
    """.split())

_CLAUSE_KWS = ("from", "where", "group", "having", "order", "limit", "as",
               "union", "for", "into", "on", "using", "join", "inner", "left",
               "right", "cross", "when", "then", "else", "end", "and", "or",
               "xor", "not", "desc", "asc", "offset")
_TABLE_CLAUSE_KWS = _CLAUSE_KWS + ("set", "values")


def parse(sql: str) -> List[StmtNode]:
    return Parser().parse(sql)


def parse_one(sql: str) -> StmtNode:
    return Parser().parse_one(sql)
