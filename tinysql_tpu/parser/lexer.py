"""SQL lexer.

Capability parity with reference parser/lexer.go (873 L) + misc.go token
tables: MySQL-ish tokens — backquoted identifiers, single/double-quoted
strings with escapes, ints/floats/scientific, hex literals, line (`--`, `#`)
and block comments, user (@v) and system (@@v) variables, multi-char
operators.  Keywords are recognized case-insensitively by the parser, not
reserved here beyond a shared set.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

# token kinds
T_EOF = "eof"
T_IDENT = "ident"
T_QIDENT = "qident"      # `quoted`
T_INT = "int"
T_FLOAT = "float"
T_STRING = "string"
T_OP = "op"
T_SYSVAR = "sysvar"      # @@name or @@global.name / @@session.name
T_USERVAR = "uservar"    # @name

_OPS = [
    "<=>", "<<", ">>", "<=", ">=", "<>", "!=", ":=", "||", "&&",
    "+", "-", "*", "/", "%", "=", "<", ">", "(", ")", ",", ".", ";",
    "!", "~", "^", "&", "|", "?",
]


class ParseError(Exception):
    def __init__(self, msg: str, pos: int = -1, line: int = -1):
        near = f" near position {pos}" if pos >= 0 else ""
        super().__init__(f"You have an error in your SQL syntax: {msg}{near}")
        self.pos = pos


@dataclass
class Token:
    kind: str
    value: object
    text: str
    pos: int

    def __repr__(self) -> str:  # pragma: no cover
        return f"Token({self.kind},{self.text!r})"


def tokenize(sql: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            i += 1
            continue
        # comments
        if c == "#" or (c == "-" and sql[i:i + 3] in ("-- ", "--\t", "--\n") or sql[i:i + 2] == "--" and i + 2 == n):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and sql[i:i + 2] == "/*":
            j = sql.find("*/", i + 2)
            if j < 0:
                raise ParseError("unterminated comment", i)
            i = j + 2
            continue
        # strings
        if c in "'\"":
            start = i
            s, i = _lex_string(sql, i, c)
            toks.append(Token(T_STRING, s, sql[start:i], start))
            continue
        # quoted identifier
        if c == "`":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "`":
                    if sql[j:j + 2] == "``":
                        buf.append("`")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise ParseError("unterminated identifier", i)
            toks.append(Token(T_QIDENT, "".join(buf), "".join(buf), i))
            i = j + 1
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            tok, i = _lex_number(sql, i)
            toks.append(tok)
            continue
        # variables
        if c == "@":
            if sql[i:i + 2] == "@@":
                j = i + 2
                while j < n and (sql[j].isalnum() or sql[j] in "_."):
                    j += 1
                toks.append(Token(T_SYSVAR, sql[i + 2:j].lower(), sql[i:j], i))
                i = j
                continue
            j = i + 1
            while j < n and (sql[j].isalnum() or sql[j] in "_."):
                j += 1
            toks.append(Token(T_USERVAR, sql[i + 1:j].lower(), sql[i:j], i))
            i = j
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_" or ord(c) > 127:
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_" or ord(sql[j]) > 127):
                j += 1
            word = sql[i:j]
            # hex literal 0x... handled in numbers; also x'ab' b'01' skipped
            toks.append(Token(T_IDENT, word, word, i))
            i = j
            continue
        # operators
        for op in _OPS:
            if sql.startswith(op, i):
                toks.append(Token(T_OP, op, op, i))
                i += len(op)
                break
        else:
            raise ParseError(f"unexpected character {c!r}", i)
    toks.append(Token(T_EOF, None, "", n))
    return toks


def _lex_string(sql: str, i: int, quote: str):
    j = i + 1
    n = len(sql)
    buf = []
    while j < n:
        c = sql[j]
        if c == "\\" and j + 1 < n:
            esc = sql[j + 1]
            buf.append({"n": "\n", "t": "\t", "r": "\r", "0": "\0",
                        "b": "\b", "Z": "\x1a", "\\": "\\",
                        "'": "'", '"': '"', "%": "\\%", "_": "\\_"}.get(esc, esc))
            j += 2
            continue
        if c == quote:
            if sql[j:j + 2] == quote * 2:  # doubled quote escape
                buf.append(quote)
                j += 2
                continue
            return "".join(buf), j + 1
        buf.append(c)
        j += 1
    raise ParseError("unterminated string", i)


def _lex_number(sql: str, i: int):
    n = len(sql)
    j = i
    if sql[j:j + 2].lower() == "0x":
        j += 2
        start = j
        while j < n and sql[j] in "0123456789abcdefABCDEF":
            j += 1
        return Token(T_INT, int(sql[start:j] or "0", 16), sql[i:j], i), j
    is_float = False
    while j < n and sql[j].isdigit():
        j += 1
    if j < n and sql[j] == ".":
        is_float = True
        j += 1
        while j < n and sql[j].isdigit():
            j += 1
    if j < n and sql[j] in "eE":
        k = j + 1
        if k < n and sql[k] in "+-":
            k += 1
        if k < n and sql[k].isdigit():
            is_float = True
            j = k
            while j < n and sql[j].isdigit():
                j += 1
    text = sql[i:j]
    if is_float:
        return Token(T_FLOAT, float(text), text, i), j
    v = int(text)
    return Token(T_INT, v, text, i), j
