"""Server binary (reference: tidb-server/main.go — flag parsing :44-81,
registerStores :120, createStoreAndDomain :127, bootstrap, signal handling
and graceful shutdown :265-291).

Run: python -m tinysql_tpu.main [-P port] [--store mocktikv] [--config f]
"""
from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from . import config as cfgmod
from .kv import new_mock_storage
from .server.http_status import StatusServer
from .server.server import Server
from .session.session import Session


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser("tinysql-tpu-server")
    ap.add_argument("--config", default="", help="TOML config file")
    ap.add_argument("--host", default=None)
    ap.add_argument("-P", "--port", type=int, default=None)
    ap.add_argument("--store", default=None, choices=["mocktikv"])
    ap.add_argument("--path", default=None, help="store path/dsn")
    ap.add_argument("--data-dir", default=None,
                    help="durable MVCC data directory (WAL + checkpoints);"
                         " empty = volatile store")
    ap.add_argument("--status", type=int, default=None,
                    help="status HTTP port")
    ap.add_argument("--log-file", default=None)
    ap.add_argument("-L", "--log-level", default=None)
    return ap


def load_config(argv) -> cfgmod.Config:
    args = build_arg_parser().parse_args(argv)
    cfg = cfgmod.load(args.config)
    # CLI overrides (reference: overrideConfig main.go:176)
    if args.host is not None:
        cfg.host = args.host
    if args.port is not None:
        cfg.port = args.port
    if args.store is not None:
        cfg.store = args.store
    if args.path is not None:
        cfg.path = args.path
    if args.data_dir is not None:
        cfg.data_dir = args.data_dir
    if args.status is not None:
        cfg.status.status_port = args.status
    if args.log_file is not None:
        cfg.log.file = args.log_file
    if args.log_level is not None:
        cfg.log.level = args.log_level
    cfgmod.store_global_config(cfg)
    return cfg


def setup_logging(cfg: cfgmod.Config) -> None:
    handlers = None
    if cfg.log.file:
        handlers = [logging.FileHandler(cfg.log.file)]
    logging.basicConfig(
        level=getattr(logging, cfg.log.level.upper(), logging.INFO),
        format="[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s",
        handlers=handlers)


def bootstrap(storage) -> None:
    """Create system state on first run (reference: session/bootstrap.go)."""
    s = Session(storage)
    try:
        s.execute("create database if not exists test")
    except Exception:
        pass


def _honor_jax_platforms_env() -> None:
    """Resolve the JAX platform at server startup: explicit JAX_PLATFORMS
    env wins over the sitecustomize-pinned config, and an unreachable
    device backend (dead TPU tunnel) pins cpu after a probed timeout —
    shared logic in ops/kernels.ensure_live_backend."""
    from .ops.kernels import ensure_live_backend
    ensure_live_backend()


def main(argv=None) -> int:
    cfg = load_config(argv if argv is not None else sys.argv[1:])
    setup_logging(cfg)
    _honor_jax_platforms_env()
    log = logging.getLogger("tinysql_tpu")
    # data_dir: CLI/config wins; "" falls through to TINYSQL_DATA_DIR env
    # (kv/txn.py resolve_data_dir); no dir at all = the volatile store
    storage = new_mock_storage(num_stores=cfg.num_stores,
                               data_dir=cfg.data_dir or None)
    if storage.data_dir:
        ri = storage.mvcc.recovery_info or {}
        log.info("durable store on %s (replayed %d wal records, "
                 "%d in-flight locks recovered)", storage.data_dir,
                 ri.get("replayed_records", 0),
                 ri.get("recovered_locks", 0))
    bootstrap(storage)
    server = Server(storage, cfg.host, cfg.port,
                    ssl_cert=cfg.security.ssl_cert,
                    ssl_key=cfg.security.ssl_key)
    port = server.start()
    status = None
    if cfg.status.report_status:
        status = StatusServer(server, cfg.status.status_host,
                              cfg.status.status_port)
        status.start()
        log.info("status server on :%d", status.port)
    log.info("server ready on :%d (store=%s)", port, cfg.store)

    stop = threading.Event()

    def on_signal(sig, frame):
        log.info("signal %s: shutting down", sig)
        stop.set()
    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    stop.wait()
    server.close()
    storage.close()  # final WAL checkpoint + fd close (no-op volatile)
    if status is not None:
        status.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
