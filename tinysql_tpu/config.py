"""Layered configuration (reference: config/config.go — defaults struct
:155, strict TOML Load :118-140 with unknown-key detection, CLI flag
overrides in tidb-server/main.go:176-234, atomic global :108)."""
from __future__ import annotations

import threading
import tomllib
from dataclasses import dataclass, field, fields, is_dataclass


class ConfigError(Exception):
    pass


@dataclass
class Log:
    level: str = "info"
    file: str = ""          # empty = stderr
    slow_threshold_ms: int = 300


@dataclass
class Status:
    report_status: bool = True
    status_host: str = "127.0.0.1"
    status_port: int = 10080


@dataclass
class Security:
    # reference: config/config.go Security section (ssl-cert/ssl-key);
    # both set => the server advertises CLIENT_SSL and accepts the
    # mid-handshake upgrade (server/conn.go:448-455,1070)
    ssl_cert: str = ""
    ssl_key: str = ""


@dataclass
class Config:
    host: str = "127.0.0.1"
    port: int = 4000
    store: str = "mocktikv"          # mocktikv | tikv
    path: str = "/tmp/tinysql_tpu"
    lease: str = "45s"
    num_stores: int = 1
    use_tpu: bool = True
    log: Log = field(default_factory=Log)
    status: Status = field(default_factory=Status)
    security: Security = field(default_factory=Security)


def _apply(obj, data: dict, prefix: str = "") -> None:
    known = {f.name: f for f in fields(obj)}
    for k, v in data.items():
        key = k.replace("-", "_")
        if key not in known:
            raise ConfigError(
                f"unknown configuration option {prefix}{k!r}")
        cur = getattr(obj, key)
        if isinstance(v, dict):
            if not is_dataclass(cur):
                raise ConfigError(
                    f"{prefix}{k} is a scalar option, not a section")
            _apply(cur, v, prefix=f"{prefix}{k}.")
        else:
            if not isinstance(v, type(cur)) and not (
                    isinstance(cur, bool) is isinstance(v, bool)
                    and isinstance(v, int) and isinstance(cur, int)):
                raise ConfigError(
                    f"bad type for {prefix}{k}: {type(v).__name__}")
            setattr(obj, key, v)


def load(path: str = "") -> Config:
    """TOML file -> Config with strict unknown-key detection
    (reference: ErrConfigValidationFailed)."""
    cfg = Config()
    if path:
        with open(path, "rb") as f:
            data = tomllib.load(f)
        _apply(cfg, data)
    return cfg


_global = Config()
_mu = threading.Lock()


def get_global_config() -> Config:
    return _global


def store_global_config(cfg: Config) -> None:
    global _global
    with _mu:
        _global = cfg
