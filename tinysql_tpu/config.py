"""Layered configuration (reference: config/config.go — defaults struct
:155, strict TOML Load :118-140 with unknown-key detection, CLI flag
overrides in tidb-server/main.go:176-234, atomic global :108)."""
from __future__ import annotations

import threading
from dataclasses import dataclass, field, fields, is_dataclass

try:
    import tomllib  # Python 3.11+
except ImportError:  # 3.10 runners: minimal strict-subset parser below
    tomllib = None


class ConfigError(Exception):
    pass


@dataclass
class Log:
    level: str = "info"
    file: str = ""          # empty = stderr
    slow_threshold_ms: int = 300


@dataclass
class Status:
    report_status: bool = True
    status_host: str = "127.0.0.1"
    status_port: int = 10080


@dataclass
class Security:
    # reference: config/config.go Security section (ssl-cert/ssl-key);
    # both set => the server advertises CLIENT_SSL and accepts the
    # mid-handshake upgrade (server/conn.go:448-455,1070)
    ssl_cert: str = ""
    ssl_key: str = ""


@dataclass
class Config:
    host: str = "127.0.0.1"
    port: int = 4000
    store: str = "mocktikv"          # mocktikv | tikv
    path: str = "/tmp/tinysql_tpu"
    lease: str = "45s"
    num_stores: int = 1
    use_tpu: bool = True
    # persistent XLA compile-cache directory; "" = <repo>/.jax_cache
    # (ops/kernels.py _cache_dir resolution: sysvar tidb_compile_cache_dir
    # > TINYSQL_JAX_CACHE env > this entry > default)
    compile_cache_dir: str = ""
    # durability arming (kv/wal.py): directory for the MVCC WAL +
    # checkpoints.  "" = volatile in-memory store, byte-identical to the
    # pre-WAL behavior.  Resolution: --data-dir CLI > this entry >
    # TINYSQL_DATA_DIR env (kv/txn.py resolve_data_dir)
    data_dir: str = ""
    log: Log = field(default_factory=Log)
    status: Status = field(default_factory=Status)
    security: Security = field(default_factory=Security)


def _apply(obj, data: dict, prefix: str = "") -> None:
    known = {f.name: f for f in fields(obj)}
    for k, v in data.items():
        key = k.replace("-", "_")
        if key not in known:
            raise ConfigError(
                f"unknown configuration option {prefix}{k!r}")
        cur = getattr(obj, key)
        if isinstance(v, dict):
            if not is_dataclass(cur):
                raise ConfigError(
                    f"{prefix}{k} is a scalar option, not a section")
            _apply(cur, v, prefix=f"{prefix}{k}.")
        else:
            if not isinstance(v, type(cur)) and not (
                    isinstance(cur, bool) is isinstance(v, bool)
                    and isinstance(v, int) and isinstance(cur, int)):
                raise ConfigError(
                    f"bad type for {prefix}{k}: {type(v).__name__}")
            setattr(obj, key, v)


def _parse_toml_minimal(text: str) -> dict:
    """Config-file TOML subset for pre-3.11 interpreters: `[section]`
    headers (dotted allowed) and `key = scalar` lines with string / int /
    float / bool scalars.  Enough for every config this server reads;
    anything fancier needs the stdlib tomllib."""
    root: dict = {}
    cur = root
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            cur = root
            for part in line[1:-1].strip().split("."):
                cur = cur.setdefault(part.strip(), {})
            continue
        if "=" not in line:
            raise ConfigError(f"bad TOML line {lineno}: {raw!r}")
        key, _, val = line.partition("=")
        key = key.strip().strip('"')
        val = val.strip()
        if val[:1] in ('"', "'"):
            # quoted string: close at the matching quote; anything after
            # it may only be an inline comment
            end = val.find(val[0], 1)
            rest = val[end + 1:].strip() if end > 0 else "!"
            if end < 0 or (rest and not rest.startswith("#")):
                raise ConfigError(
                    f"bad TOML string at line {lineno}: {raw!r}")
            cur[key] = val[1:end]
            continue
        val = val.split("#", 1)[0].strip()
        if val in ("true", "false"):
            cur[key] = val == "true"
        else:
            try:
                cur[key] = int(val)
            except ValueError:
                try:
                    cur[key] = float(val)
                except ValueError:
                    raise ConfigError(
                        f"bad TOML value at line {lineno}: {raw!r}")
    return root


def load(path: str = "") -> Config:
    """TOML file -> Config with strict unknown-key detection
    (reference: ErrConfigValidationFailed)."""
    cfg = Config()
    if path:
        if tomllib is not None:
            with open(path, "rb") as f:
                data = tomllib.load(f)
        else:
            with open(path, "r", encoding="utf-8") as f:
                data = _parse_toml_minimal(f.read())
        _apply(cfg, data)
    return cfg


_global = Config()
_mu = threading.Lock()


def get_global_config() -> Config:
    return _global


def store_global_config(cfg: Config) -> None:
    global _global
    with _mu:
        _global = cfg
