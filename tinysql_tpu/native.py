"""ctypes binding for the native runtime library (native/tinysql_native.cpp):
memcomparable batch codec + the int64 join hash table.

Loads native/libtinysql_native.so, building it with g++ on first use if
missing.  Every caller must handle `lib() is None` (no toolchain): the
pure-python paths remain the semantic reference.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

_lib = None
_tried = False
_mu = threading.Lock()

_SO = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "native", "libtinysql_native.so")


def lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _mu:
        if _tried:
            return _lib
        try:
            src = os.path.join(os.path.dirname(_SO), "tinysql_native.cpp")
            stale = (os.path.exists(_SO) and os.path.exists(src)
                     and os.path.getmtime(src) > os.path.getmtime(_SO))
            if not os.path.exists(_SO) or stale:
                import importlib.util
                spec = importlib.util.spec_from_file_location(
                    "tsnative_build",
                    os.path.join(os.path.dirname(_SO), "build.py"))
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                mod.build()
            l = ctypes.CDLL(_SO)
            l.mc_encode_batch.restype = ctypes.c_int
            l.mc_encode_bytes.restype = ctypes.c_int64
            l.mc_decode_bytes.restype = ctypes.c_int64
            l.i64ht_build.restype = ctypes.c_void_p
            l.i64ht_probe.restype = ctypes.c_int64
            l.i64ht_free.restype = None
            _lib = l
        except Exception:
            _lib = None
        _tried = True
        return _lib


# ---- batch memcomparable encode -------------------------------------------

_KIND = {"int": 0, "uint": 1, "float": 2}


def mc_encode_column(values: np.ndarray, kind: str) -> Optional[np.ndarray]:
    """Encode an int64/uint64/float64 column into n rows of 9 key bytes
    (flag + big-endian payload).  Returns uint8 [n, 9] or None if the
    native library is unavailable."""
    l = lib()
    if l is None:
        return None
    v = np.ascontiguousarray(values)
    n = len(v)
    out = np.empty((n, 9), dtype=np.uint8)
    rc = l.mc_encode_batch(
        v.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(n),
        ctypes.c_int(_KIND[kind]), out.ctypes.data_as(ctypes.c_void_p))
    return out if rc == 0 else None


# ---- join hash table -------------------------------------------------------

class I64HashTable:
    """Build-once probe-many int64 hash table (util/mvmap analogue).
    Falls back to None when the native library is unavailable."""

    def __init__(self, keys: np.ndarray, valid: Optional[np.ndarray] = None):
        l = lib()
        assert l is not None
        self._l = l
        self._keys = np.ascontiguousarray(keys, dtype=np.int64)
        self._valid = (np.ascontiguousarray(valid, dtype=np.uint8)
                       if valid is not None else None)
        self._h = l.i64ht_build(
            self._keys.ctypes.data_as(ctypes.c_void_p),
            self._valid.ctypes.data_as(ctypes.c_void_p)
            if self._valid is not None else None,
            ctypes.c_int64(len(self._keys)))

    @staticmethod
    def try_build(keys: np.ndarray,
                  valid: Optional[np.ndarray] = None
                  ) -> Optional["I64HashTable"]:
        return I64HashTable(keys, valid) if lib() is not None else None

    def probe(self, keys: np.ndarray,
              valid: Optional[np.ndarray] = None):
        """Returns (match_row_ids, per_probe_counts): the build row ids
        matching each probe key, concatenated in probe order."""
        k = np.ascontiguousarray(keys, dtype=np.int64)
        va = (np.ascontiguousarray(valid, dtype=np.uint8)
              if valid is not None else None)
        n = len(k)
        counts = np.empty(n, dtype=np.int32)
        cap = max(n, 64)
        while True:
            out = np.empty(cap, dtype=np.int64)
            total = self._l.i64ht_probe(
                ctypes.c_void_p(self._h),
                k.ctypes.data_as(ctypes.c_void_p),
                va.ctypes.data_as(ctypes.c_void_p) if va is not None
                else None,
                ctypes.c_int64(n),
                out.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int64(cap),
                counts.ctypes.data_as(ctypes.c_void_p))
            if total <= cap:
                return out[:total], counts
            cap = int(total)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self._l.i64ht_free(ctypes.c_void_p(h))
            except Exception:
                pass
            self._h = None
