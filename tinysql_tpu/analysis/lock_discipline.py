"""Pass 3 — lock-discipline lint (LD3xx).

For the threaded subsystems the pass infers, per class, a lock-to-field
guard map from the code itself: a field that is ever MUTATED while a
``threading`` lock attribute of the same class is held is declared
guarded by that lock.  It then flags:

- LD301: a mutation of a guarded field outside every lock scope
  (``__init__`` is exempt — construction is single-threaded by the
  publish-before-share rule);
- LD302: a READ of a guarded field outside every lock scope (torn reads
  of multi-step state; a deliberate GIL-atomic read needs a suppression
  with its reasoning);
- LD303: the dict-slot idiom (``with s["lock"]: s["owner"] = ...``,
  ddl/owner.py): a subscript write through a name that is elsewhere
  locked via ``name["lock"]`` but written here with no lock held.

Mutations are attribute stores/aug-stores/deletes, subscript stores into
the field, and calls of known mutating container methods
(append/pop/update/...).  Lock attributes themselves and classes with no
lock attributes are skipped — single-threaded helper classes carry no
discipline to enforce.  Nested function definitions (inline thread
targets) are analyzed with an EMPTY held-lock set: they run later, on
their own thread, regardless of what the enclosing method held.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .diag import Diagnostic, SourceFile, register_rules

register_rules({
    "LD301": "guarded field mutated outside its lock scope",
    "LD302": "guarded field read outside its lock scope",
    "LD303": "locked dict slot written with no lock held",
})

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTATORS = {"append", "extend", "insert", "pop", "popitem", "clear",
             "update", "setdefault", "add", "remove", "discard",
             "appendleft", "popleft"}


def _self_attr(e: ast.expr) -> Optional[str]:
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
            and e.value.id == "self":
        return e.attr
    return None


def _lock_fields(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = node.value.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                (fn.id if isinstance(fn, ast.Name) else None)
            if name in _LOCK_CTORS:
                for tgt in node.targets:
                    a = _self_attr(tgt)
                    if a:
                        out.add(a)
    return out


#: event = ("write"|"read", field, node, held_locks, method_name)
_Event = Tuple[str, str, ast.AST, FrozenSet[str], str]


class _MethodWalker:
    def __init__(self, locks: Set[str], method: str):
        self.locks = locks
        self.method = method
        self.events: List[_Event] = []

    # ---- statements -----------------------------------------------------
    def walk(self, stmts, held: FrozenSet[str]) -> None:
        for s in stmts:
            self._stmt(s, held)

    def _with_locks(self, node: ast.With) -> FrozenSet[str]:
        got = set()
        for item in node.items:
            a = _self_attr(item.context_expr)
            if a in self.locks:
                got.add(a)
        return frozenset(got)

    def _stmt(self, s: ast.stmt, held: FrozenSet[str]) -> None:
        if isinstance(s, ast.With):
            for item in s.items:
                self._reads(item.context_expr, held, skip_locks=True)
            self.walk(s.body, held | self._with_locks(s))
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.walk(s.body, frozenset())  # inline thread target
        elif isinstance(s, (ast.If, ast.While)):
            self._reads(s.test, held)
            self.walk(s.body, held)
            self.walk(s.orelse, held)
        elif isinstance(s, ast.For):
            self._reads(s.iter, held)
            self.walk(s.body, held)
            self.walk(s.orelse, held)
        elif isinstance(s, ast.Try):
            for blk in ([s.body, s.orelse, s.finalbody]
                        + [h.body for h in s.handlers]):
                self.walk(blk, held)
        elif isinstance(s, ast.Assign):
            for tgt in s.targets:
                self._write_target(tgt, held)
            self._reads(s.value, held)
        elif isinstance(s, ast.AugAssign):
            self._write_target(s.target, held)
            self._reads(s.value, held)
        elif isinstance(s, ast.Delete):
            for tgt in s.targets:
                self._write_target(tgt, held)
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self._call_mutations(child, held)
                    self._reads(child, held)

    # ---- expressions ----------------------------------------------------
    def _write_target(self, tgt: ast.expr, held: FrozenSet[str]) -> None:
        a = _self_attr(tgt)
        if a is not None:
            self.events.append(("write", a, tgt, held, self.method))
            return
        if isinstance(tgt, ast.Subscript):
            a = _self_attr(tgt.value)
            if a is not None:
                self.events.append(("write", a, tgt, held, self.method))
            else:
                self._reads(tgt.value, held)
            self._reads(tgt.slice, held)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._write_target(e, held)

    def _call_mutations(self, e: ast.expr, held: FrozenSet[str]) -> None:
        for node in ast.walk(e):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                a = _self_attr(node.func.value)
                if a is not None:
                    self.events.append(("write", a, node, held,
                                        self.method))

    def _reads(self, e: ast.expr, held: FrozenSet[str],
               skip_locks: bool = False) -> None:
        self._call_mutations(e, held)
        for node in ast.walk(e):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                a = _self_attr(node)
                if a is None or (skip_locks and a in self.locks):
                    continue
                self.events.append(("read", a, node, held, self.method))


def _lint_class(sf: SourceFile, cls: ast.ClassDef) -> List[Diagnostic]:
    locks = _lock_fields(cls)
    if not locks:
        return []  # single-threaded helper: nothing to enforce
    events: List[_Event] = []
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mw = _MethodWalker(locks, node.name)
            mw.walk(node.body, frozenset())
            events.extend(mw.events)
    guarded: Dict[str, Set[str]] = {}
    for kind, field, node, held, method in events:
        if kind == "write" and held and field not in locks:
            guarded.setdefault(field, set()).update(held)
    out: List[Diagnostic] = []
    seen: Set[tuple] = set()
    for kind, field, node, held, method in events:
        if field not in guarded or field in locks or method == "__init__":
            continue
        if held & guarded[field]:
            continue
        key = (kind, field, node.lineno, node.col_offset)
        if key in seen:
            continue
        seen.add(key)
        lock_names = ",".join(sorted(guarded[field]))
        rule = "LD301" if kind == "write" else "LD302"
        verb = "mutated" if kind == "write" else "read"
        out.append(Diagnostic(
            rule,
            f"{cls.name}.{field} is guarded by self.{lock_names} "
            f"(inferred) but {verb} in `{method}` with no lock held",
            sf.path, node.lineno, node.col_offset))
    return out


def _dict_lock_names(tree: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.With):
            for item in node.items:
                ce = item.context_expr
                if isinstance(ce, ast.Subscript) \
                        and isinstance(ce.value, ast.Name) \
                        and isinstance(ce.slice, ast.Constant) \
                        and ce.slice.value == "lock":
                    out.add(ce.value.id)
    return out


def _walk_dict_writes(sf, stmts, held: FrozenSet[str],
                      locked_names: Set[str],
                      fname: str) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    for s in stmts:
        if isinstance(s, ast.With):
            got = set(held)
            for item in s.items:
                ce = item.context_expr
                if isinstance(ce, ast.Subscript) \
                        and isinstance(ce.value, ast.Name) \
                        and isinstance(ce.slice, ast.Constant) \
                        and ce.slice.value == "lock":
                    got.add(ce.value.id)
            out.extend(_walk_dict_writes(sf, s.body, frozenset(got),
                                         locked_names, fname))
            continue
        if isinstance(s, ast.Assign):
            for tgt in s.targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id in locked_names \
                        and tgt.value.id not in held:
                    out.append(Diagnostic(
                        "LD303",
                        f"`{tgt.value.id}[...]` written in `{fname}` "
                        f"without holding `{tgt.value.id}[\"lock\"]`",
                        sf.path, tgt.lineno, tgt.col_offset))
        if isinstance(s, (ast.If, ast.While, ast.For)):
            out.extend(_walk_dict_writes(sf, s.body, held, locked_names,
                                         fname))
            out.extend(_walk_dict_writes(sf, s.orelse, held, locked_names,
                                         fname))
        elif isinstance(s, ast.Try):
            for blk in ([s.body, s.orelse, s.finalbody]
                        + [h.body for h in s.handlers]):
                out.extend(_walk_dict_writes(sf, blk, held, locked_names,
                                             fname))
    return out


def _lint_dict_slots(sf: SourceFile) -> List[Diagnostic]:
    locked_names = _dict_lock_names(sf.tree)
    if not locked_names:
        return []
    out: List[Diagnostic] = []
    for fn in ast.walk(sf.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.extend(_walk_dict_writes(sf, fn.body, frozenset(),
                                         locked_names, fn.name))
    return out


def lint_lock_discipline(sf: SourceFile) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef):
            diags.extend(_lint_class(sf, node))
    diags.extend(_lint_dict_slots(sf))
    return sf.filter(diags)
