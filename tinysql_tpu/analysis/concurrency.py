"""Pass 6 — whole-program concurrency analysis (CC7xx).

Unlike every other qlint pass, this one is NOT per-file: it consumes the
ENTIRE package at once, builds a cross-module call graph, and reasons
about which code runs on which threads.  Four cooperating analyses:

**Thread-root graph.**  Every thread entry point is discovered from the
AST: ``threading.Thread(target=...)`` (including the devpipe idiom
``Thread(target=cctx.run, args=(real_target,))``), the first argument of
``ThreadPoolExecutor.submit`` (including the distsql idiom
``submit(ctx.run, real_target, ...)``), and functions handed to
``BlockPipeline(stage_fn, ...)`` (the staging producer runs them on its
own thread).  Reachability is computed from each root over a name-based
call graph (direct calls, ``self.`` methods, module-alias calls,
constructor calls, and ``self.attr``/local calls through inferred
``self.x = ClassName(...)`` types).  A handful of SEED_EDGES document
the dynamic dispatches the AST cannot see (the pool worker invoking
``Session.execute_stmt`` through ``entry.session``, the prewarm worker
driving ``Session.query``); they are the machine-readable catalogue of
the known worker loops.  Everything additionally reachable from
public/zero-caller functions or module bodies carries the synthetic
``main`` root.  A function (or a piece of state) is *multi-root* when
two or more distinct roots reach it — that is the precondition for
every CC7xx rule: single-threaded code carries no concurrency
discipline to enforce.

**CC701 — shared-state races.**  Module-level mutable containers
(dict/list/set/deque/... literals or constructors) and instance
attributes of lock-carrying classes that are WRITTEN from multi-root
code: the guard of a piece of state is inferred as the INTERSECTION of
the locks held across all of its write sites (lexically held ``with``
locks plus caller-held locks propagated one level: a helper whose every
call site holds ``_mu`` analyzes as entered with ``_mu`` held).  An
empty intersection over multi-root writes means no lock consistently
protects the state — a data race.  ``__init__`` and module-body writes
are exempt (publish-before-share), as are ``threading.local()`` and
``contextvars.ContextVar`` bindings (per-thread/per-context by
construction).  This subsumes LD301/LD303's per-class and dict-slot
maps with ONE cross-module inference (docs/LINT.md has the LD3xx
deprecation path).

**CC702 — lock-order deadlock cycles.**  Every ``with lock:`` region
nested (lexically or through a call, locks-acquired propagated
transitively) inside another lock's region contributes an edge
``outer -> inner`` to the global acquisition graph; ``Condition(lock)``
aliases to its underlying lock.  A cycle means two threads can acquire
the participating locks in opposite orders — deadlock.

**CC703 — blocking-under-lock.**  Calls that can block indefinitely or
sleep — ``time.sleep``, ``queue.Queue.get/put/join``, ``Thread.join``,
``Event.wait``, ``block_until_ready`` (a device sync!), socket
send/recv/accept/connect — issued while any catalogued lock is held.
``Condition.wait`` is exempt (it releases the lock it waits on).
Receivers are typed from assignments (``self._q = queue.Queue()``,
``t = threading.Thread(...)``), so ``",".join(...)`` or ``dict.get``
never misfire.

**CC704 — context-hop discipline.**  A thread spawn whose target
(transitively, depth-limited) touches ``contextvars``-scoped state —
the obs fan-out (``record``/``record_hwm``/``record_bucket``/``span``/
``current``), ``interrupt.check``, or a module-level ``ContextVar``'s
``get``/``set`` — without the spawn being wrapped in
``contextvars.copy_context()`` and without the target establishing its
OWN scope (``activate``/``QueryObs``/``copy_context`` on its path).
This is the bug class PR 8 fixed by hand in server/pool.py: spans and
counters silently landing on an orphan context.

Run it through ``tools/lint.py --pass conc`` (whole package) or over an
explicit file set (the two-file fixture test proves findings appear
only when both halves are in the batch).  Dynamic twin:
``tools/race_stress.py`` converts PLAUSIBLE findings into CONFIRMED
ones under a shrunk ``sys.setswitchinterval``.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .diag import Diagnostic, SourceFile, register_rules

register_rules({
    "CC701": "shared state written from >=2 thread roots without a "
             "consistently held guard",
    "CC702": "lock acquisition order cycle across threads (deadlock)",
    "CC703": "blocking/sleeping call while holding a lock",
    "CC704": "thread target touches context-scoped state without "
             "copy_context or its own scope",
})

_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTORS = {"Condition"}
_LOCKLIKE_CTORS = _LOCK_CTORS | _COND_CTORS | {"Semaphore",
                                               "BoundedSemaphore"}
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter", "WeakSet",
                    "WeakValueDictionary", "WeakKeyDictionary"}
_PERTHREAD_CTORS = {"local", "ContextVar"}  # threading.local / contextvars
_MUTATORS = {"append", "extend", "insert", "pop", "popitem", "clear",
             "update", "setdefault", "add", "remove", "discard",
             "appendleft", "popleft"}
#: ambient contextvars-scoped touch points (obs/context.py fan-out +
#: utils/interrupt) — CC704's "uses the submitting thread's context"
_AMBIENT_ATTRS = {"record", "record_hwm", "record_bucket", "span",
                  "current", "current_op", "check"}
_AMBIENT_OWNERS = {"_obs", "obs", "context", "_interrupt", "interrupt",
                   "_ctx"}
#: calls that ESTABLISH a scope of their own (or hop one across): a
#: target reaching these needs no inherited context
_SCOPE_ATTRS = {"activate", "copy_context"}

#: dynamic-dispatch edges the AST cannot see — the catalogue of known
#: worker-loop hand-offs (module-path suffix -> module-path suffix).
#: Each entry is an ordinary call edge added to the graph when both
#: endpoints resolve, so thread reach flows through ``entry.session``-
#: style indirections.
SEED_EDGES: List[Tuple[str, str]] = [
    # pool workers drive statements through _Entry.session
    ("server.pool:StatementPool._exec_entry",
     "session.session:Session.execute_stmt"),
    # the prewarm worker replays sample SQL on its internal session
    ("session.prewarm:PrewarmWorker._warm_family",
     "session.session:Session.query"),
    ("session.prewarm:PrewarmWorker._warm_family",
     "session.session:Session.execute"),
]

MAIN_ROOT = "main"


# =========================================================================
# module model
# =========================================================================

def _call_name(fn: ast.expr) -> Optional[str]:
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _self_attr(e: ast.expr) -> Optional[str]:
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
            and e.value.id == "self":
        return e.attr
    return None


class _ClassInfo:
    __slots__ = ("name", "lock_fields", "cond_alias", "queue_fields",
                 "thread_fields", "event_fields", "attr_types")

    def __init__(self, name: str):
        self.name = name
        self.lock_fields: Set[str] = set()
        #: Condition field -> the lock field it wraps (same OS lock)
        self.cond_alias: Dict[str, str] = {}
        self.queue_fields: Set[str] = set()
        self.thread_fields: Set[str] = set()
        self.event_fields: Set[str] = set()
        #: self.attr -> ClassName it is constructed from
        self.attr_types: Dict[str, str] = {}


class _Func:
    __slots__ = ("mod", "cls", "name", "node", "qual",
                 "calls", "writes", "acquires", "blocking", "spawns",
                 "ambient", "establishes", "entry_held", "nested_in")

    def __init__(self, mod: str, cls: Optional[str], name: str, node,
                 nested_in: Optional[str] = None):
        self.mod = mod
        self.cls = cls
        self.name = name
        self.node = node
        self.qual = f"{mod}:{cls + '.' if cls else ''}{name}"
        #: (callee qual | None-unresolved, held frozenset, lineno)
        self.calls: List[tuple] = []
        #: (state_id, node, held, is_init)
        self.writes: List[tuple] = []
        #: (lock_id, node, held-before)
        self.acquires: List[tuple] = []
        #: (reason, node, held)
        self.blocking: List[tuple] = []
        #: (target_qual | None, node, ctx_wrapped)
        self.spawns: List[tuple] = []
        self.ambient = False
        self.establishes = False
        self.entry_held: FrozenSet = frozenset()
        self.nested_in = nested_in


class _Module:
    def __init__(self, sf: SourceFile, modpath: str):
        self.sf = sf
        self.modpath = modpath
        self.imports: Dict[str, str] = {}     # alias -> dotted target
        self.containers: Dict[str, int] = {}  # name -> def lineno
        self.locks: Set[str] = set()
        self.classes: Dict[str, _ClassInfo] = {}
        self.funcs: List[_Func] = []
        self.body_calls: List[tuple] = []     # module-body call names


def _modpath_for(path: str) -> str:
    """Dotted module path: the longest package-ish suffix of the file
    path (``a/b/c.py`` -> ``a.b.c``), stable across absolute roots."""
    norm = os.path.normpath(path)
    parts = norm.split(os.sep)
    parts[-1] = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    # keep at most the trailing 4 components: enough to be unique in a
    # package tree, short enough for messages
    return ".".join(p for p in parts[-4:] if p not in ("", "."))


def _scan_module(sf: SourceFile) -> _Module:
    m = _Module(sf, _modpath_for(sf.path))
    # imports anywhere in the file (this tree lazy-imports inside
    # functions pervasively) — aliases are module-scoped for resolution
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                m.imports[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            for a in node.names:
                m.imports[a.asname or a.name] = (base + "." + a.name
                                                 if base else a.name)
    for node in sf.tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            val = node.value
            kind = _value_kind(val)
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if kind == "container":
                    m.containers[t.id] = node.lineno
                elif kind == "lock":
                    m.locks.add(t.id)
        elif isinstance(node, ast.ClassDef):
            m.classes[node.name] = _scan_class(node)
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            nm = _call_name(node.value.func)
            if nm:
                m.body_calls.append((nm, node.value, node.lineno))
        elif isinstance(node, (ast.For, ast.If, ast.With, ast.Try)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    nm = _call_name(sub.func)
                    if nm:
                        m.body_calls.append((nm, sub, sub.lineno))
    return m


def _value_kind(val: Optional[ast.expr]) -> Optional[str]:
    if val is None:
        return None
    if isinstance(val, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                        ast.DictComp, ast.SetComp)):
        return "container"
    if isinstance(val, ast.Call):
        nm = _call_name(val.func)
        if nm in _PERTHREAD_CTORS:
            return "perthread"
        if nm in _LOCK_CTORS or nm in _COND_CTORS:
            return "lock"
        if nm in _CONTAINER_CTORS:
            return "container"
    return None


def _scan_class(cls: ast.ClassDef) -> _ClassInfo:
    ci = _ClassInfo(cls.name)
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        nm = _call_name(node.value.func)
        for tgt in node.targets:
            a = _self_attr(tgt)
            if a is None:
                continue
            if nm in _LOCK_CTORS:
                ci.lock_fields.add(a)
            elif nm in _COND_CTORS:
                args = node.value.args
                inner = _self_attr(args[0]) if args else None
                if inner:
                    ci.cond_alias[a] = inner
                else:
                    ci.lock_fields.add(a)  # Condition() owns its lock
            elif nm == "Queue" or nm in ("LifoQueue", "PriorityQueue",
                                         "SimpleQueue"):
                ci.queue_fields.add(a)
            elif nm == "Thread":
                ci.thread_fields.add(a)
            elif nm == "Event":
                ci.event_fields.add(a)
            elif nm and nm[0].isupper() and nm not in _LOCKLIKE_CTORS:
                ci.attr_types[a] = nm
    return ci


# =========================================================================
# per-function walker
# =========================================================================

class _Walker:
    """One pass over a function body collecting events with the
    LEXICALLY held lock set.  Nested defs are walked as separate
    functions with an empty held set (they run later, possibly on
    another thread)."""

    def __init__(self, mod: _Module, cls: Optional[_ClassInfo],
                 func: _Func, out_funcs: List[_Func]):
        self.mod = mod
        self.cls = cls
        self.func = func
        self.out = out_funcs
        #: local name -> inferred kind ("thread"|"queue"|"event"|"ctx"
        #: |ClassName)
        self.local_types: Dict[str, str] = {}

    # ---- lock identity ---------------------------------------------------
    def _lock_id(self, e: ast.expr) -> Optional[tuple]:
        a = _self_attr(e)
        if a is not None and self.cls is not None:
            a = self.cls.cond_alias.get(a, a)
            if a in self.cls.lock_fields:
                return ("C", self.mod.modpath, self.cls.name, a)
            return None
        if isinstance(e, ast.Name) and e.id in self.mod.locks:
            return ("M", self.mod.modpath, e.id)
        if isinstance(e, ast.Subscript) \
                and isinstance(e.slice, ast.Constant) \
                and e.slice.value == "lock" \
                and isinstance(e.value, ast.Name):
            return ("D", self.mod.modpath, e.value.id)
        return None

    # ---- statements ------------------------------------------------------
    def walk(self, stmts, held: FrozenSet) -> None:
        for s in stmts:
            self._stmt(s, held)

    def _stmt(self, s: ast.stmt, held: FrozenSet) -> None:
        if isinstance(s, ast.With):
            got = set()
            for item in s.items:
                lid = self._lock_id(item.context_expr)
                if lid is not None:
                    got.add(lid)
                    self.func.acquires.append((lid, item.context_expr,
                                               held))
                else:
                    self._expr(item.context_expr, held)
            self.walk(s.body, held | frozenset(got))
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = _Func(self.mod.modpath,
                        self.cls.name if self.cls else None,
                        s.name, s, nested_in=self.func.qual)
            self.out.append(sub)
            _Walker(self.mod, self.cls, sub, self.out).walk(
                s.body, frozenset())
        elif isinstance(s, (ast.If, ast.While)):
            self._expr(s.test, held)
            self.walk(s.body, held)
            self.walk(s.orelse, held)
        elif isinstance(s, ast.For):
            self._expr(s.iter, held)
            self.walk(s.body, held)
            self.walk(s.orelse, held)
        elif isinstance(s, ast.Try):
            for blk in ([s.body, s.orelse, s.finalbody]
                        + [h.body for h in s.handlers]):
                self.walk(blk, held)
        elif isinstance(s, ast.Return) and s.value is not None:
            self._expr(s.value, held)
        elif isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            val = getattr(s, "value", None)
            if val is not None:
                self._infer_local(targets, val)
                self._expr(val, held)
            for t in targets:
                self._write_target(t, held)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                self._write_target(t, held)
        elif isinstance(s, ast.Expr):
            self._expr(s.value, held)
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self._expr(child, held)

    def _infer_local(self, targets, val) -> None:
        if len(targets) != 1 or not isinstance(targets[0], ast.Name):
            return
        name = targets[0].id
        if not isinstance(val, ast.Call):
            return
        nm = _call_name(val.func)
        if nm == "Thread":
            self.local_types[name] = "thread"
        elif nm in ("Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"):
            self.local_types[name] = "queue"
        elif nm == "Event":
            self.local_types[name] = "event"
        elif nm == "copy_context":
            self.local_types[name] = "ctx"
        elif nm and nm[0].isupper():
            self.local_types[name] = nm

    # ---- writes ----------------------------------------------------------
    def _state_id(self, base: ast.expr) -> Optional[tuple]:
        """State identity of a mutation receiver: a module-level
        container (here or through a module alias) or an instance attr
        of a lock-carrying class."""
        if isinstance(base, ast.Name):
            if base.id in self.mod.containers:
                return ("G", self.mod.modpath, base.id)
            return None
        a = _self_attr(base)
        if a is not None and self.cls is not None \
                and self.cls.lock_fields \
                and a not in self.cls.lock_fields \
                and a not in self.cls.cond_alias:
            return ("A", self.mod.modpath, self.cls.name, a)
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name):
            tgt = self.mod.imports.get(base.value.id)
            if tgt is not None:
                return ("X", tgt, base.attr)  # cross-module: resolve later
        return None

    def _write_target(self, tgt: ast.expr, held: FrozenSet) -> None:
        if isinstance(tgt, ast.Subscript):
            sid = self._state_id(tgt.value)
            if sid is not None:
                self._note_write(sid, tgt, held)
            else:
                self._expr(tgt.value, held)
            self._expr(tgt.slice, held)
            return
        sid = self._state_id(tgt)
        if sid is not None and sid[0] == "A":
            self._note_write(sid, tgt, held)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._write_target(e, held)

    def _note_write(self, sid: tuple, node, held: FrozenSet) -> None:
        is_init = self.func.name in ("__init__", "__new__") \
            and self.func.nested_in is None
        self.func.writes.append((sid, node, held, is_init))

    # ---- expressions: calls, mutators, spawns, blocking ------------------
    def _recv_kind(self, recv: ast.expr) -> Optional[str]:
        a = _self_attr(recv)
        if a is not None and self.cls is not None:
            if a in self.cls.thread_fields:
                return "thread"
            if a in self.cls.queue_fields:
                return "queue"
            if a in self.cls.event_fields:
                return "event"
            return None
        if isinstance(recv, ast.Name):
            k = self.local_types.get(recv.id)
            if k in ("thread", "queue", "event"):
                return k
        return None

    def _expr(self, e: ast.expr, held: FrozenSet) -> None:
        for node in ast.walk(e):
            if not isinstance(node, ast.Call):
                continue
            self._call(node, held)

    def _call(self, node: ast.Call, held: FrozenSet) -> None:
        fn = node.func
        # ---- spawns -----------------------------------------------------
        nm = _call_name(fn)
        if nm == "Thread":
            self._spawn_thread(node)
        elif nm == "submit" and isinstance(fn, ast.Attribute):
            self._spawn_submit(node)
        elif nm == "BlockPipeline" and node.args:
            tq = self._resolve_ref(node.args[0])
            # the pipeline copies its creator's context by construction
            self.func.spawns.append((tq, node, True))
        # ---- mutator calls on shared state ------------------------------
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
            sid = self._state_id(fn.value)
            if sid is not None:
                self._note_write(sid, node, held)
        # ---- blocking-under-lock (CC703): record regardless of the
        # LEXICAL held set — a caller-held lock (entry_held) only
        # becomes known after propagation, so filtering happens in the
        # rule, not here
        reason = self._blocking_reason(node)
        if reason:
            self.func.blocking.append((reason, node, held))
        # ---- ambient-context / scope markers (CC704) ---------------------
        if isinstance(fn, ast.Attribute):
            if fn.attr in _AMBIENT_ATTRS and (
                    (isinstance(fn.value, ast.Name)
                     and fn.value.id in _AMBIENT_OWNERS)
                    or fn.attr in ("record", "record_hwm",
                                   "record_bucket")):
                self.func.ambient = True
            if fn.attr in ("get", "set") \
                    and isinstance(fn.value, ast.Name) \
                    and self._is_contextvar(fn.value.id):
                self.func.ambient = True
            if fn.attr in _SCOPE_ATTRS:
                self.func.establishes = True
            if fn.attr == "run" and self._is_ctx(fn.value):
                self.func.establishes = True
        elif isinstance(fn, ast.Name) and fn.id in _SCOPE_ATTRS:
            self.func.establishes = True
        # ---- the call edge ----------------------------------------------
        callee = self._resolve_call(fn)
        self.func.calls.append((callee, held, node.lineno))

    def _is_contextvar(self, name: str) -> bool:
        # module-level `X = contextvars.ContextVar(...)` assignments
        for n in self.mod.sf.tree.body:
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and _call_name(n.value.func) == "ContextVar":
                for t in n.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return True
        return False

    def _is_ctx(self, e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return self.local_types.get(e.id) == "ctx" \
                or "ctx" in e.id.lower()
        if isinstance(e, ast.Attribute):
            return "ctx" in e.attr.lower()
        return False

    def _blocking_reason(self, node: ast.Call) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if fn.attr == "sleep" and isinstance(recv, ast.Name) \
                    and recv.id == "time":
                return "time.sleep"
            if fn.attr == "block_until_ready":
                return "block_until_ready (device sync)"
            kind = self._recv_kind(recv)
            if kind == "queue" and fn.attr in ("get", "put", "join"):
                return f"queue.{fn.attr}"
            if kind == "thread" and fn.attr == "join":
                return "Thread.join"
            if kind == "event" and fn.attr == "wait":
                return "Event.wait"
            if fn.attr in ("recv", "accept", "connect", "sendall",
                           "makefile") and isinstance(recv, ast.Name) \
                    and ("sock" in recv.id.lower()
                         or recv.id == "socket"):
                return f"socket.{fn.attr}"
        elif isinstance(fn, ast.Name) and fn.id == "sleep":
            if self.mod.imports.get("sleep", "").startswith("time"):
                return "time.sleep"
        return None

    # ---- spawn helpers ---------------------------------------------------
    def _spawn_thread(self, node: ast.Call) -> None:
        target = None
        args_kw = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg == "args":
                args_kw = kw.value
        if target is None:
            return
        wrapped = False
        if isinstance(target, ast.Attribute) and target.attr == "run" \
                and self._is_ctx(target.value):
            wrapped = True
            # devpipe idiom: the real entry rides args=(real_target,)
            if isinstance(args_kw, (ast.Tuple, ast.List)) and args_kw.elts:
                target = args_kw.elts[0]
        tq = self._resolve_ref(target)
        self.func.spawns.append((tq, node, wrapped))

    def _spawn_submit(self, node: ast.Call) -> None:
        if not node.args:
            return
        first = node.args[0]
        wrapped = False
        if isinstance(first, ast.Attribute) and first.attr == "run" \
                and self._is_ctx(first.value):
            wrapped = True
            if len(node.args) > 1:
                first = node.args[1]
        tq = self._resolve_ref(first)
        if tq is not None or not wrapped:
            self.func.spawns.append((tq, node, wrapped))

    # ---- resolution ------------------------------------------------------
    def _resolve_ref(self, e: ast.expr) -> Optional[str]:
        """A function REFERENCE (not call): qual or None."""
        a = _self_attr(e)
        if a is not None and self.cls is not None:
            return f"{self.mod.modpath}:{self.cls.name}.{a}"
        if isinstance(e, ast.Name):
            if self.cls is not None:
                # nested stage fns defined inside a method index under
                # the class; _find_qual falls back to module level
                return f"{self.mod.modpath}:{self.cls.name}.{e.id}"
            return f"{self.mod.modpath}:{e.id}"
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name):
            tgt = self.mod.imports.get(e.value.id)
            if tgt:
                return f"?{tgt}:{e.attr}"  # cross-module, resolve later
            ty = self.local_types.get(e.value.id)
            if ty and ty not in ("thread", "queue", "event", "ctx"):
                return f"{self.mod.modpath}:{ty}.{e.attr}"
        return None

    def _resolve_call(self, fn: ast.expr) -> Optional[str]:
        if isinstance(fn, ast.Name):
            return f"{self.mod.modpath}:{fn.id}"
        if isinstance(fn, ast.Attribute):
            a = _self_attr(fn)
            if a is not None and self.cls is not None:
                ty = self.cls.attr_types.get(a)
                if ty:  # self.pool.run(...) -> StatementPool.run later?
                    return None
                return f"{self.mod.modpath}:{self.cls.name}.{a}"
            if isinstance(fn.value, ast.Name):
                base = fn.value.id
                tgt = self.mod.imports.get(base)
                if tgt:
                    return f"?{tgt}:{fn.attr}"
                ty = self.local_types.get(base)
                if ty and ty not in ("thread", "queue", "event", "ctx"):
                    return f"{self.mod.modpath}:{ty}.{fn.attr}"
            elif isinstance(fn.value, ast.Attribute):
                a2 = _self_attr(fn.value)
                if a2 is not None and self.cls is not None:
                    ty = self.cls.attr_types.get(a2)
                    if ty:
                        return f"{self.mod.modpath}:{ty}.{fn.attr}"
        return None


# =========================================================================
# the whole-program analysis
# =========================================================================

class _Program:
    def __init__(self, sources: List[SourceFile]):
        self.modules: List[_Module] = [_scan_module(sf) for sf in sources]
        self.by_path: Dict[str, SourceFile] = {sf.path: sf
                                               for sf in sources}
        self.funcs: Dict[str, _Func] = {}
        self._index()
        self._resolve()
        self._propagate_held()
        self.roots = self._compute_roots()

    # ---- indexing --------------------------------------------------------
    def _index(self) -> None:
        for m in self.modules:
            out: List[_Func] = []
            for node in m.sf.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    f = _Func(m.modpath, None, node.name, node)
                    out.append(f)
                    _Walker(m, None, f, out).walk(node.body, frozenset())
                elif isinstance(node, ast.ClassDef):
                    ci = m.classes[node.name]
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            f = _Func(m.modpath, node.name, sub.name, sub)
                            out.append(f)
                            _Walker(m, ci, f, out).walk(sub.body,
                                                        frozenset())
            m.funcs = out
            for f in out:
                self.funcs.setdefault(f.qual, f)

    def _find_qual(self, ref: str) -> Optional[str]:
        """Resolve a ``?module:name`` cross-module ref (or check a direct
        qual) against the index; module matching is by dotted-suffix."""
        if not ref:
            return None
        if ref in self.funcs:
            return ref
        if not ref.startswith("?"):
            if ":" in ref:
                mod, name = ref.split(":", 1)
                # a CLASS name: constructor -> __init__
                cand = f"{mod}:{name}.__init__"
                if cand in self.funcs:
                    return cand
                # class-qualified miss -> module-level function
                if "." in name:
                    bare = f"{mod}:{name.rsplit('.', 1)[1]}"
                    if bare in self.funcs:
                        return bare
            return None
        modref, name = ref[1:].split(":", 1)
        tail = modref.split(".")
        for m in self.modules:
            mp = m.modpath.split(".")
            if mp[-len(tail):] == tail or mp[-1] == tail[-1]:
                q = f"{m.modpath}:{name}"
                if q in self.funcs:
                    return q
                q2 = f"{m.modpath}:{name}.__init__"
                if q2 in self.funcs:
                    return q2
        return None

    def _resolve(self) -> None:
        for f in self.funcs.values():
            f.calls = [(self._find_qual(c) if c else None, held, ln)
                       for c, held, ln in f.calls]
            f.spawns = [(self._find_qual(t) if t else None, node, wrapped)
                        for t, node, wrapped in f.spawns]
        # the hand-seeded dynamic-dispatch edges (known worker loops)
        for src_sfx, dst_sfx in SEED_EDGES:
            src = self._suffix_func(src_sfx)
            dst = self._suffix_func(dst_sfx)
            if src is not None and dst is not None:
                src.calls.append((dst.qual, frozenset(), 0))

    def _suffix_func(self, sfx: str) -> Optional[_Func]:
        msfx, name = sfx.split(":", 1)
        for q, f in self.funcs.items():
            mod, fname = q.split(":", 1)
            if fname == name and (mod.endswith(msfx)
                                  or mod.split(".")[-1]
                                  == msfx.split(".")[-1]):
                return f
        return None

    # ---- caller-held propagation ----------------------------------------
    def _propagate_held(self) -> None:
        callers: Dict[str, List[FrozenSet]] = {}
        for _ in range(3):
            callers.clear()
            for f in self.funcs.values():
                eh = f.entry_held
                for callee, held, _ln in f.calls:
                    if callee is not None:
                        callers.setdefault(callee, []).append(held | eh)
            changed = False
            for q, sets in callers.items():
                f = self.funcs.get(q)
                if f is None or f.name in ("__init__", "__new__"):
                    continue
                inter = frozenset.intersection(*map(frozenset, sets)) \
                    if sets else frozenset()
                if inter != f.entry_held:
                    f.entry_held = inter
                    changed = True
            if not changed:
                break

    # ---- thread roots ----------------------------------------------------
    def _compute_roots(self) -> Dict[str, Set[str]]:
        edges: Dict[str, List[str]] = {}
        has_caller: Set[str] = set()
        for f in self.funcs.values():
            lst = edges.setdefault(f.qual, [])
            for callee, _h, _ln in f.calls:
                if callee is not None:
                    lst.append(callee)
                    has_caller.add(callee)
            # a nested def belongs to its parent's reach (closures run
            # where — and as often as — their enclosing scope wires them)
            if f.nested_in is not None:
                edges.setdefault(f.nested_in, []).append(f.qual)
                has_caller.add(f.qual)
        entries: Set[str] = set()
        for f in self.funcs.values():
            for target, _node, _w in f.spawns:
                if target is not None:
                    entries.add(target)

        def reach(starts: Set[str]) -> Set[str]:
            seen = set(starts)
            stack = list(starts)
            while stack:
                cur = stack.pop()
                for nxt in edges.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            return seen

        roots: Dict[str, Set[str]] = {q: set() for q in self.funcs}
        for e in sorted(entries):
            for q in reach({e}):
                if q in roots:
                    roots[q].add(e)
        main_seeds = {q for q in self.funcs
                      if q not in has_caller and q not in entries}
        # module bodies call into the graph at import time (main)
        for m in self.modules:
            for nm, _node, _ln in m.body_calls:
                q = f"{m.modpath}:{nm}"
                if q in self.funcs:
                    main_seeds.add(q)
        for q in reach(main_seeds):
            if q in roots:
                roots[q].add(MAIN_ROOT)
        return roots

    # ---- public: the thread-root report ---------------------------------
    def thread_root_report(self) -> Dict[str, List[str]]:
        entries: Dict[str, List[str]] = {}
        for f in self.funcs.values():
            for target, node, wrapped in f.spawns:
                if target is not None:
                    entries.setdefault(target, []).append(
                        f"{f.qual}:{getattr(node, 'lineno', 0)}")
        return entries


def thread_roots(sources: List[SourceFile]) -> Dict[str, List[str]]:
    """Discovered thread entry points -> their spawn sites (the
    ``--roots`` introspection surface and the race-stress catalogue)."""
    return _Program(sources).thread_root_report()


# =========================================================================
# rules
# =========================================================================

def _fmt_lock(lid: tuple) -> str:
    if lid[0] == "M":
        return f"{lid[1]}.{lid[2]}"
    if lid[0] == "C":
        return f"{lid[1]}.{lid[2]}.{lid[3]}"
    if lid[0] == "D":
        return f"{lid[1]}.{lid[2]}[\"lock\"]"
    return str(lid)


def _fmt_state(sid: tuple) -> str:
    if sid[0] == "G":
        return f"{sid[1]}.{sid[2]}"
    return f"{sid[1]}.{sid[2]}.{sid[3]}"


def _cc701(prog: _Program) -> List[Diagnostic]:
    # gather write events per state id, cross-module refs folded in
    writes: Dict[tuple, List[tuple]] = {}
    for f in prog.funcs.values():
        eff_extra = f.entry_held
        for sid, node, held, is_init in f.writes:
            if is_init:
                continue
            if sid[0] == "X":  # alias.NAME -> owning module's container
                owner = None
                tail = sid[1].split(".")
                for m in prog.modules:
                    if m.modpath.split(".")[-1] == tail[-1] \
                            or m.modpath.endswith(sid[1]):
                        if sid[2] in m.containers:
                            owner = ("G", m.modpath, sid[2])
                            break
                if owner is None:
                    continue
                sid = owner
            writes.setdefault(sid, []).append(
                (f, node, held | eff_extra))
    out: List[Diagnostic] = []
    for sid, evs in sorted(writes.items(), key=lambda kv: str(kv[0])):
        root_union: Set[str] = set()
        for f, _n, _h in evs:
            root_union |= prog.roots.get(f.qual, set())
        if len(root_union) < 2:
            continue
        guard = frozenset.intersection(*[frozenset(h) for _f, _n, h in evs])
        if guard:
            continue
        locks_seen: Set[tuple] = set()
        for _f, _n, h in evs:
            locks_seen |= h
        hint = (" (locks held at other sites: "
                + ", ".join(sorted(_fmt_lock(x) for x in locks_seen))
                + ")") if locks_seen else " (no lock at any write site)"
        nroots = ", ".join(sorted(r.split(":")[-1] for r in root_union))
        seen_lines: Set[tuple] = set()
        for f, node, held in evs:
            if held:
                continue  # only the unguarded sites are actionable
            path = _path_of(prog, f.mod)
            key = (path, node.lineno)
            if key in seen_lines:
                continue
            seen_lines.add(key)
            out.append(Diagnostic(
                "CC701",
                f"`{_fmt_state(sid)}` is written from >=2 thread roots "
                f"[{nroots}] with no consistently held guard{hint}; "
                f"write in `{f.name}` holds nothing",
                path, node.lineno, getattr(node, "col_offset", 0)))
    return out


def _lock_roots(prog: _Program) -> Dict[tuple, Set[str]]:
    """Thread roots that reach each lock's acquire sites — the
    contention precondition: a lock only ever taken from ONE root has
    no second thread to deadlock or stall (multi-root gating for
    CC702/CC703, same contract as CC701)."""
    out: Dict[tuple, Set[str]] = {}
    for f in prog.funcs.values():
        for lid, _node, _held in f.acquires:
            out.setdefault(lid, set()).update(
                prog.roots.get(f.qual, set()))
    return out


def _cc702(prog: _Program) -> List[Diagnostic]:
    # transitive acquired-set per function (2 rounds is plenty for the
    # helper-under-lock chains in this tree)
    acq: Dict[str, Set[tuple]] = {q: {lid for lid, _n, _h in f.acquires}
                                  for q, f in prog.funcs.items()}
    for _ in range(2):
        for q, f in prog.funcs.items():
            for callee, _h, _ln in f.calls:
                if callee in acq:
                    acq[q] |= acq[callee]
    edges: Dict[tuple, Set[tuple]] = {}
    witness: Dict[Tuple[tuple, tuple], tuple] = {}
    for q, f in prog.funcs.items():
        for lid, node, held in f.acquires:
            for h in (held | f.entry_held):
                if h != lid:
                    edges.setdefault(h, set()).add(lid)
                    witness.setdefault((h, lid),
                                       (f, getattr(node, "lineno", 0)))
        # call-through acquisition: holding h, call g which acquires l
        for callee, held, ln in f.calls:
            if callee is None:
                continue
            for h in (held | f.entry_held):
                for l2 in acq.get(callee, ()):
                    if l2 != h:
                        edges.setdefault(h, set()).add(l2)
                        witness.setdefault((h, l2), (f, ln))
    # cycle detection (DFS, report each cycle's edges once); a cycle
    # only deadlocks when >= 2 roots can traverse its locks
    lroots = _lock_roots(prog)
    out: List[Diagnostic] = []
    color: Dict[tuple, int] = {}
    stack: List[tuple] = []
    reported: Set[frozenset] = set()

    def dfs(u: tuple) -> None:
        color[u] = 1
        stack.append(u)
        for v in sorted(edges.get(u, ()), key=str):
            if color.get(v, 0) == 0:
                dfs(v)
            elif color.get(v) == 1:
                cyc = stack[stack.index(v):] + [v]
                key = frozenset(cyc)
                if key in reported:
                    continue
                reported.add(key)
                roots: Set[str] = set()
                for lid in key:
                    roots |= lroots.get(lid, set())
                if len(roots) < 2:
                    continue  # single-root: no second thread to oppose
                pairs = list(zip(cyc, cyc[1:]))
                f, ln = witness[pairs[0]]
                order = " -> ".join(_fmt_lock(x) for x in cyc)
                out.append(Diagnostic(
                    "CC702",
                    f"lock-order cycle: {order} (witness edge in "
                    f"`{f.name}`; a thread taking these in the opposite "
                    f"order deadlocks)",
                    _path_of(prog, f.mod), ln))
        stack.pop()
        color[u] = 2

    for u in sorted(edges, key=str):
        if color.get(u, 0) == 0:
            dfs(u)
    return out


def _cc703(prog: _Program) -> List[Diagnostic]:
    lroots = _lock_roots(prog)
    out = []
    for f in prog.funcs.values():
        for reason, node, held in f.blocking:
            eff = held | f.entry_held
            if not eff:
                continue
            # contention precondition: some held lock must be taken
            # from >= 2 roots — nobody stalls behind a one-root lock
            roots: Set[str] = set()
            for lid in eff:
                roots |= lroots.get(lid, set())
            if len(roots) < 2:
                continue
            locks = ", ".join(sorted(_fmt_lock(x) for x in eff))
            out.append(Diagnostic(
                "CC703",
                f"`{reason}` called in `{f.name}` while holding "
                f"{locks}: every thread contending on the lock stalls "
                f"behind this wait",
                _path_of(prog, f.mod), node.lineno,
                getattr(node, "col_offset", 0)))
    return out


def _cc704(prog: _Program) -> List[Diagnostic]:
    out = []
    for f in prog.funcs.values():
        for target, node, wrapped in f.spawns:
            if wrapped or target is None:
                continue
            tf = prog.funcs.get(target)
            if tf is None:
                continue
            uses, establishes = _bfs_ctx(prog, tf, depth=3)
            if uses and not establishes:
                out.append(Diagnostic(
                    "CC704",
                    f"thread target `{tf.name}` (spawned in `{f.name}`) "
                    f"touches contextvars-scoped obs/interrupt state "
                    f"but the spawn neither copies the submitting "
                    f"context (contextvars.copy_context) nor opens its "
                    f"own scope — counters/spans land on an orphan "
                    f"context",
                    _path_of(prog, f.mod), node.lineno,
                    getattr(node, "col_offset", 0)))
    return out


def _bfs_ctx(prog: _Program, start: _Func, depth: int) -> Tuple[bool, bool]:
    seen = {start.qual}
    frontier = [start]
    uses = establishes = False
    for _ in range(depth):
        nxt: List[_Func] = []
        for f in frontier:
            uses = uses or f.ambient
            establishes = establishes or f.establishes
            for callee, _h, _ln in f.calls:
                if callee and callee not in seen:
                    seen.add(callee)
                    g = prog.funcs.get(callee)
                    if g is not None:
                        nxt.append(g)
        frontier = nxt
    for f in frontier:  # the last ring's own markers still count
        uses = uses or f.ambient
        establishes = establishes or f.establishes
    return uses, establishes


def _path_of(prog: _Program, modpath: str) -> str:
    for m in prog.modules:
        if m.modpath == modpath:
            return m.sf.path
    return modpath


# =========================================================================
# entry point
# =========================================================================

def lint_concurrency(sources: List[SourceFile]) -> List[Diagnostic]:
    """The CC7xx pass over one whole-program batch of sources.  Inline
    suppressions are honored per owning file."""
    if not sources:
        return []
    prog = _Program(sources)
    diags = _cc701(prog) + _cc702(prog) + _cc703(prog) + _cc704(prog)
    out: List[Diagnostic] = []
    for d in diags:
        sf = prog.by_path.get(d.path)
        if sf is not None and sf.suppressed(d.rule, d.line):
            continue
        out.append(d)
    out.sort(key=lambda d: (d.path, d.line, d.rule))
    return out
