"""Pass 2 — plan-device invariant checker (PD2xx).

Walks PHYSICAL plans after placement and verifies the device enforcer's
invariants, using the same `tpu_admissibility` predicate the enforcer
itself places with (planner/device.py) so checker and placement cannot
drift:

- PD201: an operator marked `use_tpu` whose hot loop is NOT expressible
  as device kernels (admissibility violation — the premature-placement
  bug class of "Premature Dimensional Collapse", PAPERS.md).
- PD202: a `use_tpu` operator that derive_stats never costed — placement
  ran before estimation, so the min-rows cost gate compared garbage.
- PD203: malformed mesh join strategy (strategy outside
  broadcast/shuffle, strategy without its cost record, or strategy on a
  non-TPU node).
- PD204: `use_tpu` on an operator class with no device lowering at all
  (readers, limits, duals — the device never scans KV).
- PD205: EXPLAIN device annotation inconsistent with placement (the
  rendered task column must say `tpu` exactly when the node is placed on
  the TPU tier).
- PD206: malformed CPU-fallback edge: a child that is not a physical
  operator or lost its schema — the materialization boundary between
  tiers needs both.
- PD207: malformed mesh shard annotation: `mesh_shards` that is not a
  power of two, exceeds the live device count, or sits on an operator
  the sharded tier cannot run (checked with the same `mesh_admissible`
  predicate place_devices annotates with, so checker and placement
  cannot drift).

Runs three ways: offline over the SQL corpus in tests/ (`check_corpus`,
driven by tools/lint.py), as an opt-in runtime verifier inside the
optimizer (`verify_plan`, gated by the `tidb_qlint_verify` sysvar), and
directly over any plan (`check_plan`).
"""
from __future__ import annotations

import ast
import os
from typing import List, Optional

from .diag import Diagnostic, register_rules

register_rules({
    "PD201": "TPU placement violates kernel admissibility",
    "PD202": "TPU placement without a derived row estimate",
    "PD203": "malformed mesh join strategy",
    "PD204": "TPU placement on an operator with no device lowering",
    "PD205": "EXPLAIN device annotation inconsistent with placement",
    "PD206": "malformed CPU-fallback edge (non-operator or schema-less child)",
    "PD207": "malformed mesh shard annotation (non-power-of-two, over the "
             "device count, or on a mesh-inadmissible operator)",
})

_DEVICE_OPS = ("PhysicalHashAgg", "PhysicalHashJoin", "PhysicalSort",
               "PhysicalTopN", "PhysicalProjection", "PhysicalSelection")


class PlanDeviceError(Exception):
    """Raised by the opt-in runtime verifier on the first bad plan."""

    def __init__(self, diags: List[Diagnostic]):
        self.diags = diags
        super().__init__("; ".join(d.format() for d in diags))


def _node_path(path: List[str]) -> str:
    return "/".join(path) or "<root>"


def _live_device_count() -> Optional[int]:
    """Device count when a backend is already live; None offline (the
    checker must not force a jax backend just to validate an
    annotation)."""
    import sys
    if "jax" not in sys.modules:
        return None
    try:
        return int(len(sys.modules["jax"].devices()))
    except Exception:
        return None


def check_plan(p, path: Optional[List[str]] = None,
               where: str = "<plan>") -> List[Diagnostic]:
    """All PD2xx checks over one placed physical plan tree."""
    from ..planner.device import mesh_admissible, tpu_admissibility
    from ..planner.physical import PhysicalPlan
    path = (path or []) + [p.op_name()]
    out: List[Diagnostic] = []
    use_tpu = bool(getattr(p, "use_tpu", False))
    device_capable = any(type(p).__name__ == n or
                         any(b.__name__ == n for b in type(p).__mro__)
                         for n in _DEVICE_OPS)
    if use_tpu and not device_capable:
        out.append(Diagnostic(
            "PD204", f"{_node_path(path)}: use_tpu on {p.op_name()}, "
            "which has no device lowering", where))
    elif use_tpu:
        reason = tpu_admissibility(p)
        if reason is not None:
            out.append(Diagnostic(
                "PD201", f"{_node_path(path)}: placed on TPU but "
                f"inadmissible — {reason}", where))
        if not getattr(p, "has_estimate", False):
            out.append(Diagnostic(
                "PD202", f"{_node_path(path)}: placed on TPU with no "
                "derived row estimate (derive_stats must run before "
                "place_devices)", where))
    strategy = getattr(p, "mesh_strategy", None)
    if strategy is not None:
        if strategy not in ("broadcast", "shuffle"):
            out.append(Diagnostic(
                "PD203", f"{_node_path(path)}: mesh_strategy "
                f"{strategy!r} not in broadcast/shuffle", where))
        if not use_tpu:
            out.append(Diagnostic(
                "PD203", f"{_node_path(path)}: mesh_strategy on a "
                "non-TPU node", where))
        cost = getattr(p, "mesh_cost", None)
        if not (isinstance(cost, dict) and "broadcast_bytes" in cost
                and "shuffle_bytes" in cost):
            out.append(Diagnostic(
                "PD203", f"{_node_path(path)}: mesh_strategy without "
                "its broadcast/shuffle cost record", where))
    ms = getattr(p, "mesh_shards", None)
    if ms is not None:
        ms = int(ms)
        if ms < 1 or (ms & (ms - 1)) != 0:
            out.append(Diagnostic(
                "PD207", f"{_node_path(path)}: mesh_shards {ms} is not "
                "a power of two — shard_bucket only mints power-of-two "
                "shard counts", where))
        if not use_tpu:
            out.append(Diagnostic(
                "PD207", f"{_node_path(path)}: mesh_shards on a "
                "non-TPU node — the sharded tier only runs placed "
                "operators", where))
        else:
            reason = mesh_admissible(p)
            if reason is not None:
                out.append(Diagnostic(
                    "PD207", f"{_node_path(path)}: mesh_shards on a "
                    f"mesh-inadmissible operator — {reason}", where))
        ndev = _live_device_count()
        if ndev is not None and ms > ndev:
            out.append(Diagnostic(
                "PD207", f"{_node_path(path)}: mesh_shards {ms} "
                f"exceeds the {ndev} live device(s)", where))
    for c in p.children:
        if not isinstance(c, PhysicalPlan) or c.schema is None:
            out.append(Diagnostic(
                "PD206", f"{_node_path(path)}: child "
                f"{type(c).__name__} is not a schema-bearing physical "
                "operator — the tier boundary cannot materialize it",
                where))
            continue
        out.extend(check_plan(c, path, where))
    return out


def _explain_tasks(p) -> List[tuple]:
    """(op_name, rendered_task, node) rows in explain_text order."""
    from ..planner.explain import explain_text
    from ..planner.physical import PhysicalTableReader
    rows = explain_text(p)
    nodes: List[object] = []

    def walk(n):
        nodes.append(n)
        if isinstance(n, PhysicalTableReader):
            nodes.append(n.scan)
        for c in n.children:
            walk(c)
    walk(p)
    return [(r[0].strip(), r[2], n) for r, n in zip(rows, nodes)]


def check_explain_consistency(p, where: str = "<plan>") -> List[Diagnostic]:
    """PD205: the EXPLAIN task column must render `tpu` exactly for
    placed nodes (scans render `cop`, everything else `root`)."""
    out: List[Diagnostic] = []
    for name, task, node in _explain_tasks(p):
        if node is None:
            continue
        placed = bool(getattr(node, "use_tpu", False))
        from ..planner.physical import (PhysicalTableReader,
                                        PhysicalTableScan)
        if isinstance(node, (PhysicalTableScan,)):
            expect = "cop"
        elif isinstance(node, PhysicalTableReader):
            expect = "root"
        else:
            expect = "tpu" if placed else "root"
        if task != expect:
            out.append(Diagnostic(
                "PD205", f"EXPLAIN renders task {task!r} for {name} "
                f"but placement implies {expect!r}", where))
        if placed and "(TPU)" not in name and expect == "tpu":
            out.append(Diagnostic(
                "PD205", f"EXPLAIN name {name!r} lacks the (TPU) "
                "marker for a TPU-placed node", where))
    return out


def verify_plan(p, where: str = "<plan>") -> None:
    """Opt-in runtime verifier (tidb_qlint_verify): raise on the first
    invariant violation instead of executing a mis-placed plan."""
    diags = check_plan(p, where=where) + check_explain_consistency(p, where)
    if diags:
        raise PlanDeviceError(diags)


# =========================================================================
# offline corpus mode
# =========================================================================

def _plan_and_check(session, sql: str, where: str) -> List[Diagnostic]:
    """Plan `sql` with the TPU tier enabled and run both plan checks.
    Replay/planning failures are skipped (the extraction replays test
    fixtures only approximately); only INVARIANT violations report."""
    from ..parser import ast as past
    from ..parser import parse
    from ..planner.builder import PlanBuilder
    try:
        stmts = parse(sql)
    except Exception:
        return []
    out: List[Diagnostic] = []
    for stmt in stmts:
        if not isinstance(stmt, past.SelectStmt):
            continue
        try:
            builder = PlanBuilder(session)
            logical = builder.build_select(stmt)
            phys = session._optimize(logical, True)
        except Exception:
            continue
        finally:
            session._pinned_is = None
        out.extend(check_plan(phys, where=where))
        out.extend(check_explain_consistency(phys, where=where))
    return out


def _extract_testkit_statements(path: str):
    """(test_name, [sql, ...]) per test function: the constant-string
    arguments of tk.must_exec / tk.must_query calls, in source order."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for fn in tree.body:
        if not isinstance(fn, ast.FunctionDef) \
                or not fn.name.startswith("test_"):
            continue
        stmts = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("must_exec", "must_query") \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                stmts.append((node.lineno, node.func.attr,
                              node.args[0].value))
        yield fn.name, sorted(stmts)


def check_corpus_testkit(path: str) -> List[Diagnostic]:
    """Replay each test function's statements into a fresh TestKit with
    the TPU tier ON and check every SELECT's placed plan."""
    from ..utils.testkit import TestKit
    out: List[Diagnostic] = []
    for test_name, stmts in _extract_testkit_statements(path):
        tk = TestKit()
        try:
            tk.must_exec("create database test")
            tk.must_exec("use test")
            tk.must_exec("set @@tidb_use_tpu = 1")
            tk.must_exec("set @@tidb_tpu_min_rows = 0")
        except Exception:
            continue
        for lineno, kind, sql in stmts:
            where = f"{path}::{test_name}"
            low = sql.lstrip().lower()
            if low.startswith("select"):
                diags = _plan_and_check(tk.session, sql, where)
                for d in diags:
                    d.line = lineno
                out.extend(diags)
            if kind == "must_exec" and not (
                    low.startswith("set") and "tidb_use_tpu" in low):
                try:
                    tk.must_exec(sql)
                except Exception:
                    pass  # approximate replay: skip what doesn't apply
    return out


def check_corpus_fuzz(path: str, n_queries: Optional[int] = None
                      ) -> List[Diagnostic]:
    """Drive tests/test_sqlite_diff.py's own seeded generator (module
    imported by path; the `engines` fixture body builds the schema) and
    check the placed plan of every generated query."""
    import importlib.util
    spec = importlib.util.spec_from_file_location("_qlint_fuzz", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fixture_fn = mod.engines
    while hasattr(fixture_fn, "__wrapped__"):
        fixture_fn = fixture_fn.__wrapped__
    s, _lite, rng = fixture_fn()
    s.execute("set @@tidb_use_tpu = 1")
    s.execute("set @@tidb_tpu_min_rows = 0")
    gen = mod._Gen(rng)
    out: List[Diagnostic] = []
    for i in range(n_queries if n_queries is not None else mod.N_QUERIES):
        q = gen.query()
        out.extend(_plan_and_check(s, q, f"{path}::query[{i}] {q!r}"))
    return out


def check_corpus(repo_root: str,
                 fuzz_queries: Optional[int] = None) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    tk_path = os.path.join(repo_root, "tests", "test_sql.py")
    fz_path = os.path.join(repo_root, "tests", "test_sqlite_diff.py")
    if os.path.exists(tk_path):
        out.extend(check_corpus_testkit(tk_path))
    if os.path.exists(fz_path):
        out.extend(check_corpus_fuzz(fz_path, fuzz_queries))
    return out
