"""qlint pass: observability counter discipline (OB4xx).

The device-economics counters (``ops/kernels.STATS``,
``ops/progcache.STATS``) are written ONLY through their owning module's
accessors (``kernels.stats_add`` / ``kernels.stats_hwm``; progcache's
own locked ``get``).  The accessors are what fan every increment out to
the active per-query observability scope (obs/context.py) — a direct
``STATS[...] += 1`` elsewhere updates the global dict but silently
vanishes from per-query attribution, EXPLAIN ANALYZE, and the slow log,
and (being unlocked read-modify-write from arbitrary threads) can lose
increments under the devpipe producer.

Rules:

- **OB401**: direct subscript write (``STATS[k] = ...`` /
  ``STATS[k] += ...``) to a name or attribute called ``STATS`` outside
  the owning modules.
- **OB402**: mutating-method call (``STATS.update/clear/setdefault/
  pop``) on such a target outside the owning modules.

Reads (``STATS["dispatches"]``, ``dict(STATS)``) are fine anywhere —
that is what /metrics does.
"""
from __future__ import annotations

import ast
import os
from typing import List

from .diag import Diagnostic, SourceFile, register_rules

register_rules({
    "OB401": "direct STATS[...] write outside the owning module — use "
             "kernels.stats_add/stats_hwm so per-query scopes see it",
    "OB402": "mutating STATS method call (update/clear/setdefault/pop) "
             "outside the owning module",
})

#: modules that own a STATS dict and its accessors
OWNING_MODULES = ("kernels.py", "progcache.py")

_MUTATORS = {"update", "clear", "setdefault", "pop", "popitem"}


def _is_stats_target(e: ast.expr) -> bool:
    """``STATS`` / ``kernels.STATS`` / ``x.y.STATS``."""
    if isinstance(e, ast.Name):
        return e.id == "STATS"
    if isinstance(e, ast.Attribute):
        return e.attr == "STATS"
    return False


def lint_obs_discipline(sf: SourceFile) -> List[Diagnostic]:
    if os.path.basename(sf.path) in OWNING_MODULES:
        return []
    diags: List[Diagnostic] = []
    for node in ast.walk(sf.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Subscript) and _is_stats_target(t.value):
                diags.append(Diagnostic(
                    "OB401",
                    "direct STATS[...] write — route through "
                    "kernels.stats_add/stats_hwm (per-query scopes and "
                    "/metrics depend on the accessor fan-out)",
                    sf.path, t.lineno))
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS \
                and _is_stats_target(node.func.value):
            diags.append(Diagnostic(
                "OB402",
                f"STATS.{node.func.attr}(...) mutates the counter table "
                "outside its owning module — use the accessors",
                sf.path, node.lineno))
    return sf.filter(diags)
