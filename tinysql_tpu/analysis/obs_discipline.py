"""qlint pass: observability counter discipline (OB4xx).

The device-economics counters (``ops/kernels.STATS``,
``ops/progcache.STATS``) are written ONLY through their owning module's
accessors (``kernels.stats_add`` / ``kernels.stats_hwm``; progcache's
own locked ``get``).  The accessors are what fan every increment out to
the active per-query observability scope (obs/context.py) — a direct
``STATS[...] += 1`` elsewhere updates the global dict but silently
vanishes from per-query attribution, EXPLAIN ANALYZE, and the slow log,
and (being unlocked read-modify-write from arbitrary threads) can lose
increments under the devpipe producer.

Rules:

- **OB401**: direct subscript write (``STATS[k] = ...`` /
  ``STATS[k] += ...``) to a name or attribute called ``STATS`` outside
  the owning modules.
- **OB402**: mutating-method call (``STATS.update/clear/setdefault/
  pop``) on such a target outside the owning modules.
- **OB403**: statement-summary store write (``stmtsummary.ingest`` /
  ``STORE.ingest`` / ``.reset``) outside the designated session
  statement-close hook (``session/session.py _finish_obs``) and the
  store's own module.  Any other writer double-counts statements or
  bypasses the window-rotation/eviction accounting behind
  ``information_schema.statements_summary`` and the /metrics latency
  histograms.
- **OB405**: device-time counter write outside the owning modules.
  The device-time keys (``device_s`` / ``profiled_dispatches`` /
  ``compile_s``) carry MEASURED walls: ``device_s`` is only ever real
  when the sampling profiler closed the dispatch with
  ``block_until_ready`` (ops/profiler.py via ops/kernels.counted_jit),
  and ``compile_s`` is the program-build wall timed inside
  ops/progcache.get.  A ``stats_add``/``record`` of those keys anywhere
  else would publish a host submit wall as device truth — the exact
  fiction ISSUE 11 removes.
- **OB406**: continuous-profiler fold/attribution writes outside
  ``obs/conprof.py``.  The statement CPU counters (``cpu_s`` /
  ``cpu_samples``) are SAMPLE-ESTIMATED truth: only the profiler's
  sampler tick — which walks ``sys._current_frames()``, resolves the
  executing thread through the interrupt registry, and caps each
  increment at the statement's elapsed wall — may write them.  Any
  other writer would publish un-sampled wall time as CPU attribution
  (breaking the ``sum_cpu_ms <= exec wall`` invariant), and any
  out-of-module mutation of the profiler's window store
  (``sample_once`` / ``reset`` on the module or its ``PROF``/
  ``Profiler`` instances) would corrupt the rotation/eviction
  accounting behind ``information_schema.continuous_profiling``.
- **OB407**: heap/HBM accumulator writes outside ``obs/memprof.py``.
  The memory keys (``heap_kb`` / ``heap_peak_kb`` / ``hbm_bytes``) are
  MEASURED truth: ``heap_kb`` is the sampler tick's traced-delta split
  across executing statements (so the per-statement sum stays ≤ the
  process's measured growth), ``heap_peak_kb`` is the tracemalloc
  high-water mark, and ``hbm_bytes`` is the live device-buffer census.
  Any other writer would publish a guess as measurement and break the
  ≤-growth invariant behind ``statements_summary.sum_heap_alloc_kb``;
  and any out-of-module mutation of the heap profiler's window store
  (``sample_once`` / ``reset`` on the module or its ``PROF``/
  ``HeapProfiler`` instances) would corrupt the rotation/eviction
  accounting behind ``information_schema.memory_usage`` and
  ``/debug/heap``.
- **OB404**: metric-name drift.  In any module that touches the
  time-series ring (imports ``obs/tsring.py``, or IS it), every
  ``tinysql_*`` metric-name string literal must be declared in the
  central registry (``obs/metrics.METRICS``).  The registry is the one
  definition /metrics, the ring, ``metrics_history`` and
  ``metrics_summary`` all share — a name invented at a sample site
  would produce a time series no other surface knows, and a typo would
  silently sample nothing (the ring also drops unregistered names at
  runtime; this rule catches them at lint time).  ``obs/metrics.py``
  itself is exempt: it IS the registry.

Reads (``STATS["dispatches"]``, ``dict(STATS)``, ``stmtsummary.rows()``,
``snapshot()``, ``histogram_snapshot()``) are fine anywhere — that is
what /metrics and the mem-tables do.
"""
from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set

from .diag import Diagnostic, SourceFile, register_rules

register_rules({
    "OB401": "direct STATS[...] write outside the owning module — use "
             "kernels.stats_add/stats_hwm so per-query scopes see it",
    "OB402": "mutating STATS method call (update/clear/setdefault/pop) "
             "outside the owning module",
    "OB403": "statement-summary store write outside the designated "
             "session statement-close hook",
    "OB404": "metric name not declared in the central registry "
             "(obs/metrics.METRICS) — /metrics, the time-series ring, "
             "and metrics_summary must share one name set",
    "OB405": "device-time counter write outside the owning "
             "profiler/kernels/progcache modules — only a "
             "block_until_ready-closed dispatch or a timed program "
             "build may claim device/compile wall",
    "OB406": "continuous-profiler fold/attribution write outside "
             "obs/conprof.py — only the sampler tick may claim "
             "statement CPU (cpu_s/cpu_samples) or mutate the "
             "window store",
    "OB407": "heap/HBM accumulator write outside obs/memprof.py — only "
             "the heap profiler's sampler tick may claim statement "
             "memory (heap_kb/heap_peak_kb/hbm_bytes) or mutate the "
             "window store",
})

#: modules that own a STATS dict and its accessors (the serving layer's
#: admission/batching counters follow the same discipline: locked
#: accessor writes inside the owning module, snapshot reads anywhere)
OWNING_MODULES = ("kernels.py", "progcache.py", "admission.py",
                  "batching.py", "spill.py", "shardops.py", "wal.py",
                  "flight.py")

#: modules allowed to write the statement-summary store: the store
#: itself and the session statement-close hook that feeds it
SUMMARY_WRITER_MODULES = ("stmtsummary.py", "session.py")

_MUTATORS = {"update", "clear", "setdefault", "pop", "popitem"}

#: mutating entry points on the summary store / its module facade
_SUMMARY_WRITERS = {"ingest", "reset"}

#: device-time counter keys (OB405) and the modules that own their
#: truth: kernels.counted_jit (the block_until_ready-closed dispatch),
#: ops/profiler.py (the sampling decision + histogram), and
#: ops/progcache.py (the timed program build -> compile_s)
DEVTIME_KEYS = {"device_s", "profiled_dispatches", "compile_s"}
DEVTIME_OWNING_MODULES = ("kernels.py", "profiler.py", "progcache.py")

#: accumulator entry points a device-time key could ride through
_DEVTIME_SINKS = {"stats_add", "stats_hwm", "record", "record_hwm",
                  "add_counter", "add_device"}

#: statement-CPU attribution keys (OB406) and their owning module: the
#: continuous profiler's sampler tick is the ONLY writer — these carry
#: sample-estimated on-thread time capped at the statement's wall
CPU_KEYS = {"cpu_s", "cpu_samples"}
CONPROF_OWNING_MODULE = "conprof.py"

#: mutating entry points on the profiler store / its module facade
_CONPROF_WRITERS = {"sample_once", "reset"}

#: statement-memory attribution keys (OB407) and their owning module:
#: the heap profiler's sampler tick is the ONLY writer — these carry
#: the traced-delta split (≤ measured process growth), the tracemalloc
#: peak, and the device-buffer census
HEAP_KEYS = {"heap_kb", "heap_peak_kb", "hbm_bytes"}
MEMPROF_OWNING_MODULE = "memprof.py"

#: mutating entry points on the heap-profiler store / its module facade
_MEMPROF_WRITERS = {"sample_once", "reset"}


def _is_stats_target(e: ast.expr) -> bool:
    """``STATS`` / ``kernels.STATS`` / ``x.y.STATS``."""
    if isinstance(e, ast.Name):
        return e.id == "STATS"
    if isinstance(e, ast.Attribute):
        return e.attr == "STATS"
    return False


def _is_summary_target(e: ast.expr, module_aliases: set,
                       store_aliases: set) -> bool:
    """``stmtsummary`` (under any import alias) / ``obs.stmtsummary`` /
    ``stmtsummary.STORE`` / a ``STORE`` imported FROM stmtsummary — but
    not an unrelated module-level ``STORE`` global."""
    if isinstance(e, ast.Name):
        return e.id in module_aliases or e.id in store_aliases
    if isinstance(e, ast.Attribute):
        if e.attr == "stmtsummary":
            return True
        return e.attr == "STORE" \
            and _is_summary_target(e.value, module_aliases,
                                   store_aliases)
    return False


def _summary_import_aliases(sf: SourceFile):
    """(module aliases, writer names, STORE names) bound by any import
    of stmtsummary — ``from …obs import stmtsummary as sm`` /
    ``import …obs.stmtsummary as z`` / ``from …stmtsummary import
    ingest as x, STORE as st``.  Only names provably from stmtsummary
    qualify, so an unrelated local ``ingest`` helper or ``STORE``
    global stays silent."""
    modules, writers, stores = {"stmtsummary"}, set(), set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.rsplit(".", 1)[-1] == "stmtsummary" \
                        and alias.asname:
                    modules.add(alias.asname)
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module.rsplit(".", 1)[-1] == "stmtsummary":
                for alias in node.names:
                    if alias.name in _SUMMARY_WRITERS:
                        writers.add(alias.asname or alias.name)
                    elif alias.name == "STORE":
                        stores.add(alias.asname or alias.name)
            else:
                for alias in node.names:
                    if alias.name == "stmtsummary":
                        modules.add(alias.asname or alias.name)
    return modules, writers, stores


def _lint_summary_writes(sf: SourceFile) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    module_aliases, writer_aliases, store_aliases = \
        _summary_import_aliases(sf)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Attribute)
               and f.attr in _SUMMARY_WRITERS
               and _is_summary_target(f.value, module_aliases,
                                      store_aliases)) \
            or (isinstance(f, ast.Name) and f.id in writer_aliases)
        if hit:
            diags.append(Diagnostic(
                "OB403",
                "statement-summary store write — only the session's "
                "statement-close hook (_finish_obs) may ingest; any "
                "other writer double-counts or bypasses window/eviction "
                "accounting",
                sf.path, node.lineno))
    return diags


# ---- OB405: device-time write discipline ----------------------------------

def _lint_devtime_writes(sf: SourceFile) -> List[Diagnostic]:
    """Flag accumulator calls whose FIRST argument is a device-time key
    literal (``stats_add("device_s", ...)``, ``_obs.record("compile_s",
    ...)``) outside the owning modules.  obs/context.py defines the
    generic fan-out but never names the keys; any module NAMING one is
    claiming to have measured device time."""
    diags: List[Diagnostic] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if name not in _DEVTIME_SINKS:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and arg.value in DEVTIME_KEYS:
            diags.append(Diagnostic(
                "OB405",
                f"`{name}({arg.value!r}, ...)` writes a device-time "
                "counter outside the owning profiler/kernels/progcache "
                "modules — only a block_until_ready-closed dispatch or "
                "a timed program build may claim device/compile wall",
                sf.path, node.lineno))
    return diags


# ---- OB406: continuous-profiler write discipline --------------------------

def _conprof_import_aliases(sf: SourceFile):
    """(module aliases, writer names, profiler-instance names) bound by
    any import of conprof — the OB403 matching contract: a name
    READING as the module (bare ``conprof`` / any ``.conprof``
    attribute) matches by naming convention, exactly like OB403's
    ``stmtsummary``; the generic names (``reset`` / ``sample_once`` /
    ``PROF``) qualify only when PROVABLY imported from conprof, so an
    unrelated local ``reset`` helper or ``PROF`` global stays silent."""
    modules, writers, profs = {"conprof"}, set(), set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.rsplit(".", 1)[-1] == "conprof" \
                        and alias.asname:
                    modules.add(alias.asname)
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module.rsplit(".", 1)[-1] == "conprof":
                for alias in node.names:
                    if alias.name in _CONPROF_WRITERS:
                        writers.add(alias.asname or alias.name)
                    elif alias.name in ("PROF", "Profiler"):
                        profs.add(alias.asname or alias.name)
            else:
                for alias in node.names:
                    if alias.name == "conprof":
                        modules.add(alias.asname or alias.name)
    return modules, writers, profs


def _is_conprof_target(e: ast.expr, module_aliases: set,
                       prof_aliases: set) -> bool:
    """``conprof`` (under any alias) / ``obs.conprof`` /
    ``conprof.PROF`` / a ``PROF`` imported FROM conprof."""
    if isinstance(e, ast.Name):
        return e.id in module_aliases or e.id in prof_aliases
    if isinstance(e, ast.Attribute):
        if e.attr == "conprof":
            return True
        return e.attr == "PROF" \
            and _is_conprof_target(e.value, module_aliases, prof_aliases)
    return False


def _lint_conprof_writes(sf: SourceFile) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    module_aliases, writer_aliases, prof_aliases = \
        _conprof_import_aliases(sf)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # (a) a statement-CPU key laundered through an accumulator sink
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if name in _DEVTIME_SINKS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and arg.value in CPU_KEYS:
                diags.append(Diagnostic(
                    "OB406",
                    f"`{name}({arg.value!r}, ...)` writes a statement-"
                    "CPU counter outside obs/conprof.py — only the "
                    "profiler's sampler tick may claim cpu_s/"
                    "cpu_samples (sample-estimated, wall-capped)",
                    sf.path, node.lineno))
                continue
        # (b) a mutating call on the profiler store itself
        hit = (isinstance(f, ast.Attribute)
               and f.attr in _CONPROF_WRITERS
               and _is_conprof_target(f.value, module_aliases,
                                      prof_aliases)) \
            or (isinstance(f, ast.Name) and f.id in writer_aliases)
        if hit:
            diags.append(Diagnostic(
                "OB406",
                "continuous-profiler store write outside "
                "obs/conprof.py — window rotation/eviction accounting "
                "belongs to the sampler",
                sf.path, node.lineno))
    return diags


# ---- OB407: heap-profiler write discipline --------------------------------

#: accumulator entry points a memory key could ride through — the
#: device-time sinks plus the high-water-mark scope accessor memprof's
#: attribution actually uses
_MEMPROF_SINKS = _DEVTIME_SINKS | {"hwm_counter"}


def _memprof_import_aliases(sf: SourceFile):
    """(module aliases, writer names, profiler-instance names) bound by
    any import of memprof — the OB406 matching contract: a name READING
    as the module (bare ``memprof`` / any ``.memprof`` attribute)
    matches by naming convention; the generic names (``reset`` /
    ``sample_once`` / ``PROF``) qualify only when PROVABLY imported
    from memprof, so an unrelated local ``reset`` helper or ``PROF``
    global stays silent."""
    modules, writers, profs = {"memprof"}, set(), set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.rsplit(".", 1)[-1] == "memprof" \
                        and alias.asname:
                    modules.add(alias.asname)
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module.rsplit(".", 1)[-1] == "memprof":
                for alias in node.names:
                    if alias.name in _MEMPROF_WRITERS:
                        writers.add(alias.asname or alias.name)
                    elif alias.name in ("PROF", "HeapProfiler"):
                        profs.add(alias.asname or alias.name)
            else:
                for alias in node.names:
                    if alias.name == "memprof":
                        modules.add(alias.asname or alias.name)
    return modules, writers, profs


def _is_memprof_target(e: ast.expr, module_aliases: set,
                       prof_aliases: set) -> bool:
    """``memprof`` (under any alias) / ``obs.memprof`` /
    ``memprof.PROF`` / a ``PROF`` imported FROM memprof."""
    if isinstance(e, ast.Name):
        return e.id in module_aliases or e.id in prof_aliases
    if isinstance(e, ast.Attribute):
        if e.attr == "memprof":
            return True
        return e.attr == "PROF" \
            and _is_memprof_target(e.value, module_aliases, prof_aliases)
    return False


def _lint_memprof_writes(sf: SourceFile) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    module_aliases, writer_aliases, prof_aliases = \
        _memprof_import_aliases(sf)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # (a) a statement-memory key laundered through an accumulator
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if name in _MEMPROF_SINKS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and arg.value in HEAP_KEYS:
                diags.append(Diagnostic(
                    "OB407",
                    f"`{name}({arg.value!r}, ...)` writes a statement-"
                    "memory counter outside obs/memprof.py — only the "
                    "heap profiler's sampler tick may claim heap_kb/"
                    "heap_peak_kb/hbm_bytes (measured, ≤-growth-capped)",
                    sf.path, node.lineno))
                continue
        # (b) a mutating call on the heap-profiler store itself
        hit = (isinstance(f, ast.Attribute)
               and f.attr in _MEMPROF_WRITERS
               and _is_memprof_target(f.value, module_aliases,
                                      prof_aliases)) \
            or (isinstance(f, ast.Name) and f.id in writer_aliases)
        if hit:
            diags.append(Diagnostic(
                "OB407",
                "heap-profiler store write outside obs/memprof.py — "
                "window rotation/eviction accounting belongs to the "
                "sampler",
                sf.path, node.lineno))
    return diags


# ---- OB404: metric-name registry discipline -------------------------------

#: matches the exported metric naming convention; deliberately excludes
#: dotted logger names ("tinysql_tpu.pool") by construction and the bare
#: package name explicitly
_METRIC_NAME_RE = re.compile(r"^tinysql_[a-z0-9_]+$")
_NON_METRIC_NAMES = {"tinysql_tpu"}

#: the registry module itself — where names are DECLARED — is exempt
_REGISTRY_MODULE = "metrics.py"


def _metric_registry() -> Optional[Set[str]]:
    """The live central registry, or None when it cannot be imported
    (lint must degrade to silence, not crash, in a stripped checkout)."""
    try:
        from ..obs.metrics import METRICS
        return set(METRICS)
    except Exception:
        return None


def _imports_tsring(sf: SourceFile) -> bool:
    """Provable tsring import under any form: ``import …obs.tsring [as
    x]``, ``from …obs.tsring import RING``, ``from …obs import tsring
    [as t]``."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.rsplit(".", 1)[-1] == "tsring":
                    return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.rsplit(".", 1)[-1] == "tsring":
                return True
            for alias in node.names:
                if alias.name == "tsring":
                    return True
    return False


def _lint_metric_names(sf: SourceFile) -> List[Diagnostic]:
    if os.path.basename(sf.path) != "tsring.py" \
            and not _imports_tsring(sf):
        return []
    registry = _metric_registry()
    if registry is None:
        return []
    # f-string fragments are PARTIAL names (f"tinysql_x_{k}_total") —
    # judging them against the registry would be judging half a name
    in_fstring = {id(c) for n in ast.walk(sf.tree)
                  if isinstance(n, ast.JoinedStr) for c in n.values}
    diags: List[Diagnostic] = []
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Constant)
                and isinstance(node.value, str)) \
                or id(node) in in_fstring:
            continue
        name = node.value
        if name in _NON_METRIC_NAMES or name in registry \
                or not _METRIC_NAME_RE.match(name):
            continue
        diags.append(Diagnostic(
            "OB404",
            f"metric name `{name}` is not declared in the central "
            "registry (obs/metrics.METRICS) — the ring drops it at "
            "sample time and no other surface (/metrics, "
            "metrics_summary) will ever know it; declare it there "
            "first", sf.path, node.lineno))
    return diags


def lint_obs_discipline(sf: SourceFile) -> List[Diagnostic]:
    base = os.path.basename(sf.path)
    diags: List[Diagnostic] = []
    # OB403 has its OWN allowlist: the STATS-owning modules are exactly
    # the ones most tempted to push counters at the summary store, so
    # the OB401/OB402 ownership exemption must not cover them here
    if base not in SUMMARY_WRITER_MODULES:
        diags.extend(_lint_summary_writes(sf))
    if base != _REGISTRY_MODULE:
        diags.extend(_lint_metric_names(sf))
    if base not in DEVTIME_OWNING_MODULES:
        diags.extend(_lint_devtime_writes(sf))
    if base != CONPROF_OWNING_MODULE:
        diags.extend(_lint_conprof_writes(sf))
    if base != MEMPROF_OWNING_MODULE:
        diags.extend(_lint_memprof_writes(sf))
    if base in OWNING_MODULES:
        return sf.filter(diags)
    for node in ast.walk(sf.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Subscript) and _is_stats_target(t.value):
                diags.append(Diagnostic(
                    "OB401",
                    "direct STATS[...] write — route through "
                    "kernels.stats_add/stats_hwm (per-query scopes and "
                    "/metrics depend on the accessor fan-out)",
                    sf.path, t.lineno))
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS \
                and _is_stats_target(node.func.value):
            diags.append(Diagnostic(
                "OB402",
                f"STATS.{node.func.attr}(...) mutates the counter table "
                "outside its owning module — use the accessors",
                sf.path, node.lineno))
    return sf.filter(diags)
